"""Optimizer-state HBM levers for MoE expert banks (VERDICT r4 #2).

An 8-expert top-2 MoE carries an 8x-overprovisioned expert bank whose
AdamW pass is pure HBM traffic independent of batch: every step reads
grad+param+m+v and writes param+m+v for mostly-inactive weights
(profiled at 12.8% of the Mixtral step, docs/benchmarks.md). The three
standard levers, each expressible per-subtree so the dense params keep
exact AdamW:

- :func:`scale_by_adam_low_precision` — store m and/or v in bf16 with
  stochastic rounding (unbiased over steps; plain rounding stalls small
  accumulations).
- Adafactor-style factored second moment for the expert tensors only
  (via :func:`partition` + ``optax.adafactor``).
- :func:`every_k` — apply the expert-bank update every k-th step with
  the update scaled by k (same expected LR). CAUTION: this single-program
  ``lax.cond`` form does NOT realize the HBM saving — cond cannot alias
  loop-carried state across the branch, so the skip branch's pass-through
  of m/v/params is a COPY that measured away the entire win (and -15%
  with donation disabled; VERDICT r5 #2). For the real saving use
  :func:`deferred_pair` + ``train.make_gspmd_deferred_train_step``
  (two jitted programs; the skip program aliases donated buffers and
  DCEs the dead dL/dW einsums — +22% on Mixtral).

:func:`partition` routes subtrees to different transforms by parameter
path (``optax.multi_transform`` with a path-predicate labeler).

Reference parity: none — the reference's MoE story is the raw
``hvd.alltoall`` primitive (SURVEY §2.2); the expert-update levers are
standard MoE practice (Adafactor: Shazeer & Stern 2018; deferred expert
updates appear in large-scale MoE training systems) re-expressed as
optax transforms.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


def _cast(tree, dtype):
    if dtype is None:
        return tree
    return jax.tree_util.tree_map(lambda x: x.astype(dtype), tree)


def _stochastic_round(key, x, dtype):
    """Unbiased f32 -> bf16 rounding: add a uniform 16-bit value below the
    truncation point, then truncate the mantissa (bf16 = f32's top 16
    bits). E[result] = x, so tiny moment deltas accumulate in expectation
    instead of being swallowed by round-to-nearest."""
    assert dtype == jnp.bfloat16, "stochastic rounding implemented for bf16"
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    noise = jax.random.bits(key, shape=x.shape, dtype=jnp.uint32) & 0xFFFF
    return jax.lax.bitcast_convert_type(
        (bits + noise) & jnp.uint32(0xFFFF0000), jnp.float32
    ).astype(jnp.bfloat16)


class ScaleByAdamLPState(NamedTuple):
    count: Any
    mu: Any
    nu: Any
    key: Any


def scale_by_adam_low_precision(b1: float = 0.9, b2: float = 0.999,
                                eps: float = 1e-8,
                                mu_dtype=None, nu_dtype=None,
                                stochastic_rounding: bool = True,
                                seed: int = 0):
    """``optax.scale_by_adam`` with the moments STORED in ``mu_dtype`` /
    ``nu_dtype`` (e.g. ``jnp.bfloat16``), computed in f32. Storing v in
    bf16 halves its HBM traffic; with ``stochastic_rounding`` the cast is
    unbiased so v's tiny per-step increments survive (plain
    round-to-nearest freezes v once ``b2*v`` dominates the update)."""

    def init(params):
        mu = _cast(jax.tree_util.tree_map(jnp.zeros_like, params), mu_dtype)
        nu = _cast(jax.tree_util.tree_map(jnp.zeros_like, params), nu_dtype)
        return ScaleByAdamLPState(jnp.zeros((), jnp.int32), mu, nu,
                                  jax.random.PRNGKey(seed))

    def _store(key, new, dtype):
        if dtype is None:
            return new
        if not stochastic_rounding or dtype != jnp.bfloat16:
            return _cast(new, dtype)
        leaves, treedef = jax.tree_util.tree_flatten(new)
        keys = jax.random.split(key, len(leaves))
        return jax.tree_util.tree_unflatten(
            treedef, [_stochastic_round(k, l, dtype)
                      for k, l in zip(keys, leaves)])

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        kmu, knu, knext = jax.random.split(state.key, 3)
        f32 = jnp.float32
        mu_new = jax.tree_util.tree_map(
            lambda g, m: b1 * m.astype(f32) + (1 - b1) * g.astype(f32),
            updates, state.mu)
        nu_new = jax.tree_util.tree_map(
            lambda g, v: b2 * v.astype(f32)
            + (1 - b2) * jnp.square(g.astype(f32)),
            updates, state.nu)
        c = count.astype(f32)
        bc1 = 1 - b1 ** c
        bc2 = 1 - b2 ** c
        out = jax.tree_util.tree_map(
            lambda m, v, g: ((m / bc1) / (jnp.sqrt(v / bc2) + eps))
            .astype(g.dtype),
            mu_new, nu_new, updates)
        return out, ScaleByAdamLPState(
            count, _store(kmu, mu_new, mu_dtype),
            _store(knu, nu_new, nu_dtype), knext)

    return optax.GradientTransformation(init, update)


def adamw_low_precision(learning_rate, b1: float = 0.9, b2: float = 0.999,
                        eps: float = 1e-8, weight_decay: float = 1e-4,
                        mu_dtype=None, nu_dtype=None,
                        stochastic_rounding: bool = True):
    """AdamW with reduced-precision moment storage (drop-in for
    ``optax.adamw``; ``optax.adamw(mu_dtype=...)`` covers only m)."""
    return optax.chain(
        scale_by_adam_low_precision(b1, b2, eps, mu_dtype=mu_dtype,
                                    nu_dtype=nu_dtype,
                                    stochastic_rounding=stochastic_rounding),
        optax.add_decayed_weights(weight_decay),
        optax.scale_by_learning_rate(learning_rate))


class EveryKState(NamedTuple):
    count: Any
    inner: Any


def every_k(inner: optax.GradientTransformation, k: int,
            scale: Optional[float] = None):
    """Apply ``inner`` only every k-th step, scaling its update by
    ``scale`` (default k, preserving the expected per-step LR); the other
    k-1 steps emit zero updates and do NOT touch inner state. The
    applied update uses the CURRENT gradient (no accumulator: an
    accumulator would itself read+write a bank-sized buffer every step,
    spending what the deferral saves).

    PERFORMANCE CAUTION: do not expect an HBM saving from this form.
    ``lax.cond`` cannot alias the untouched m/v through the branch, so
    the skip branch COPIES the moments every step — measured to cancel
    the entire ~(k-1)/k traffic win (VERDICT r5 #2; hvd-analyze flags
    the pattern as ``jax-cond-carry``). ``every_k`` remains useful for
    SEMANTIC deferral (same expected LR with stale-free updates); for
    the real HBM/throughput win use :func:`deferred_pair` with
    ``train.make_gspmd_deferred_train_step``, which compiles separate
    apply/skip programs so donated buffers alias and the dead gradient
    einsums are DCE'd.

    CONSTRAINT: ``inner``'s internal step count only advances on apply
    steps (its state is untouched on skips), so any schedule or
    bias-correction inside it runs k-times slower than the dense params'.
    Use a CONSTANT learning rate inside ``inner`` (``moe_adamw`` enforces
    this for its ``"deferred"`` variant); Adam bias correction warming up
    k-times slower only damps the expert bank's first ~k/(1-b2) steps."""
    if k < 1:
        raise ValueError(f"every_k needs k >= 1, got {k}")
    s = float(k if scale is None else scale)

    def init(params):
        return EveryKState(jnp.zeros((), jnp.int32), inner.init(params))

    def update(updates, state, params=None):
        count = state.count + 1

        def apply(_):
            out, inner_state = inner.update(updates, state.inner, params)
            out = jax.tree_util.tree_map(lambda u: (u * s).astype(u.dtype),
                                         out)
            return out, inner_state

        def skip(_):
            zeros = jax.tree_util.tree_map(jnp.zeros_like, updates)
            return zeros, state.inner

        out, inner_state = jax.lax.cond(count % k == 0, apply, skip,
                                        operand=None)
        return out, EveryKState(count, inner_state)

    return optax.GradientTransformation(init, update)


def partition(transforms: dict,
              labeler: Callable[[str], str]) -> optax.GradientTransformation:
    """``optax.multi_transform`` keyed by parameter PATH: ``labeler``
    maps each leaf's ``/``-joined lower-cased key path to a label in
    ``transforms``."""

    def label_tree(params):
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        labels = []
        for path, _ in flat:
            segs = [str(getattr(p, "key", getattr(p, "name", p))).lower()
                    for p in path]
            # Under GSPMD init the params arrive flax-BOXED (nn.Partitioned
            # wraps each array, adding a 'value' path segment); at update
            # time they are unboxed. Strip the wrapper segment so the same
            # leaf gets the same label in both shapes — otherwise the
            # masked state built at init mismatches the update-time tree.
            joined = "/".join(s for s in segs if s != "value")
            labels.append(labeler(joined))
        return jax.tree_util.tree_unflatten(treedef, labels)

    return optax.multi_transform(transforms, label_tree)


def is_expert_param(path: str) -> bool:
    """The routed expert bank: ``moe/{w1,w2,w3}`` leaves (leading E dim);
    router and norms are always-active (same selector as the MoE MFU
    accounting in benchmarks/mixtral.py)."""
    return "moe" in path and path.rsplit("/", 1)[-1] in ("w1", "w2", "w3")


def frozen_like(inner: optax.GradientTransformation):
    """Same state STRUCTURE as ``inner``, zero updates, state passed
    through untouched. The skip-program half of :func:`deferred_pair`:
    because the state is an unmodified donated jit input, XLA aliases its
    buffers to the output — zero HBM traffic — which ``lax.cond`` inside
    one program cannot do (measured: the cond form's pass-through copies
    ate the entire saving, docs/benchmarks.md r5)."""

    def update(updates, state, params=None):
        del params
        return jax.tree_util.tree_map(jnp.zeros_like, updates), state

    return optax.GradientTransformation(inner.init, update)


class DeferredPair(NamedTuple):
    """A matched (apply, skip) optimizer pair plus its cadence — one
    value, so the update scale (baked into ``apply``) and the dispatch
    cadence (consumed by ``train.make_gspmd_deferred_train_step``) can
    never disagree."""
    apply: Any
    skip: Any
    every: int


def deferred_pair(learning_rate, *, every: int = 4,
                  weight_decay: float = 1e-4, b1: float = 0.9,
                  b2: float = 0.999, eps: float = 1e-8,
                  expert_nu_dtype=None,
                  is_expert: Callable[[str], bool] = is_expert_param):
    """TWO-program expert-update deferral: returns a :class:`DeferredPair`
    of optimizers with identical state structure. Compile each into its
    own jitted step with donation (``train.make_gspmd_deferred_train_
    step``); the skip program's expert param/m/v alias straight through
    (zero optimizer HBM for the bank on k-1 of k steps) while the apply
    program applies the ``every``-scaled AdamW update from the current
    gradient. Constant LR only (same constraint as :func:`every_k`).
    ``expert_nu_dtype=jnp.bfloat16`` stacks the reduced-precision second
    moment on the apply program."""
    if callable(learning_rate):
        raise ValueError("deferred_pair needs a constant learning rate "
                         "(the expert arm ticks only on apply steps)")
    dense = optax.adamw(learning_rate, b1=b1, b2=b2, eps=eps,
                        weight_decay=weight_decay)
    if expert_nu_dtype is not None:
        expert_inner = adamw_low_precision(
            learning_rate, b1=b1, b2=b2, eps=eps,
            weight_decay=weight_decay, nu_dtype=expert_nu_dtype)
    else:
        expert_inner = optax.adamw(learning_rate, b1=b1, b2=b2, eps=eps,
                                   weight_decay=weight_decay)
    expert_apply = optax.chain(expert_inner, optax.scale(float(every)))
    labeler = (lambda p: "expert" if is_expert(p) else "dense")
    opt_apply = partition({"dense": dense, "expert": expert_apply}, labeler)
    opt_skip = partition({"dense": dense,
                          "expert": frozen_like(expert_apply)}, labeler)
    return DeferredPair(opt_apply, opt_skip, every)


def moe_adamw(learning_rate, *, expert_variant: str = "adamw",
              weight_decay: float = 1e-4, b1: float = 0.9, b2: float = 0.999,
              eps: float = 1e-8, every: int = 4,
              is_expert: Callable[[str], bool] = is_expert_param):
    """AdamW with a selectable treatment for the expert bank (dense params
    always get exact AdamW):

    - ``"adamw"``      exact AdamW everywhere (baseline)
    - ``"bf16_nu"``    expert v stored bf16 + stochastic rounding
    - ``"bf16_munu"``  expert m AND v stored bf16 + stochastic rounding
    - ``"factored"``   Adafactor for expert tensors (factored v, no m)
    - ``"deferred"``   expert update applied every ``every`` steps at
                       ``every``-scaled LR, skipped (zero HBM) otherwise
    """
    dense = optax.adamw(learning_rate, b1=b1, b2=b2, eps=eps,
                        weight_decay=weight_decay)
    if expert_variant == "adamw":
        return dense
    if expert_variant == "bf16_nu":
        expert = adamw_low_precision(learning_rate, b1=b1, b2=b2, eps=eps,
                                     weight_decay=weight_decay,
                                     nu_dtype=jnp.bfloat16)
    elif expert_variant == "bf16_munu":
        expert = adamw_low_precision(learning_rate, b1=b1, b2=b2, eps=eps,
                                     weight_decay=weight_decay,
                                     mu_dtype=jnp.bfloat16,
                                     nu_dtype=jnp.bfloat16)
    elif expert_variant == "factored":
        expert = optax.adafactor(learning_rate, decay_rate=b2,
                                 weight_decay_rate=weight_decay)
    elif expert_variant == "deferred":
        if callable(learning_rate):
            # every_k only ticks the inner transform on apply steps, so a
            # schedule inside it would advance k-times slower than the
            # dense params' — silently diverging LRs (r5 review).
            raise ValueError(
                "expert_variant='deferred' needs a constant learning rate "
                "(the deferred inner AdamW's schedule count advances only "
                "every k steps; see every_k's docstring)")
        expert = every_k(dense, every)
    else:
        raise ValueError(f"unknown expert_variant {expert_variant!r}")
    return partition({"dense": dense, "expert": expert},
                     lambda p: "expert" if is_expert(p) else "dense")
