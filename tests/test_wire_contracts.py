"""Wire-contract drivers (VERDICT r5 #6 / ISSUE 14 / ISSUE 16 → ISSUE 17).

The topology × payload × hop-count invariants these tests used to spell
out inline now live in the contract registry
(``horovod_tpu/analysis/contracts.py``), declared once and checked both
here and by ``python -m horovod_tpu.analysis --contracts``:

- **adasum-butterfly**: log₂(n) permute rounds, FULL working buffer,
  XOR-partner topology (collectives/adasum.py);
- **ring-attention**: exactly the K and V shards rotate the +1 ring,
  nothing else rides the step (parallel/ring.py);
- **pipeline-handoff**: ONE activation permute per schedule tick,
  stage i → i+1 (parallel/pipeline.py);
- **decode-tp / verify-tp / prefill-tp** (tp ∈ {1, 2, 4}) and
  **decode-tp8 / verify-tp8** (llama + mixtral at tp = 8): exactly
  ``2·n_layers`` activation all-reduces over the full tp group — zero
  permutes, zero resharding (models/decode.py).

Builds are memoized in the registry, so these drivers and the full
``--contracts`` matrix (tests/test_contracts.py) share one lowering per
family per pytest process.
"""

import pytest

import horovod_tpu  # noqa: F401  (compat shims before any jax use)
from horovod_tpu.analysis import contracts
from wire_accounting import collective_wire_costs


@pytest.mark.parametrize("family", [
    "adasum-butterfly", "ring-attention", "pipeline-handoff",
    "decode-tp", "verify-tp", "prefill-tp", "decode-tp8", "verify-tp8",
])
def test_wire_contract(family):
    findings = contracts.check_family(family)
    assert not findings, "\n".join(f.format() for f in findings)


def test_permute_parse_single_pair():
    """The tensor<1x2xi64> single-pair rendering parses too (a 2-device
    permute or a single handoff prints without nested brackets)."""
    hlo = '''
    %0 = "stablehlo.collective_permute"(%arg0) <{channel_handle =
      #stablehlo.channel_handle<handle = 1, type = 0>,
      source_target_pairs = dense<[[0, 1]]> : tensor<1x2xi64>}> :
      (tensor<4x2xf32>) -> tensor<4x2xf32>
    '''.replace("\n      ", " ")
    perms = [c for c in collective_wire_costs(hlo)
             if c["op"] == "collective_permute"]
    assert len(perms) == 1
    assert perms[0]["pairs"] == [[0, 1]]
    assert perms[0]["n_links"] == 1
    assert perms[0]["operand_bytes"] == 32
