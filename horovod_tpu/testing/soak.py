"""Seeded chaos-soak harness: every fault kind against ONE live world.

Reference parity: upstream horovod proves each elastic failure mode with
its own scripted integration test (``test/integration/test_elastic_*``,
SURVEY.md §6) — one fault, one run, one assertion. This module is the
missing composition layer: a **seeded random schedule** drawn from the
full fault menu (``testing/faults.py`` — kill / hang / delay / corrupt /
nan / desync / torn / preempt / rpc_* / resume_* / replica_* /
traffic_spike) thrown at a single live np=3 train + publish + serve
world, with **global invariants** checked after the dust settles:

1.  the training job exits 0 and every surviving rank reaches the final
    step (no lost or phantom generations);
2.  the committed-step ledger covers every step exactly and is monotone
    across generations modulo bounded committed-rollback replay;
3.  zero accepted-request loss on the serving side — shedding under
    spike is allowed, a failed or hung accepted request is not;
4.  coordinator journal replay reproduces the final world (training
    driver journal) and the final fleet registry (serving journal);
5.  every abnormal exit left a post-mortem: flight dumps + incident
    reports when a crash-class fault fired, the "preempt flight ring
    dumped" trace when a preemption fired — and NO failure record when
    only graceful preemptions fired;
6.  the last published commit is resumable by a fresh process
    (``ObjectState.load_latest``);
7.  no orphaned processes survive the run (every child is tagged with a
    run id and /proc is swept afterwards);
8.  at least ``min_fired`` scheduled events actually fired (a soak that
    silently skipped its chaos is worse than one that failed), inside
    the wall-clock budget.

Determinism contract: :func:`make_schedule` is a pure function of its
seed — same seed, same schedule, byte for byte (pinned by
tests/test_soak.py). The *timeline* of a run still varies with
scheduling noise; the invariants are written against outcomes, not
timings, which is what makes the soak re-runnable as a guardrail
(benchmarks/soak.py → soak_history.jsonl).

Topology: the training arm is a REAL ``hvdrun`` subprocess over three
loopback hosts with per-host commit dirs (the blob-mesh resume seam the
``resume_*`` faults target becomes live whenever a preempted host
rejoins with stale blobs); the serving arm is real replica subprocesses
(InferenceServer + ReplicaAgent) joined to a harness-owned journaled
coordinator, fed by a publisher thread that gates the training arm's
newest commits into the serving plane — so one schedule genuinely
exercises train, publish, and serve at once.
"""

from __future__ import annotations

import glob
import json
import os
import random
import re
import shutil
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..core.logging import get_logger

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Lethal step-axis faults: each retires a generation (or, for preempt,
#: gracefully shrinks one). The schedule spaces them so every generation
#: makes progress — a soak must terminate.
_LETHAL = ("preempt", "kill", "torn")

#: env: profile-independent run-id tag injected into every child process
#: so the post-run /proc sweep can find orphans (invariant 7).
RUN_ID_ENV = "SOAK_RUN_ID"

#: Profile knobs. "full" is the guardrail soak (benchmarks/soak.py);
#: "smoke" is the fixed-seed tier-1 variant (tests/test_soak.py) —
#: benign-heavy, lethal cap 1 (one preempt), sized to finish under a
#: minute on the 8-vCPU test mesh.
PROFILES: Dict[str, Dict[str, Any]] = {
    "full": dict(steps=110, events=26, step_sleep=0.15, replicas=3,
                 cooldown_s=15, min_np_env=2, time_budget_s=420.0,
                 min_fired=20, traffic_min=50, stall_s=30),
    "smoke": dict(steps=14, events=7, step_sleep=0.1, replicas=2,
                  cooldown_s=6, min_np_env=None, time_budget_s=75.0,
                  min_fired=5, traffic_min=10, stall_s=20),
}


# -- schedule generation ------------------------------------------------------


def make_schedule(seed: int, *, steps: int, events: int,
                  profile: str = "full") -> List[Dict[str, Any]]:
    """Draw a deterministic fault schedule from ``seed``.

    Pure: two calls with the same arguments return identical lists
    (pinned by tests/test_soak.py — the whole point of a seeded soak is
    that a red run is re-runnable). Events are dicts::

        {"kind": ..., "arm": "train"|"replica"|"traffic",
         "rank": int|None, "axis": "step"|"round"|"call"|"fetch"|"req",
         "at": int, "params": {...}}

    Termination constraints baked in: lethal step faults all target
    rank 1 (present in every np>=2 world, so they cannot be stranded by
    renumbering), are spaced so each generation commits fresh progress,
    and crash-class faults that feed blacklist strikes are capped below
    the ban threshold.
    """
    rng = random.Random(seed)
    out: List[Dict[str, Any]] = []
    used_steps: set = set()
    used_axis: Dict[str, set] = {"round": set(), "call": set()}

    def pick_axis(axis: str, lo: int, hi: int) -> int:
        # Distinct slots per axis: the fault hooks fire at most ONE
        # fault per counter tick, so two events sharing call=N would
        # shadow each other.
        for _ in range(64):
            s = rng.randrange(lo, max(lo + 1, hi))
            if s not in used_axis[axis]:
                break
        used_axis[axis].add(s)
        return s

    def ev(kind: str, arm: str, axis: str, at: int,
           rank: Optional[int] = None, **params: Any) -> None:
        out.append({"kind": kind, "arm": arm, "rank": rank,
                    "axis": axis, "at": int(at), "params": dict(params)})

    def pick_step(lo: int, hi: int) -> int:
        for _ in range(64):
            s = rng.randrange(lo, max(lo + 1, hi))
            if s not in used_steps:
                break
        used_steps.add(s)
        return s

    # Lethal plan first, on a spaced grid. full: two preemptions (the
    # tentpole path, once per cooldown cycle), one SIGKILL (the crash
    # path the preemptions must be distinguishable from), one torn
    # commit (exactly ONE: torn exits 1, which accrues a blacklist
    # strike — two on one host would ban it). smoke: one preemption.
    lethal = (["preempt", "kill", "preempt", "torn"]
              if profile == "full" else ["preempt"])
    lo, hi = 4, max(6, steps - 10)
    seg = max(8, (hi - lo) // max(1, len(lethal)))
    for i, kind in enumerate(lethal):
        at = min(hi - 1, lo + i * seg + rng.randrange(min(4, seg)))
        used_steps.update(range(at - 1, at + 2))
        ev(kind, "train", "step", at, rank=1)

    if profile == "full":
        # Serving-side chaos: one replica SIGKILLed mid-request, one
        # wedged (the failure liveness probes miss). Victim slots are
        # fixed (1 and 2) — the spec rides each victim's own env.
        ev("replica_kill", "replica", "req", rng.randrange(8, 26), slot=1)
        ev("replica_hang", "replica", "req", rng.randrange(20, 36), slot=2)
        # Opportunistic blob-mesh faults: they fire only when a rejoining
        # host actually delta-fetches (guaranteed plausible by the
        # preemptions above, not guaranteed to fire — min_fired absorbs).
        ev("resume_delay", "train", "fetch", 0,
           seconds=round(rng.uniform(0.5, 1.5), 2))
        ev("resume_corrupt", "train", "fetch", 1)

    # Offered-load spike(s): applied by the harness traffic thread.
    n_spikes = 2 if profile == "full" else 1
    for _ in range(n_spikes):
        ev("traffic_spike", "traffic", "req",
           rng.randrange(15, 46) if profile == "full"
           else rng.randrange(8, 21),
           factor=rng.choice([2, 3, 4]),
           seconds=round(rng.uniform(1.0, 2.0), 1))

    # Benign fill up to the requested event count, cycling the menu so
    # every kind appears before any repeats.
    benign = (["nan", "desync", "delay", "rpc_delay", "hang", "corrupt",
               "rpc_drop", "rpc_refuse", "rpc_garble", "rpc_badsig"]
              if profile == "full"
              else ["nan", "desync", "delay", "rpc_delay", "hang"])
    i = 0
    while len(out) < events:
        kind = benign[i % len(benign)]
        i += 1
        if kind in ("nan", "desync"):
            ev(kind, "train", "step", pick_step(2, steps - 2))
        elif kind == "hang":
            ev(kind, "train", "step", pick_step(2, steps - 2),
               seconds=round(rng.uniform(0.5, 1.5), 2))
        elif kind == "corrupt":
            # path= is a placeholder substituted at render time.
            ev(kind, "train", "step", pick_step(2, steps - 2),
               path="{state_dir}")
        elif kind == "delay":
            ev(kind, "train", "round", pick_axis("round", 2, 26),
               seconds=round(rng.uniform(0.2, 0.8), 2))
        else:   # rpc_*
            params = {}
            if kind == "rpc_delay":
                params["seconds"] = round(rng.uniform(0.3, 1.0), 2)
            # Low call indexes: a worker's coordinator client issues only
            # a dozen-odd calls per process lifetime (register + notify +
            # polls), so higher slots would never be reached.
            ev(kind, "train", "call", pick_axis("call", 3, 16), **params)
    return out


def schedule_to_specs(schedule: List[Dict[str, Any]], *, state_dir: str
                      ) -> Tuple[str, Dict[int, str], List[Dict[str, Any]]]:
    """Render a schedule into the ``HOROVOD_FAULT_SPEC`` grammar.

    Returns ``(train_spec, replica_specs, traffic_events)``: the train
    spec rides the hvdrun ``--fault-spec`` flag (all workers share it +
    one marker dir, so each event fires once per world), replica specs
    are keyed by victim slot (each victim subprocess carries only its
    own), and traffic events are applied by the harness traffic thread
    directly — offered load is a property of the driver, not of any
    replica (testing/faults.py docstring).
    """
    train_parts: List[str] = []
    replica_specs: Dict[int, List[str]] = {}
    traffic: List[Dict[str, Any]] = []
    for e in schedule:
        params = dict(e["params"])
        if e["arm"] == "traffic":
            traffic.append(e)
            continue
        kv = []
        if e["rank"] is not None:
            kv.append(f"rank={e['rank']}")
        kv.append(f"{e['axis']}={e['at']}")
        for k, v in sorted(params.items()):
            if k == "slot":
                continue
            if k == "path":
                v = str(v).format(state_dir=state_dir)
            kv.append(f"{k}={v}")
        part = f"{e['kind']}:{','.join(kv)}"
        if e["arm"] == "replica":
            replica_specs.setdefault(int(params["slot"]), []).append(part)
        else:
            train_parts.append(part)
    return (";".join(train_parts),
            {slot: ";".join(parts) for slot, parts in replica_specs.items()},
            traffic)


# -- child process templates --------------------------------------------------

#: The training worker: an elastic ObjectState loop with per-host commit
#: dirs (blob-mesh resume seam), every fault seam exercised per step
#: (on_step arms/fires step faults; maybe_poison/maybe_desync run the
#: nan/desync seams; allgather drives engine rounds for delay faults;
#: commits drive the torn seam), and a shared executed-step ledger
#: ("<step> <np>" appended by rank 0 just before the commit seam) the
#: coverage/monotonicity invariants read back.
SOAK_WORKER = """
import json
import os
import time
from horovod_tpu.platform import honor_jax_platforms_env
honor_jax_platforms_env()
import numpy as np
import horovod_tpu as hvd
from horovod_tpu import elastic
from horovod_tpu.optimizer import allgather_object
from horovod_tpu.testing import faults

hvd.init()
N = int(os.environ["SOAK_STEPS"])
SLEEP = float(os.environ["SOAK_STEP_SLEEP"])
TRACE = os.environ["SOAK_TRACE_FILE"]
_dir = os.path.join(os.environ["SOAK_STATE_DIR"],
                    os.environ.get("HOROVOD_HOSTNAME", "local"))
state = elastic.ObjectState(commit_dir=_dir, step=0, w=np.float32(0.0))

@elastic.run
def train(state):
    while state.step < N:
        step = state.step
        allgather_object(float(step))
        faults.on_step(step, rank=hvd.rank())
        grads = faults.maybe_poison({"g": np.ones(4, np.float32)})
        params = faults.maybe_desync({"w": np.asarray(state.w)})
        time.sleep(SLEEP)
        state.w = np.float32(
            float(np.asarray(params["w"]).reshape(-1)[0]) + 1.0)
        state.step = step + 1
        # Ledger BEFORE commit: commit() is also the graceful-reset seam
        # (check_host_updates raises AFTER persisting), so a post-commit
        # write would lose the reset step forever. Pre-commit writes can
        # only DUPLICATE (crash before durability -> replay re-logs),
        # which the monotonicity invariant tolerates.
        if hvd.rank() == 0:
            with open(TRACE, "a") as f:
                f.write("%d %d\\n" % (step, hvd.size()))
        state.commit()
    return state.step

train(state)
print(json.dumps({"final_step": state.step, "size": hvd.size(),
                  "rank": hvd.rank()}), flush=True)
"""

#: A serving replica: InferenceServer + ReplicaAgent against the
#: harness coordinator, adopting published generations from the
#: training arm's commit store (tests/test_fleet_chaos.py is the
#: single-fault version of this worker).
SOAK_REPLICA = """
import os
import time
from horovod_tpu.platform import honor_jax_platforms_env
honor_jax_platforms_env()
import numpy as np
from horovod_tpu.checkpoint.store import BlobStore
from horovod_tpu.elastic.service import CoordinatorClient
from horovod_tpu.serving import InferenceServer, ModelRegistry
from horovod_tpu.serving.fleet import ReplicaAgent

key = bytes.fromhex(os.environ["KEY_HEX"])
store = BlobStore(os.path.join(os.environ["SOAK_SERVE_DIR"], "cas"))
reg = ModelRegistry(store=store)
assert reg.poll_store(store), "no published generation to adopt"


def forward(payload, inputs, padded_n):
    w = float(np.asarray(payload["attrs"]["w"]).reshape(-1)[0])
    return [w + float(q["x"]) for q in inputs]


srv = InferenceServer(reg, forward, window_s=0.002,
                      request_timeout_s=30.0,
                      rank=int(os.environ["REPLICA_RANK"]))
client = CoordinatorClient(os.environ["COORD_ADDR"], key,
                           watch_publish=True)
agent = ReplicaAgent(srv, client, replica_id=os.environ["REPLICA_ID"],
                     rank=int(os.environ["REPLICA_RANK"]))
assert agent.registered
agent.start()
print("ready", flush=True)
while not agent._closing:
    time.sleep(0.2)
"""


# -- the soak run -------------------------------------------------------------


def _scan_orphans(run_id: str, retries: int = 8) -> List[int]:
    """Sweep /proc for live processes still tagged with our run id.
    Retries briefly: children observed mid-exit are not orphans."""
    needle = f"{RUN_ID_ENV}={run_id}".encode()
    me = os.getpid()
    found: List[int] = []
    for _ in range(retries):
        found = []
        for path in glob.glob("/proc/[0-9]*/environ"):
            try:
                with open(path, "rb") as fh:
                    data = fh.read()
            except OSError:
                continue
            if needle in data:
                pid = int(path.split("/")[2])
                if pid != me:
                    found.append(pid)
        if not found:
            return []
        time.sleep(0.5)
    return found


def _count_fired(marker_root: str) -> Dict[str, int]:
    """Fired events by kind, from the one-shot marker files every armed
    fault writes BEFORE acting (testing/faults.py) — the ground truth of
    "events survived", independent of log parsing."""
    by_kind: Dict[str, int] = {}
    for path in glob.glob(os.path.join(marker_root, "**", "hvd_fault.*"),
                          recursive=True):
        parts = os.path.basename(path).split(".")
        if len(parts) >= 3:
            by_kind[parts[2]] = by_kind.get(parts[2], 0) + 1
    return by_kind


def run_soak(seed: int, workdir: str, *, profile: str = "full",
             steps: Optional[int] = None, events: Optional[int] = None,
             time_budget_s: Optional[float] = None) -> Dict[str, Any]:
    """Run one seeded soak; returns the result record (``ok`` plus the
    per-invariant verdicts — never raises for an invariant failure, so
    the caller always gets the full picture)."""
    cfg = dict(PROFILES[profile])
    if steps is not None:
        cfg["steps"] = steps
    if events is not None:
        cfg["events"] = events
    if time_budget_s is not None:
        cfg["time_budget_s"] = time_budget_s
    steps = int(cfg["steps"])
    log = get_logger()
    t0 = time.monotonic()

    schedule = make_schedule(seed, steps=steps, events=int(cfg["events"]),
                             profile=profile)
    state_dir = os.path.join(workdir, "state")
    coord_dir = os.path.join(workdir, "coord")
    flight_dir = os.path.join(workdir, "flight")
    marker_root = os.path.join(workdir, "markers")
    serve_dir = os.path.join(workdir, "serve")
    for d in (state_dir, coord_dir, flight_dir, serve_dir,
              os.path.join(marker_root, "train")):
        os.makedirs(d, exist_ok=True)
    train_spec, replica_specs, traffic_events = schedule_to_specs(
        schedule, state_dir=state_dir)
    trace_path = os.path.join(workdir, "step_trace")
    run_id = f"hvdsoak-{seed}-{os.getpid()}"

    problems: List[str] = []
    invariants: Dict[str, bool] = {}

    def inv(name: str, cond: bool, detail: str = "") -> None:
        invariants[name] = bool(cond)
        if not cond:
            problems.append(f"{name}: {detail}" if detail else name)
            log.warning("soak invariant FAILED — %s (%s)", name, detail)

    # ---- serving plane: harness-owned journaled coordinator -------------
    from ..elastic import constants as C
    from ..elastic import journal as journal_mod
    from ..elastic.service import CoordinatorClient, CoordinatorService
    from ..elastic.state import ObjectState
    from ..checkpoint.store import newest_manifest_seq
    from ..runner import secret as _secret
    from ..serving import Publisher
    from ..serving.fleet import (FleetClient, FleetOverloadedError,
                                 FleetRequestError)

    key = _secret.make_secret_key()
    serve_journal = os.path.join(serve_dir, "wal.jsonl")
    svc = CoordinatorService(key, bind_host="127.0.0.1",
                             journal_path=serve_journal)
    admin = CoordinatorClient(f"127.0.0.1:{svc.port}", key)

    # ---- training arm: a real hvdrun over three loopback hosts ----------
    disco = os.path.join(workdir, "discover.sh")
    with open(disco, "w") as fh:
        fh.write("#!/bin/sh\necho localhost:1\necho 127.0.0.2:1\n"
                 "echo 127.0.0.3:1\n")
    os.chmod(disco, 0o755)
    worker_py = os.path.join(workdir, "soak_worker.py")
    with open(worker_py, "w") as fh:
        fh.write(SOAK_WORKER)

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("HOROVOD_FAULT_SPEC", None)
    env.update({
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        RUN_ID_ENV: run_id,
        "SOAK_STEPS": str(steps),
        "SOAK_STEP_SLEEP": str(cfg["step_sleep"]),
        "SOAK_TRACE_FILE": trace_path,
        "SOAK_STATE_DIR": state_dir,
        "HOROVOD_FAULT_MARKER_DIR": os.path.join(marker_root, "train"),
        "HOROVOD_FLIGHT_DIR": flight_dir,
        C.COORD_DIR_ENV: coord_dir,
        C.PREEMPT_COOLDOWN_ENV: str(cfg["cooldown_s"]),
        "HOROVOD_PEER_FAILURE_GRACE_SECONDS": "2",
        C.MIN_NP_WAIT_ENV: "90",
        "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS": str(cfg["stall_s"]),
        "HOROVOD_LOG_LEVEL": "INFO",
    })
    if cfg["min_np_env"]:
        env[C.MIN_NP_ENV] = str(cfg["min_np_env"])
    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
           "-np", "3", "--min-np", "1", "--max-np", "3",
           "--host-discovery-script", disco,
           "--fault-spec", train_spec,
           sys.executable, worker_py]
    log.info("soak: launching training arm (seed=%d profile=%s %d events): "
             "%s", seed, profile, len(schedule), train_spec)
    train_proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE, text=True, env=env)

    stop = threading.Event()
    publishes = [0]
    traffic_stats = {"attempted": 0, "served": 0, "shed": 0, "failed": 0}
    spikes_applied = [0]
    replica_procs: List[subprocess.Popen] = []
    lh_dir = os.path.join(state_dir, "localhost")

    def _publish_loop() -> None:
        """Gate the training arm's newest commit into the serving plane
        whenever it advances (benchmarks/fleet.py publish cadence, but
        event-driven off the on-disk manifest seq — the harness has no
        in-process view of the workers' commit counters)."""
        pub = None
        last = -1
        while not stop.is_set():
            try:
                seq = newest_manifest_seq(lh_dir)
                if seq > last:
                    if pub is None:
                        pub = Publisher(
                            lh_dir, every=1,
                            counters=lambda: {"steps_skipped": 0,
                                              "rollbacks": 0})
                    rec = pub.maybe_publish(seq)
                    if rec is not None and admin.announce_publish(rec):
                        publishes[0] += 1
                        last = seq
            except Exception as err:    # noqa: BLE001 — chaos-tolerant:
                # a mid-write or fault-truncated manifest fails the
                # publish gate this tick and is retried on the next.
                log.info("soak publisher: skipped a tick (%s)", err)
            stop.wait(0.4)

    def _traffic_loop() -> None:
        """Serial request driver with schedule-applied load spikes; the
        zero-accepted-loss invariant reads these counters."""
        # timeout_s bounds what one wedged replica (replica_hang) costs
        # per round-robin hit before failover — it stays in the routing
        # set until the heartbeat grace deadline health-gates it, so a
        # long timeout here would throttle the whole driver.
        fc = FleetClient(coord=CoordinatorClient(
            f"127.0.0.1:{svc.port}", key), timeout_s=2.5, refresh_s=0.2,
            max_tries=12)
        spikes = sorted(traffic_events, key=lambda e: e["at"])
        spike_until = 0.0
        base_pause = 0.05
        while not stop.is_set():
            n = traffic_stats["attempted"]
            while spikes and n >= spikes[0]["at"]:
                e = spikes.pop(0)
                spike_until = time.monotonic() + float(
                    e["params"]["seconds"])
                spikes_applied[0] += 1
                log.warning("soak: traffic_spike at offered request %d "
                            "(factor=%s seconds=%s)", n,
                            e["params"]["factor"], e["params"]["seconds"])
            traffic_stats["attempted"] = n + 1
            try:
                out = fc.predict({"x": float(n)})
                if out.get("ok"):
                    traffic_stats["served"] += 1
                else:
                    traffic_stats["failed"] += 1
            except FleetOverloadedError:
                traffic_stats["shed"] += 1
            except FleetRequestError:
                traffic_stats["failed"] += 1
            if time.monotonic() >= spike_until:
                stop.wait(base_pause)

    pub_thread = threading.Thread(target=_publish_loop, daemon=True)
    pub_thread.start()

    # Replicas need a published generation to adopt; wait for the
    # training arm's first commit to clear the publish gate.
    deadline = time.monotonic() + 120
    while publishes[0] == 0 and time.monotonic() < deadline \
            and train_proc.poll() is None:
        time.sleep(0.2)
    serving_up = publishes[0] > 0
    traffic_thread: Optional[threading.Thread] = None
    if serving_up:
        replica_py = os.path.join(workdir, "soak_replica.py")
        with open(replica_py, "w") as fh:
            fh.write(SOAK_REPLICA)
        for i in range(int(cfg["replicas"])):
            renv = dict(env)
            renv.pop("HOROVOD_FAULT_SPEC", None)
            mdir = os.path.join(marker_root, f"replica{i}")
            os.makedirs(mdir, exist_ok=True)
            renv.update({
                "KEY_HEX": key.hex(),
                "COORD_ADDR": f"127.0.0.1:{svc.port}",
                "SOAK_SERVE_DIR": lh_dir,
                "REPLICA_ID": f"soak-{i}",
                "REPLICA_RANK": str(901 + i),
                "HOROVOD_FAULT_MARKER_DIR": mdir,
                C.REPLICA_GRACE_ENV: "5",
            })
            if i in replica_specs:
                renv["HOROVOD_FAULT_SPEC"] = replica_specs[i]
            replica_procs.append(subprocess.Popen(
                [sys.executable, replica_py], stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True, env=renv))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            view = admin.get_replicas()
            if view and len(view.get("replicas", [])) == len(replica_procs):
                break
            time.sleep(0.2)
        traffic_thread = threading.Thread(target=_traffic_loop, daemon=True)
        traffic_thread.start()

    # ---- ride out the chaos --------------------------------------------
    budget = float(cfg["time_budget_s"])
    timed_out = False
    try:
        t_out, t_err = train_proc.communicate(
            timeout=max(10.0, budget - (time.monotonic() - t0)))
    except subprocess.TimeoutExpired:
        timed_out = True
        train_proc.kill()
        t_out, t_err = train_proc.communicate(timeout=30)
    combined = t_out + t_err
    # Persist the training arm's log: a red invariant is diagnosed from
    # the workdir (callers that keep it), not from a vanished pipe.
    with open(os.path.join(workdir, "train.log"), "w") as fh:
        fh.write(combined)

    stop.set()
    if traffic_thread is not None:
        traffic_thread.join(timeout=30)
    pub_thread.join(timeout=10)
    # Serving journal parity is checked against the LIVE registry after
    # the publisher quiesces but before replica teardown (both sides
    # must have seen the same register/kill/drain mutations).
    serve_parity, serve_detail = True, ""
    if serving_up:
        jstate = journal_mod.replay(serve_journal)
        view = admin.get_replicas() or {}
        live_ids = sorted(r.get("replica_id", r.get("id"))
                          for r in view.get("replicas", []))
        jrep = sorted((jstate or {}).get("replicas", {}).keys())
        serve_parity = (jstate is not None and jrep == live_ids
                        and jstate.get("publish_seq") == publishes[0])
        serve_detail = (f"journal replicas {jrep} vs live {live_ids}; "
                        f"journal publish_seq "
                        f"{(jstate or {}).get('publish_seq')} vs "
                        f"announced {publishes[0]}")
    for p in replica_procs:
        if p.poll() is None:
            p.terminate()
    for p in replica_procs:
        try:
            p.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate(timeout=10)

    elapsed = time.monotonic() - t0
    fired_by_kind = _count_fired(marker_root)
    fired = sum(fired_by_kind.values()) + spikes_applied[0]

    # ---- invariants -----------------------------------------------------
    inv("train_completed", not timed_out and train_proc.returncode == 0,
        f"rc={train_proc.returncode} timed_out={timed_out} "
        f"tail={combined[-1500:]!r}")
    worker_lines = [json.loads(ln) for ln in t_out.splitlines()
                    if ln.startswith("{")]
    inv("final_step_reached",
        bool(worker_lines) and all(w["final_step"] == steps
                                   for w in worker_lines),
        f"worker exits: {worker_lines}")

    ledger: List[Tuple[int, int]] = []
    try:
        with open(trace_path) as fh:
            ledger = [tuple(map(int, ln.split())) for ln in fh
                      if ln.strip()]
    except OSError:
        pass
    led_steps = [s for s, _ in ledger]
    inv("step_coverage", sorted(set(led_steps)) == list(range(steps)),
        f"covered {len(set(led_steps))}/{steps} steps")
    # Monotone across generations modulo committed rollback: a crash may
    # legitimately replay the few steps between the last durable commit
    # and the death point; anything deeper means lost progress.
    deep = [(a, b) for a, b in zip(led_steps, led_steps[1:])
            if b <= a and b < a - 6]
    inv("step_monotone", not deep, f"rollbacks deeper than 6 steps: {deep}")

    if serving_up:
        inv("zero_request_loss",
            traffic_stats["failed"] == 0
            and traffic_stats["served"] >= int(cfg["traffic_min"]),
            f"traffic={traffic_stats} (min served {cfg['traffic_min']})")
        inv("journal_parity_serve", serve_parity, serve_detail)
    else:
        inv("serving_started", False, "no generation was ever published")

    # Training-driver journal replay must land on the final world: the
    # last launched generation exactly — or, benignly, a version AHEAD
    # of it (a cooled-down host rejoining in the race window between the
    # last step and driver exit journals a trailing update_world that
    # never launches). Replay landing BEHIND the last launch means lost
    # records.
    jtrain = journal_mod.replay(os.path.join(coord_dir,
                                             "coordinator.journal"))
    gens = [(int(m.group(1)), int(m.group(2))) for m in re.finditer(
        r"launching generation v(\d+) over .* \(np=(\d+)\)", combined)]
    inv("journal_parity_train",
        jtrain is not None and gens
        and ((jtrain["version"], jtrain["np"]) == gens[-1]
             or jtrain["version"] > gens[-1][0]),
        f"replayed (v={jtrain and jtrain['version']}, "
        f"np={jtrain and jtrain['np']}) vs last launch {gens[-1:]}")

    # Post-mortem completeness: crash-class faults must leave flight
    # evidence; graceful preemptions must leave their ring dump AND no
    # failure record (the whole point of the distinct preempt plane).
    crash_fired = (fired_by_kind.get("kill", 0)
                   + fired_by_kind.get("torn", 0))
    failure_seq = (jtrain or {}).get("failure_seq", -1)
    incidents = glob.glob(os.path.join(flight_dir, "incident_*.json"))
    if crash_fired:
        inv("flight_on_abnormal",
            failure_seq >= crash_fired and len(incidents) >= 1
            and bool(glob.glob(os.path.join(flight_dir, "flight_*.jsonl"))),
            f"failure_seq={failure_seq} incidents={len(incidents)} "
            f"for {crash_fired} crash fault(s)")
    else:
        inv("flight_on_abnormal",
            failure_seq == 0 and not incidents,
            f"failure record without a crash fault: seq={failure_seq} "
            f"incidents={incidents}")
    if fired_by_kind.get("preempt"):
        inv("preempt_graceful",
            "preempt flight ring dumped to" in combined
            and "no blacklist strike" in combined,
            "preempt fired without the graceful-handoff trace")

    # The last commit is resumable by a fresh process: the soak's
    # durable outcome. max over hosts — a host cooling down at exit
    # legitimately holds an older (but loadable) commit.
    best = -1
    for host_dir in sorted(glob.glob(os.path.join(state_dir, "*"))):
        try:
            st = ObjectState(commit_dir=host_dir, step=0)
            if st.load_latest():
                best = max(best, int(st.step))
        except Exception as err:    # noqa: BLE001 — a corrupt-fault
            log.info("soak: %s did not restore (%s)", host_dir, err)
    inv("commit_resumable", best == steps,
        f"freshest restorable commit at step {best}, want {steps}")

    orphans = _scan_orphans(run_id)
    inv("no_orphans", not orphans, f"pids still alive: {orphans}")

    inv("events_fired", fired >= int(cfg["min_fired"]),
        f"{fired} fired < {cfg['min_fired']} required "
        f"(by kind: {fired_by_kind})")
    inv("bounded", elapsed <= budget,
        f"{elapsed:.0f}s > {budget:.0f}s budget")

    svc.close()
    rec = {
        "bench": "soak", "seed": seed, "profile": profile, "steps": steps,
        "events_planned": len(schedule), "events_fired": fired,
        "fired_by_kind": fired_by_kind, "spikes_applied": spikes_applied[0],
        "generations": gens, "failure_seq": failure_seq,
        "publishes": publishes[0], "requests": dict(traffic_stats),
        "elapsed_s": round(elapsed, 1),
        "invariants": invariants, "problems": problems,
        "ok": all(invariants.values()),
    }
    log.info("soak: %s", json.dumps(rec, sort_keys=True))
    return rec
