"""``@hvd.elastic.run`` — the fault-tolerant training-loop wrapper.

Reference parity: ``horovod/common/elastic.py run_fn`` (SURVEY.md §3.4):

    FAILURE: collective error → HorovodInternalError → shutdown → re-init
             → state.restore() (rollback) → retry
    HOSTS UPDATED: driver notification → HostsUpdatedInterrupt at commit
             → shutdown → re-init → state.sync() → retry

TPU delta (the honest part): a JAX process cannot resize its device world
in-process — the XLA backend pins topology at ``jax.distributed.initialize``
— so "shutdown → re-init" comes in two modes (``HOROVOD_ELASTIC_MODE``):

- ``restart`` (default, TPU-true): the wrapper persists state (commits
  already did), then **exits the process** with ``RESTART_EXIT_CODE``. The
  elastic driver relaunches the generation with the new membership and the
  wrapper restores the newest on-disk commit before re-entering the train
  function. Same observable loop as the reference, with the process
  boundary where TPU reality puts it (slice membership change ⇒ recompile
  anyway, SURVEY.md §7 "hard parts").
- ``inprocess``: re-init inside the process (hvd.shutdown/init), valid when
  the device topology is unchanged — single-host tests and same-size
  worker replacement. This is the closest analog of the reference's gloo
  re-rendezvous path.
"""

from __future__ import annotations

import functools
import os
import sys
from typing import Callable

from ..core import lifecycle as _lifecycle
from ..core import telemetry as _telemetry
from ..core.exceptions import (HorovodInternalError, HostsUpdatedInterrupt,
                               PreemptionInterrupt)
from ..core.logging import get_logger
from . import constants as C
from .state import State


def _mode() -> str:
    """Explicit HOROVOD_ELASTIC_MODE wins; otherwise 'restart' only when a
    driver is actually present to relaunch us (it exports the coordinator
    address). A standalone run exiting with RESTART_EXIT_CODE would just
    die — fall back to in-process retry there."""
    mode = os.environ.get(C.MODE_ENV)
    if mode:
        return mode
    return "restart" if os.environ.get(C.COORD_ADDR_ENV) else "inprocess"


def _reset_limit() -> int:
    try:
        return int(os.environ.get(C.RESET_LIMIT_ENV, "0"))
    except ValueError:
        return 0


def _drain_commits(state: State, timeout: float = 30.0) -> None:
    """Make the newest async commit durable before a restart exit: the
    relaunched generation resumes from disk, so an in-flight background
    write abandoned here would silently roll the world back one commit."""
    flush = getattr(state, "flush_commits", None)
    if flush is None:
        return
    try:
        if not flush(timeout=timeout):
            get_logger().warning(
                "in-flight commit did not drain cleanly before restart — "
                "resuming from the previous published manifest")
    except Exception as err:    # noqa: BLE001 — exit path must not wedge
        get_logger().warning("commit drain failed before restart: %s", err)


def _reinitialize() -> None:
    """In-process re-init (topology-unchanged path)."""
    import horovod_tpu as hvd
    hvd.shutdown()
    hvd.init()
    # The step monitor's peer-failure flag is scoped to the OLD world:
    # left armed, its long-expired grace deadline would instantly abandon
    # every step of the recovered run (core/watchdog.py).
    from ..core.watchdog import monitor
    monitor().reset_for_recovery()


def _graceful_handoff(state: State, signum: int) -> None:
    """The preemption exit sequence (core/lifecycle.py caught the reclaim
    signal; ``check_host_updates`` raised at the seam AFTER ``save()`` ran
    — the out-of-cadence commit is already in flight): drain the commit
    writer so it is durable, dump the flight ring (graceful teardown must
    not lose the victim's trace — incident assembly reads these), post
    the journaled coordinator ``preempt`` notice so peers reset
    gracefully, and exit with the code the driver maps to host-cooldown."""
    _telemetry.inc("hvd_preempt_handoffs_total")
    _telemetry.record_event("preempt", signum=int(signum))
    _drain_commits(state)
    dump = _telemetry.dump_flight("preempt")
    if dump:
        # Logged (not just written): later generations reuse the rank's
        # flight file name, so the victim's dump path in the job log is
        # the durable pointer post-mortems grep for.
        get_logger().info("preempt flight ring dumped to %s", dump)
    from .state import notification_manager
    client = getattr(notification_manager, "_client", None)
    host = os.environ.get("HOROVOD_HOSTNAME")
    if client is not None and host:
        try:
            client.notify_preempt(host)
        except Exception as err:    # noqa: BLE001 — best-effort; the exit
            get_logger().warning(    # code alone still skips the blacklist
                "preempt notice to the coordinator failed: %s", err)
    get_logger().warning(
        "preemption handoff complete (signal %d) — exiting with "
        "PREEMPT_EXIT_CODE for host-cooldown relaunch", signum)
    sys.stdout.flush()
    sys.stderr.flush()
    # HARD exit (no atexit): sys.exit would run the distributed runtime's
    # shutdown barrier, which blocks until every peer also shuts down —
    # but the peers are NOT exiting with us, they are parked in the next
    # collective waiting for the graceful /world push. A victim wedged in
    # that barrier never delivers its exit code, so the driver never
    # starts the cooldown, and the runtime eventually F-aborts the whole
    # generation as if the departure were a crash.
    os._exit(C.PREEMPT_EXIT_CODE)


def run(func: Callable) -> Callable:
    """Decorate ``func(state, *args, **kwargs)`` with the elastic loop."""

    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        import horovod_tpu as hvd
        if not hvd.is_initialized():
            hvd.init()
        from .state import notification_manager
        notification_manager.init_from_env()
        notification_manager.register()
        # Preemption plane: catch SIGTERM/SIGUSR1 and hand off gracefully
        # at the next step seam. Only meaningful under a driver that maps
        # PREEMPT_EXIT_CODE to a cooldown relaunch (restart mode); install
        # is a no-op off the main thread (thread-sim ranks) and under
        # HOROVOD_PREEMPT_SIGNALS="".
        if _mode() == "restart":
            _lifecycle.install()
        # Process-restart resume: adopt the newest persisted commit (no-op
        # when there is none or no commit dir is configured).
        if hasattr(state, "load_latest") and state.load_latest():
            latency = getattr(state, "_last_resume_latency_s", None)
            get_logger().info(
                "restored persisted elastic commit%s",
                "" if latency is None else " (resume latency %.3fs)" % latency)
        # A fresh generation starts from synced state (reference: run_fn
        # syncs before the first call so late joiners match rank 0).
        state.sync()
        resets = 0
        limit = _reset_limit()
        while True:
            try:
                return func(state, *args, **kwargs)
            except HorovodInternalError as err:
                from ..core.watchdog import monitor
                # The survivor's rescue record: the data plane failed and
                # this rank is entering recovery. Ring-dump NOW — the
                # restart path below hard-exits (os._exit skips atexit).
                _telemetry.inc("hvd_elastic_rescues_total")
                _telemetry.record_event("rescue", reason=str(err)[:200])
                _telemetry.dump_flight("horovod_internal_error")
                if monitor().heartbeat().get("control_plane_lost"):
                    # Not a data-plane failure: the coordinator stayed
                    # unreachable past HOROVOD_COORDINATOR_LOST_TIMEOUT_
                    # SECONDS. Exit/reset anyway — if the driver crash-
                    # restarted its service the relaunch reconnects us; if
                    # the driver is truly gone, exiting beats polling it
                    # forever.
                    get_logger().error("control plane lost: escalating via "
                                       "the elastic reset path")
                else:
                    get_logger().warning("collective failure: rolling back "
                                         "to last commit")
                if _mode() == "restart":
                    # State was persisted at the last commit; ask the driver
                    # for a relaunch with whatever membership is now alive.
                    # HARD exit (no atexit): this error means the data-plane
                    # transport is lost, and the graceful path runs the
                    # distributed runtime's shutdown barrier — which blocks
                    # forever against the hung/dead peer that caused this
                    # very error (the hung-peer chaos test wedged exactly
                    # there). The driver only needs the exit code. The
                    # HostsUpdatedInterrupt path below keeps sys.exit: there
                    # every peer is alive and exiting together.
                    _drain_commits(state)
                    sys.stdout.flush()
                    sys.stderr.flush()
                    os._exit(C.RESTART_EXIT_CODE)
                state.restore()
                _reinitialize()
                # Repair cross-process divergence: peers may have committed
                # at different steps before the failure, so rolled-back
                # states can differ — re-sync from rank 0 (the reference's
                # run_fn also syncs on the retry path).
                state.sync()
            except PreemptionInterrupt as e:
                # MUST precede HostsUpdatedInterrupt (its parent class).
                # The seam commit already saved; hand off and exit with
                # the cooldown code — never the blacklist-feeding one.
                get_logger().warning(
                    "preemption observed at the step seam (signal %d): "
                    "graceful handoff", e.signum)
                _graceful_handoff(state, e.signum)
            except HostsUpdatedInterrupt as e:
                get_logger().info("hosts updated: resetting")
                _telemetry.inc("hvd_generation_changes_total")
                _telemetry.record_event("generation_change",
                                        mode=_mode())
                if _mode() == "restart":
                    _drain_commits(state)
                    sys.exit(C.RESTART_EXIT_CODE)
                _reinitialize()
                if not e.skip_sync:
                    state.sync()
            resets += 1
            if limit and resets >= limit:
                get_logger().error("reset limit %d reached; aborting", limit)
                sys.exit(C.ABORT_EXIT_CODE)
            state.on_reset()

    return wrapper
