"""Examples smoke tests: every shipped example must run end-to-end.

Reference analog: the reference CI executes its ``examples/`` scripts under
``horovodrun`` in the docker test matrix (SURVEY.md §4). Here each example
runs as a subprocess on the 8-virtual-device CPU mesh (the documented smoke
invocation from each script's docstring, shapes minimised for CI).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXAMPLES = [
    ("train_resnet.py", ["--model", "tiny", "--image-size", "32",
                         "--batch-size", "16", "--steps", "2",
                         "--warmup", "1"], "images/sec"),
    ("train_llama.py", ["--model", "tiny", "--dp", "2", "--sp", "2",
                        "--tp", "2", "--batch-size", "4", "--seq-len", "32",
                        "--steps", "2", "--warmup", "1"], "tokens/sec"),
    ("train_llama.py", ["--model", "tiny", "--batch-size", "4",
                        "--seq-len", "32", "--steps", "2", "--warmup", "1",
                        "--remat-policy", "dots_attn"], "tokens/sec"),
    ("train_mixtral.py", ["--dp", "2", "--ep", "4", "--batch-size", "4",
                          "--seq-len", "32", "--steps", "2",
                          "--warmup", "1"], "tokens/sec"),
    ("train_bert.py", ["--model", "tiny", "--batch-size", "16",
                       "--seq-len", "32", "--steps", "2",
                       "--warmup", "1"], "tokens/sec"),
    ("train_dlrm.py", ["--model", "tiny", "--dp", "2", "--ep", "4",
                       "--batch-size", "64", "--steps", "2",
                       "--warmup", "1"], "examples/sec"),
    ("train_adasum.py", ["--batch-size", "8", "--seq-len", "32",
                         "--steps", "2", "--warmup", "1"], "tokens/sec"),
    ("torch_synthetic.py", ["--steps", "2", "--warmup", "1",
                            "--fp16-allreduce"], "images/sec"),
    ("tensorflow_keras_synthetic.py", ["--steps", "2"], "weight-norm"),
    ("train_pipeline.py", ["--steps", "3", "--microbatches", "4"],
     "schedule=1f1b"),
    ("train_pipeline.py", ["--steps", "3", "--microbatches", "4",
                           "--schedule", "gpipe"], "schedule=gpipe"),
]


@pytest.mark.parametrize("script,args,expect",
                         EXAMPLES, ids=[e[0] for e in EXAMPLES])
def test_example_runs(script, args, expect):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, (
        f"{script} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    assert expect in proc.stdout, proc.stdout[-2000:]
    assert "loss=" in proc.stdout
