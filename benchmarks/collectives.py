"""Collective bus-bandwidth microbenchmark.

BASELINE north star: ≥90% ICI bus-bandwidth utilization. Sweeps message
sizes through in-graph allreduce / allgather / alltoall / reducescatter
over the mesh rank axis and reports **bus bandwidth** with the standard
ring-algorithm formulas (NCCL-tests convention, so numbers compare
directly to the reference's GPU reports):

    allreduce:      busBW = 2(n-1)/n · bytes / t
    allgather:      busBW = (n-1)/n · total_bytes / t
    reducescatter:  busBW = (n-1)/n · in_bytes / t
    alltoall:       busBW = (n-1)/n · bytes / t

Each op is timed as a DEPENDENT chain inside ``lax.scan`` (output feeds the
next input) so XLA cannot hoist or overlap away the transfers; wall time
comes from the slope between two chain lengths (common.py).

Set ``HOROVOD_BENCH_ICI_PEAK_GBPS`` (per-chip bidirectional ICI, GB/s) to
also report utilization as ``vs_baseline``; hardware peaks differ per TPU
generation, so none is assumed.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from common import (emit, median_ratio, on_tpu, slope_time,
                    slope_time_paired, sync)


def sweep_fusion():
    """``--sweep-fusion``: interleaved HOROVOD_FUSION_THRESHOLD sweep.

    Times a grouped (fused) gradient-shaped allreduce — a pytree of mixed
    leaf sizes mimicking a model's grads — under 2–3 bucket sizes applied
    via ``fusion_threshold_override`` at trace time, INTERLEAVED through
    ``slope_time_paired`` (±10% tunnel-noise trap: never time arms in
    separate blocks). Prints a per-size ratio table against the uncapped
    single-buffer arm so bucket tuning is a reproducible artifact instead
    of folklore. In this chained microbench the collectives have no
    backward compute to hide behind — the table isolates the pure
    bucketing overhead (launch/rendezvous per bucket); overlap GAINS show
    up in the train-step A/B (profile_resnet.py on the CPU mesh,
    benchmarks/resnet.py on chip).
    """
    import horovod_tpu as hvd
    from horovod_tpu.collectives import ops
    from horovod_tpu.collectives.ops import fusion_threshold_override
    smap = jax.shard_map  # compat-shimmed (check_vma) only AFTER hvd import

    hvd.init()
    n = hvd.size()
    mesh = hvd.mesh()
    axis = hvd.RANK_AXIS
    if n == 1:
        emit("fusion_sweep", 0.0, "skipped (1 rank)")
        return
    # Gradient-shaped tree: a few big leaves + a tail of small ones
    # (the realistic shape: conv/matmul kernels + biases/norm scales).
    if on_tpu():
        big, small, n_small = 4 << 20, 16 << 10, 24   # ~17 MB/device
        thresholds = [("uncapped", 1 << 62), ("4mb", 4 << 20),
                      ("256kb", 256 << 10)]
    else:
        big, small, n_small = 256 << 10, 4 << 10, 12  # CPU mesh: ~1.1 MB
        thresholds = [("uncapped", 1 << 62), ("64kb", 64 << 10),
                      ("8kb", 8 << 10)]
    leaves = [jnp.ones((big // 4,), jnp.float32) for _ in range(4)] + \
             [jnp.ones((small // 4,), jnp.float32) for _ in range(n_small)]
    total_mb = sum(l.size * 4 for l in leaves) / (1 << 20)

    def make_run(thr):
        def chained(k):
            def fn(tree):
                def one(c, _):
                    return ops.grouped_allreduce(c, ops.Sum), ()
                c, _ = lax.scan(one, tree, None, length=k)
                return c
            # Leaves replicated (P() prefix-broadcasts over the tree):
            # grads are replicated per-device in the DP step too.
            return jax.jit(smap(
                fn, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False))
        with fusion_threshold_override(thr):
            fns = {k: chained(k) for k in (2, 8)}
            for f in fns.values():
                sync(f(leaves))  # compile under the override

        def run(k):
            sync(fns[k](leaves))
        return run

    runs = {name: make_run(thr) for name, thr in thresholds}
    times, rounds = slope_time_paired(runs, s_short=2, s_long=8,
                                      return_rounds=True)
    print(f"\nfusion sweep: {len(leaves)} leaves, {total_mb:.1f} MB/device, "
          f"{n} ranks (ratio >1 = faster than uncapped)")
    print(f"{'threshold':<10} {'ms/allreduce':>14} {'ratio_vs_uncapped':>19}")
    for name, _ in thresholds:
        ratio = median_ratio(rounds, "uncapped", name)
        print(f"{name:<10} {times[name]*1e3:>14.3f} {ratio:>19.3f}")
        emit(f"fusion_sweep_{name}", times[name] * 1e3, "ms/op",
             ratio if name != "uncapped" else None)


def main():
    import horovod_tpu as hvd
    from horovod_tpu.collectives import ops

    hvd.init()
    n = hvd.size()
    mesh = hvd.mesh()
    axis = hvd.RANK_AXIS
    peak = float(os.environ.get("HOROVOD_BENCH_ICI_PEAK_GBPS", "0")) or None
    if n == 1:
        # Bus-bandwidth formulas are 0 at n=1; nothing rides the wire.
        emit("collectives_busbw", 0.0,
             "GB/s (1 rank — run on a multi-chip mesh)")
        return

    sizes_mb = [1, 8, 64] if on_tpu() else [1]

    def time_chain(body, shard_elems, k_short=2, k_long=8):
        """Seconds per op for body: (shard,) -> (shard,) chained k times."""
        x = jnp.ones((n * shard_elems,), jnp.float32)

        def make(k):
            def chained(v):
                def one(c, _):
                    return body(c), ()
                c, _ = lax.scan(one, v, None, length=k)
                return c
            return jax.jit(shard_map(chained, mesh=mesh, in_specs=P(axis),
                                     out_specs=P(axis), check_vma=False))

        fns = {k: make(k) for k in (k_short, k_long)}

        def run(k):
            sync(fns[k](x))
        return slope_time(run, k_short, k_long)

    for mb in sizes_mb:
        elems = mb * (1 << 20) // 4          # per-shard payload elements
        bytes_ = elems * 4

        # allreduce: (elems,) -> (elems,), dependent by construction.
        t = time_chain(lambda v: ops.allreduce(v, ops.Sum), elems)
        bw = 2 * (n - 1) / n * bytes_ / t / 1e9
        emit(f"allreduce_busbw_{mb}mb", bw, f"GB/s ({n} ranks)",
             None if peak is None else bw / peak)

        # allgather: gather to (n*elems,), keep own chunk -> (elems,).
        def ag_body(v):
            g = ops.allgather(v)
            i = lax.axis_index(axis)
            return lax.dynamic_slice(g, (i * v.shape[0],), (v.shape[0],))
        t = time_chain(ag_body, elems)
        bw = (n - 1) / n * bytes_ * n / t / 1e9
        emit(f"allgather_busbw_{mb}mb", bw, f"GB/s ({n} ranks)",
             None if peak is None else bw / peak)

        # alltoall: (elems,) -> (elems,) when elems % n == 0.
        a2a_elems = (elems // n) * n
        t = time_chain(lambda v: ops.alltoall(v), a2a_elems)
        bw = (n - 1) / n * a2a_elems * 4 / t / 1e9
        emit(f"alltoall_busbw_{mb}mb", bw, f"GB/s ({n} ranks)",
             None if peak is None else bw / peak)

        # reducescatter: (elems,) -> (elems/n,), tiled back up to keep the
        # chain shape-stable (adds one cheap HBM pass vs the transfer).
        def rs_body(v):
            r = ops.reducescatter(v, ops.Sum)
            return jnp.tile(r, n)[:v.shape[0]]
        t = time_chain(rs_body, a2a_elems)
        bw = (n - 1) / n * a2a_elems * 4 / t / 1e9
        emit(f"reducescatter_busbw_{mb}mb", bw, f"GB/s ({n} ranks)",
             None if peak is None else bw / peak)


if __name__ == "__main__":
    if "--sweep-fusion" in sys.argv:
        sweep_fusion()
    else:
        main()
