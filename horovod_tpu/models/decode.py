"""Paged KV-cache decode path for the autoregressive models (Llama, Mixtral).

Reference analog: none — SURVEY.md §2 confirms upstream Horovod never served
inference; this is the TPU-native step past the reference (PARITY.md §7).
The design follows the production paged-attention layout
(jax.experimental.pallas.ops.tpu.paged_attention): a preallocated device
pool of fixed-size KV blocks, per-sequence block tables mapping logical
positions to physical blocks, and single-token queries attending against
the gathered pages.

Three jit-once programs per model config:

- **prefill** (one compile per prompt bucket): the full causal forward over
  one padded prompt, capturing every layer's post-RoPE K and raw V and
  bulk-writing them into the slot's blocks. Returns all-position logits so
  the last real position seeds generation (and so parity tests can compare
  against ``model.apply`` directly).
- **decode step** (ONE compile for the serving lifetime): a fixed-width
  slot batch ``[S]`` advances one token. Per layer: project q/k/v for the
  new token, write k/v at ``(table[pos//bs], pos % bs)`` (an S-row scatter —
  per-step writes are tiny; the CLAUDE.md scatter trap is about bulk data
  movement), then read the whole context back with ``jnp.take`` over the
  block tables — the attention READ side is pure gather, and the MoE
  dispatch reuses the sort-based gather-only plan from ``parallel/moe.py``.
  Inactive/stalled slots carry zero-padded block tables, so their writes
  target the reserved null block 0 — and are zero-masked via ``active`` so
  block 0 stays all-zero — while their logits are garbage the engine
  discards (active-mask semantics, no recompile on admit/retire).
- **verify step** (ONE compile per draft width — the engine fixes ONE):
  the decode step widened to a ``[S, K]`` window of candidate tokens per
  slot for speculative decoding. One forward scores all K candidate
  positions: the causal mask inside the window falls out of the same
  ``t <= pos+j`` admission the paged reads already use, K/V writes stay
  the decode step's masked scatter (so the null-block invariant holds for
  masked slots, and rejected candidates' writes are overwritten by the
  next window before any mask can admit them), and greedy
  longest-matching-prefix acceptance on the host makes the emitted stream
  bit-identical to single-token decode (``tests/test_decode_parity.py``).

The math is a pure-jnp mirror of the flax modules (same einsum
formulations, same f32 islands: RMSNorm, attention softmax, router,
lm-head accumulation), operating on the plain params pytree the export
seam (``train.step_builder.export_decode_params``) produces — no flax
``apply`` in the serve path, so remat/scan/sow machinery never enters the
decode program. Handles both checkpoint layouts: unrolled ``block_i`` keys
and scanned ``layers``-stacked ``[L, ...]`` leaves.

**Tensor-parallel variants** (:func:`make_prefill_tp` /
:func:`make_decode_step_tp`): the same programs shard_map-partitioned
over a ``tp`` mesh axis, megatron-style — attention heads and MLP/expert
hidden matrices column-parallel (wq/wk/wv/w1/w3 split on the output dim),
their mates row-parallel (wo/w2 split on the input dim), KV pools sharded
on the head dimension (``[L, n_blocks, bs, n_kv/tp, hd]``), block tables
and slot state replicated. Per layer exactly TWO ``lax.psum`` collectives
ride the wire — one after attention-out, one after MLP/expert-down, both
before the residual add — and nothing else: no permutes, no gathers of
KV across shards (each shard's gather-only page reads stay local; the
CLAUDE.md scatter trap stays honored per shard). Embedding, norms, the
router, and the lm head are replicated, so the greedy argmax is local
and bit-identical on every shard (``tests/test_wire_contracts.py`` pins
the collective count and operand bytes; ``tests/test_decode_parity.py``
pins tp>1 token streams against tp=1).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.moe import sorted_combine, sorted_dispatch, topk_router_sorted
from .llama import LlamaConfig, rope

NULL_BLOCK = 0  #: block 0 is reserved — inactive slots write/read here


def is_moe(cfg: LlamaConfig) -> bool:
    """Mixtral-family configs carry an expert bank (duck-typed so this
    module never imports mixtral.py)."""
    return getattr(cfg, "n_experts", 0) > 0


def init_kv_pools(cfg: LlamaConfig, n_blocks: int,
                  block_size: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Zeroed K and V pools, shape ``[L, n_blocks, block_size, n_kv, hd]``
    in the model compute dtype (block 0 is the null block)."""
    head_dim = cfg.dim // cfg.n_heads
    shape = (cfg.n_layers, n_blocks, block_size, cfg.n_kv_heads, head_dim)
    return jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype)


def layer_params(params, i: int):
    """Layer ``i``'s param subtree for either checkpoint layout: unrolled
    ``block_i`` keys, or the scanned ``layers`` node with [L, ...]-stacked
    leaves (``i`` is a Python int — the slice is static at trace time)."""
    if "layers" in params:
        return jax.tree.map(lambda leaf: leaf[i], params["layers"]["block"])
    return params[f"block_{i}"]


# -- pure-jnp mirrors of the flax modules ------------------------------------

def _rmsnorm(x, scale, eps, dtype):
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (norm * scale).astype(dtype)


def _dense(x, kernel, dtype):
    return jnp.einsum("...d,df->...f", x.astype(dtype), kernel.astype(dtype))


def _mlp(p, c, x):
    gate = _dense(x, p["w1"]["kernel"], c.dtype)
    up = _dense(x, p["w3"]["kernel"], c.dtype)
    return _dense(jax.nn.silu(gate) * up, p["w2"]["kernel"], c.dtype)


def _moe(p, c, tokens):
    """Gather-only routed expert bank on a flat ``[T, D]`` token batch —
    the same sort-based dispatch plan as models/mixtral.py MoEMLP (the
    one-hot scatter formulation profiled slower than the expert matmuls,
    r4)."""
    E = c.n_experts
    T = tokens.shape[0]
    logits = jnp.einsum("td,de->te", tokens.astype(jnp.float32),
                        p["router"]["kernel"].astype(jnp.float32))
    capacity = max(1, int(c.capacity_factor * c.top_k * T / E))
    r = topk_router_sorted(logits, E, capacity, c.top_k)
    dispatched = sorted_dispatch(tokens, r, E, capacity)
    h = jax.nn.silu(jnp.einsum("ecd,edm->ecm", dispatched,
                               p["w1"].astype(c.dtype)))
    h = h * jnp.einsum("ecd,edm->ecm", dispatched, p["w3"].astype(c.dtype))
    out = jnp.einsum("ecm,emd->ecd", h, p["w2"].astype(c.dtype))
    return sorted_combine(out, r, T).astype(c.dtype)


def _ffn(lp, c, x, moe: bool, axis: Optional[str] = None):
    """The block's second half-residual on ``[..., D]`` activations.

    Under tensor parallelism (``axis`` set) the MLP/expert hidden dim is
    sharded, so the down-projection yields a PARTIAL sum — it is
    all-reduced over ``axis`` before the residual add (the per-layer
    MLP-down collective of the wire contract)."""
    y = _rmsnorm(x, lp["mlp_norm"]["scale"], c.norm_eps, c.dtype)
    if moe:
        flat = y.reshape(-1, y.shape[-1])
        part = _moe(lp["moe"], c, flat).reshape(y.shape)
    else:
        part = _mlp(lp["mlp"], c, y)
    if axis is not None:
        part = jax.lax.psum(part, axis)
    return x + part


def _lm_head(params, c, x):
    if c.tie_embeddings:
        return jnp.einsum("...d,vd->...v", x.astype(c.dtype),
                          params["embedding"].astype(c.dtype),
                          preferred_element_type=jnp.float32)
    return jnp.einsum("...d,dv->...v", x.astype(c.dtype),
                      params["lm_head"].astype(c.dtype),
                      preferred_element_type=jnp.float32)


def _attn_prefill(p, c, x, positions, n_heads=None, n_kv=None):
    """Causal attention over the whole (padded) prompt — the training
    formulation verbatim (materialized softmax path of llama.Attention),
    additionally returning the pre-repeat post-RoPE K and raw V for the
    cache. ``n_heads``/``n_kv`` default to the config's counts; the TP
    path passes the per-shard locals (the q/k/v/o kernels it sees are the
    column/row slices, so every shape below stays consistent)."""
    head_dim = c.dim // c.n_heads
    n_heads = n_heads or c.n_heads
    n_kv = n_kv or c.n_kv_heads
    B, T = x.shape[0], x.shape[1]
    q = _dense(x, p["wq"]["kernel"], c.dtype).reshape(
        B, T, n_heads, head_dim)
    k = _dense(x, p["wk"]["kernel"], c.dtype).reshape(
        B, T, n_kv, head_dim)
    v = _dense(x, p["wv"]["kernel"], c.dtype).reshape(
        B, T, n_kv, head_dim)
    q = rope(q, positions, c.rope_theta)
    k = rope(k, positions, c.rope_theta)
    rep = n_heads // n_kv
    kr = jnp.repeat(k, rep, axis=2)
    vr = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / head_dim ** 0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kr).astype(jnp.float32) * scale
    mask = jnp.tril(jnp.ones((T, T), bool))
    s = jnp.where(mask[None, None], s, -1e30)
    pr = jax.nn.softmax(s, axis=-1).astype(c.dtype)
    o = jnp.einsum("bhqk,bkhd->bqhd", pr, vr).reshape(
        B, T, n_heads * head_dim)
    return _dense(o, p["wo"]["kernel"], c.dtype), k, v


def _make_prefill(cfg: LlamaConfig, block_size: int, *, shards: int = 1,
                  axis: Optional[str] = None):
    """Prefill body parameterized by shard count: with ``shards > 1`` the
    per-device view sees ``n_heads/shards`` query heads, ``n_kv/shards``
    KV heads, locally-sliced kernels, and a head-sharded pool slice; the
    attention-out and MLP-down partials are psum'd over ``axis``."""
    moe = is_moe(cfg)
    n_heads_l = cfg.n_heads // shards
    n_kv_l = cfg.n_kv_heads // shards

    def prefill(params, k_pool, v_pool, tokens, block_ids):
        T = tokens.shape[1]
        if T % block_size:
            raise ValueError(f"prefill bucket {T} must be a multiple of "
                             f"block_size {block_size}")
        x = jnp.take(params["embedding"], tokens, axis=0).astype(cfg.dtype)
        positions = jnp.arange(T)[None, :]
        ks, vs = [], []
        for i in range(cfg.n_layers):
            lp = layer_params(params, i)
            h, k, v = _attn_prefill(
                lp["attn"], cfg,
                _rmsnorm(x, lp["attn_norm"]["scale"], cfg.norm_eps,
                         cfg.dtype),
                positions, n_heads_l, n_kv_l)
            if axis is not None:
                h = jax.lax.psum(h, axis)
            x = _ffn(lp, cfg, x + h, moe, axis)
            ks.append(k[0])
            vs.append(v[0])
        x = _rmsnorm(x, params["final_norm"]["scale"], cfg.norm_eps,
                     cfg.dtype)
        logits = _lm_head(params, cfg, x)
        n_ch = T // block_size
        head_dim = cfg.dim // cfg.n_heads
        shape = (cfg.n_layers, n_ch, block_size, n_kv_l, head_dim)
        k_all = jnp.stack(ks).reshape(shape).astype(k_pool.dtype)
        v_all = jnp.stack(vs).reshape(shape).astype(v_pool.dtype)
        k_pool = k_pool.at[:, block_ids].set(k_all)
        v_pool = v_pool.at[:, block_ids].set(v_all)
        return logits, k_pool, v_pool

    return prefill


def make_prefill(cfg: LlamaConfig, block_size: int):
    """Build the prefill program for ``cfg``: one compile per prompt
    bucket (the bucketed-prefill discipline — compile count is bounded by
    configuration, not traffic).

    ``prefill(params, k_pool, v_pool, tokens[1, T], block_ids[T // bs])
    -> (logits[1, T, V] f32, k_pool, v_pool)`` — K/V for positions
    ``0..T-1`` land in the slot's blocks; positions at or beyond the real
    prompt length hold padding K/V, which is harmless because the decode
    mask only admits ``t <= pos`` and position ``pos`` is rewritten by the
    decode step itself before its first read.
    """
    return _make_prefill(cfg, block_size)


def _make_decode(cfg: LlamaConfig, block_size: int, *, shards: int = 1,
                 axis: Optional[str] = None):
    """Decode-step body parameterized by shard count — same structure as
    :func:`_make_prefill`; every KV page read/write below operates on the
    shard's LOCAL heads, so the gather-only read discipline holds
    per shard with zero cross-shard KV movement."""
    moe = is_moe(cfg)
    head_dim = cfg.dim // cfg.n_heads
    rep = cfg.n_heads // cfg.n_kv_heads
    scale = 1.0 / head_dim ** 0.5
    n_heads_l = cfg.n_heads // shards
    n_kv_l = cfg.n_kv_heads // shards

    def decode(params, k_pool, v_pool, tokens, positions, block_tables,
               active):
        S = tokens.shape[0]
        bmax = block_tables.shape[1]
        t_max = bmax * block_size
        x = jnp.take(params["embedding"], tokens, axis=0).astype(cfg.dtype)
        blk = jnp.take_along_axis(
            block_tables, (positions // block_size)[:, None], axis=1)[:, 0]
        off = positions % block_size
        pos2 = positions[:, None]
        mask = jnp.arange(t_max)[None, :] <= positions[:, None]
        for i in range(cfg.n_layers):
            lp = layer_params(params, i)
            ap = lp["attn"]
            h = _rmsnorm(x, lp["attn_norm"]["scale"], cfg.norm_eps,
                         cfg.dtype)
            q = _dense(h, ap["wq"]["kernel"], cfg.dtype).reshape(
                S, 1, n_heads_l, head_dim)
            k = _dense(h, ap["wk"]["kernel"], cfg.dtype).reshape(
                S, 1, n_kv_l, head_dim)
            v = _dense(h, ap["wv"]["kernel"], cfg.dtype).reshape(
                S, 1, n_kv_l, head_dim)
            q = rope(q, pos2, cfg.rope_theta)[:, 0]
            k = rope(k, pos2, cfg.rope_theta)[:, 0]
            v = v[:, 0]
            # write the new token's K/V (S-row scatter), then READ the
            # whole context back as a gather over the block tables.
            # Masked slots (inactive or stalled) target the null block
            # through their zero-padded tables; their values are zeroed so
            # block 0 stays all-zero — the invariant padded reads rely on.
            act = active[:, None, None]
            k_pool = k_pool.at[i, blk, off].set(
                jnp.where(act, k, 0).astype(k_pool.dtype))
            v_pool = v_pool.at[i, blk, off].set(
                jnp.where(act, v, 0).astype(v_pool.dtype))
            kb = jnp.take(k_pool[i], block_tables, axis=0).reshape(
                S, t_max, n_kv_l, head_dim)
            vb = jnp.take(v_pool[i], block_tables, axis=0).reshape(
                S, t_max, n_kv_l, head_dim)
            # grouped-query form: head h reads kv group h // rep — the
            # same pairing as the training path's jnp.repeat, without
            # materializing the repeated K/V
            qg = q.reshape(S, n_kv_l, rep, head_dim)
            s = jnp.einsum("sgrd,stgd->sgrt", qg, kb).astype(
                jnp.float32) * scale
            s = jnp.where(mask[:, None, None, :], s, -1e30)
            pr = jax.nn.softmax(s, axis=-1).astype(cfg.dtype)
            o = jnp.einsum("sgrt,stgd->sgrd", pr, vb).reshape(
                S, n_heads_l * head_dim)
            attn_out = _dense(o, ap["wo"]["kernel"], cfg.dtype)
            if axis is not None:
                attn_out = jax.lax.psum(attn_out, axis)
            x = _ffn(lp, cfg, x + attn_out, moe, axis)
        x = _rmsnorm(x, params["final_norm"]["scale"], cfg.norm_eps,
                     cfg.dtype)
        logits = _lm_head(params, cfg, x)
        next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # logits/next_tokens rows for masked slots are garbage the engine
        # discards (it keeps their pending tokens via jnp.where); only the
        # K/V writes above need masking, to preserve the null block.
        return logits, next_tokens, k_pool, v_pool

    return decode


def make_decode_step(cfg: LlamaConfig, block_size: int):
    """Build the single-token decode program for ``cfg`` — ONE compile for
    the serving lifetime (fixed slot width S and block-table width Bmax;
    admit/retire only flips the active mask and table contents).

    ``decode(params, k_pool, v_pool, tokens[S], positions[S],
    block_tables[S, Bmax], active[S])
    -> (logits[S, V] f32, next_tokens[S] i32, k_pool, v_pool)``

    Greedy next tokens are computed on device so the engine can feed them
    straight back without a host round-trip (lint-decode-host-sync).
    """
    return _make_decode(cfg, block_size)


def _make_verify(cfg: LlamaConfig, block_size: int, *, shards: int = 1,
                 axis: Optional[str] = None):
    """Speculative verify body — the decode step widened to a k-token
    window per slot. Same shard parameterization as :func:`_make_decode`;
    the window axis rides every einsum as a batch dim, so the gather-only
    read discipline and the per-layer collective placement are unchanged.
    """
    moe = is_moe(cfg)
    head_dim = cfg.dim // cfg.n_heads
    rep = cfg.n_heads // cfg.n_kv_heads
    scale = 1.0 / head_dim ** 0.5
    n_heads_l = cfg.n_heads // shards
    n_kv_l = cfg.n_kv_heads // shards

    def verify(params, k_pool, v_pool, tokens, positions, block_tables,
               active):
        S, K = tokens.shape
        bmax = block_tables.shape[1]
        t_max = bmax * block_size
        x = jnp.take(params["embedding"], tokens, axis=0).astype(cfg.dtype)
        # window position j of slot s sits at logical position pos[s] + j
        pos_k = positions[:, None] + jnp.arange(
            K, dtype=positions.dtype)[None, :]
        blk = jnp.take_along_axis(block_tables, pos_k // block_size, axis=1)
        off = pos_k % block_size
        # Past-context AND in-window causality in ONE mask: context token
        # t is admitted for window row j iff t <= pos+j, and window token
        # j' (written to the pool below at position pos+j') satisfies that
        # exactly when j' <= j.
        mask = jnp.arange(t_max)[None, None, :] <= pos_k[:, :, None]
        for i in range(cfg.n_layers):
            lp = layer_params(params, i)
            ap = lp["attn"]
            h = _rmsnorm(x, lp["attn_norm"]["scale"], cfg.norm_eps,
                         cfg.dtype)
            q = _dense(h, ap["wq"]["kernel"], cfg.dtype).reshape(
                S, K, n_heads_l, head_dim)
            k = _dense(h, ap["wk"]["kernel"], cfg.dtype).reshape(
                S, K, n_kv_l, head_dim)
            v = _dense(h, ap["wv"]["kernel"], cfg.dtype).reshape(
                S, K, n_kv_l, head_dim)
            q = rope(q, pos_k, cfg.rope_theta)
            k = rope(k, pos_k, cfg.rope_theta)
            # Write ALL K candidate positions ([S, K]-row scatter), masked
            # exactly like the decode step: inactive/stalled slots write
            # zeros through their zero-padded tables into the null block.
            # Rejected candidates' K/V DO land in the pool — harmlessly:
            # the engine rewinds ``positions`` to the accepted prefix, the
            # mask never admits a position beyond the rewound ``pos``, and
            # the next window (which always starts at the rewound pos and
            # spans past every stale position) overwrites them before any
            # later row's mask can admit them (tests/test_spec_decode.py
            # pins this across block boundaries).
            act = active[:, None, None, None]
            k_pool = k_pool.at[i, blk, off].set(
                jnp.where(act, k, 0).astype(k_pool.dtype))
            v_pool = v_pool.at[i, blk, off].set(
                jnp.where(act, v, 0).astype(v_pool.dtype))
            kb = jnp.take(k_pool[i], block_tables, axis=0).reshape(
                S, t_max, n_kv_l, head_dim)
            vb = jnp.take(v_pool[i], block_tables, axis=0).reshape(
                S, t_max, n_kv_l, head_dim)
            qg = q.reshape(S, K, n_kv_l, rep, head_dim)
            s = jnp.einsum("skgrd,stgd->skgrt", qg, kb).astype(
                jnp.float32) * scale
            s = jnp.where(mask[:, :, None, None, :], s, -1e30)
            pr = jax.nn.softmax(s, axis=-1).astype(cfg.dtype)
            o = jnp.einsum("skgrt,stgd->skgrd", pr, vb).reshape(
                S, K, n_heads_l * head_dim)
            attn_out = _dense(o, ap["wo"]["kernel"], cfg.dtype)
            if axis is not None:
                attn_out = jax.lax.psum(attn_out, axis)
            x = _ffn(lp, cfg, x + attn_out, moe, axis)
        x = _rmsnorm(x, params["final_norm"]["scale"], cfg.norm_eps,
                     cfg.dtype)
        logits = _lm_head(params, cfg, x)
        next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return logits, next_tokens, k_pool, v_pool

    return verify


def make_verify_step(cfg: LlamaConfig, block_size: int):
    """Build the speculative k-token verify program for ``cfg`` — ONE
    compile per draft width K (the engine uses one fixed
    ``HOROVOD_DECODE_SPEC_K``, so one compile for the serving lifetime).

    ``verify(params, k_pool, v_pool, tokens[S, K], positions[S],
    block_tables[S, Bmax], active[S])
    -> (logits[S, K, V] f32, next_tokens[S, K] i32, k_pool, v_pool)``

    ``tokens[s, 0]`` is slot ``s``'s pending (already-sampled, not yet
    cached) token at position ``positions[s]``; ``tokens[s, 1:]`` are the
    host-drafted candidates at the following positions. Row ``j`` of
    ``next_tokens[s]`` is the model's greedy continuation after consuming
    window tokens ``0..j`` — so ``next_tokens[s, 0]`` is always the TRUE
    next token, and draft ``tokens[s, j+1]`` is accepted exactly when it
    equals ``next_tokens[s, j]`` with every earlier draft accepted (the
    lossless longest-matching-prefix rule; the engine applies it on host
    where the drafts already live). A caller that never accepts drafts
    reads ``next_tokens[:, 0]`` and gets the plain decode step's stream.
    """
    return _make_verify(cfg, block_size)


# -- tensor-parallel (tp) decode plane ---------------------------------------

def validate_tp(cfg: LlamaConfig, tp: int) -> None:
    """The divisibility contract for the megatron-style plan: query and KV
    heads split the head dim, the MLP/expert hidden dim splits its
    matrices. ``tp=1`` is always valid (the unsharded programs)."""
    if tp <= 1:
        return
    for name, value in (("n_heads", cfg.n_heads),
                        ("n_kv_heads", cfg.n_kv_heads),
                        ("hidden_dim", cfg.hidden_dim)):
        if value % tp:
            raise ValueError(
                f"tp={tp} does not divide cfg.{name}={value}")


def kv_pool_spec(axis: str = "tp") -> P:
    """PartitionSpec of the paged KV pools under tensor parallelism:
    ``[L, n_blocks, block_size, n_kv{sharded}, head_dim]`` — block
    geometry replicated, heads split."""
    return P(None, None, None, axis, None)


def decode_param_specs(cfg: LlamaConfig, params, axis: str = "tp"):
    """PartitionSpec pytree mirroring ``params`` for the megatron plan:
    wq/wk/wv and MLP/expert up-projections column-parallel (output dim),
    wo and down-projections row-parallel (input dim), everything else —
    embedding, norms, router, lm head — replicated. Handles both the
    unrolled ``block_i`` and scanned ``layers`` checkpoint layouts (the
    scanned ``[L, ...]`` leaves get a leading ``None``)."""
    def block_specs(block, pfx):
        col = P(*pfx, None, axis)
        row = P(*pfx, axis, None)
        specs = {}
        for key, sub in block.items():
            if key == "attn":
                specs[key] = {"wq": {"kernel": col}, "wk": {"kernel": col},
                              "wv": {"kernel": col}, "wo": {"kernel": row}}
            elif key == "mlp":
                specs[key] = {"w1": {"kernel": col}, "w3": {"kernel": col},
                              "w2": {"kernel": row}}
            elif key == "moe":
                specs[key] = {"router": {"kernel": P()},
                              "w1": P(*pfx, None, None, axis),
                              "w3": P(*pfx, None, None, axis),
                              "w2": P(*pfx, None, axis, None)}
            else:                          # norms and future replicated bits
                specs[key] = jax.tree.map(lambda _: P(), sub)
        return specs

    specs = {}
    for key, sub in params.items():
        if key == "layers":
            specs[key] = {"block": block_specs(sub["block"], (None,))}
        elif key.startswith("block_"):
            specs[key] = block_specs(sub, ())
        else:                              # embedding / final_norm / lm_head
            specs[key] = jax.tree.map(lambda _: P(), sub)
    return specs


def decode_leaf_shard_axis(path_names: Sequence[Any], shape,
                           tp: int) -> Optional[int]:
    """Which array axis of a decode-params leaf the tp plan splits, or
    ``None`` if the leaf is replicated (or indivisible). Keyed on the
    trailing path names so it works for both checkpoint layouts — this is
    the single source of truth the per-shard CAS layer (publisher shard
    plans, registry shard selectors) derives byte movement from."""
    names = tuple(str(n) for n in path_names)
    if not names:
        return None
    leaf, parent = names[-1], (names[-2] if len(names) >= 2 else None)
    if leaf == "kernel" and parent in ("wq", "wk", "wv", "w1", "w3"):
        ax = len(shape) - 1                # column-parallel: output dim
    elif leaf == "kernel" and parent in ("wo", "w2"):
        ax = len(shape) - 2                # row-parallel: input dim
    elif leaf in ("w1", "w3") and parent == "moe":
        ax = len(shape) - 1                # [.., E, D, M]: expert hidden
    elif leaf == "w2" and parent == "moe":
        ax = len(shape) - 2                # [.., E, M, D]: expert hidden
    else:
        return None
    return ax if (tp > 0 and shape[ax] % tp == 0) else None


def _shard_mapped(cfg, mesh, axis, body, n_pools, n_extra, n_outs):
    """Wrap ``body`` in shard_map lazily — the param PartitionSpec tree
    needs the concrete params structure, so construction happens on first
    call (and jit caches the result by tracing, not by wrapper identity)."""
    from jax import shard_map               # backfilled by horovod_tpu.compat
    pool_s = kv_pool_spec(axis)

    def wrapped(params, *args):
        specs = decode_param_specs(cfg, params, axis)
        in_specs = (specs,) + (pool_s,) * n_pools + (P(),) * n_extra
        out_specs = (P(),) * (n_outs - n_pools) + (pool_s,) * n_pools
        sm = shard_map(body, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
        return sm(params, *args)

    return wrapped


def make_prefill_tp(cfg: LlamaConfig, block_size: int, mesh,
                    axis: str = "tp"):
    """:func:`make_prefill` partitioned over ``mesh[axis]``. Same
    signature and semantics; the pools are the head-sharded global views
    (:func:`kv_pool_spec`), params follow :func:`decode_param_specs`,
    tokens/block_ids and the returned logits are replicated."""
    tp = mesh.shape[axis]
    validate_tp(cfg, tp)
    body = _make_prefill(cfg, block_size, shards=tp, axis=axis)
    return _shard_mapped(cfg, mesh, axis, body, n_pools=2, n_extra=2,
                         n_outs=3)


def make_decode_step_tp(cfg: LlamaConfig, block_size: int, mesh,
                        axis: str = "tp"):
    """:func:`make_decode_step` partitioned over ``mesh[axis]``. The wire
    contract: exactly ``2 * n_layers`` all-reduces of ``[S, D]``
    activations (attention-out + MLP/expert-down) and NOTHING else — no
    collective-permutes, no cross-shard KV gathers; slot state, tables,
    logits, and next_tokens stay replicated so the engine's host logic is
    mesh-agnostic (``tests/test_wire_contracts.py`` pins this)."""
    tp = mesh.shape[axis]
    validate_tp(cfg, tp)
    body = _make_decode(cfg, block_size, shards=tp, axis=axis)
    return _shard_mapped(cfg, mesh, axis, body, n_pools=2, n_extra=4,
                         n_outs=4)


def make_verify_step_tp(cfg: LlamaConfig, block_size: int, mesh,
                        axis: str = "tp"):
    """:func:`make_verify_step` partitioned over ``mesh[axis]``. The wire
    contract is the decode step's, re-pinned at the window width: exactly
    ``2 * n_layers`` all-reduces of the ``[S, K, D]`` (= ``S·K × D``
    bytes) activations and NOTHING else — zero collective-permutes, zero
    cross-shard KV movement (``tests/test_wire_contracts.py``
    ``test_tp_verify_wire_contract`` pins count, operand bytes, and the
    absence of permutes)."""
    tp = mesh.shape[axis]
    validate_tp(cfg, tp)
    body = _make_verify(cfg, block_size, shards=tp, axis=axis)
    return _shard_mapped(cfg, mesh, axis, body, n_pools=2, n_extra=4,
                         n_outs=4)
