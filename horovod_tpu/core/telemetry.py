"""Unified run telemetry: metrics registry + flight recorder.

The reference observes per-tensor lifecycle through the Timeline
(``horovod/common/timeline.cc``) and detects stuck collectives with the
stall inspector (``horovod/common/stall_inspector.cc``), but neither
exports run-wide metrics nor leaves a post-mortem record when a rank
dies.  This module is the TPU rebuild's single telemetry surface:

* a process-wide, lock-cheap :class:`Registry` of counters, gauges and
  bounded histograms (label cardinality capped so a runaway label value
  cannot blow up memory or the wire format);
* a fixed-size :class:`FlightRecorder` ring of recent structured events
  (step begin/end, collective issue, sentinel verdicts, watchdog
  heartbeats, elastic generation changes, checkpoint commit/restore,
  coordinator RPC retries) that dumps atomically to
  ``flight_<rank>.jsonl`` on abnormal exit;
* Prometheus text rendering (served by the coordinator at
  ``GET /metrics``) and a compact cumulative-delta export pushed to the
  coordinator piggybacked on the existing poll cadence;
* :func:`assemble_incident` — the elastic driver's cross-rank
  post-mortem: surviving rings + the coordinator journal tail lined up
  into one ``incident_<failure_seq>.json``.

Every recording call is host-side only: values handed to the registry
or the ring must already live on the host (no ``.block_until_ready()``
or ``np.asarray`` on traced values inside a step loop — hvd-analyze's
``lint-blocking-telemetry`` rule enforces this).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .logging import get_logger

ENABLE_ENV = "HOROVOD_TELEMETRY"
RING_ENV = "HOROVOD_TELEMETRY_RING"
FLIGHT_DIR_ENV = "HOROVOD_FLIGHT_DIR"

DEFAULT_RING = 256
MAX_SERIES_PER_METRIC = 64
DEFAULT_BUCKETS = (0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 60.0)

# How many trailing events per rank an incident report keeps.
INCIDENT_TAIL = 64


def _env_rank() -> int:
    # HOROVOD_PROCESS_ID is what runner/exec_run.py stamps on each worker
    # it launches; the others cover foreign launchers.
    for var in ("HOROVOD_PROCESS_ID", "HOROVOD_RANK", "PMI_RANK",
                "OMPI_COMM_WORLD_RANK"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def _series_id(name: str, labels: Dict[str, Any]) -> str:
    """Render ``name{k="v",...}`` — the Prometheus sample id doubles as
    the wire/journal key so merges are plain dict updates."""
    if not labels:
        return name
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in sorted(labels.items())
    )
    return "%s{%s}" % (name, inner)


def inject_label(sid: str, key: str, value: Any) -> str:
    """Insert one label into a series id (used to tag per-rank samples)."""
    pair = '%s="%s"' % (key, value)
    if sid.endswith("}"):
        name, _, rest = sid.partition("{")
        return "%s{%s,%s" % (name, pair, rest)
    return "%s{%s}" % (sid, pair)


class Registry:
    """Lock-cheap metrics registry.

    One lock guards three flat dicts keyed by Prometheus sample id; an
    increment is a dict update under the lock (sub-microsecond), so the
    registry is safe to hit from the step loop, the watchdog thread and
    the coordinator poll thread at once.  Per metric name at most
    ``max_series`` distinct label sets are kept; overflow increments the
    ``hvd_telemetry_series_dropped_total`` self-counter instead of
    growing without bound.
    """

    def __init__(self, max_series: int = MAX_SERIES_PER_METRIC):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        # name -> (boundaries, {sid_prefix: [bucket counts..., +inf]}, sums, counts)
        self._hist_bounds: Dict[str, Tuple[float, ...]] = {}
        self._hist_counts: Dict[str, List[int]] = {}
        self._hist_sum: Dict[str, float] = {}
        self._hist_n: Dict[str, int] = {}
        self._series_per_name: Dict[str, int] = {}
        self._max_series = max_series
        self._dropped = 0
        self._dirty: set = set()

    def _admit(self, store: Dict[str, Any], name: str, sid: str) -> bool:
        if sid in store:
            return True
        n = self._series_per_name.get(name, 0)
        if n >= self._max_series:
            self._dropped += 1
            return False
        self._series_per_name[name] = n + 1
        return True

    def inc(self, name: str, value: float = 1.0, **labels: Any) -> None:
        sid = _series_id(name, labels)
        with self._lock:
            if not self._admit(self._counters, name, sid):
                return
            self._counters[sid] = self._counters.get(sid, 0.0) + value
            self._dirty.add(("c", sid))

    def set_gauge(self, name: str, value: float, **labels: Any) -> None:
        sid = _series_id(name, labels)
        with self._lock:
            if not self._admit(self._gauges, name, sid):
                return
            self._gauges[sid] = float(value)
            self._dirty.add(("g", sid))

    def observe(self, name: str, value: float,
                buckets: Optional[Tuple[float, ...]] = None,
                **labels: Any) -> None:
        sid = _series_id(name, labels)
        with self._lock:
            bounds = self._hist_bounds.get(name)
            if bounds is None:
                bounds = tuple(buckets or DEFAULT_BUCKETS)
                self._hist_bounds[name] = bounds
            if not self._admit(self._hist_n, name, sid):
                return
            counts = self._hist_counts.get(sid)
            if counts is None:
                counts = [0] * (len(bounds) + 1)
                self._hist_counts[sid] = counts
            i = 0
            while i < len(bounds) and value > bounds[i]:
                i += 1
            counts[i] += 1
            self._hist_sum[sid] = self._hist_sum.get(sid, 0.0) + value
            self._hist_n[sid] = self._hist_n.get(sid, 0) + 1
            self._dirty.add(("h", sid))

    # -- export ----------------------------------------------------------

    def _flatten_hist_locked(self, sid: str) -> Dict[str, float]:
        """Histograms go over the wire as plain monotone counters
        (``_bucket{le=..}``, ``_sum``, ``_count``) so the coordinator
        can aggregate them with the same sum-merge as counters."""
        name, _, rest = sid.partition("{")
        labels = "{" + rest if rest else ""
        bounds = self._hist_bounds.get(name, DEFAULT_BUCKETS)
        out: Dict[str, float] = {}
        cum = 0
        for b, c in zip(tuple(bounds) + (float("inf"),),
                        self._hist_counts.get(sid, [])):
            cum += c
            le = "+Inf" if b == float("inf") else repr(b)
            base = "%s_bucket" % name
            bsid = _series_id(base, {})
            if labels:
                bsid = base + labels
            out[inject_label(bsid, "le", le)] = float(cum)
        out["%s_sum%s" % (name, labels)] = self._hist_sum.get(sid, 0.0)
        out["%s_count%s" % (name, labels)] = float(self._hist_n.get(sid, 0))
        return out

    def export(self, dirty_only: bool = False) -> Dict[str, Dict[str, float]]:
        """Compact snapshot: ``{"c": {sid: cumulative}, "g": {sid: v}}``.

        With ``dirty_only`` the dicts carry only series touched since the
        previous dirty export (values stay cumulative, so a lost push is
        healed by the next one).
        """
        with self._lock:
            if dirty_only:
                dirty, self._dirty = self._dirty, set()
                c = {s: self._counters[s] for k, s in dirty
                     if k == "c" and s in self._counters}
                g = {s: self._gauges[s] for k, s in dirty
                     if k == "g" and s in self._gauges}
                for k, s in dirty:
                    if k == "h":
                        c.update(self._flatten_hist_locked(s))
            else:
                c = dict(self._counters)
                g = dict(self._gauges)
                for s in self._hist_n:
                    c.update(self._flatten_hist_locked(s))
            if self._dropped:
                c["hvd_telemetry_series_dropped_total"] = float(self._dropped)
        return {"c": c, "g": g}

    def counter_value(self, name: str, **labels: Any) -> float:
        with self._lock:
            return self._counters.get(_series_id(name, labels), 0.0)

    def gauge_value(self, name: str, **labels: Any) -> Optional[float]:
        with self._lock:
            return self._gauges.get(_series_id(name, labels))


def render_prometheus(per_rank: Dict[Any, Dict[str, Dict[str, float]]]) -> str:
    """Prometheus text exposition from per-rank compact snapshots.

    Per-rank samples get a ``rank`` label injected; the fleet rollup is
    emitted with no ``rank`` label — counters SUMMED across ranks, gauges
    AVERAGED (a sum of per-rank ``hvd_step_mfu_proxy``/wall gauges would
    be meaningless; the across-rank mean is the fleet MFU-proxy the
    coordinator dashboard wants — ISSUE 11).
    """
    lines: List[str] = []
    typed: set = set()
    rollup: Dict[str, float] = {}
    g_sum: Dict[str, float] = {}
    g_n: Dict[str, int] = {}

    def _emit(sid: str, value: float, kind: str) -> None:
        name = sid.partition("{")[0]
        if name not in typed:
            typed.add(name)
            lines.append("# TYPE %s %s" % (name, kind))
        if value == int(value):
            lines.append("%s %d" % (sid, int(value)))
        else:
            lines.append("%s %s" % (sid, repr(value)))

    for rank in sorted(per_rank, key=str):
        snap = per_rank[rank]
        for sid, v in sorted(snap.get("c", {}).items()):
            _emit(inject_label(sid, "rank", rank), v, "counter")
            rollup[sid] = rollup.get(sid, 0.0) + v
        for sid, v in sorted(snap.get("g", {}).items()):
            _emit(inject_label(sid, "rank", rank), v, "gauge")
            g_sum[sid] = g_sum.get(sid, 0.0) + v
            g_n[sid] = g_n.get(sid, 0) + 1
    for sid, v in sorted(rollup.items()):
        _emit(sid, v, "counter")
    for sid, v in sorted(g_sum.items()):
        _emit(sid, v / g_n[sid], "gauge")
    return "\n".join(lines) + "\n"


class FlightRecorder:
    """Fixed-size ring of recent structured events.

    ``record`` is an append under a lock; the ring never grows past its
    construction size, so it is safe to leave armed for the whole run.
    ``dump`` writes JSONL atomically (tmp + ``os.replace``), mirroring
    ``elastic/state.py::_persist``, so a dump racing a crash never
    leaves a torn file.
    """

    def __init__(self, size: int = DEFAULT_RING):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(8, int(size)))

    def record(self, kind: str, **fields: Any) -> None:
        ev = {"t": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def dump(self, path: str) -> str:
        events = self.events()
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            for ev in events:
                f.write(json.dumps(ev, default=str) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path


class Telemetry:
    """One registry + one ring + the rank identity, per process."""

    def __init__(self, rank: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 ring_size: Optional[int] = None):
        if enabled is None:
            enabled = os.environ.get(ENABLE_ENV, "1").lower() not in (
                "0", "false", "no", "off")
        if ring_size is None:
            try:
                ring_size = int(os.environ.get(RING_ENV, str(DEFAULT_RING)))
            except ValueError:
                ring_size = DEFAULT_RING
        self.enabled = bool(enabled)
        self.rank = _env_rank() if rank is None else int(rank)
        self.registry = Registry()
        self.ring = FlightRecorder(ring_size)
        self._dump_lock = threading.Lock()


_lock = threading.Lock()
_active: Optional[Telemetry] = None


def active() -> Telemetry:
    """The process singleton, built lazily from env on first use."""
    global _active
    t = _active
    if t is None:
        with _lock:
            if _active is None:
                _active = Telemetry()
            t = _active
    return t


def configure(rank: Optional[int] = None, enabled: Optional[bool] = None,
              ring_size: Optional[int] = None) -> Telemetry:
    """(Re)build the singleton — called from ``hvd.init`` and tests."""
    global _active
    with _lock:
        _active = Telemetry(rank=rank, enabled=enabled, ring_size=ring_size)
        return _active


def reset() -> None:
    global _active
    with _lock:
        _active = None


def enabled() -> bool:
    return active().enabled


# -- module-level conveniences (no-ops when telemetry is disabled) -------

def inc(name: str, value: float = 1.0, **labels: Any) -> None:
    t = active()
    if t.enabled:
        t.registry.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels: Any) -> None:
    t = active()
    if t.enabled:
        t.registry.set_gauge(name, value, **labels)


def observe(name: str, value: float, **labels: Any) -> None:
    t = active()
    if t.enabled:
        t.registry.observe(name, value, **labels)


def record_event(kind: str, **fields: Any) -> None:
    t = active()
    if t.enabled:
        t.ring.record(kind, **fields)


def export_delta() -> Optional[Dict[str, Dict[str, float]]]:
    """Compact cumulative delta for the coordinator push; None when
    disabled or nothing changed since the last export."""
    t = active()
    if not t.enabled:
        return None
    snap = t.registry.export(dirty_only=True)
    if not snap["c"] and not snap["g"]:
        return None
    return snap


def dump_flight(reason: str, directory: Optional[str] = None) -> Optional[str]:
    """Atomically dump the ring to ``flight_<rank>.jsonl``.

    Safe on the ``os._exit`` paths (no atexit reliance); returns the
    path, or None when telemetry is disabled or no dump dir is known.
    Re-entrant calls re-dump — last writer wins, which is fine because
    later dumps strictly contain more history.
    """
    t = active()
    if not t.enabled:
        return None
    d = directory or os.environ.get(FLIGHT_DIR_ENV)
    if not d:
        return None
    try:
        with t._dump_lock:
            t.ring.record("flight_dump", reason=reason, rank=t.rank)
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, "flight_%d.jsonl" % t.rank)
            return t.ring.dump(path)
    except OSError as exc:  # a dying process must never die *harder* here
        get_logger().warning("flight dump failed: %s", exc)
        return None


# -- incident assembly (driver side) -------------------------------------

def load_flight_dumps(directory: str) -> Dict[int, List[Dict[str, Any]]]:
    """Read every ``flight_<rank>.jsonl`` under ``directory``."""
    out: Dict[int, List[Dict[str, Any]]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for fn in names:
        if not (fn.startswith("flight_") and fn.endswith(".jsonl")):
            continue
        try:
            rank = int(fn[len("flight_"):-len(".jsonl")])
        except ValueError:
            continue
        events = []
        try:
            with open(os.path.join(directory, fn)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        events.append(json.loads(line))
        except (OSError, ValueError):
            continue
        out[rank] = events
    return out


def assemble_incident(directory: str, failure_seq: int,
                      journal_tail: Optional[List[Dict[str, Any]]] = None,
                      coordinator_metrics: Optional[Dict[Any, Any]] = None,
                      failure: Optional[Dict[str, Any]] = None,
                      tail: int = INCIDENT_TAIL) -> Optional[str]:
    """Line up every surviving rank's last events around the failure.

    Writes ``incident_<failure_seq>.json`` into ``directory`` (atomic),
    embedding the per-rank event tails, the coordinator journal tail and
    the coordinator's last per-rank metrics snapshot (which carries the
    *victim's* last-known step even though the victim never dumped).
    """
    dumps = load_flight_dumps(directory)
    # Name the rollback target: the newest manifest any rank published
    # before the failure. The caller's failure dict (the driver scans the
    # commit dir) wins; otherwise fall back to the manifest_publish events
    # in the rank dumps.
    last_manifest = (failure or {}).get("last_manifest")
    if last_manifest is None:
        seqs = [ev.get("seq") for evs in dumps.values() for ev in evs
                if ev.get("kind") == "manifest_publish"
                and ev.get("seq") is not None]
        last_manifest = max(seqs) if seqs else None
    report = {
        "failure_seq": int(failure_seq),
        "created": time.time(),
        "failure": failure or {},
        "last_manifest": last_manifest,
        "ranks": {str(r): evs[-tail:] for r, evs in sorted(dumps.items())},
        "journal_tail": list(journal_tail or []),
        "coordinator_metrics": {
            str(k): v for k, v in (coordinator_metrics or {}).items()},
    }
    path = os.path.join(directory, "incident_%d.json" % int(failure_seq))
    tmp = "%s.tmp.%d" % (path, os.getpid())
    try:
        with open(tmp, "w") as f:
            json.dump(report, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError as exc:
        get_logger().warning("incident assembly failed: %s", exc)
        return None
    get_logger().info("telemetry: incident report %s (%d rank dumps)",
                      path, len(dumps))
    return path
