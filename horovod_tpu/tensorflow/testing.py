"""Multi-rank test harness for the tensorflow API (mirror of
``horovod_tpu.torch.testing``): N simulated ranks as threads over a
shared :class:`~horovod_tpu.core.engine.ThreadSimEngine` — the reference
runs its TF tests as N processes over CPU/Gloo (SURVEY.md §4); this is
the same semantics without multi-process JAX.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from . import mpi_ops as _ops
from ..core.engine import ThreadSimEngine


def run_parallel(n: int, fn: Callable[[int], object],
                 engine: Optional[ThreadSimEngine] = None) -> List[object]:
    """Run ``fn(rank)`` on ``n`` simulated ranks; returns per-rank
    results; re-raises the first rank exception."""
    eng = engine or ThreadSimEngine(n)
    _ops.shutdown()
    _ops.init(engine=eng)
    results: List[object] = [None] * n
    errors: List[BaseException] = []

    def worker(r):
        eng.set_rank(r)
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001 — propagate to caller
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    try:
        if any(t.is_alive() for t in threads):
            raise RuntimeError(
                "run_parallel: rank threads stalled (collective deadlock?)")
        if errors:
            raise errors[0]
    finally:
        _ops.shutdown()
    return results
