"""Torch-API synthetic benchmark (reference
``examples/pytorch/pytorch_synthetic_benchmark.py`` parity).

A reference training script ported with the one-line import change
(``import horovod.torch as hvd`` → ``from horovod_tpu import torch as
hvd``): init → pin to rank → broadcast params + optimizer state →
``hvd.DistributedOptimizer`` with fp16 compression → train loop. Torch
tensors live on host CPU in this build (see ``horovod_tpu/torch/``); the
TPU compute path is the JAX API (``examples/train_resnet.py``).

Run:
    python examples/torch_synthetic.py --steps 20
"""

import argparse
import time

import numpy as np
import torch

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(
    _os.path.abspath(__file__))))  # run in-repo without pip install

from horovod_tpu import torch as hvd


def _small_convnet(num_classes):
    return torch.nn.Sequential(
        torch.nn.Conv2d(3, 32, 3, padding=1), torch.nn.ReLU(),
        torch.nn.MaxPool2d(2),
        torch.nn.Conv2d(32, 64, 3, padding=1), torch.nn.ReLU(),
        torch.nn.AdaptiveAvgPool2d(1), torch.nn.Flatten(),
        torch.nn.Linear(64, num_classes))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32,
                   help="batch size PER RANK (reference convention)")
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument("--backward-passes-per-step", type=int, default=1)
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(hvd.rank())  # differ pre-broadcast on purpose

    model = _small_convnet(args.num_classes)
    optimizer = torch.optim.SGD(model.parameters(), lr=args.lr * hvd.size(),
                                momentum=0.9)

    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    compression = hvd.Compression.fp16 if args.fp16_allreduce \
        else hvd.Compression.none
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression,
        backward_passes_per_step=args.backward_passes_per_step)

    rng = np.random.RandomState(hvd.rank())
    data = torch.as_tensor(rng.randn(
        args.batch_size, 3, args.image_size, args.image_size)
        .astype(np.float32))
    target = torch.as_tensor(rng.randint(0, args.num_classes,
                                         (args.batch_size,)))
    loss_fn = torch.nn.CrossEntropyLoss()

    def one_step():
        optimizer.zero_grad()
        loss = loss_fn(model(data), target)
        loss.backward()
        optimizer.step()
        return float(loss.detach())

    if hvd.rank() == 0:
        print(f"ranks={hvd.size()} batch/rank={args.batch_size}")
    for _ in range(args.warmup):
        loss = one_step()
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = one_step()
    dt = time.perf_counter() - t0
    ips = args.batch_size * args.steps / dt
    if hvd.rank() == 0:
        print(f"loss={loss:.4f} images/sec/rank={ips:.1f} "
              f"step_ms={dt / args.steps * 1e3:.2f}")


if __name__ == "__main__":
    main()
