"""Device-side profiling — the XLA half of the observability story.

Reference parity: the reference's timeline (timeline.cc) records the full
per-tensor lifecycle because all phases happen on the host thread it owns.
Here the device-side phases (collective execution, fusion, overlap) live in
XLA's own trace. ``trace`` wraps ``jax.profiler`` so one context manager
captures a TensorBoard/Perfetto-loadable xplane trace alongside the
host-side Chrome trace from ``tools/timeline.py`` (HOROVOD_TIMELINE); load
both into Perfetto to see the merged picture, or use ``annotate`` to inject
named host spans into the xplane trace itself.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_trace: bool = False) -> Iterator[None]:
    """Capture a device trace: ``with profiler.trace("/tmp/trace"): step()``.
    View with TensorBoard's profile plugin or Perfetto."""
    jax.profiler.start_trace(logdir,
                             create_perfetto_trace=create_perfetto_trace)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span that shows up inside the device trace (TraceAnnotation).
    Usable as decorator or context manager around host code issuing work."""
    return jax.profiler.TraceAnnotation(name)


def step_marker(step: int):
    """Mark a training step boundary (shows as StepTraceAnnotation rows in
    TensorBoard's trace viewer)."""
    return jax.profiler.StepTraceAnnotation("train_step", step_num=step)
