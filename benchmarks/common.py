"""Shared benchmark machinery.

Reference analog: the reference's ``horovod/benchmarks``-style scripts +
`docs/benchmarks.rst` methodology (SURVEY.md §6). All scripts here:

- print one JSON line per metric: ``{"metric", "value", "unit",
  "vs_baseline"}`` (the bench.py schema);
- time device work by the SLOPE between a short and a long ``lax.scan``
  (two chained-dispatch lengths), so constant host-dispatch/tunnel latency
  cancels — required on remote-tunnel TPU setups where per-step
  ``block_until_ready`` is dominated by round-trips;
- auto-size DOWN on CPU meshes so the suite doubles as a shape/correctness
  check in CI (SURVEY.md §4 universal-fake-backend discipline).
"""

from __future__ import annotations

import json
import os
import sys
import time

# `python benchmarks/<x>.py` puts benchmarks/ (the script dir) on sys.path,
# not the repo root — add it so `import horovod_tpu` resolves in-repo.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The session image pre-imports jax with the axon TPU plugin; an env var
# alone doesn't switch backends (see .claude/skills/verify). Honor an
# explicit CPU request before any computation runs.
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import jax
import numpy as np

S_SHORT, S_LONG = 4, 16


def on_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"


def sync(x) -> None:
    np.asarray(jax.tree_util.tree_leaves(x)[0]).ravel()[0]


def slope_time(run, s_short: int = S_SHORT, s_long: int = S_LONG,
               repeats: int = 5) -> float:
    """Seconds per unit from two chained-scan lengths (latency cancelled).

    ``run(k)`` must execute k units ending in a device->host sync.
    Tunnel jitter is additive per measurement, so each absolute time is
    estimated as min-over-repeats before the slope is taken (a min of
    per-pair slopes would bias low — slope noise is two-sided).
    """
    return slope_time_paired({"_": run}, s_short, s_long,
                             rounds=repeats)["_"]


def slope_time_paired(runs: dict, s_short: int = S_SHORT,
                      s_long: int = S_LONG, rounds: int = 7) -> dict:
    """``slope_time`` for several configs at once, interleaved.

    Measuring config A's repeats and then config B's lets slow drift in the
    tunnel/device (other tenants, thermals) land entirely on one side and
    skew the A/B ratio. Here every round samples each (config, scan-length)
    once, in round-robin order, so drift is shared; the min over rounds per
    cell then cancels spike noise as in ``slope_time``. Returns
    ``{name: seconds-per-unit}``.
    """
    for fn in runs.values():  # warm all compiles before any timing
        fn(s_short)
        fn(s_long)
    best: dict = {(name, k): float("inf")
                  for name in runs for k in (s_short, s_long)}
    for _ in range(rounds):
        for name, fn in runs.items():
            for k in (s_short, s_long):
                t0 = time.perf_counter()
                fn(k)
                dt = time.perf_counter() - t0
                best[(name, k)] = min(best[(name, k)], dt)
    return {name: max(best[(name, s_long)] - best[(name, s_short)], 1e-9)
            / (s_long - s_short) for name in runs}


def emit(metric: str, value: float, unit: str,
         vs_baseline: float | None = None) -> None:
    line = {"metric": metric, "value": round(float(value), 3), "unit": unit}
    if vs_baseline is not None:
        line["vs_baseline"] = round(float(vs_baseline), 4)
    print(json.dumps(line), flush=True)
