"""Write-ahead journal for the coordinator service's world state.

Reference parity: the role the reference's rendezvous KV store plays for
driver restarts (``horovod/runner/elastic/rendezvous.py``, SURVEY.md §2.5)
— membership state that outlives the process serving it. Here the state is
tiny (version, hosts, np, failures, failure_seq, registrations), so a
JSON-lines append log in the driver's temp dir is enough: every mutation
appends one self-contained record, and a crashed ``CoordinatorService`` is
rebuilt by replaying the log.

Why both monotonic counters must survive a restart: survivors' step
watchers baseline ``failure_seq`` and arm only when it MOVES UP alongside
a non-empty failure list (core/watchdog.py). A restarted coordinator that
reset the seq to 0 would publish the next death at a sequence the watcher
has already seen — the rescue would silently never fire (the exact
mis-baselining bug class REVIEW r6 caught in the relaunch path).

Torn tail: a crash mid-append leaves a partial final line. Replay ignores
any undecodable line (and logs it once), so the rebuilt state is simply
"as of the last durable record" — the same contract as elastic/state.py's
checksummed commits, without needing a checksum because records are
line-framed and individually self-contained.

Compaction (pod-scale control plane): an append-only log grows with every
membership change and worker death, so at O(1000) workers with constant
churn a crash-restart replay becomes O(history). ``compact(state)`` folds
the live state into ONE ``snapshot`` record and atomically replaces the
log (tmp + rename — a crash mid-compaction leaves either the old or the
new file, never a torn one). Replay treats a ``snapshot`` record as a
reset-to-this-state, so ``version``/``failure_seq`` rebuilt from a
compacted journal are byte-for-byte the values an uncompacted replay
would produce. Appends after compaction extend the new file as usual.

The mutation records double as the **wire format of the versioned-delta
``/world`` protocol** (elastic/service.py): the coordinator's in-memory
event buffer holds exactly these records, and delta clients replay them
through the same :func:`apply_record` used here — one replay semantics,
three consumers (journal rebuild, compaction snapshot, client delta).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Optional, TextIO

from ..core.logging import get_logger


def empty_state() -> Dict[str, Any]:
    """The zero state every replay starts from."""
    return {
        "version": 0, "hosts": {}, "np": 0,
        "failures": [], "failure_seq": 0, "registrations": {},
        "metrics": {},
        "publish": None, "publish_seq": 0,
        "replicas": {}, "arbiter_seq": 0, "fleet": None,
        "preempts": [],
    }


def apply_record(state: Dict[str, Any], rec: Dict[str, Any]) -> bool:
    """Apply one mutation record to ``state`` in place. Returns False for
    unknown ops (callers log once). The single replay semantics shared by
    journal rebuild, compaction snapshots, and the delta-protocol client
    (elastic/service.py CoordinatorClient)."""
    op = rec["op"]
    if op == "world":
        state["version"] = int(rec["version"])
        state["hosts"] = dict(rec["hosts"])
        state["np"] = int(rec["np"])
        state["failures"] = []   # per-generation, cleared by update
        state["preempts"] = []   # ditto — a new generation starts clean
    elif op == "failure":
        state["failure_seq"] = int(rec["seq"])
        state["failures"].append(
            {"host": rec["host"], "code": int(rec["code"])})
    elif op == "preempt":
        # Announced graceful departure (core/lifecycle.py): a membership
        # shrink like "world", carried on the same version counter so
        # survivors take the GRACEFUL reset path — failure_seq is
        # deliberately untouched, so the peer-failure grace deadline
        # (core/watchdog.py) never arms for a preemption.
        state["version"] = int(rec["version"])
        state["hosts"] = dict(rec["hosts"])
        state["np"] = int(rec["np"])
        state["failures"] = []
        # setdefault: the delta-protocol client replays onto a state dict
        # holding only the WORLD_KEYS payload.
        state.setdefault("preempts", []).append({"host": rec["host"]})
    elif op == "register":
        state["registrations"][str(rec["process_id"])] = float(rec["ts"])
    elif op == "register_batch":
        # Coalesced per-host registration: one record (one fsync) for a
        # whole host's worth of workers instead of one per worker.
        ts = float(rec["ts"])
        for pid in rec["process_ids"]:
            state["registrations"][str(pid)] = ts
    elif op == "metrics":
        # One worker's cumulative metrics delta (core/telemetry.py wire
        # shape: {"c": {series_id: value}, "g": {...}}). Values are
        # cumulative, so merging is a plain key update and replay order
        # within a rank keeps last-writer-wins semantics.
        per_rank = state.setdefault("metrics", {}).setdefault(
            str(rec["rank"]), {"c": {}, "g": {}})
        per_rank["c"].update(rec.get("c", {}))
        per_rank["g"].update(rec.get("g", {}))
    elif op == "publish":
        # Serving-plane announcement (serving/publisher.py): the newest
        # known-good published weights. Deliberately does NOT touch
        # version/failure_seq — publishing weights is not a membership
        # event, so training workers' delta cursors never move for it.
        # publish_seq is the serving processes' own long-poll cursor.
        state["publish"] = dict(rec["record"])
        state["publish_seq"] = int(state.get("publish_seq", 0)) + 1
    elif op == "replica":
        # Serving-replica registry mutation (serving/fleet.py via the
        # coordinator's /replica endpoint). Like publish/metrics it never
        # bumps version/failure_seq — replica churn is not a membership
        # event for the TRAINING world. Heartbeats are deliberately NOT
        # journaled (too chatty; liveness is re-proven after a restart) —
        # only register / drain / deregister reach the journal, so replay
        # lands on the same fleet membership the live service had.
        reps = state.setdefault("replicas", {})
        action = rec.get("action", "register")
        rid = str(rec["replica_id"])
        if action == "deregister":
            reps.pop(rid, None)
        elif action == "drain":
            if rid in reps:
                reps[rid]["draining"] = True
        else:
            reps[rid] = {"addr": str(rec["addr"]),
                         "rank": int(rec.get("rank", 0)),
                         "draining": False}
    elif op == "arbiter":
        # One fleet-arbiter decision (elastic/arbiter.py): the target
        # fleet shape it bid for, under its own monotonic sequence.
        # Replaying the journal therefore lands a crash-restarted
        # coordinator on EXACTLY the fleet shape its predecessor last
        # decided — the arbiter resumes from there instead of from zero
        # (the chaos-tier "kill the coordinator mid-rebalance" proof).
        state["arbiter_seq"] = int(rec["seq"])
        state["fleet"] = {"serving_target": int(rec["serving_target"]),
                          "training_np": int(rec["training_np"]),
                          "reason": str(rec.get("reason", ""))}
    elif op == "snapshot":
        # Compaction marker: reset to the embedded live state.
        snap = rec["state"]
        state.clear()
        state.update(empty_state())
        state["version"] = int(snap["version"])
        state["hosts"] = dict(snap["hosts"])
        state["np"] = int(snap["np"])
        state["failures"] = [dict(f) for f in snap["failures"]]
        state["failure_seq"] = int(snap["failure_seq"])
        state["registrations"] = {str(k): float(v) for k, v
                                  in snap["registrations"].items()}
        state["metrics"] = {str(k): {"c": dict(v.get("c", {})),
                                     "g": dict(v.get("g", {}))}
                            for k, v in snap.get("metrics", {}).items()}
        pub = snap.get("publish")
        state["publish"] = dict(pub) if pub is not None else None
        state["publish_seq"] = int(snap.get("publish_seq", 0))
        state["replicas"] = {str(k): dict(v) for k, v
                             in snap.get("replicas", {}).items()}
        state["arbiter_seq"] = int(snap.get("arbiter_seq", 0))
        fleet = snap.get("fleet")
        state["fleet"] = dict(fleet) if fleet is not None else None
        state["preempts"] = [dict(p) for p in snap.get("preempts", [])]
    else:
        return False
    return True


class CoordinatorJournal:
    """Append-only JSON-lines log of coordinator state mutations, with
    periodic snapshot+truncate compaction."""

    def __init__(self, path: str):
        self.path = path
        self._fh: Optional[TextIO] = None
        #: mutation records appended since the last compaction — the
        #: service compares this against its compact-every threshold.
        self.records_since_snapshot = 0

    def _file(self) -> TextIO:
        if self._fh is None or self._fh.closed:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
        return self._fh

    def append(self, record: Dict[str, Any]) -> None:
        """Durably append one mutation record. Flush + fsync per record:
        the journal only matters when the process serving the state dies,
        so buffered-but-unwritten records would defeat its purpose. The
        write rate is human-scale (membership changes and worker deaths),
        not per-step — and per-worker bursts (registration) arrive
        coalesced as one ``register_batch`` record per host."""
        fh = self._file()
        fh.write(json.dumps(record, sort_keys=True) + "\n")
        fh.flush()
        try:
            os.fsync(fh.fileno())
        except OSError:
            pass
        except ValueError:  # closed underneath us during teardown
            pass
        self.records_since_snapshot += 1

    def compact(self, state: Dict[str, Any]) -> None:
        """Replace the whole log with one ``snapshot`` record holding
        ``state``. Atomic (tmp + rename): a crash mid-compaction leaves
        either the full old history or the full snapshot — replay handles
        both identically. The open append handle points at the OLD inode
        after the rename, so it is closed here and lazily reopened on the
        next append."""
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".compact")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                fh.write(json.dumps({"op": "snapshot", "state": state},
                                    sort_keys=True) + "\n")
                fh.flush()
                try:
                    os.fsync(fh.fileno())
                except OSError:
                    pass
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.close()
        self.records_since_snapshot = 0

    def size_bytes(self) -> int:
        """Current on-disk journal size (scale-harness observability)."""
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def close(self) -> None:
        if self._fh is not None and not self._fh.closed:
            self._fh.close()


def replay(path: str) -> Optional[Dict[str, Any]]:
    """Rebuild the coordinator state from the journal, or None when the
    journal is missing/empty. A torn final record (crash mid-append) is
    tolerated: undecodable lines are skipped."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.readlines()
    except OSError:
        return None
    state = empty_state()
    seen = 0
    for lineno, line in enumerate(lines, 1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
            known = apply_record(state, rec)
        except (ValueError, KeyError, TypeError):
            get_logger().warning(
                "coordinator journal %s: skipping undecodable record at "
                "line %d (torn tail from a crash mid-append)", path, lineno)
            continue
        seen += 1
        if not known:
            get_logger().warning(
                "coordinator journal %s: unknown op %r at line %d — "
                "skipped", path, rec.get("op"), lineno)
    return state if seen else None
