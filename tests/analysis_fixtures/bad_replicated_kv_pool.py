"""lint-replicated-kv-pool fixture: a tp-mesh decode setup that
allocates the paged-KV pools and feeds them straight to the sharded
program — jit defaults them to REPLICATED, so all 8 devices hold the
full cache and shard_map reshards it every step. Exactly ONE finding:
the placed variant, the single-device (no mesh) variant, and the
pragma'd probe below must stay clean.
"""
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.models import decode as MD
from horovod_tpu.parallel import create_mesh


def build_engine_replicated(cfg, block_size, n_blocks, slots):
    mesh = create_mesh({"tp": 8}, devices=jax.devices()[:8])
    kp, vp = MD.init_kv_pools(  # <- lint-replicated-kv-pool
        cfg, n_blocks, block_size)
    step = MD.make_decode_step_tp(cfg, block_size, mesh)
    return mesh, step, kp, vp


def build_engine_placed(cfg, block_size, n_blocks, slots):
    # Clean: pools land head-sharded on the tp mesh before first use.
    mesh = create_mesh({"tp": 8}, devices=jax.devices()[:8])
    kp, vp = MD.init_kv_pools(cfg, n_blocks, block_size)
    nd = NamedSharding(mesh, MD.kv_pool_spec())
    kp, vp = jax.device_put(kp, nd), jax.device_put(vp, nd)
    step = MD.make_decode_step_tp(cfg, block_size, mesh)
    return mesh, step, kp, vp


def build_engine_single_device(cfg, block_size, n_blocks):
    # Clean: no mesh in sight — the unsharded engine's pool allocation.
    kp, vp = MD.init_kv_pools(cfg, n_blocks, block_size)
    step = MD.make_decode_step(cfg, block_size)
    return step, kp, vp


def pool_memory_probe(cfg, block_size, n_blocks):
    # Clean: a deliberate replicated-pool probe carries the pragma.
    mesh = create_mesh({"tp": 8}, devices=jax.devices()[:8])
    kp, vp = MD.init_kv_pools(cfg, n_blocks, block_size)  # hvd-analyze: ok
    return mesh, kp.nbytes + vp.nbytes
