"""Pallas kernel tests (interpret mode on the CPU test mesh).

Mirrors the reference's approach of testing device code end-to-end through
the public API against a plain oracle (SURVEY.md §4: no C++ unit tests —
behavior is pinned via Python-level parity checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import (flash_attention, fused_combine, fused_norms_dot,
                             merge_partials)
from horovod_tpu.ops.flash_attention import _reference_partial


def _rand(shape, seed, dtype=jnp.float32):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), dtype)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("T", [64, 100])
def test_flash_matches_reference(causal, T):
    B, H, D = 2, 2, 32
    q = _rand((B, T, H, D), 0)
    k = _rand((B, T, H, D), 1)
    v = _rand((B, T, H, D), 2)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref, _, _ = _reference_partial(q, k, v, causal=causal, scale=D ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_cross_attention_unequal_lengths():
    B, H, D = 1, 2, 32
    q = _rand((B, 48, H, D), 3)
    k = _rand((B, 80, H, D), 4)
    v = _rand((B, 80, H, D), 5)
    out = flash_attention(q, k, v, causal=False, block_q=32, block_k=32)
    ref, _, _ = _reference_partial(q, k, v, causal=False, scale=D ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_bf16_close():
    B, T, H, D = 1, 64, 2, 32
    q = _rand((B, T, H, D), 6, jnp.bfloat16)
    k = _rand((B, T, H, D), 7, jnp.bfloat16)
    v = _rand((B, T, H, D), 8, jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    ref, _, _ = _reference_partial(q, k, v, causal=True, scale=D ** -0.5)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_merge_partials_equals_full_attention():
    """Attention over the full key set == merge of partials over key shards
    — the exact property ring attention relies on each ppermute step."""
    B, T, H, D = 2, 64, 2, 32
    q = _rand((B, T, H, D), 10)
    k = _rand((B, T, H, D), 11)
    v = _rand((B, T, H, D), 12)
    full, _ = flash_attention(q, k, v, causal=False, block_q=32, block_k=32,
                              return_residuals=True), None
    full = full[0]
    half = T // 2
    p1 = flash_attention(q, k[:, :half], v[:, :half], causal=False,
                         block_q=32, block_k=32, return_residuals=True)
    p2 = flash_attention(q, k[:, half:], v[:, half:], causal=False,
                         block_q=32, block_k=32, return_residuals=True)
    o, (m, l) = p1[0], p1[1]
    o2, (m2, l2) = p2[0], p2[1]
    merged, _, _ = merge_partials((o, m, l), (o2, m2, l2))
    np.testing.assert_allclose(np.asarray(merged), np.asarray(full),
                               rtol=2e-5, atol=2e-5)


def test_flash_gradients_match_reference():
    B, T, H, D = 1, 32, 2, 16
    q = _rand((B, T, H, D), 20)
    k = _rand((B, T, H, D), 21)
    v = _rand((B, T, H, D), 22)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=16, block_k=16) ** 2)

    def loss_ref(q, k, v):
        o, _, _ = _reference_partial(q, k, v, causal=True, scale=D ** -0.5)
        return jnp.sum(o ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_fused_norms_dot():
    a = _rand((1000,), 30)
    b = _rand((1000,), 31)
    dot, na, nb = fused_norms_dot(a, b)
    np.testing.assert_allclose(float(dot), float(jnp.vdot(a, b)), rtol=1e-5)
    np.testing.assert_allclose(float(na), float(jnp.vdot(a, a)), rtol=1e-5)
    np.testing.assert_allclose(float(nb), float(jnp.vdot(b, b)), rtol=1e-5)


def test_fused_combine_matches_adasum_combine():
    from horovod_tpu.collectives.adasum import _combine
    a = _rand((513, 7), 40)
    b = _rand((513, 7), 41)
    got = fused_combine(a, b)
    want = _combine(a, b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_fused_combine_zero_norm_degrades_to_sum():
    a = jnp.zeros((64,))
    b = _rand((64,), 42)
    got = fused_combine(a, b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(b),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_pallas_impl_matches_local(causal):
    """The Pallas per-shard kernel + merge_partials ring must agree with the
    single-device oracle on the 8-device CPU mesh (interpret mode)."""
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from horovod_tpu.parallel import create_mesh, local_attention, \
        ring_attention

    rng = np.random.RandomState(5)
    B, T, H, D = 2, 32, 2, 8
    q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.5)
    k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.5)
    v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    ref = np.asarray(local_attention(q, k, v, causal=causal))
    mesh = create_mesh({"sp": 8})

    def body(qb, kb, vb):
        return ring_attention(qb, kb, vb, "sp", causal=causal, impl="pallas")

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                          out_specs=P(None, "sp"), check_vma=False))
    out = np.asarray(f(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_pallas_gradients_match_jnp_impl():
    """Gradients through the pallas ring path must match the jnp ring path —
    regression for the m/l residual cotangents being dropped in the VJP."""
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from horovod_tpu.parallel import create_mesh, ring_attention

    rng = np.random.RandomState(9)
    B, T, H, D = 1, 32, 2, 8
    q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.5)
    k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.5)
    v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    mesh = create_mesh({"sp": 8})

    def loss(impl):
        def body(qb, kb, vb):
            return ring_attention(qb, kb, vb, "sp", causal=True, impl=impl)
        f = shard_map(body, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                      out_specs=P(None, "sp"), check_vma=False)
        return lambda q, k, v: jnp.sum(f(q, k, v) ** 2)

    g1 = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss("jnp"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_kv_mask_matches_reference():
    """Padding mask: masked keys never contribute; fully-masked rows are 0."""
    B, T, H, D = 2, 64, 2, 32
    q = _rand((B, T, H, D), 10)
    k = _rand((B, T, H, D), 11)
    v = _rand((B, T, H, D), 12)
    lengths = jnp.array([40, 64])
    mask = jnp.arange(T)[None, :] < lengths[:, None]
    out = flash_attention(q, k, v, causal=False, kv_mask=mask,
                          block_q=32, block_k=32)
    bias = jnp.where(mask, 0.0, -1e30)
    ref, _, _ = _reference_partial(q, k, v, bias, causal=False,
                                   scale=D ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # all-keys-masked batch row: output must be exactly zero, not NaN
    none = jnp.zeros((B, T), bool)
    out0 = flash_attention(q, k, v, causal=False, kv_mask=none,
                           block_q=32, block_k=32)
    assert not np.isnan(np.asarray(out0)).any()
    np.testing.assert_array_equal(np.asarray(out0), 0.0)


def test_flash_kv_mask_grads_flow():
    B, T, H, D = 1, 32, 2, 16
    q = _rand((B, T, H, D), 13)
    k = _rand((B, T, H, D), 14)
    v = _rand((B, T, H, D), 15)
    mask = jnp.arange(T)[None, :] < 20

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=False, kv_mask=mask,
                               block_q=16, block_k=16).sum()

    def ref_loss(q, k, v):
        bias = jnp.where(mask, 0.0, -1e30)
        o, _, _ = _reference_partial(q, k, v, bias, causal=False,
                                     scale=D ** -0.5)
        return o.sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_bwd_kernel_matches_reference(causal):
    """The dedicated blockwise backward (no-residual path) must match the
    materialised-softmax vjp, including causal tile skipping and padding in
    BOTH sequence dims (T=100/84 are not block multiples)."""
    B, H, D = 2, 2, 32
    Tq, Tk = (100, 100) if causal else (100, 84)
    q = _rand((B, Tq, H, D), 20)
    k = _rand((B, Tk, H, D), 21)
    v = _rand((B, Tk, H, D), 22)

    def loss(q, k, v):
        return (flash_attention(q, k, v, causal=causal, block_q=32,
                                block_k=32) ** 2).sum()

    def ref_loss(q, k, v):
        o, _, _ = _reference_partial(q, k, v, causal=causal,
                                     scale=D ** -0.5)
        return (o ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_residual_path_still_differentiable():
    """return_residuals=True keeps the recompute vjp (m/l cotangents from
    merge_partials must flow)."""
    B, T, H, D = 1, 32, 2, 16
    q = _rand((B, T, H, D), 23)
    k1 = _rand((B, T, H, D), 24)
    v1 = _rand((B, T, H, D), 25)
    k2 = _rand((B, T, H, D), 26)
    v2 = _rand((B, T, H, D), 27)

    def loss(q, k1, v1, k2, v2):
        o1, (m1, l1) = flash_attention(q, k1, v1, causal=False,
                                       return_residuals=True,
                                       block_q=16, block_k=16)
        o2, (m2, l2) = flash_attention(q, k2, v2, causal=False,
                                       return_residuals=True,
                                       block_q=16, block_k=16)
        o, _, _ = merge_partials((o1, m1, l1), (o2, m2, l2))
        return (o.astype(jnp.float32) ** 2).sum()

    def ref_loss(q, k1, v1, k2, v2):
        k = jnp.concatenate([k1, k2], axis=1)
        v = jnp.concatenate([v1, v2], axis=1)
        o, _, _ = _reference_partial(q, k, v, causal=False, scale=D ** -0.5)
        return (o.astype(jnp.float32) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2, 3, 4))(q, k1, v1, k2, v2)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2, 3, 4))(q, k1, v1, k2, v2)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_partitioned_under_gspmd_mesh(monkeypatch):
    """flash_attention inside jit with dp x tp sharded operands: the
    custom_partitioning rule shards batch*head and replicates seq/depth, so
    the kernel runs per-shard and matches the unsharded result."""
    monkeypatch.setenv("HOROVOD_FLASH_PARTITION", "1")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    B, T, H, D = 4, 64, 4, 32
    q = _rand((B, T, H, D), 30)
    k = _rand((B, T, H, D), 31)
    v = _rand((B, T, H, D), 32)
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "tp"))
    sh = NamedSharding(mesh, P("dp", None, "tp", None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

    def loss(q, k, v):
        return (flash_attention(q, k, v, causal=True, block_q=32,
                                block_k=32) ** 2).sum()

    f = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    val_s, grads_s = f(qs, ks, vs)
    val_r, grads_r = f(q, k, v)  # unsharded oracle (same jit, fresh compile)
    np.testing.assert_allclose(float(val_s), float(val_r), rtol=1e-4)
    for a, b in zip(grads_s, grads_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
