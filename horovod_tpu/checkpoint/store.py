"""Store abstraction: where checkpoints/artifacts live.

Reference parity: ``horovod/spark/common/store.py`` (~800 LoC of
LocalStore/HDFSStore/S3Store/DBFSStore path plumbing). The TPU build keeps
the same URL-dispatched factory (:func:`get_store`) and the same role —
resolve logical names (checkpoints, logs) to concrete paths and hand out
filesystem operations — with LocalStore implemented and remote schemes
gated on their optional clients, as the reference gates on pyarrow/boto3.
"""

from __future__ import annotations

import os
import shutil
from typing import List


class Store:
    """Path layout + filesystem ops for one artifact root."""

    def __init__(self, prefix_path: str):
        self._prefix = prefix_path.rstrip("/")

    # -- layout (reference: Store.get_checkpoint_path etc.) -----------------

    @property
    def prefix_path(self) -> str:
        return self._prefix

    def checkpoint_path(self, run_id: str) -> str:
        return f"{self._prefix}/{run_id}/checkpoints"

    def logs_path(self, run_id: str) -> str:
        return f"{self._prefix}/{run_id}/logs"

    def train_data_path(self, run_id: str) -> str:
        """Materialised training data (reference: Store.get_train_data_path
        — where the estimator's intermediate parquet lives; here fixed-
        record part files, spark/data_store.py)."""
        return f"{self._prefix}/{run_id}/train_data"

    def runs_path(self) -> str:
        return self._prefix

    # -- ops (overridden per backend) ---------------------------------------

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def is_remote(self) -> bool:
        raise NotImplementedError


class LocalStore(Store):
    """Local/NFS filesystem store (reference: LocalStore)."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def listdir(self, path: str) -> List[str]:
        return sorted(os.path.join(path, p) for p in os.listdir(path))

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.unlink(path)

    def is_remote(self) -> bool:
        return False


#: scheme -> Store subclass; remote backends register here when their
#: clients are importable (reference: store.py's matches()/filesystem
#: dispatch on path prefix).
_SCHEMES = {}


def register_scheme(scheme: str, cls) -> None:
    _SCHEMES[scheme] = cls


def get_store(prefix_path: str) -> Store:
    """URL-dispatched factory (reference: ``Store.create``).

    ``hdfs://``/``s3://``/``gs://`` require their optional clients; this
    image has none, so those schemes raise with the same guidance the
    reference gives when pyarrow/boto3 are missing.
    """
    for scheme, cls in _SCHEMES.items():
        if prefix_path.startswith(scheme + "://"):
            return cls(prefix_path)
    if "://" in prefix_path and not prefix_path.startswith("file://"):
        scheme = prefix_path.split("://", 1)[0]
        raise ValueError(
            f"no client available for {scheme}:// stores; install its "
            f"client and register_scheme({scheme!r}, YourStore) "
            f"(reference gates HDFS/S3/DBFS the same way)")
    return LocalStore(prefix_path.removeprefix("file://"))
