"""Ulysses-style sequence parallelism: all-to-all head scatter.

Capability-NEW vs the reference (SURVEY.md §5.7): the reference exposes the
``alltoall`` primitive Ulysses needs but has no sequence-parallel layer. The
scheme (DeepSpeed-Ulysses, public): activations arrive sequence-sharded
[B, T/n, H, D]; one all_to_all re-shards them head-sharded [B, T, H/n, D] so
each device runs FULL-sequence attention for its head subset; a second
all_to_all restores sequence sharding. Cost: two all_to_alls of the
activation tensor per attention layer, riding ICI; attention itself needs no
communication (contrast ring.py, which trades that for n ppermute hops of
K/V only).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax

from .ring import local_attention


def seq_to_heads(x, axis_name: str):
    """[B, T_local, H, D] -> [B, T_global, H_local, D] via one all_to_all."""
    n = lax.axis_size(axis_name)
    B, t, H, D = x.shape
    if H % n:
        raise ValueError(f"head count {H} not divisible by sp axis size {n}")
    # split heads across the axis, concatenate sequence
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def heads_to_seq(x, axis_name: str):
    """[B, T_global, H_local, D] -> [B, T_local, H, D] (inverse)."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = True,
                      scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None):
    """Sequence-parallel attention via head scatter, inside ``shard_map``
    over ``axis_name``. q/k/v: [B, T_local, H, D]; returns the same shape.
    ``attn_fn(q, k, v, causal=..., scale=...)`` defaults to the exact
    full-sequence attention (swap in a Pallas flash kernel on TPU)."""
    attn = attn_fn or local_attention
    qh = seq_to_heads(q, axis_name)
    kh = seq_to_heads(k, axis_name)
    vh = seq_to_heads(v, axis_name)
    oh = attn(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(oh, axis_name)
