"""Callback tests (reference: test/parallel/test_keras.py callback cases,
SURVEY.md §2.4)."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu import callbacks as cb
from horovod_tpu.train import TrainState, create_train_state, make_train_step
from horovod_tpu.models import ResNetTiny
from horovod_tpu.optimizer import distributed


def _state_with_injectable_lr(lr=0.1):
    opt = cb.injectable(optax.sgd, lr)
    params = {"w": jnp.ones((2, 2))}
    return TrainState(jnp.zeros((), jnp.int32), params, opt.init(params),
                      {}), opt


def test_injectable_lr_get_set():
    state, _ = _state_with_injectable_lr(0.1)
    loop = cb.CallbackLoop(state, [])
    assert loop.get_lr() == pytest.approx(0.1)
    loop.set_lr(0.5)
    assert loop.get_lr() == pytest.approx(0.5)


def test_set_lr_without_inject_raises():
    params = {"w": jnp.ones(2)}
    opt = optax.sgd(0.1)
    state = TrainState(jnp.zeros((), jnp.int32), params, opt.init(params), {})
    loop = cb.CallbackLoop(state, [])
    assert loop.get_lr() is None
    with pytest.raises(ValueError, match="inject_hyperparams"):
        loop.set_lr(0.2)


def test_injected_lr_actually_drives_updates():
    """The mutated LR must change the next compiled update (LR-as-data)."""
    state, opt = _state_with_injectable_lr(0.0)   # lr 0: no movement
    grads = {"w": jnp.ones((2, 2))}
    upd, new_opt = opt.update(grads, state.opt_state, state.params)
    assert float(jnp.abs(upd["w"]).max()) == 0.0
    loop = cb.CallbackLoop(state, [])
    loop.set_lr(1.0)
    upd, _ = opt.update(grads, loop.state.opt_state, loop.state.params)
    np.testing.assert_allclose(np.asarray(upd["w"]), -np.ones((2, 2)))


def test_warmup_callback_ramps_to_scaled_lr():
    state, _ = _state_with_injectable_lr(0.1)
    loop = cb.CallbackLoop(state, [], steps_per_epoch=10)
    w = cb.LearningRateWarmupCallback(initial_lr=0.1, warmup_epochs=2,
                                      size=8)
    loop.epoch = 0
    w.on_batch_begin(0, loop)
    assert loop.get_lr() == pytest.approx(0.1)          # start: initial_lr
    loop.epoch = 1
    w.on_batch_begin(0, loop)
    assert loop.get_lr() == pytest.approx(0.1 * 4.5)    # halfway: 1+(8-1)/2
    loop.epoch = 2
    w.on_batch_begin(0, loop)
    assert loop.get_lr() == pytest.approx(0.8)          # ramped: lr*size


def test_warmup_epoch_granularity_without_steps_per_epoch():
    state, _ = _state_with_injectable_lr(0.1)
    loop = cb.CallbackLoop(state, [])
    w = cb.LearningRateWarmupCallback(initial_lr=0.1, warmup_epochs=4,
                                      size=2)
    loop.epoch_begin(0)
    w.on_epoch_begin(0, loop)
    assert loop.get_lr() == pytest.approx(0.1)
    w.on_epoch_begin(2, loop)
    assert loop.get_lr() == pytest.approx(0.15)


def test_schedule_callback_staircase_and_window():
    state, _ = _state_with_injectable_lr(1.0)
    loop = cb.CallbackLoop(state, [])
    sc = cb.LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda e: 0.1 ** e,
        start_epoch=1, end_epoch=3)
    sc.on_epoch_begin(0, loop)
    assert loop.get_lr() == pytest.approx(1.0)   # before window: untouched
    loop.epoch = 1
    sc.on_epoch_begin(1, loop)
    assert loop.get_lr() == pytest.approx(0.1)
    loop.epoch = 3
    sc.on_epoch_begin(3, loop)
    assert loop.get_lr() == pytest.approx(0.1)   # after window: untouched


def test_schedule_callback_continuous():
    state, _ = _state_with_injectable_lr(1.0)
    loop = cb.CallbackLoop(state, [], steps_per_epoch=4)
    sc = cb.LearningRateScheduleCallback(
        initial_lr=2.0, multiplier=lambda e: 1.0 / (1.0 + e),
        staircase=False)
    loop.epoch = 1
    sc.on_batch_begin(2, loop)                   # epoch_float = 1.5
    assert loop.get_lr() == pytest.approx(2.0 / 2.5)


def test_broadcast_callback_single_process_noop_shapes():
    state, _ = _state_with_injectable_lr(0.1)
    loop = cb.CallbackLoop(state, [cb.BroadcastGlobalVariablesCallback(0)])
    loop.train_begin()
    np.testing.assert_allclose(np.asarray(loop.state.params["w"]),
                               np.ones((2, 2)))


def test_metric_average_single_process_noop():
    logs = {"loss": 1.5, "acc": 0.5, "name": "x"}
    cb.MetricAverageCallback().on_epoch_end(0, cb.CallbackLoop(
        _state_with_injectable_lr()[0], []), logs)
    assert logs == {"loss": 1.5, "acc": 0.5, "name": "x"}


def test_warmup_schedule_pure_optax():
    sched = cb.warmup_schedule(0.1, size=4, warmup_steps=10)
    assert float(sched(0)) == pytest.approx(0.1)
    assert float(sched(5)) == pytest.approx(0.1 * 2.5)
    assert float(sched(10)) == pytest.approx(0.4)
    assert float(sched(100)) == pytest.approx(0.4)
    after = optax.constant_schedule(0.123)
    sched2 = cb.warmup_schedule(0.1, size=4, warmup_steps=10, after=after)
    assert float(sched2(50)) == pytest.approx(0.123)


def test_callbacks_in_real_train_loop(mesh8):
    """Full integration: warmup callback drives an injectable-LR
    DistributedOptimizer through the jitted train step."""
    opt = cb.injectable(
        lambda learning_rate: distributed(optax.sgd(learning_rate)),
        learning_rate=0.05)
    model = ResNetTiny(num_classes=10, axis_name=hvd.RANK_AXIS)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(16, 8, 8, 3).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 10, size=(16,)))
    state = create_train_state(model, __import__("jax").random.PRNGKey(0),
                               images[:1], opt)
    step = make_train_step(
        model, opt,
        lambda lg, y: optax.softmax_cross_entropy_with_integer_labels(
            lg, y).mean(), donate=False)
    loop = cb.CallbackLoop(state, [
        cb.BroadcastGlobalVariablesCallback(),
        cb.LearningRateWarmupCallback(0.05, warmup_epochs=1, size=8),
        cb.MetricAverageCallback(),
    ], steps_per_epoch=2)
    loop.train_begin()
    losses = []
    for epoch in range(2):
        loop.epoch_begin(epoch)
        for b in range(2):
            loop.batch_begin(b)
            new_state, loss = step(loop.state, images, labels)
            loop.state = new_state
            loop.batch_end(b, {"loss": float(loss)})
            losses.append(float(loss))
        loop.epoch_end(epoch, {"loss": losses[-1]})
    loop.train_end()
    assert losses[-1] < losses[0]            # it actually trained
    assert loop.get_lr() == pytest.approx(0.4)   # warmup completed: lr*8
