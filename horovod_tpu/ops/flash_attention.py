"""Pallas TPU flash attention with ring-mergeable softmax residuals.

Capability-NEW vs the reference (SURVEY.md §5.7): the reference never touches
activations, so it has no attention kernel at all. This is the hot-op half of
the framework's long-context story (parallel/ring.py is the collective half):
a blockwise-softmax attention kernel that keeps the [Tq, Tk] score matrix out
of HBM entirely — each (q-block, k-block) tile is produced in VMEM, folded
into running (max, denominator, accumulator) state, and discarded. Scores hit
the MXU as [block_q, D] x [D, block_k] matmuls in fp32.

Two properties matter for the distributed design:

- **Residuals** (``return_residuals=True``): the kernel can return the
  running max ``m`` and denominator ``l`` alongside the normalised output, so
  two partial attentions over disjoint key sets can be combined *exactly*
  with :func:`merge_partials`. That is precisely what ring attention needs —
  each ppermute step computes a partial against the resident K/V shard and
  merges it into the carry, so the kernel composes with the ICI ring without
  any cross-step state inside the kernel.
- **Causal block skipping**: with ``causal=True`` tiles strictly above the
  diagonal are predicated off with ``pl.when``, saving ~half the MXU work.

The backward pass recomputes probability tiles from the saved
(q, k, v, o, m, l) — the standard flash trade of FLOPs for HBM (SURVEY.md §7
lists remat as the stock TPU memory lever). Two implementations exist:
dedicated blockwise Pallas kernels (FlashAttention-2 split: a dQ pass and a
dK/dV pass) used on the common ``return_residuals=False`` model path, and a
materialised-softmax jnp recompute vjp kept for ``return_residuals=True``,
where the (m, l) outputs carry real cotangents from ring-attention partial
merging that the kernels do not model.

On non-TPU backends the kernel runs in Pallas interpreter mode, which is how
the CPU test mesh exercises it (the reference's CPU+Gloo fake-backend trick,
SURVEY.md §4).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # finite mask value: exp() underflows cleanly, no NaN algebra

_LANE = 128


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fa_kernel(q_ref, k_ref, v_ref, *refs, scale, causal, bq, bk, nk,
               has_bias):
    """One (batch*head, q-block, k-block) grid step.

    Scratch (persists across the innermost k-block grid dim):
      acc [bq, D] f32 — unnormalised output accumulator
      m_s [bq, 128] f32 — running row max (broadcast over lanes)
      l_s [bq, 128] f32 — running denominator (broadcast over lanes)
    """
    if has_bias:
        bias_ref, o_ref, m_ref, l_ref, acc, m_s, l_s = refs
    else:
        o_ref, m_ref, l_ref, acc, m_s, l_s = refs
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    # Causal: skip tiles strictly above the diagonal (no q position in this
    # block can see any k position in that block).
    visible = ((iq + 1) * bq - 1 >= ik * bk) if causal else (ik >= 0)

    @pl.when(visible)
    def _compute():
        # Keep the input dtype (bf16 on TPU) for both MXU dots and accumulate
        # in f32 via preferred_element_type — casting up first would force
        # fp32 MXU passes, ~4x the matmul cost for no accuracy the f32
        # accumulation doesn't already give.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        k_pos = ik * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            q_pos = iq * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Rows still fully masked have m_new == NEG_INF; exp(s - m_new) would
        # be exp(0) = 1 there, so zero those probabilities explicitly.
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        corr = jnp.exp(m_prev - m_new)
        l_s[:] = jnp.broadcast_to(
            l_s[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True),
            l_s.shape)
        acc[:] = acc[:] * corr + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)

    @pl.when(ik == nk - 1)
    def _emit():
        l = l_s[:, :1]
        o_ref[0] = (acc[:] / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)
        # Residuals are [BH, Tq, 1] so the block's trailing dims (bq, 1)
        # satisfy the TPU tiling rule (sublane divisible by 8, lane equal to
        # the array dim).
        m_ref[0] = m_s[:, :1]
        l_ref[0] = l_s[:, :1]


def _pad_axis(x, axis, mult):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x, n
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), n


def _fa_pallas(q, k, v, bias3, *, causal, scale, block_q, block_k,
               interpret):
    """Forward pallas_call on PADDED folded shapes. q [BH, Tq, D],
    k/v [BH, Tk, D], bias3 None or [BH, 1, Tk]; Tq/Tk block multiples."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    nq, nk = Tq // block_q, Tk // block_k
    kern = functools.partial(_fa_kernel, scale=scale, causal=causal,
                             bq=block_q, bk=block_k, nk=nk,
                             has_bias=bias3 is not None)
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
    ]
    operands = [q, k, v]
    if bias3 is not None:
        operands.append(bias3)
        in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)))
    return pl.pallas_call(
        kern,
        grid=(BH, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Tq, 1), jnp.float32),
            jax.ShapeDtypeStruct((BH, Tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)


def _pad_bias3(bias, BH, Tk0, Tk):
    """Build the [BH, 1, Tk] additive-bias operand, or None when there is
    neither a mask nor key padding. Padded key columns get NEG_INF here —
    as DATA, not a kernel constant, so the kernels never capture a
    sequence-length scalar (interpret-mode custom_partitioning
    closure-converts captured constants into tracers)."""
    if bias is None:
        if Tk == Tk0:
            return None
        bias = jnp.zeros((BH, Tk0), jnp.float32)
    pad = Tk - bias.shape[1]
    if pad:
        bias = jnp.pad(bias, ((0, 0), (0, pad)), constant_values=NEG_INF)
    return bias[:, None, :]


def _partition_enabled() -> bool:
    """Whether to wrap the pallas calls in ``custom_partitioning`` so they
    compose with GSPMD/jit sharding (batch*head dim partitioned, sequence
    and depth replicated). On by default on TPU; CPU meshes opt in via
    ``HOROVOD_FLASH_PARTITION=1`` (tests use this with interpret mode)."""
    import os
    env = os.environ.get("HOROVOD_FLASH_PARTITION")
    if env is not None:
        return env not in ("0", "false", "False", "")
    return jax.default_backend() == "tpu"


@functools.lru_cache(maxsize=None)
def _sharded_wrapper(kind: str, has_bias: bool):
    """Build the ``custom_partitioning`` wrapper for ``kind`` in
    {"fwd", "bwd"}: dim 0 (batch*head) is partitioned, sequence/depth are
    replicated. Static config travels as ``static_argnums`` —
    custom_partitioning closure-converts, so closed-over ints feeding jax
    ops would come back as tracers. One wrapper per ``has_bias`` so the
    no-mask path never materialises a zeros bias.

    The Shardy rule declares need-replication factors; the ``partition``
    callback additionally FORCES dim-1/2 replication on its returned
    shardings so the legacy (non-Shardy) GSPMD path reshards rather than
    running the kernel on silently-wrong local sequence blocks."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec

    n_arrays = (3 if kind == "fwd" else 7) + int(has_bias)
    bias_term = ", b s k" if has_bias else ""
    if kind == "fwd":
        rule = f"b q d, b k d, b k d{bias_term} -> b q d, b q s, b q s"
    else:
        rule = (f"b q d, b k d, b k d, b q d, b q s, b q s, b q s"
                f"{bias_term} -> b q d, b k d, b k d")

    def run(arrays, causal, scale, block_q, block_k, interpret):
        bias3 = arrays[-1] if has_bias else None
        core = arrays[:n_arrays - 1] if has_bias else arrays
        if kind == "fwd":
            return _fa_pallas(*core, bias3, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              interpret=interpret)
        return _fa_bwd_pallas(*core, bias3, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              interpret=interpret)

    def impl(*args):
        return run(args[:n_arrays], *args[n_arrays:])

    wrapped = custom_partitioning(
        impl, static_argnums=tuple(range(n_arrays, n_arrays + 5)))

    def _dim0(sharding):
        spec = sharding.spec
        return spec[0] if len(spec) else None

    def partition(causal, scale, block_q, block_k, interpret,
                  mesh, arg_shapes, result_shape):
        arg_shardings = tuple(
            NamedSharding(mesh, PartitionSpec(_dim0(a.sharding), None, None))
            for a in arg_shapes)
        out_shardings = tuple(
            NamedSharding(mesh, PartitionSpec(_dim0(r.sharding), None, None))
            for r in result_shape)

        def lower(*arrays):
            return run(arrays, causal, scale, block_q, block_k, interpret)

        return mesh, lower, out_shardings, arg_shardings

    def infer(causal, scale, block_q, block_k, interpret,
              mesh, arg_shapes, shape):
        sh = NamedSharding(
            mesh, PartitionSpec(_dim0(arg_shapes[0].sharding), None, None))
        return (sh, sh, sh)

    wrapped.def_partition(
        partition=partition,
        infer_sharding_from_operands=infer,
        sharding_rule=rule,
        need_replication_factors=("q", "d", "k", "s"))
    return wrapped


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "interpret", "partition"))
def _fa_call(q, k, v, bias=None, *, causal, scale, block_q, block_k,
             interpret, partition):
    """q [BH, Tq, D], k/v [BH, Tk, D], optional additive score bias
    [BH, Tk] → (o [BH, Tq, D], m, l [BH, Tq])."""
    BH, Tq0, D = q.shape
    q, Tq0 = _pad_axis(q, 1, block_q)
    k, Tk0 = _pad_axis(k, 1, block_k)
    v, _ = _pad_axis(v, 1, block_k)
    Tq, Tk = q.shape[1], k.shape[1]
    bias3 = _pad_bias3(bias, BH, Tk0, Tk)
    if partition:
        w = _sharded_wrapper("fwd", bias3 is not None)
        args = (q, k, v) + ((bias3,) if bias3 is not None else ())
        o, m, l = w(*args, causal, scale, block_q, block_k, interpret)
    else:
        o, m, l = _fa_pallas(q, k, v, bias3, causal=causal, scale=scale,
                             block_q=block_q, block_k=block_k,
                             interpret=interpret)
    return o[:, :Tq0], m[:, :Tq0, 0], l[:, :Tq0, 0]


def _recompute_p_ds(q, k, v, do, m, l, dsum, bias_tile, *, scale, causal,
                    bq, bk, iq, ik):
    """Shared backward-tile recompute: probability tile ``p`` and score
    cotangent ``ds`` for one (q-block, k-block) pair, from the saved softmax
    stats. Masking must mirror ``_fa_kernel`` exactly."""
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    k_pos = ik * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    if bias_tile is not None:
        s = s + bias_tile.astype(jnp.float32)
    if causal:
        q_pos = iq * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    l = jnp.where(l == 0.0, 1.0, l)
    p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m)) / l
    dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    ds = p * (dp - dsum)  # dsum: rowsum(dO*O), the FA2 correction term
    return p, ds


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, d_ref,
                      *refs, scale, causal, bq, bk, nk, has_bias):
    """dQ pass: grid (BH, q-block, k-block), k innermost; recomputes the
    probability tile from the saved (m, l) softmax stats (FlashAttention-2
    backward), folds dS·K into a per-q-block accumulator."""
    if has_bias:
        bias_ref, dq_ref, acc = refs
    else:
        dq_ref, acc = refs
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    visible = ((iq + 1) * bq - 1 >= ik * bk) if causal else (ik >= 0)

    @pl.when(visible)
    def _compute():
        k = k_ref[0]
        _, ds = _recompute_p_ds(
            q_ref[0], k, v_ref[0], do_ref[0], m_ref[0], l_ref[0], d_ref[0],
            bias_ref[0] if has_bias else None, scale=scale, causal=causal,
            bq=bq, bk=bk, iq=iq, ik=ik)
        acc[:] = acc[:] + lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(ik == nk - 1)
    def _emit():
        dq_ref[0] = acc[:].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, d_ref,
                       *refs, scale, causal, bq, bk, nq, has_bias):
    """dK/dV pass: grid (BH, k-block, q-block), q innermost. Padded q rows
    contribute nothing because their dO (and rowsum term) are zero-padded."""
    if has_bias:
        bias_ref, dk_ref, dv_ref, dk_acc, dv_acc = refs
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = refs
    ikb = pl.program_id(1)
    iqb = pl.program_id(2)

    @pl.when(iqb == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    visible = ((iqb + 1) * bq - 1 >= ikb * bk) if causal else (iqb >= 0)

    @pl.when(visible)
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        p, ds = _recompute_p_ds(
            q, k_ref[0], v_ref[0], do, m_ref[0], l_ref[0], d_ref[0],
            bias_ref[0] if has_bias else None, scale=scale, causal=causal,
            bq=bq, bk=bk, iq=iqb, ik=ikb)
        dv_acc[:] = dv_acc[:] + lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] = dk_acc[:] + lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(iqb == nq - 1)
    def _emit():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "interpret"))
def _fa_bwd_pallas(q, k, v, do, m3, l3, dsum, bias3, *, causal, scale,
                   block_q, block_k, interpret):
    """Backward pallas_calls on PADDED folded shapes → (dq, dk, dv)."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    nq, nk = Tq // block_q, Tk // block_k

    base_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),   # q
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),   # k
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),   # v
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),   # do
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),   # m
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),   # l
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),   # dsum
    ]
    operands = [q, k, v, do, m3, l3, dsum]
    if bias3 is not None:
        operands.append(bias3)
        base_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)))

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=block_q, bk=block_k, nk=nk,
                          has_bias=bias3 is not None),
        grid=(BH, nq, nk),
        in_specs=base_specs,
        out_specs=[pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((BH, Tq, D), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(*operands)[0]

    # dK/dV pass iterates q INNERMOST: swap the grid index meaning (i = k
    # block, j = q block) by re-deriving every spec.
    kv_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, j, 0)),   # q
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0)),   # k
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0)),   # v
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, j, 0)),   # do
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),   # m
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),   # l
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),   # dsum
    ]
    if bias3 is not None:
        kv_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, i)))
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=block_q, bk=block_k, nq=nq,
                          has_bias=bias3 is not None),
        grid=(BH, nk, nq),
        in_specs=kv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Tk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return dq, dk, dv


def _fa_bwd_call(q, k, v, do, o, m, l, bias=None, *, causal, scale,
                 block_q, block_k, interpret, partition):
    """Folded-[BH] backward. Returns (dq, dk, dv) in the input dtypes."""
    BH, Tq0, D = q.shape
    dsum = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                   keepdims=True)  # [BH, Tq, 1] — the FA2 rowsum(dO*O) term
    q, _ = _pad_axis(q, 1, block_q)
    do, _ = _pad_axis(do, 1, block_q)
    dsum, _ = _pad_axis(dsum, 1, block_q)
    m3, _ = _pad_axis(m[..., None].astype(jnp.float32), 1, block_q)
    l3, _ = _pad_axis(l[..., None].astype(jnp.float32), 1, block_q)
    k, Tk0 = _pad_axis(k, 1, block_k)
    v, _ = _pad_axis(v, 1, block_k)
    Tq, Tk = q.shape[1], k.shape[1]
    bias3 = _pad_bias3(bias, BH, Tk0, Tk)
    if partition:
        w = _sharded_wrapper("bwd", bias3 is not None)
        args = (q, k, v, do, m3, l3, dsum) + (
            (bias3,) if bias3 is not None else ())
        dq, dk, dv = w(*args, causal, scale, block_q, block_k, interpret)
    else:
        dq, dk, dv = _fa_bwd_pallas(q, k, v, do, m3, l3, dsum, bias3,
                                    causal=causal, scale=scale,
                                    block_q=block_q, block_k=block_k,
                                    interpret=interpret)
    return dq[:, :Tq0], dk[:, :Tk0], dv[:, :Tk0]


def _reference_partial(q, k, v, bias=None, *, causal, scale):
    """Blockless jnp oracle with the same (o, m, l) partial semantics.

    Used as the recompute path of the backward pass and by the test suite.
    q [B, Tq, H, D]; k/v [B, Tk, H, D]; optional additive score bias
    [B, Tk]; returns o [B,Tq,H,D], m/l [B,H,Tq].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)[:, None, None, :]
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        mask = jnp.arange(Tq)[:, None] >= jnp.arange(Tk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m[..., None]))
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o = o / jnp.where(l == 0.0, 1.0, l).transpose(0, 2, 1)[..., None]
    return o.astype(q.dtype), m, l


def _fold(x, B, H, D):
    return x.transpose(0, 2, 1, 3).reshape(B * H, -1, D)


def _fold_bias(bias, B, H, Tk):
    # [B, Tk] → [BH, Tk] to match the folded batch*head leading dim.
    return jnp.broadcast_to(bias[:, None, :], (B, H, Tk)).reshape(B * H, Tk)


def _fa_fwd_impl(q, k, v, bias, causal, scale, block_q, block_k):
    """Plain (non-vjp) forward shared by both custom_vjp cores."""
    from jax.ad_checkpoint import checkpoint_name
    interpret = _use_interpret()
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    fbias = None if bias is None else _fold_bias(bias, B, H, Tk)
    o, m, l = _fa_call(_fold(q, B, H, D), _fold(k, B, H, D),
                       _fold(v, B, H, D), fbias, causal=causal,
                       scale=scale, block_q=block_q, block_k=block_k,
                       interpret=interpret,
                       partition=_partition_enabled())
    o = o.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    # Named so a remat policy can SAVE the kernel's outputs — they are
    # exactly the custom-vjp residuals (o, m, l), so a policy that keeps
    # them (models' "dots_attn") skips the whole fwd-kernel re-run inside
    # the backward of a remat block, at [B,T,H,D] + 2x[B,H,T] per layer.
    return (checkpoint_name(o, "attn_out"),
            checkpoint_name(m.reshape(B, H, Tq), "attn_lse_m"),
            checkpoint_name(l.reshape(B, H, Tq), "attn_lse_l"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _fa_core(q, k, v, bias, causal, scale, block_q, block_k):
    return _fa_fwd_impl(q, k, v, bias, causal, scale, block_q, block_k)


def _fa_fwd(q, k, v, bias, causal, scale, block_q, block_k):
    out = _fa_core(q, k, v, bias, causal, scale, block_q, block_k)
    return out, (q, k, v, bias)


def _fa_bwd(causal, scale, block_q, block_k, res, cts):
    q, k, v, bias = res
    do, dm, dl = cts
    # The m/l residuals carry real cotangents when the caller merges partials
    # (ring attention weights each partial by exp(m_i - m) * l_i), so the
    # recompute must differentiate through all three outputs.

    def recompute(q, k, v, bias):
        return _reference_partial(q, k, v, bias, causal=causal, scale=scale)

    _, vjp = jax.vjp(recompute, q, k, v, bias)
    return vjp((do.astype(q.dtype), dm.astype(jnp.float32),
                dl.astype(jnp.float32)))


_fa_core.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _fa_core_nores(q, k, v, bias, causal, scale, block_q, block_k):
    """Output-only core used when the caller does not need (m, l): its
    backward runs the dedicated blockwise Pallas kernels instead of the
    materialised-softmax recompute, keeping the [Tq, Tk] matrix out of HBM
    in BOTH passes. ``bias`` receives a zero cotangent — it only ever
    derives from a (constant) kv padding mask on this path."""
    return _fa_fwd_impl(q, k, v, bias, causal, scale, block_q, block_k)[0]


def _fa_fwd_nores(q, k, v, bias, causal, scale, block_q, block_k):
    o, m, l = _fa_fwd_impl(q, k, v, bias, causal, scale, block_q, block_k)
    return o, (q, k, v, bias, o, m, l)


def _fa_bwd_nores(causal, scale, block_q, block_k, res, do):
    q, k, v, bias, o, m, l = res
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    fbias = None if bias is None else _fold_bias(bias, B, H, Tk)
    fm = m.reshape(B * H, Tq)
    fl = l.reshape(B * H, Tq)
    dq, dk, dv = _fa_bwd_call(
        _fold(q, B, H, D), _fold(k, B, H, D), _fold(v, B, H, D),
        _fold(do, B, H, D), _fold(o, B, H, D), fm, fl, fbias,
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=_use_interpret(), partition=_partition_enabled())
    unfold = lambda x, T: x.reshape(B, H, T, D).transpose(0, 2, 1, 3)
    dbias = None if bias is None else jnp.zeros_like(bias)
    return unfold(dq, Tq), unfold(dk, Tk), unfold(dv, Tk), dbias


_fa_core_nores.defvjp(_fa_fwd_nores, _fa_bwd_nores)


def flash_attention(q, k, v, *, causal: bool = True,
                    kv_mask=None,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    return_residuals: bool = False):
    """Blockwise (flash) attention on [B, T, H, D] tensors.

    ``kv_mask`` is an optional [B, Tk] bool array marking real (attendable)
    keys — the BERT-style padding mask; masked keys never win the softmax.

    Returns the attention output, plus ``(m, l)`` softmax residuals of shape
    [B, H, Tq] when ``return_residuals`` — feed those to
    :func:`merge_partials` to combine attention over disjoint key shards
    (ring attention's per-step merge).

    Block defaults were swept on v5e (T=4096 causal fwd+bwd, interleaved
    A/B): 512 beats 128 by ~4x (grid overhead) and the materialised-softmax
    path by ~5x at D=64 / ~10x at D=128; blocks are clamped to the padded
    sequence length so short inputs still work.
    """
    D = q.shape[-1]
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    bias = None
    if kv_mask is not None:
        bias = jnp.where(kv_mask, 0.0, NEG_INF).astype(jnp.float32)
    # Clamp to the sequence length rounded UP to a multiple of 8: block
    # sublane dims must stay 8-divisible for the TPU tiling rule (padding
    # covers the remainder).
    block_q = min(block_q, -(-max(q.shape[1], 1) // 8) * 8)
    block_k = min(block_k, -(-max(k.shape[1], 1) // 8) * 8)
    if return_residuals:
        o, m, l = _fa_core(q, k, v, bias, causal, float(scale), block_q,
                           block_k)
        return o, (m, l)
    # No residuals requested → the blockwise backward kernels apply (the
    # recompute-vjp core is only needed when (m, l) carry cotangents).
    return _fa_core_nores(q, k, v, bias, causal, float(scale), block_q,
                          block_k)


def merge_partials(p1: Tuple, p2: Tuple) -> Tuple:
    """Exactly combine two attention partials over disjoint key sets.

    Each partial is ``(o [B,T,H,D], m [B,H,T], l [B,H,T])`` with ``o``
    normalised by its own ``l`` (a partial that saw zero keys has l == 0 and
    contributes nothing). Returns the combined partial in the same form —
    associative and commutative, so ring steps can fold in any order.
    """
    o1, m1, l1 = p1
    o2, m2, l2 = p2
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(jnp.maximum(m1 - m, NEG_INF)) * l1
    a2 = jnp.exp(jnp.maximum(m2 - m, NEG_INF)) * l2
    l = a1 + a2
    den = jnp.where(l == 0.0, 1.0, l)
    w1 = (a1 / den).transpose(0, 2, 1)[..., None]
    w2 = (a2 / den).transpose(0, 2, 1)[..., None]
    o = o1.astype(jnp.float32) * w1 + o2.astype(jnp.float32) * w2
    return o.astype(o1.dtype), m, l
