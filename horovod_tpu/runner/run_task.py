"""Worker-side bootstrap for the ``horovod_tpu.runner.run()`` function API.

Reference parity: ``horovod/runner/run_task.py`` — the launcher pickles the
user function (cloudpickle), workers exec this module which loads and runs
it, returning the result via a per-process file (the reference returns
results over its task service; a results dir on a shared/local FS is the
launcher-local equivalent).

The multi-host mode (no argv; r4) receives the cloudpickled function in
``HOROVOD_RUN_FUNC_B64`` (the ssh-forwarded env — the reference ships
the fn over its driver/task RPC), allgathers every worker's result over
the engine so rank 0 holds the full list, and rank 0 writes ONE results
blob to ``HOROVOD_RUN_RESULTS_DIR`` on ITS host; the launcher reads it
locally or fetches it over ssh (``api._fetch_remote_results``).
"""

from __future__ import annotations

import os
import sys


def main(fn_path: str = None, results_dir: str = None) -> int:
    import cloudpickle
    env_mode = fn_path is None
    if env_mode:
        import base64
        b64 = os.environ["HOROVOD_RUN_FUNC_B64"]
        i = 1
        while f"HOROVOD_RUN_FUNC_B64_{i}" in os.environ:  # overflow chunks
            b64 += os.environ[f"HOROVOD_RUN_FUNC_B64_{i}"]
            i += 1
        blob = base64.b64decode(b64)
        fn, args, kwargs = cloudpickle.loads(blob)
        results_dir = os.environ["HOROVOD_RUN_RESULTS_DIR"]
    else:
        with open(fn_path, "rb") as f:
            fn, args, kwargs = cloudpickle.load(f)
    import horovod_tpu as hvd
    hvd.init()
    try:
        result = fn(*args, **kwargs)
        code = 0
    except BaseException:
        import traceback
        traceback.print_exc()
        # Ship the formatted traceback as the "result" so the launcher can
        # raise with the real worker error, not just an exit code.
        result, code = traceback.format_exc(), 1
    pid = os.environ.get("HOROVOD_PROCESS_ID", "0")
    if env_mode:
        # Every rank participates in the gather (failed ones contribute
        # their traceback); rank 0 writes the single results blob. The
        # result is CLOUDpickled to bytes BEFORE the gather — the object
        # gather serializes with plain pickle, so a lambda-valued result
        # would otherwise crash the worker outside the traceback path
        # and strand the peers in the collective.
        try:
            blob = cloudpickle.dumps((code, result))
        except Exception:
            import traceback
            code = code or 1
            blob = cloudpickle.dumps(
                (1, "result not picklable:\n" + traceback.format_exc()))
        from horovod_tpu.optimizer import allgather_object
        blobs = allgather_object(blob)
        if hvd.rank() == 0:
            all_results = [cloudpickle.loads(b) for b in blobs]
            os.makedirs(results_dir, exist_ok=True)
            tmp = os.path.join(results_dir, f".results.all.pkl.{os.getpid()}")
            with open(tmp, "wb") as f:
                cloudpickle.dump(all_results, f)
            os.replace(tmp, os.path.join(results_dir, "results.all.pkl"))
        return code
    with open(os.path.join(results_dir, f"result.{pid}.pkl"), "wb") as f:
        cloudpickle.dump((code, result), f)
    return code


if __name__ == "__main__":
    raise SystemExit(main(*sys.argv[1:3]))
