"""Bench-parity regression tests (VERDICT r5 Weak #1).

BENCH_r05's ``vs_baseline`` 0.9631 fell outside the stated ±0.02 band.
The bisect suspicion was that the r5 train.py deferral change
(``make_gspmd_deferred_train_step``) taxed ``make_train_step``. These
tests pin the graph-level facts that rule that out permanently:

1. bench.py's two arms (hvd DistributedOptimizer step vs plain step)
   compile to programs with byte-identical collective-op sets on the
   bench's 1-device mesh — the distributed machinery inserts nothing
   the plain arm doesn't have, so any measured ratio shift is NOISE,
   not graph tax. (The r5 reading was re-attributed to across-session
   tunnel noise; see docs/benchmarks.md "Parity band".)
2. The deferred factory at ``every=1`` emits collective HLO
   byte-identical to the standard GSPMD step it wraps — the deferral
   is graph-level inert at k=1 and cannot tax the standard arms.

Collective HLO is compared post-SPMD-partitioning (``.compile()``):
GSPMD inserts collectives during partitioning, so stablehlo lowering
alone would compare nothing.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute", "all-to-all")


def _collective_signature(compiled) -> list:
    """Sorted (opcode, shape, replica_groups) tuples from optimized HLO —
    instruction ids/channel ids vary run to run, the collective structure
    must not."""
    text = compiled.as_text()
    sig = []
    for line in text.splitlines():
        m = re.search(
            r"=\s+(\S+)\s+(all-reduce|all-gather|reduce-scatter|"
            r"collective-permute|all-to-all)(?:-start)?\(", line)
        if not m:
            continue
        groups = re.search(r"replica_groups=(\{[^}]*\}|\[[^\]]*\][^,)]*)",
                           line)
        sig.append((m.group(2), m.group(1),
                    groups.group(1) if groups else ""))
    return sorted(sig)


def test_bench_arms_collective_hlo_identical():
    """bench.py's hvd arm vs plain arm, exactly as the bench builds them
    (1-device mesh, same model factory, scan_steps): identical collective
    sets — on one chip both must be EMPTY (force_axis_size1 collapses the
    distributed collectives to identity)."""
    import horovod_tpu as hvd
    from horovod_tpu.models import ResNetTiny
    from horovod_tpu.optimizer import distributed
    from horovod_tpu.train import create_train_state, make_train_step

    hvd.init()

    def loss_fn(logits, y):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(4, 32, 32, 3).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 1000, size=(4,)))

    model = ResNetTiny(num_classes=1000, axis_name=hvd.RANK_AXIS,
                       dtype=jnp.float32)
    dopt = distributed(optax.sgd(0.1, momentum=0.9))
    state = create_train_state(model, jax.random.PRNGKey(0), images[:1],
                               dopt)
    mesh1 = jax.sharding.Mesh(np.asarray(jax.devices()[:1]),
                              (hvd.RANK_AXIS,))
    step_hvd = make_train_step(model, dopt, loss_fn, scan_steps=4,
                               mesh=mesh1, donate=False)

    model_p = ResNetTiny(num_classes=1000, axis_name=None,
                         dtype=jnp.float32)
    popt = optax.sgd(0.1, momentum=0.9)
    pstate = create_train_state(model_p, jax.random.PRNGKey(0), images[:1],
                                popt, broadcast=False)
    step_plain = make_train_step(model_p, popt, loss_fn, scan_steps=4,
                                 mesh=mesh1, donate=False)

    sig_hvd = _collective_signature(
        step_hvd.lower(state, images, labels).compile())
    sig_plain = _collective_signature(
        step_plain.lower(pstate, images, labels).compile())
    assert sig_hvd == sig_plain
    assert sig_hvd == []    # 1-chip: the machinery must insert NOTHING


def test_deferred_every1_collective_hlo_identical_to_standard_step():
    """make_gspmd_deferred_train_step(every=1) — the r5 change — lowers
    to collective HLO byte-identical to make_gspmd_train_step over the
    same optimizer on a real 8-way CPU data-parallel mesh."""
    from horovod_tpu.models.llama import LOGICAL_RULES
    from horovod_tpu.models.mixtral import Mixtral, mixtral_tiny
    from horovod_tpu.optimizer import deferred_pair
    from horovod_tpu.parallel import create_mesh
    from horovod_tpu.train import (create_gspmd_train_state,
                                   make_gspmd_deferred_train_step,
                                   make_gspmd_train_step)

    cfg = mixtral_tiny()
    mesh = create_mesh({"dp": 8})
    model = Mixtral(cfg)
    pair = deferred_pair(1e-3, every=1)
    assert pair.every == 1
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))
    state = create_gspmd_train_state(model, pair.apply,
                                     jax.random.PRNGKey(0), tokens, mesh,
                                     LOGICAL_RULES)

    standard = make_gspmd_train_step(model, pair.apply, mesh,
                                     LOGICAL_RULES, donate=False)
    deferred = make_gspmd_deferred_train_step(model, pair, mesh,
                                              LOGICAL_RULES, donate=False)

    sig_std = _collective_signature(
        standard.lower(state, tokens).compile())
    sig_dfr = _collective_signature(
        deferred.lower_apply(state, tokens).compile())
    assert sig_std, "8-way DP step must contain collectives"
    assert sig_dfr == sig_std
    # every=1 means EVERY dispatch is the apply program — the skip program
    # never runs, so the deferred step and the standard step execute the
    # same collective graph every step.
    from horovod_tpu.train import GSPMDTrainState
    assert isinstance(state, GSPMDTrainState)
