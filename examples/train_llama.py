"""Llama fine-tuning over a dp×sp×tp GSPMD mesh (BASELINE config 3).

Reference analog: the reference's language-model scripts
(``examples/pytorch/pytorch_synthetic_benchmark.py`` pattern) are DP-only —
the model must fit one GPU. The TPU-native rebuild shards the model itself:
params carry logical axis names (``models/llama.py LOGICAL_RULES``), tokens
shard batch-over-dp and sequence-over-sp, and XLA inserts every collective
— including the DP gradient psum the reference needed its whole runtime
for (SURVEY.md §7 "architecture stance").

Run (single host, all local devices, axes auto-factored):
    python examples/train_llama.py --steps 20
CPU smoke test (8 virtual devices, dp2×sp2×tp2):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_llama.py --model tiny --dp 2 --sp 2 --tp 2 \
        --batch-size 4 --seq-len 64 --steps 3

Layer-loop trade (``LlamaConfig.scan_layers``): the default "auto" unrolls
small configs (n_layers ≤ 8 — this script's tiny model, fast compile AND
fast steps) and scans big ones (llama3_8b — bounded compile time). The
HEADLINE bench numbers (docs/benchmarks.md r5) run ``scan_layers=False``
(unrolled) even at 32 layers: +13% step throughput for ~3x compile time.
Pass an explicit True/False to pin the choice — it is checkpoint-visible
(scan stacks params under one "layers" node; unrolled uses block_i).
"""

import argparse
import time

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(
    _os.path.abspath(__file__))))  # run in-repo without pip install

from horovod_tpu.platform import honor_jax_platforms_env
honor_jax_platforms_env()

import jax
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models.llama import (LOGICAL_RULES, Llama, llama3_8b,
                                      llama_tiny)
from horovod_tpu.parallel import create_mesh
from horovod_tpu.train import create_gspmd_train_state, make_gspmd_train_step

MODELS = {"llama3-8b": llama3_8b, "tiny": llama_tiny}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="tiny", choices=MODELS)
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel axis size (0 = all devices)")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel axis size")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel axis size")
    p.add_argument("--batch-size", type=int, default=8,
                   help="global batch (sequences per step)")
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--attention-impl", choices=["auto", "ring", "ulysses"],
                   default="auto",
                   help="context-parallel attention over the sp axis "
                        "(docs/long-context.md); auto = dense/flash")
    p.add_argument("--remat-policy",
                   choices=["default", "full", "dots", "dots_attn", "attn"],
                   default="default",
                   help="checkpoint policy; attn/dots_attn save the "
                        "flash residuals so the backward skips the "
                        "fwd-kernel re-run (docs/benchmarks.md, remat "
                        "section)")
    args = p.parse_args()

    hvd.init()
    n = hvd.size()
    dp = args.dp or max(1, n // (args.sp * args.tp))
    if dp * args.sp * args.tp != n:
        raise SystemExit(f"dp*sp*tp = {dp}*{args.sp}*{args.tp} != {n} devices")
    mesh = create_mesh({"dp": dp, "sp": args.sp, "tp": args.tp})

    import dataclasses
    cfg = MODELS[args.model]()
    if args.attention_impl != "auto":
        cfg = dataclasses.replace(cfg, attention_impl=args.attention_impl)
    if args.remat_policy != "default":
        cfg = dataclasses.replace(cfg, remat=True,
                                  remat_policy=args.remat_policy)
    model = Llama(cfg)
    opt = optax.adamw(args.lr, weight_decay=0.01)

    rng = np.random.RandomState(0)
    tokens = np.asarray(rng.randint(1, cfg.vocab_size,
                                    (args.batch_size, args.seq_len)))

    state = create_gspmd_train_state(model, opt, jax.random.PRNGKey(0),
                                     tokens, mesh, LOGICAL_RULES)
    step = make_gspmd_train_step(model, opt, mesh, LOGICAL_RULES,
                                 data_axes=("dp",), seq_axis="sp")

    print(f"mesh dp={dp} sp={args.sp} tp={args.tp} "
          f"platform={jax.devices()[0].platform} model={args.model}")
    for _ in range(args.warmup):
        state, loss = step(state, tokens)
    if args.warmup:
        float(np.asarray(loss))  # sync
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, loss = step(state, tokens)
    final_loss = float(np.asarray(loss))
    dt = time.perf_counter() - t0
    tps = args.batch_size * args.seq_len * args.steps / dt
    print(f"loss={final_loss:.4f} tokens/sec={tps:.0f} "
          f"tokens/sec/chip={tps / n:.0f} step_ms={dt / args.steps * 1e3:.1f}")


if __name__ == "__main__":
    main()
