"""Elastic state objects: commit / restore / sync.

Reference parity (SURVEY.md §3.4, §5.3/§5.4): ``horovod/common/elastic.py``
(``State``, ``ObjectState``) and ``horovod/torch/elastic/state.py``
(``TorchState``). Semantics preserved:

- ``commit()`` — snapshot the state (the in-memory checkpoint the training
  loop rolls back to after a failure) and check for host updates.
- ``restore()`` — roll back to the last commit (after
  ``HorovodInternalError``).
- ``sync()`` — make every worker identical to rank 0 (after membership
  change, when no rollback is needed).
- reset callbacks — user hooks run after a re-initialisation (the reference
  uses these to rebuild samplers/optimizers for the new world size).

TPU deltas:

- Snapshots are **host copies** (``jax.device_get``) of array pytrees:
  device buffers die with the mesh on reset, host snapshots do not.
- When ``HOROVOD_ELASTIC_COMMIT_DIR`` is set (the elastic driver always
  sets it), ``commit()`` also persists the snapshot to disk atomically —
  on EVERY process, each to its own local disk, so losing any host (even
  the one that was process 0) leaves survivors a restore point; restores
  pick the newest commit across the relaunched world. This is what makes
  **process-restart elasticity** (the TPU-true mode — see
  elastic/run_fn.py) lossless: a relaunched generation restores the latest
  commit instead of starting over. The reference keeps commits purely
  in-memory because its workers survive resets; ours may not.
- ``JaxState`` is the ``TorchState`` analog holding ``params``/``opt_state``
  pytrees plus arbitrary scalar attrs (epoch, batch, ...).
- Commits are **pipelined and content-addressed** (PR 9): ``commit()``
  takes a cheap on-device copy and returns; a double-buffered background
  writer (:class:`_CommitWriter`) overlaps the device→host transfer and
  serialization with subsequent steps, stores each pytree leaf as a
  blake2b-addressed blob (``checkpoint/store.py`` :class:`BlobStore` —
  unchanged leaves dedup across commits and across ranks sharing the
  directory), and publishes one small manifest atomically LAST. The step
  loop only ever blocks on BACK-PRESSURE — the previous commit still in
  flight (``hvd_commit_stall_seconds``). ``HOROVOD_COMMIT_ASYNC=0``
  restores the inline write. Legacy single-frame commits
  (``state.latest.pkl``/``state.prev.pkl``) still restore; the
  newest→oldest fallback walk now spans manifests first, then frames
  (docs/checkpointing.md).
"""

from __future__ import annotations

import copy
import hashlib
import hmac
import os
import pickle
import random
import tempfile
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

import jax

from ..core import telemetry as _telemetry
from ..core.exceptions import HostsUpdatedInterrupt
from ..core.logging import get_logger
from . import constants as C


class WorkerNotificationManager:
    """Commit-time membership watcher (worker side).

    Reference parity: ``horovod/runner/elastic/worker.py``'s
    WorkerNotificationManager, with the push inverted into a rate-limited
    poll of the driver's coordinator service (see elastic/service.py).

    Pod-scale cadence (benchmarks/control_plane.py): SPMD commits happen
    in lockstep (collectives synchronize the steps), so N workers whose
    rate-limiters all expire together poll the coordinator on aligned
    ticks — a thundering herd every interval. The gap to the next allowed
    poll is therefore drawn per-worker as ``interval * uniform(1-j, 1+j)``
    (``HOROVOD_ELASTIC_POLL_JITTER``, decorrelated: each gap independent),
    and the interval itself stretches to the server-advertised ``poll_s``
    pacing so aggregate request rate stays ~flat as the world grows. The
    FIRST poll of a generation stays immediate — a membership bump that
    predates the launch must be observed at the first commit, not an
    interval later. ``_clock``/``_rng`` are injectable (fake-clock tests).
    """

    def __init__(self):
        self._client = None
        self._launch_version: Optional[int] = None
        self._next_poll_due = 0.0    # 0 = first check() polls immediately
        self._poll_interval_s = C.DEFAULT_POLL_INTERVAL_S
        self._jitter = C.DEFAULT_POLL_JITTER
        self._pending = False
        self._lock = threading.Lock()
        self._clock: Callable[[], float] = time.monotonic
        self._rng = random.Random()

    def init_from_env(self) -> None:
        addr = os.environ.get(C.COORD_ADDR_ENV)
        if not addr or self._client is not None:
            return
        from ..runner import secret as _secret
        key_s = os.environ.get(_secret.ENV_VAR)
        if not key_s:
            return
        from .service import CoordinatorClient
        self._client = CoordinatorClient(addr, _secret.decode(key_s))
        v = os.environ.get(C.WORLD_VERSION_ENV)
        self._launch_version = int(v) if v else None
        iv = os.environ.get(C.POLL_INTERVAL_ENV)
        if iv:
            try:
                # The driver pins this to its discovery cadence so a short
                # generation (few commits) still observes a mid-run bump.
                self._poll_interval_s = float(iv)
            except ValueError:
                pass
        jv = os.environ.get(C.POLL_JITTER_ENV)
        if jv:
            try:
                self._jitter = max(0.0, float(jv))
            except ValueError:
                pass

    def _schedule_next_poll(self, now: float) -> None:
        """Earliest next poll: the configured interval stretched to the
        server's advertised pacing, jittered so lockstep workers drift
        apart instead of herding on aligned ticks. Caller holds the lock."""
        interval = self._poll_interval_s
        adv = getattr(self._client, "advertised_poll_s", None)
        if adv:
            interval = max(interval, float(adv))
        if self._jitter > 0:
            gap = interval * self._rng.uniform(1.0 - self._jitter,
                                               1.0 + self._jitter)
        else:
            gap = interval
        self._next_poll_due = now + max(gap, 0.0)

    def check(self) -> None:
        """Raise HostsUpdatedInterrupt if membership moved past the version
        this worker generation was launched with."""
        with self._lock:
            if self._pending:
                self._pending = False
                raise HostsUpdatedInterrupt()
            if self._client is None or self._launch_version is None:
                return
            now = self._clock()
            if now < self._next_poll_due:
                return
            self._schedule_next_poll(now)
            from ..core.exceptions import HorovodInternalError
            from .service import CoordinatorLostError
            try:
                world = self._client.get_world()
            except CoordinatorLostError as e:
                # Persistent control-plane loss (the retrying client's
                # continuous-failure window elapsed): escalate instead of
                # treating a dead driver as "no change" forever. The step
                # monitor is marked first so heartbeats/observers see WHY,
                # then HorovodInternalError unwinds to @elastic.run —
                # restart-exit under a (possibly restarted) driver, or an
                # in-process reset attempt standalone.
                get_logger().error("%s", e)
                from ..core.watchdog import monitor
                monitor().notify_control_plane_lost(str(e))
                raise HorovodInternalError(str(e)) from e
            # Piggyback the compact metrics delta on the poll this commit
            # already paid for — the coordinator aggregates it for
            # GET /metrics. Best-effort: cumulative values mean a dropped
            # push is healed by the next one.
            delta = _telemetry.export_delta()
            if delta is not None:
                try:
                    self._client.push_metrics(_telemetry.active().rank,
                                              delta)
                except Exception as push_err:  # noqa: BLE001
                    get_logger().debug("telemetry push skipped: %s",
                                       push_err)
            if world is not None and world["version"] > self._launch_version:
                get_logger().info(
                    "membership version %d > launch version %d: hosts updated",
                    world["version"], self._launch_version)
                # Don't re-raise forever on subsequent checks: the interrupt
                # fires once per observed change.
                self._launch_version = world["version"]
                raise HostsUpdatedInterrupt()

    def signal(self) -> None:
        """Inject a host-update (tests / in-process driver)."""
        with self._lock:
            self._pending = True

    def register(self) -> bool:
        """Announce this worker to the driver (reference:
        registration.py last-seen bookkeeping; feeds the driver's
        ``registered_workers`` observability view). The client retries
        under the RPC backoff policy; a False return is logged here AND
        surfaces driver-side when the start-timeout trips (the driver
        names workers that never registered)."""
        with self._lock:
            if self._client is None:
                return True
            pid = os.environ.get("HOROVOD_PROCESS_ID")
            if pid is None:
                return True
            ok = self._client.register(int(pid))
        if not ok:
            get_logger().warning(
                "worker registration with the coordinator failed after "
                "retries (process_id=%s) — the driver will log this "
                "worker as never-registered at its start-timeout", pid)
        return ok


notification_manager = WorkerNotificationManager()


class State:
    """Base state machinery (reference: common/elastic.py State)."""

    def __init__(self):
        self._reset_callbacks: List[Callable[[], None]] = []

    def register_reset_callbacks(self,
                                 callbacks: List[Callable[[], None]]) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def reset(self) -> None:
        """Override: rebuild world-size-dependent members."""

    def commit(self) -> None:
        self.save()
        self.check_host_updates()

    def check_host_updates(self) -> None:
        # Preemption check first, and before the rate-limited poll: the
        # lifecycle flag is a local attribute read (no RPC), and commit()
        # already ran save() — so the commit that carried us to this seam
        # IS the out-of-cadence commit the preemption grace window buys.
        from ..core import lifecycle as _lifecycle
        if _lifecycle.preempt_requested():
            from ..core.exceptions import PreemptionInterrupt
            raise PreemptionInterrupt(_lifecycle.preempt_signum())
        notification_manager.init_from_env()
        notification_manager.check()

    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError


def _commit_path(commit_dir: str) -> str:
    return os.path.join(commit_dir, "state.latest.pkl")


def _prev_commit_path(commit_dir: str) -> str:
    return os.path.join(commit_dir, "state.prev.pkl")


#: Commit-integrity trailer: <pickle body><16-byte blake2b digest><magic>.
#: The magic goes LAST so a truncation — the dominant real-world corruption
#: (full disk, killed writer, chopped copy) — always destroys it and the
#: file is recognizably damaged rather than mis-verified.
_CHECK_MAGIC = b"HVDCK1\n"
_CHECK_DIGEST_SIZE = 16


def _frame(body: bytes) -> bytes:
    digest = hashlib.blake2b(body, digest_size=_CHECK_DIGEST_SIZE).digest()
    return body + digest + _CHECK_MAGIC


def _unframe(blob: bytes) -> Optional[bytes]:
    """Verified pickle body, or None when the checksum fails. Files without
    the trailer (pre-integrity commits) are accepted as-is — their only
    protection is pickle's own parse errors, exactly the legacy behavior."""
    if not blob.endswith(_CHECK_MAGIC):
        return blob
    body = blob[:-(len(_CHECK_MAGIC) + _CHECK_DIGEST_SIZE)]
    digest = blob[len(body):-len(_CHECK_MAGIC)]
    want = hashlib.blake2b(body, digest_size=_CHECK_DIGEST_SIZE).digest()
    return body if hmac.compare_digest(digest, want) else None


def _persist(commit_dir: str, payload: Dict[str, Any]) -> None:
    """Atomic write (tmp + rename) so a crash mid-commit never corrupts the
    restore point, with a checksum trailer and one-deep rotation: the
    previous committed generation survives as ``state.prev.pkl`` so
    ``load_persisted`` can fall back when the newest commit fails
    verification (docs/failure_model.md — corruption containment).

    EVERY process persists to its own local disk (the commit_dir path is
    per-host), so losing any host — including the one that was process 0 —
    leaves survivors with a usable restore point; ``load_persisted_world``
    picks the newest across the relaunched world.
    """
    os.makedirs(commit_dir, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=commit_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(_frame(pickle.dumps(payload)))
        latest = _commit_path(commit_dir)
        if os.path.exists(latest):
            # Rotate BEFORE replacing: latest is still intact here, so the
            # fallback is always a fully-written commit.
            os.replace(latest, _prev_commit_path(commit_dir))
        os.replace(tmp, latest)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _load_verified(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path, "rb") as f:
            blob = f.read()
        body = _unframe(blob)
        if body is None:
            get_logger().error(
                "commit %s failed checksum verification — ignoring it",
                path)
            return None
        return pickle.loads(body)
    except (OSError, pickle.UnpicklingError, EOFError):
        return None


# ---------------------------------------------------------------------------
# Content-addressed commits: per-leaf blobs + manifest (checkpoint/store.py)
# ---------------------------------------------------------------------------

_CAS_SUBDIR = "cas"


class _LeafRef:
    """Placeholder leaf inside a pickled pytree *skeleton*: an index into
    the manifest's leaf-blob list. Pickling the skeleton (the original
    containers with ``_LeafRef`` leaves) instead of a ``PyTreeDef`` keeps
    the on-disk format independent of jax's treedef pickling across the
    versions compat.py bridges."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __getstate__(self):
        return self.index

    def __setstate__(self, state):
        self.index = state


def _cas_store(commit_dir: str):
    from ..checkpoint.store import BlobStore
    return BlobStore(os.path.join(commit_dir, _CAS_SUBDIR))


def _checkpoint_keep() -> int:
    try:
        return int(os.environ.get(C.CHECKPOINT_KEEP_ENV,
                                  str(C.DEFAULT_CHECKPOINT_KEEP)))
    except ValueError:
        return C.DEFAULT_CHECKPOINT_KEEP


def _commit_async_default() -> bool:
    return os.environ.get(C.COMMIT_ASYNC_ENV, "1").lower() \
        not in ("0", "false", "off")


#: Live commit writers, so a same-process reader (tests; the in-process
#: elastic mode) can drain in-flight writes before walking the store.
_WRITERS: "weakref.WeakSet[_CommitWriter]" = weakref.WeakSet()


def _flush_writers_for(commit_dir: str,
                       timeout: Optional[float] = 60.0) -> None:
    for w in list(_WRITERS):
        if w.commit_dir == commit_dir:
            w.flush(timeout=timeout)


#: Post-commit hooks: ``fn(commit_dir, seq)`` called on the WRITER thread
#: after each manifest publish + retention sweep. The serving publisher
#: (serving/publisher.py) attaches its publish gate here so gate work
#: (manifest read-back, blob re-hash) runs off the step loop. Hook
#: exceptions are logged and swallowed — a broken hook must never kill
#: the commit writer.
_COMMIT_HOOKS: List[Callable[[str, int], None]] = []


def register_commit_hook(fn: Callable[[str, int], None]):
    """Register a post-commit hook; returns ``fn`` (decorator-friendly)."""
    _COMMIT_HOOKS.append(fn)
    return fn


def unregister_commit_hook(fn: Callable[[str, int], None]) -> bool:
    try:
        _COMMIT_HOOKS.remove(fn)
        return True
    except ValueError:
        return False


def _fire_commit_hooks(commit_dir: str, seq: int) -> None:
    for fn in list(_COMMIT_HOOKS):
        try:
            fn(commit_dir, seq)
        except Exception as err:    # noqa: BLE001 — must not kill the writer
            get_logger().error(
                "post-commit hook %r failed (seq=%s): %s", fn, seq, err)


class _CommitWriter:
    """Double-buffered background persister for one state object.

    ``submit()`` is the step-path half: consult the identity cache
    (an array leaf that is literally the SAME immutable ``jax.Array``
    object as last commit reuses its digest — zero transfer, zero
    serialization), take cheap on-device copies of changed array leaves
    and start their device→host DMA, then enqueue. The only blocking the
    step loop ever sees is back-pressure: the previous commit still in
    flight (depth-1 double buffer). The on-device copy — not the live
    array — is what the writer later reads, so donating the live buffer
    to the next jitted step cannot invalidate the snapshot.

    The writer half (a lazily-started daemon thread that exits when
    idle) finishes the host transfer, pickles each leaf, stores blobs by
    content address and publishes the manifest atomically LAST, then
    retention-sweeps (``HOROVOD_CHECKPOINT_KEEP``). A crash anywhere
    before the publish leaves the previous manifest as the restore point
    — never a torn one.
    """

    _IDLE_EXIT_S = 5.0

    def __init__(self, commit_dir: str, async_enabled: bool):
        self.commit_dir = commit_dir
        self.async_enabled = async_enabled
        self.store = _cas_store(commit_dir)
        self._cond = threading.Condition()
        self._job: Optional[Dict[str, Any]] = None
        self._thread: Optional[threading.Thread] = None
        self._last_error: Optional[BaseException] = None
        self._cache_treedef = None          # identity cache validity key
        self._cache: List[tuple] = []       # (leaf_ref|None, digest, nbytes)
        self._last_host_leaves: List[Any] = []
        _WRITERS.add(self)

    # -- step-path half ------------------------------------------------------

    @staticmethod
    def _device_copy(leaf):
        """Cheap asynchronous on-device copy with its host DMA started."""
        import jax.numpy as jnp
        try:
            snap = jnp.copy(leaf)
        except Exception:          # noqa: BLE001 — odd array types: live ref
            snap = leaf
        try:
            snap.copy_to_host_async()
        except Exception:          # noqa: BLE001 — optional fast path only
            pass
        return snap

    def submit(self, seq: int, payload: Dict[str, Any],
               on_snapshot: Optional[Callable[[Dict[str, Any]], None]] = None
               ) -> None:
        t0 = time.perf_counter()
        with self._cond:
            while self._job is not None:    # back-pressure: depth-1 buffer
                self._cond.wait()
        leaves, treedef = jax.tree_util.tree_flatten(payload)
        cache_ok = (self._cache_treedef is not None
                    and treedef == self._cache_treedef
                    and len(self._cache) == len(leaves)
                    and len(self._last_host_leaves) == len(leaves))
        plans = []
        for i, leaf in enumerate(leaves):
            if cache_ok and isinstance(leaf, jax.Array):
                prev_leaf, digest, nbytes = self._cache[i]
                if prev_leaf is leaf:
                    plans.append(("cached", digest, nbytes, leaf))
                    continue
            if isinstance(leaf, jax.Array):
                plans.append(("fetch", self._device_copy(leaf), leaf))
            else:
                plans.append(("host", copy.deepcopy(leaf), leaf))
        job = {"seq": int(seq), "treedef": treedef, "plans": plans,
               "on_snapshot": on_snapshot}
        if not self.async_enabled:
            try:
                self._run_job(job)
            finally:
                _telemetry.observe("hvd_commit_stall_seconds",
                                   time.perf_counter() - t0)
            return
        with self._cond:
            self._job = job
            _telemetry.set_gauge("hvd_commit_inflight", 1.0)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._writer_loop, name="hvd-commit-writer",
                    daemon=True)
                self._thread.start()
            self._cond.notify_all()
        _telemetry.observe("hvd_commit_stall_seconds",
                           time.perf_counter() - t0)

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait until no commit is in flight; False on timeout or when
        the last background write failed (already logged)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._job is not None:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
            ok = self._last_error is None
            self._last_error = None
            return ok

    # -- writer half ---------------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            with self._cond:
                deadline = time.monotonic() + self._IDLE_EXIT_S
                while self._job is None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return      # idle: exit; the next submit restarts us
                    self._cond.wait(timeout=remaining)
                job = self._job
            try:
                self._run_job(job)
            except BaseException as err:    # noqa: BLE001 — must not die
                self._last_error = err
                _telemetry.inc("hvd_commit_write_failures_total")
                get_logger().error(
                    "async commit write failed (seq=%s): %s — the previous "
                    "manifest remains the restore point",
                    job.get("seq"), err)
            finally:
                with self._cond:
                    self._job = None
                    _telemetry.set_gauge("hvd_commit_inflight", 0.0)
                    self._cond.notify_all()

    def _run_job(self, job: Dict[str, Any]) -> None:
        import numpy as np
        t0 = time.perf_counter()
        host_leaves: List[Any] = []
        entries: List[list] = []
        new_cache: List[tuple] = []
        bytes_written = bytes_deduped = 0
        for i, plan in enumerate(job["plans"]):
            kind = plan[0]
            if kind == "cached":
                _, digest, nbytes, orig = plan
                host_leaves.append(self._last_host_leaves[i])
                entries.append([digest, nbytes])
                bytes_deduped += nbytes
                new_cache.append((orig, digest, nbytes))
                continue
            if kind == "fetch":
                _, dev, orig = plan
                val = np.asarray(jax.device_get(dev))
            else:
                _, val, orig = plan
            blob = pickle.dumps(val, protocol=4)
            digest, wrote = self.store.put_blob(blob)
            if wrote:
                bytes_written += len(blob)
            else:
                bytes_deduped += len(blob)
            host_leaves.append(val)
            entries.append([digest, len(blob)])
            # Only IMMUTABLE leaves join the identity cache — a mutated
            # numpy buffer keeps its object id and must re-hash.
            new_cache.append((orig if isinstance(orig, jax.Array) else None,
                              digest, len(blob)))
        skeleton = jax.tree_util.tree_unflatten(
            job["treedef"], [_LeafRef(i) for i in range(len(entries))])
        skel_blob = pickle.dumps(skeleton, protocol=4)
        skel_digest, wrote = self.store.put_blob(skel_blob)
        if wrote:
            bytes_written += len(skel_blob)
        else:
            bytes_deduped += len(skel_blob)
        try:
            topo = {"process_index": jax.process_index(),
                    "process_count": jax.process_count()}
        except Exception:           # noqa: BLE001 — metadata only
            topo = {}
        # Chaos seam (testing/faults.py `torn` kind): die HERE — blobs
        # durable, manifest not yet published — to prove restores land on
        # the previous complete manifest, never a mixed one.
        if os.environ.get("HOROVOD_FAULT_SPEC"):
            from ..testing import faults as _faults
            _faults.maybe_torn_commit()
        self.store.publish_manifest({
            "seq": job["seq"], "skeleton": skel_digest, "leaves": entries,
            "topology": topo,
        })
        self.store.gc(_checkpoint_keep())
        self._cache_treedef = job["treedef"]
        self._cache = new_cache
        self._last_host_leaves = host_leaves
        _telemetry.inc("hvd_checkpoint_bytes_written_total", bytes_written)
        _telemetry.inc("hvd_checkpoint_bytes_deduped_total", bytes_deduped)
        _telemetry.set_gauge("hvd_last_manifest_seq", float(job["seq"]))
        _telemetry.observe("hvd_commit_write_seconds",
                           time.perf_counter() - t0)
        _telemetry.record_event("manifest_publish", seq=job["seq"],
                                bytes_written=bytes_written,
                                bytes_deduped=bytes_deduped)
        if job["on_snapshot"] is not None:
            job["on_snapshot"](jax.tree_util.tree_unflatten(
                job["treedef"], host_leaves))
        _fire_commit_hooks(self.commit_dir, int(job["seq"]))


def _path_name(entry) -> str:
    """One jax tree-path entry as a plain name (DictKey.key /
    GetAttrKey.name / SequenceKey.idx) — the shared leaf-keying scheme of
    the per-shard CAS layer (serving/publisher.py writes ``shards`` with
    it; the registry and the resume path select with it)."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _select_parts(manifest: Dict[str, Any], digest: str, names: tuple,
                  shard_selector) -> Optional[List[int]]:
    """Part indices of leaf ``digest`` the target sharding wants, or None
    for the whole-leaf blob (no shards entry, no selector, or the
    selector declined — e.g. the manifest was sharded for a DIFFERENT
    topology and read-compatibility demands the whole-leaf fallback)."""
    if shard_selector is None:
        return None
    meta = (manifest.get("shards") or {}).get(digest)
    if meta is None:
        return None
    sel = shard_selector(names, meta)
    if sel is None:
        return None
    return [int(i) for i in sel] or None


def _manifest_need(store, manifest: Dict[str, Any],
                   shard_selector=None) -> List[str]:
    """The digests THIS rank must hold to materialize the manifest under
    ``shard_selector`` — whole-leaf blobs by default; for a shard-selected
    leaf only the selected PART blobs (the topology-change delta: a
    resharded target pulls its slices, never the whole tensor). Requires
    the skeleton blob to be local (fetch it first)."""
    skeleton = pickle.loads(store.get_blob(manifest["skeleton"]))
    flat, _ = jax.tree_util.tree_flatten_with_path(skeleton)
    entries = manifest["leaves"]
    need: List[str] = [manifest["skeleton"]]
    for path, ref in flat:
        if not isinstance(ref, _LeafRef):
            raise ValueError("manifest skeleton holds a non-ref leaf "
                             f"({type(ref).__name__})")
        digest = entries[ref.index][0]
        names = tuple(_path_name(p) for p in path)
        sel = _select_parts(manifest, digest, names, shard_selector)
        if sel is None:
            need.append(digest)
        else:
            meta = manifest["shards"][digest]
            need.extend(meta["parts"][i][0] for i in sel)
    return list(dict.fromkeys(need))


def _unpack_manifest(store, manifest: Dict[str, Any],
                     shard_selector=None) -> Dict[str, Any]:
    """Materialize a payload from a manifest. Every blob read re-hashes
    against its content address (verify-at-restore); a mismatch raises
    ``BlobIntegrityError`` upward and the caller walks to an older
    manifest. With ``shard_selector`` (topology-change restore), a leaf
    with a manifest ``shards`` entry the selector claims is assembled
    from its selected PART blobs (concatenated along the shard axis,
    mirroring serving/registry.py ``_materialize``) instead of the
    whole-leaf blob."""
    skeleton = pickle.loads(store.get_blob(manifest["skeleton"]))
    if shard_selector is None:
        refs, treedef = jax.tree_util.tree_flatten(skeleton)
        entries = manifest["leaves"]
        leaves = []
        for ref in refs:
            if not isinstance(ref, _LeafRef):
                raise ValueError("manifest skeleton holds a non-ref leaf "
                                 f"({type(ref).__name__})")
            leaves.append(pickle.loads(store.get_blob(entries[ref.index][0])))
        return jax.tree_util.tree_unflatten(treedef, leaves)
    import numpy as np
    flat, treedef = jax.tree_util.tree_flatten_with_path(skeleton)
    entries = manifest["leaves"]
    leaves = []
    for path, ref in flat:
        if not isinstance(ref, _LeafRef):
            raise ValueError("manifest skeleton holds a non-ref leaf "
                             f"({type(ref).__name__})")
        digest = entries[ref.index][0]
        names = tuple(_path_name(p) for p in path)
        sel = _select_parts(manifest, digest, names, shard_selector)
        if sel is None:
            leaves.append(pickle.loads(store.get_blob(digest)))
            continue
        meta = manifest["shards"][digest]
        parts = [np.asarray(pickle.loads(
            store.get_blob(meta["parts"][i][0]))) for i in sel]
        leaves.append(parts[0] if len(parts) == 1 else np.concatenate(
            parts, axis=int(meta.get("axis", 0))))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _load_cas(commit_dir: str):
    """Newest readable content-addressed commit: ``(payload, manifest)``
    or ``(None, None)``. Digest mismatches and torn manifests are LOUD
    (error log) and fall back to the previous complete manifest."""
    from ..checkpoint.store import BlobIntegrityError
    store = _cas_store(commit_dir)
    for seq in reversed(store.manifest_seqs()):
        manifest = store.read_manifest(seq)
        if manifest is None:
            get_logger().error(
                "commit manifest %d in %s is torn/unreadable — falling "
                "back to an older manifest", seq, commit_dir)
            continue
        try:
            return _unpack_manifest(store, manifest), manifest
        except BlobIntegrityError as err:
            get_logger().error(
                "commit manifest %d failed content-address verification "
                "(%s) — falling back to an older manifest", seq, err)
        except Exception as err:    # noqa: BLE001 — missing blob, bad pickle
            get_logger().error(
                "commit manifest %d unreadable (%s) — falling back to an "
                "older manifest", seq, err)
    return None, None


#: commit dirs whose legacy-frame restore already logged the one-time
#: migration note.
_MIGRATION_NOTED: set = set()


def _load_local_commit(commit_dir: str) -> Optional[Dict[str, Any]]:
    """Newest verified LOCAL commit with its provenance:
    ``{"payload", "seq", "manifest"}`` (``manifest`` None for legacy
    single-frame commits), or None."""
    _flush_writers_for(commit_dir)
    cas_payload, manifest = _load_cas(commit_dir)
    legacy = _load_verified(_commit_path(commit_dir))
    if legacy is None:
        legacy = _load_verified(_prev_commit_path(commit_dir))
        if legacy is not None and cas_payload is None:
            get_logger().warning(
                "newest commit in %s unreadable — falling back to the "
                "previous committed generation (seq=%s)", commit_dir,
                legacy.get("seq"))
    if cas_payload is None and legacy is None:
        return None
    use_legacy = cas_payload is None or (
        legacy is not None
        and int(legacy.get("seq", 0)) > int(cas_payload.get("seq", 0)))
    if use_legacy:
        if commit_dir not in _MIGRATION_NOTED:
            _MIGRATION_NOTED.add(commit_dir)
            get_logger().info(
                "restored a legacy single-frame commit from %s (seq=%s); "
                "future commits write the content-addressed store under "
                "%s/%s — the frames stay readable but are ignored once a "
                "newer manifest exists", commit_dir, legacy.get("seq"),
                commit_dir, _CAS_SUBDIR)
        return {"payload": legacy, "seq": int(legacy.get("seq", 0)),
                "manifest": None}
    return {"payload": cas_payload, "seq": int(cas_payload.get("seq", 0)),
            "manifest": manifest}


def load_persisted(commit_dir: str) -> Optional[Dict[str, Any]]:
    """The newest VERIFIED local commit: content-addressed manifests
    preferred, legacy single-frame commits (``state.latest.pkl`` /
    ``state.prev.pkl``) still restored via the same newest→oldest walk."""
    local = _load_local_commit(commit_dir)
    return None if local is None else local["payload"]


#: Per-rank accounting of the last peer-sourced resume (bytes fetched,
#: retries, per-source blob counts, topology delta) — chaos workers and
#: the byte-accounting tests read it after ``load_latest``.
_LAST_RESUME_STATS: Dict[str, Any] = {}


def last_resume_stats() -> Dict[str, Any]:
    """Accounting of this process's most recent ``load_persisted_world``
    peer fetch (empty before the first resume)."""
    return dict(_LAST_RESUME_STATS)


def load_persisted_world(commit_dir: str,
                         shard_selector=None) -> Optional[Dict[str, Any]]:
    """The newest persisted commit across ALL processes of the (re)launched
    world. A relaunched generation may have a different process 0 whose
    disk never saw a commit (lost-host recovery); every process reports its
    local commit sequence number and the highest one wins.

    Fault-tolerant peer-sourced resume (elastic/blobmesh.py): the winning
    rank ships only its small MANIFEST; every rank then materializes
    leaves from its LOCAL blob store (shared disks and peer-identical
    content make most blobs local hits) and fetches ONLY ITS OWN missing
    digests point-to-point from digest-elected peers — sources spread
    across every rank that possesses a blob (the former single owner is
    just a tie-break), with retry/backoff, re-election away from dead or
    corrupt sources, and the whole resume bounded by
    ``HOROVOD_RESUME_TIMEOUT_SECONDS``. With ``shard_selector``
    (topology-change restore — regrown process count, reshaped tp), a
    leaf carried in the manifest ``shards`` map moves as the selected
    PART blobs only; mismatched plans fall back to the whole-leaf blob.
    Legacy single-frame owners fall back to the upstream-style
    whole-payload broadcast-on-reset."""
    local = _load_local_commit(commit_dir) if commit_dir else None
    if jax.process_count() == 1:
        if local is None:
            return None
        if shard_selector is not None and local["manifest"] is not None:
            return _unpack_manifest(_cas_store(commit_dir),
                                    local["manifest"], shard_selector)
        return local["payload"]
    import numpy as np
    from jax.experimental import multihost_utils
    from ..optimizer.functions import allgather_object, broadcast_object
    from . import blobmesh as _mesh
    t_start = time.monotonic()
    deadline_s = _mesh.resume_deadline_s()
    deadline = None if deadline_s <= 0 else t_start + deadline_s
    seq = -1 if local is None else int(local["seq"])
    seqs = multihost_utils.process_allgather(np.asarray([seq], np.int64))
    seqs = np.asarray(seqs).reshape(-1)
    owner = int(np.argmax(seqs))
    if seqs[owner] < 0:
        return None
    me = jax.process_index()
    head = broadcast_object(
        None if local is None else {"seq": local["seq"],
                                    "manifest": local["manifest"]},
        root_rank=owner)
    if head is None:
        return None
    manifest = head.get("manifest")
    if manifest is None:
        # Legacy single-frame owner: whole-payload broadcast (upstream's
        # elastic broadcast-on-reset, PARITY.md).
        return broadcast_object(
            None if local is None else local["payload"], root_rank=owner)
    store = _cas_store(commit_dir)
    topo = manifest.get("topology") or {}
    topo_np = int(topo.get("process_count", 0) or 0)
    if topo_np and topo_np != jax.process_count():
        get_logger().info(
            "topology-change restore: manifest seq=%s committed by a "
            "%d-process world, restoring into %d processes",
            manifest.get("seq"), topo_np, jax.process_count())
    # Any digest the manifest can reference, selector-independent — the
    # possession exchange covers the superset so election never needs a
    # second collective round once the skeleton lands.
    all_digests = [manifest["skeleton"]] + [e[0] for e in manifest["leaves"]]
    for meta in (manifest.get("shards") or {}).values():
        all_digests.extend(p[0] for p in meta["parts"])
    all_digests = list(dict.fromkeys(all_digests))
    possessed = [d for d in all_digests if store.has_blob(d)]
    key = _mesh.mesh_key(commit_dir)
    service = _mesh.BlobPeerService(store, key, rank=me)
    stats: Dict[str, Any] = {"blobs_fetched": 0, "bytes_fetched": 0,
                             "retries": 0, "sources": {},
                             "topology_from": topo_np or None,
                             "shard_selected": 0, "whole_leaf": 0}
    try:
        world = allgather_object({"rank": me, "addr": service.addr,
                                  "possess": possessed})
        possession = {int(w["rank"]): set(w["possess"]) for w in world}
        addrs = {int(w["rank"]): w["addr"] for w in world}
        # Pod-local preference: elect same-host possessors first (the
        # copy crosses loopback, not the fabric); host = the addr's host
        # part, mine taken from my own advertised serve addr.
        hosts = {r: a.rsplit(":", 1)[0] for r, a in addrs.items()}
        local_host = hosts.get(me)
        # The skeleton names the leaves; without it the selector cannot
        # run — fetch it first if missing (tiny blob, same failover).
        if not store.has_blob(manifest["skeleton"]):
            skel = [manifest["skeleton"]]
            s = _mesh.fetch_missing(
                store, skel,
                _mesh.assign_sources(skel, possession, owner,
                                     hosts=hosts, local_host=local_host),
                addrs, key, deadline=deadline)
            for k in ("blobs_fetched", "bytes_fetched", "retries"):
                stats[k] += s[k]
            for r, n in s["sources"].items():
                stats["sources"][r] = stats["sources"].get(r, 0) + n
        needed = _manifest_need(store, manifest, shard_selector)
        missing = [d for d in needed if not store.has_blob(d)]
        s = _mesh.fetch_missing(
            store, missing,
            _mesh.assign_sources(missing, possession, owner,
                                 hosts=hosts, local_host=local_host),
            addrs, key, deadline=deadline)
        for k in ("blobs_fetched", "bytes_fetched", "retries"):
            stats[k] += s[k]
        for r, n in s["sources"].items():
            stats["sources"][r] = stats["sources"].get(r, 0) + n
        # Completion barrier: keep every peer's service up until ALL
        # ranks finished fetching (a dead peer bounds out through the
        # engine's stall watchdog, not a hang).
        allgather_object({"rank": me, "done": True})
    finally:
        service.close()
    whole = set(e[0] for e in manifest["leaves"])
    stats["shard_selected"] = sum(1 for d in needed
                                  if d not in whole
                                  and d != manifest["skeleton"])
    stats["whole_leaf"] = sum(1 for d in needed if d in whole)
    stats["blobs_needed"] = len(needed)
    stats["blobs_missing"] = len(missing)
    _LAST_RESUME_STATS.clear()
    _LAST_RESUME_STATS.update(stats)
    _telemetry.record_event(
        "resume_fetch", manifest_seq=int(manifest["seq"]),
        blobs_total=len(needed), blobs_missing=len(missing),
        bytes_fetched=stats["bytes_fetched"], retries=stats["retries"],
        sources=len(stats["sources"]),
        topology_from=topo_np or jax.process_count(),
        topology_to=jax.process_count())
    return _unpack_manifest(store, manifest, shard_selector)


class _CommitterMixin:
    """Shared persistence plumbing for the concrete state classes:
    lazily-built :class:`_CommitWriter` + drain/telemetry helpers."""

    _commit_dir: Optional[str]
    _commit_async: bool

    def _committer(self) -> _CommitWriter:
        if self.__dict__.get("_writer") is None:
            self._writer = _CommitWriter(self._commit_dir,
                                         self._commit_async)
        return self._writer

    def flush_commits(self, timeout: Optional[float] = None) -> bool:
        """Drain the in-flight async commit (if any). run_fn calls this
        before a restart exit so the newest commit is durable for the
        relaunched generation."""
        w = self.__dict__.get("_writer")
        return True if w is None else w.flush(timeout=timeout)

    def _record_commit(self, seq: int) -> None:
        _telemetry.inc("hvd_commits_total")
        _telemetry.record_event("checkpoint_commit", seq=seq)

    def _record_restore(self, seq: int, t0: float) -> None:
        latency = time.perf_counter() - t0
        self._last_resume_latency_s = latency
        _telemetry.inc("hvd_restores_total")
        _telemetry.set_gauge("hvd_resume_latency_seconds", latency)
        _telemetry.record_event("checkpoint_restore", seq=seq,
                                latency_s=round(latency, 6))


class FrameworkState(_CommitterMixin, State):
    """Shared machinery for the framework-binding states (torch / tf):
    arbitrary scalar attributes, in-memory snapshots, disk-persisted
    commits (``HOROVOD_ELASTIC_COMMIT_DIR``) with ``load_latest`` for
    process-restart resume — so every framework state plugs into BOTH
    elastic modes (in-process reset and restart; elastic/run_fn.py).

    Subclasses own the framework half via three hooks:
    ``_framework_snapshot() -> picklable``, ``_framework_restore(snap)``,
    and ``_framework_broadcast()`` (make live state match rank 0).
    ``_GUARDED`` lists the subclass-owned attribute names exempt from the
    scalar-attr routing."""

    _GUARDED: tuple = ()

    def __init__(self, commit_dir: Optional[str] = None,
                 commit_async: Optional[bool] = None, **kwargs: Any):
        self._scalars: Dict[str, Any] = dict(kwargs)
        self._saved_scalars: Dict[str, Any] = dict(kwargs)
        self._commit_dir = commit_dir or os.environ.get(C.COMMIT_DIR_ENV)
        self._commit_async = (_commit_async_default() if commit_async is None
                              else bool(commit_async))
        self._writer: Optional[_CommitWriter] = None
        self._last_resume_latency_s: Optional[float] = None
        self._commit_seq = 0
        self._saved_fw: Any = None
        super().__init__()
        # In-memory snapshot only: persisting here would clobber a
        # previous generation's on-disk commit before load_latest().
        self._saved_fw = self._framework_snapshot()

    # -- scalar attribute routing (epoch=, batch=, ...) ----------------------

    def __getattr__(self, name):
        scalars = self.__dict__.get("_scalars", {})
        if name in scalars:
            return scalars[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name.startswith("_") or name in type(self)._GUARDED:
            super().__setattr__(name, value)
        elif "_scalars" in self.__dict__ and name in self._scalars:
            self._scalars[name] = value
        else:
            super().__setattr__(name, value)

    # -- framework hooks -----------------------------------------------------

    def _framework_snapshot(self) -> Any:
        raise NotImplementedError

    def _framework_restore(self, snap: Any) -> None:
        raise NotImplementedError

    def _framework_broadcast(self) -> None:
        raise NotImplementedError

    def _broadcast_scalars(self, scalars: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    # -- State contract ------------------------------------------------------

    def save(self) -> None:
        self._saved_fw = self._framework_snapshot()
        self._saved_scalars = dict(self._scalars)
        if self._commit_dir:
            self._commit_seq += 1
            # The snapshot is already host picklables; the writer hashes
            # and stores each leaf as a content-addressed blob off-thread
            # (unchanged leaves dedup by digest even without identity hits).
            self._committer().submit(
                self._commit_seq,
                {"seq": self._commit_seq, "fw": self._saved_fw,
                 "scalars": self._saved_scalars})
            self._record_commit(self._commit_seq)

    def restore(self) -> None:
        if self._saved_fw is not None:
            self._framework_restore(self._saved_fw)
        self._scalars = dict(self._saved_scalars)

    def load_latest(self, shard_selector=None) -> bool:
        """Adopt the newest persisted commit across the (re)launched
        world; returns True if one was found. ``shard_selector`` (see
        ``load_persisted_world``) enables topology-change restore via the
        manifest ``shards`` map."""
        if not self._commit_dir:
            return False
        t0 = time.perf_counter()
        payload = load_persisted_world(self._commit_dir,
                                       shard_selector=shard_selector)
        if payload is None:
            return False
        self._commit_seq = int(payload.get("seq", 0))
        self._saved_fw = payload.get("fw")
        self._saved_scalars = dict(payload.get("scalars", {}))
        self.restore()
        self._record_restore(self._commit_seq, t0)
        return True

    def sync(self) -> None:
        self._framework_broadcast()
        self._scalars = self._broadcast_scalars(self._scalars)
        self.save()


class ObjectState(_CommitterMixin, State):
    """State whose attrs are arbitrary picklable objects
    (reference: common/elastic.py ObjectState)."""

    #: attr names excluded from snapshots.
    _INTERNAL = ("_reset_callbacks", "_saved", "_commit_dir", "_commit_seq",
                 "_commit_async", "_writer", "_last_resume_latency_s")

    def __init__(self, commit_dir: Optional[str] = None,
                 commit_async: Optional[bool] = None, **kwargs):
        super().__init__()
        self._commit_dir = commit_dir or os.environ.get(C.COMMIT_DIR_ENV)
        self._commit_async = (_commit_async_default() if commit_async is None
                              else bool(commit_async))
        self._writer: Optional[_CommitWriter] = None
        self._last_resume_latency_s: Optional[float] = None
        self._commit_seq = 0
        self._saved: Dict[str, Any] = {}
        for k, v in kwargs.items():
            setattr(self, k, v)
        # In-memory snapshot only: persisting here would clobber a previous
        # generation's on-disk commit before load_latest() can adopt it.
        self._saved = self._snapshot()

    def _public_attrs(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()
                if k not in self._INTERNAL}

    def _snapshot(self) -> Dict[str, Any]:
        return {k: self._host_copy(v) for k, v in self._public_attrs().items()}

    @staticmethod
    def _host_copy(v: Any) -> Any:
        """Device arrays → host numpy (survives mesh teardown); everything
        else deep-copied."""
        import numpy as np
        leaves, treedef = jax.tree_util.tree_flatten(v)
        out = []
        for leaf in leaves:
            if isinstance(leaf, jax.Array):
                out.append(np.asarray(jax.device_get(leaf)))
            else:
                out.append(copy.deepcopy(leaf))
        return jax.tree_util.tree_unflatten(treedef, out)

    def save(self) -> None:
        if not self._commit_dir:
            self._saved = self._snapshot()
            return
        self._commit_seq += 1

        def _adopt(host_payload: Dict[str, Any],
                   _self: "ObjectState" = self) -> None:
            _self._saved = host_payload["attrs"]

        # LIVE attr refs, not a host snapshot: the writer takes cheap
        # on-device copies of array leaves (identity-cache hits skip even
        # that) and finishes the host transfer + pickle off-thread; the
        # in-memory rollback snapshot (_saved) is adopted from the SAME
        # host leaves once written, so async == sync bit-for-bit.
        self._committer().submit(
            self._commit_seq,
            {"seq": self._commit_seq, "attrs": dict(self._public_attrs())},
            on_snapshot=_adopt)
        self._record_commit(self._commit_seq)

    def restore(self) -> None:
        # An in-flight async commit is adopting _saved from the writer
        # thread — drain it so we roll back to the NEWEST commit.
        self.flush_commits()
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v) if not isinstance(v, jax.Array)
                    else v)

    def load_latest(self, shard_selector=None) -> bool:
        """Adopt the newest persisted commit across the world (process-
        restart resume; survives losing the former process 0's disk).
        Returns True if one was found. ``shard_selector`` (see
        ``load_persisted_world``) enables topology-change restore via the
        manifest ``shards`` map."""
        if not self._commit_dir:
            return False
        t0 = time.perf_counter()
        payload = load_persisted_world(self._commit_dir,
                                       shard_selector=shard_selector)
        if payload is None:
            return False
        self._commit_seq = int(payload.get("seq", 0))
        self._saved = payload.get("attrs", payload)
        self.restore()
        self._record_restore(self._commit_seq, t0)
        return True

    def sync(self) -> None:
        """Every process adopts process 0's attrs (reference: state.sync()
        broadcast from new rank 0). Broadcasts the HOST snapshot — live
        device buffers may be non-fully-addressable shards that cannot be
        pickled (and would be wrong to ship whole from one host anyway)."""
        from ..optimizer.functions import broadcast_object
        synced = broadcast_object(self._snapshot(), root_rank=0)
        for k, v in synced.items():
            setattr(self, k, v)
        self.save()


class JaxState(ObjectState):
    """``TorchState`` analog: model/optimizer pytrees + loop counters.

    Usage::

        state = JaxState(params=params, opt_state=opt_state,
                         epoch=0, batch=0)
        state.commit()                       # after each (few) step(s)
        params = state.params                # restored/synced on reset

    Arrays are snapshotted as host copies and restored as host numpy — the
    next jitted step re-places them onto the (possibly new) mesh, which is
    exactly what a post-reset recompile needs.
    """
