"""Op-level device profile of the ResNet-50 train step.

VERDICT r2 weak #1 / next #3: the "conv-shape bound" MFU claim needs an
op-level time breakdown, not an assertion. This captures a jax.profiler
xplane trace of the jitted train step, parses it with the xplane proto
TF ships (``tensorflow.tsl.profiler.protobuf.xplane_pb2``), aggregates
device-plane event durations by HLO op category, and prints:

  - the top-K ops by total device time (name, category, time, share)
  - a category rollup (convolution / fusion / all-reduce / copy / other)
  - the overlap fraction: share of collective time hidden behind compute
    (``xprof.collective_overlap`` — the ISSUE 6 metric)

Usage (real chip):  python benchmarks/profile_resnet.py [batch]

On the 8-device CPU mesh the script instead runs the bucketed-vs-
monolithic overlap A/B (docs/fusion.md): the same DP train step traced
twice — once with one uncapped fused gradient allreduce, once with
reverse-layer buckets via ``fusion_threshold_override`` — printing both
overlap fractions. Scheduled bucketing must RAISE the fraction:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
    python benchmarks/profile_resnet.py [batch]

Artifacts: docs/benchmarks.md table is generated from this output.
"""

import collections
import json
import os
import sys
import tempfile

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))
sys.path.insert(0, _here)
# Shared xplane parsing (r4): one parser for all profilers — the
# device-plane layout notes live in xprof.py's docstring. CPU op events
# need the thunk-runtime flag armed BEFORE jax parses XLA_FLAGS.
from xprof import (collective_overlap, ensure_cpu_op_events,  # noqa: E402
                   make_categorize, parse_xplane, short_name)

ensure_cpu_op_events()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402
from common import peak_flops  # noqa: E402  (pins jax_platforms=cpu too)

STEPS = 8  # one scan: enough occurrences to average per-op time

#: Bucket size for the CPU-mesh A/B's bucketed arm. ResNet-50 carries
#: ~100 MB of f32 grads; 4 MB → ~25 reverse-layer buckets, enough for the
#: first buckets to fly while backward still runs without drowning the
#: 8-process rendezvous in tiny collectives.
CPU_AB_BUCKET_BYTES = 4 * 1024 * 1024

categorize = make_categorize()


def _build(batch):
    import horovod_tpu as hvd
    from horovod_tpu.models import ResNet50
    from horovod_tpu.optimizer import distributed
    from horovod_tpu.train import create_train_state

    def loss_fn(logits, y):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    model = ResNet50(axis_name=hvd.RANK_AXIS, dtype=jnp.bfloat16)
    dopt = distributed(optax.sgd(0.1, momentum=0.9))
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(batch, 224, 224, 3).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 1000, size=(batch,)))
    state0 = create_train_state(model, jax.random.PRNGKey(0), images[:1],
                                dopt)
    return model, dopt, loss_fn, state0, images, labels


def _cpu_overlap_ab(batch):
    """Bucketed-vs-monolithic overlap A/B on the virtual-device CPU mesh."""
    from horovod_tpu.collectives.ops import fusion_threshold_override
    from horovod_tpu.train import make_train_step

    model, dopt, loss_fn, state0, images, labels = _build(batch)
    arms = [("monolithic", 1 << 62), ("bucketed", CPU_AB_BUCKET_BYTES)]
    results = {}
    for name, thr in arms:
        # Fresh step per arm: the threshold is baked in at trace time.
        step = make_train_step(model, dopt, loss_fn, donate=False)
        with fusion_threshold_override(thr):
            _, loss = step(state0, images, labels)  # warm/compile
            np.asarray(loss)
            logdir = tempfile.mkdtemp(prefix=f"resnet_ovl_{name}_")
            with jax.profiler.trace(logdir):
                for _ in range(2):
                    _, loss = step(state0, images, labels)
                    np.asarray(loss)
        ovl = collective_overlap(logdir)
        results[name] = ovl
        print(f"{name:11s} overlap_fraction="
              f"{ovl['overlap_fraction']}  "
              f"(hidden {ovl['hidden_ms']:.1f} / "
              f"{ovl['collective_ms']:.1f} ms collective, "
              f"{ovl['n_collective_events']} events)", flush=True)
    mono = results["monolithic"]["overlap_fraction"]
    buck = results["bucketed"]["overlap_fraction"]
    out = {"metric": "resnet50_overlap_ab", "batch": batch,
           "bucket_bytes": CPU_AB_BUCKET_BYTES,
           "monolithic": results["monolithic"],
           "bucketed": results["bucketed"]}
    if mono is not None and buck is not None:
        out["overlap_gain"] = round(buck - mono, 4)
        print(f"overlap gain (bucketed - monolithic): {buck - mono:+.3f}")
    print("\n" + json.dumps(out))


def main():
    import horovod_tpu as hvd

    hvd.init()
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind}  batch {batch}", flush=True)
    if jax.default_backend() == "cpu" and jax.device_count() > 1:
        # CPU mesh: the op table is meaningless on shared host cores —
        # run the overlap A/B instead (the tier's acceptance metric).
        # 16 images (2/device) keeps the CPU compile+run inside minutes;
        # pass an explicit batch to scale up.
        _cpu_overlap_ab(batch if len(sys.argv) > 1 else 16)
        return

    from horovod_tpu.train import make_train_step

    model, dopt, loss_fn, state0, images, labels = _build(batch)
    step = make_train_step(model, dopt, loss_fn, scan_steps=STEPS,
                           donate=False)
    # warm/compile outside the trace
    _, loss = step(state0, images, labels)
    np.asarray(loss)

    logdir = tempfile.mkdtemp(prefix="resnet_xplane_")
    with jax.profiler.trace(logdir):
        _, loss = step(state0, images, labels)
        np.asarray(loss)

    totals, counts, planes, wall_ps, async_ps = parse_xplane(logdir)
    if not totals:
        print(f"no device events; planes seen: {planes}")
        return
    overlap = collective_overlap(logdir)
    grand = sum(totals.values())
    print(f"module wall: {wall_ps/1e9:.1f} ms / {STEPS} steps = "
          f"{wall_ps/1e9/STEPS:.2f} ms/step; leaf-op occupancy "
          f"{grand/1e9:.1f} ms ({grand/max(wall_ps,1):.0%}); async DMA "
          f"span-sum {async_ps/1e9:.1f} ms (overlap, not occupancy)")
    if overlap["overlap_fraction"] is not None:
        print(f"overlap fraction: {overlap['overlap_fraction']:.3f} "
              f"({overlap['hidden_ms']:.1f} of "
              f"{overlap['collective_ms']:.1f} ms collective hidden)")
    print(f"\n{'op':<52} {'category':<20} {'ms':>8} {'share':>7} {'n':>5}")
    rows = []
    for name, ps in totals.most_common(25):
        cat = categorize(name)
        sn = short_name(name)
        rows.append({"op": sn, "category": cat,
                     "ms": round(ps / 1e9, 3),
                     "share": round(ps / grand, 4),
                     "n": counts[name]})
        print(f"{sn[:52]:<52} {cat:<20} {ps/1e9:>8.3f} {ps/grand:>6.1%} "
              f"{counts[name]:>5}")
    roll = collections.Counter()
    for name, ps in totals.items():
        roll[categorize(name)] += ps
    print("\ncategory rollup:")
    for cat, ps in roll.most_common():
        print(f"  {cat:<20} {ps/1e9:>9.3f} ms  {ps/grand:>6.1%}")
    peak = peak_flops()
    out = {"metric": "resnet50_profile", "batch": batch,
           "wall_ms_per_step": round(wall_ps / 1e9 / STEPS, 3),
           "occupancy_ms_per_step": round(grand / 1e9 / STEPS, 3),
           "categories": {c: round(p / grand, 4) for c, p in roll.items()},
           "overlap": overlap,
           "top": rows[:10]}
    if np.isfinite(peak):
        out["peak_tflops"] = round(peak / 1e12, 1)
    print("\n" + json.dumps(out))


if __name__ == "__main__":
    main()
