"""Shared xplane-trace parsing for the op-occupancy profilers.

Extracted from ``profile_resnet.py`` (r3) so every BASELINE config's
profile (`profile_resnet.py`, `profile_bert.py`, `profile_llama.py`,
`profile_mixtral.py`, `profile_dlrm.py`)
reads the device plane identically: the TPU device plane's "XLA Ops"
line holds leaf HLO op spans (drop the `%while` scan umbrella and
module events — what remains sums to device occupancy); "Async XLA Ops"
are overlapped DMA windows, NOT occupancy, tallied separately.

The event metadata name is the FULL HLO instruction text (verified on
this image's jax/libtpu — no ``tf_op``/op_name stats are populated), so
shape-based attribution is possible: callers can pass extra (category,
regex) pairs matched against the instruction text, e.g. to tell a
``bf16[8,1280,512]`` dispatch einsum from a ``bf16[8,1280,1792]``
expert matmul.
"""

import collections
import glob
import json
import os
import re

#: XLA:CPU only emits per-op trace events under the thunk runtime; without
#: this flag the host plane holds nothing but client-infra spans and the
#: overlap metric has no events to intersect. Call :func:`ensure_cpu_op_events`
#: BEFORE importing jax when profiling on the CPU mesh. (TPU device planes
#: always carry "XLA Ops"; the flag is never needed — or set — there.)
CPU_THUNK_FLAG = "--xla_cpu_use_thunk_runtime=true"


def ensure_cpu_op_events():
    """Arm per-op CPU trace events (no-op unless JAX_PLATFORMS selects cpu).

    Must run before jax parses XLA_FLAGS (i.e. before the first backend
    touch); safe to call unconditionally at the top of a profile script."""
    if "cpu" not in os.environ.get("JAX_PLATFORMS", ""):
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_use_thunk_runtime" not in flags:
        # Appends only CPU_THUNK_FLAG, vetted on this image's CPU backend
        # (and unreachable under the TPU backend — gated above).
        os.environ["XLA_FLAGS"] = (  # hvd-analyze: ok
            flags + " " + CPU_THUNK_FLAG).strip()


_BASE_CATEGORIES = [
    ("convolution", re.compile(r"convolution|conv\d|^conv")),
    ("collective", re.compile(r"all-reduce|reduce-scatter|all-gather|"
                              r"all-to-all|collective")),
    ("sort", re.compile(r"^sort|sort\.")),
    ("gather/scatter", re.compile(r"gather|scatter|dynamic-slice|"
                                  r"dynamic-update")),
    ("matmul", re.compile(r"^dot|einsum|matmul")),
    ("copy/transpose", re.compile(r"copy|transpose|bitcast|slice")),
    ("reduce/bn", re.compile(r"reduce|batch-norm")),
    ("fusion(elementwise)", re.compile(r"fusion|fused")),
]


def parse_xplane(logdir):
    """(totals: name->ps, counts, plane_names, wall_ps, async_ps) for the
    newest xplane.pb under ``logdir``; see module docstring for layout."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    paths = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                             recursive=True), key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {logdir}")
    space = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        space.ParseFromString(f.read())
    totals = collections.Counter()
    counts = collections.Counter()
    async_total = 0
    wall_ps = 0
    plane_names = []
    for plane in space.planes:
        plane_names.append(plane.name)
        if "/device:TPU" not in plane.name:
            continue
        meta = plane.event_metadata
        for line in plane.lines:
            if line.name == "Async XLA Ops":
                # Overlapped DMA windows tallied SEPARATELY — reported as
                # overlap, never added into occupancy (CLAUDE.md trap).
                async_total += sum(  # hvd-analyze: ok — overlap, not occupancy
                    ev.duration_ps for ev in line.events)
                continue
            if line.name == "XLA Modules":
                # Module wall, not occupancy — umbrella filtering is moot.
                wall_ps += sum(  # hvd-analyze: ok — wall, not occupancy
                    ev.duration_ps for ev in line.events)
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                name = meta[ev.metadata_id].name if ev.metadata_id in meta \
                    else str(ev.metadata_id)
                stripped = name.lstrip("%")
                if stripped.startswith(("while", "tuple.", "jit_")):
                    continue  # scan-loop/module umbrellas, not leaf work
                totals[name] += ev.duration_ps
                counts[name] += 1
    return totals, counts, plane_names, wall_ps, async_total


_COLLECTIVE_RE = re.compile(
    r"all-reduce|all_reduce|reduce-scatter|reduce_scatter|all-gather|"
    r"all_gather|all-to-all|all_to_all|collective-permute|collective")
#: CPU thunk events are bare HLO op names ("dot.3", "all-reduce.1");
#: anything with spaces/colons is client infra (ExecuteHelper, listeners).
_CPU_OP_RE = re.compile(r"^%?[A-Za-z][\w.\-]*$")
_UMBRELLAS = ("while", "tuple.", "jit_")


def _merge(intervals):
    """Sorted union of (start, end) intervals (shared attribution core —
    lazy import keeps this module importable before the backend is up)."""
    from horovod_tpu.tools.perf import merge_intervals
    return merge_intervals(intervals)


def _hidden_ps(collective, compute_union):
    """Σ over collective intervals of their intersection with the union."""
    from horovod_tpu.tools.perf import intersect_ps
    return intersect_ps(collective, compute_union)


def step_budget(logdir, steps, **kw):
    """Step-time budget record for the newest trace under ``logdir`` —
    the ISSUE 11 attribution core (``horovod_tpu.tools.perf``): disjoint
    occupancy categories + host gap that sum to device wall, per-category
    top ops, optional MFU. See docs/profiling.md."""
    from horovod_tpu.tools.perf import attribute_logdir
    return attribute_logdir(logdir, steps, **kw)


def _plane_op_intervals(plane):
    """(collective, compute) interval lists for one plane, or None when the
    plane carries no XLA op events. TPU device planes: "XLA Ops" is the
    serial per-core line and "Async XLA Ops" holds the overlapped DMA spans
    (collective by construction — they only exist for async collectives
    and their intersection with the compute line IS the hidden time). CPU
    host plane (thunk runtime): every executor thread line carries bare
    HLO-op-name events; umbrellas and infra spans are dropped."""
    is_tpu = "/device:TPU" in plane.name
    is_cpu = plane.name == "/host:CPU"
    if not (is_tpu or is_cpu):
        return None
    meta = plane.event_metadata
    coll, comp = [], []
    for line in plane.lines:
        if is_tpu and line.name not in ("XLA Ops", "Async XLA Ops"):
            continue
        if is_cpu and line.name == "python":
            continue
        force_coll = is_tpu and line.name == "Async XLA Ops"
        for ev in line.events:
            if ev.duration_ps <= 0:
                continue
            name = meta[ev.metadata_id].name if ev.metadata_id in meta else ""
            stripped = name.lstrip("%")
            if stripped.startswith(_UMBRELLAS):
                continue
            if is_cpu and not _CPU_OP_RE.match(name):
                continue
            iv = (ev.offset_ps, ev.offset_ps + ev.duration_ps)
            if force_coll or _COLLECTIVE_RE.search(stripped.lower()):
                coll.append(iv)
            else:
                comp.append(iv)
    if not coll and not comp:
        return None
    return coll, comp


def collective_overlap(logdir):
    """Overlap-fraction metric: what share of the step's collective time is
    hidden behind compute, from the newest xplane.pb under ``logdir``.

    Per device plane (TPU cores; the whole /host:CPU plane on the CPU
    mesh), collective op spans are intersected with the union of compute op
    spans: a monolithic post-backward allreduce sits in a compute-silent
    window (fraction → 0), while reverse-layer buckets run while backward
    still produces the remaining grads (fraction → 1). Returns
    ``{"collective_ms", "hidden_ms", "exposed_ms", "overlap_fraction",
    "n_collective_events"}``; ``overlap_fraction`` is None when the trace
    holds no collective spans."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    paths = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                             recursive=True), key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {logdir}")
    space = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        space.ParseFromString(f.read())
    total = hidden = n_coll = 0
    for plane in space.planes:
        ivs = _plane_op_intervals(plane)
        if ivs is None:
            continue
        coll, comp = ivs
        n_coll += len(coll)
        total += sum(e - s for s, e in coll)
        hidden += _hidden_ps(coll, _merge(comp))
    return {
        "collective_ms": round(total / 1e9, 3),
        "hidden_ms": round(hidden / 1e9, 3),
        "exposed_ms": round((total - hidden) / 1e9, 3),
        "overlap_fraction": (round(hidden / total, 4) if total else None),
        "n_collective_events": n_coll,
    }


def short_name(name):
    """'%loop_fusion.12 = bf16[...] fusion(...)' -> 'loop_fusion.12'"""
    return name.split(" = ")[0].lstrip("%")


def make_categorize(extra=()):
    """Categorizer over the FULL instruction text: ``extra`` is an
    ordered list of (category, compiled-regex) checked FIRST against the
    whole instruction (shapes included), then the op-kind fallbacks run
    on the short name."""
    def categorize(name):
        for cat, pat in extra:
            if pat.search(name):
                return cat
        low = short_name(name).lower()
        for cat, pat in _BASE_CATEGORIES:
            if pat.search(low):
                return cat
        return "other"
    return categorize


def report(metric, totals, counts, wall_ps, async_ps, steps, *,
           categorize=None, extra_json=None, top_k=25, overlap=None):
    """Print the top-K table + category rollup + one JSON line; returns
    the rollup dict {category: share}. ``overlap`` is an optional
    :func:`collective_overlap` result folded into the print + JSON."""
    from common import peak_flops
    import numpy as np
    categorize = categorize or make_categorize()
    grand = sum(totals.values())
    print(f"module wall: {wall_ps/1e9:.1f} ms / {steps} steps = "
          f"{wall_ps/1e9/steps:.2f} ms/step; leaf-op occupancy "
          f"{grand/1e9:.1f} ms ({grand/max(wall_ps,1):.0%}); async DMA "
          f"span-sum {async_ps/1e9:.1f} ms (overlap, not occupancy)")
    if overlap is not None and overlap.get("overlap_fraction") is not None:
        print(f"overlap fraction: {overlap['overlap_fraction']:.3f} "
              f"({overlap['hidden_ms']:.1f} of {overlap['collective_ms']:.1f}"
              f" ms collective hidden behind compute; "
              f"{overlap['exposed_ms']:.1f} ms exposed)")
    print(f"\n{'op':<52} {'category':<22} {'ms':>8} {'share':>7} {'n':>5}")
    rows = []
    for name, ps in totals.most_common(top_k):
        cat = categorize(name)
        sn = short_name(name)
        rows.append({"op": sn, "category": cat,
                     "ms": round(ps / 1e9, 3),
                     "share": round(ps / grand, 4),
                     "n": counts[name]})
        print(f"{sn[:52]:<52} {cat:<22} {ps/1e9:>8.3f} {ps/grand:>6.1%} "
              f"{counts[name]:>5}")
    roll = collections.Counter()
    for name, ps in totals.items():
        roll[categorize(name)] += ps
    print("\ncategory rollup:")
    for cat, ps in roll.most_common():
        print(f"  {cat:<22} {ps/1e9:>9.3f} ms  {ps/grand:>6.1%}")
    peak = peak_flops()
    out = {"metric": metric,
           "wall_ms_per_step": round(wall_ps / 1e9 / steps, 3),
           "occupancy_ms_per_step": round(grand / 1e9 / steps, 3),
           "categories": {c: round(p / grand, 4) for c, p in roll.items()},
           "top": rows[:10]}
    if np.isfinite(peak):
        out["peak_tflops"] = round(peak / 1e12, 1)
    if overlap is not None:
        out["overlap"] = overlap
    if extra_json:
        out.update(extra_json)
    print("\n" + json.dumps(out))
    return {c: p / grand for c, p in roll.items()}
