"""Protocol tests for the production JaxProcessEngine.

The engine's only transport primitive is ``_allgather_fixed`` (XLA DCN
allgather on real pods). Here K engine instances share a thread-barrier
fake of that primitive, which exercises the full round protocol — header
negotiation, mismatch detection, joined-rank zero contributions — without
multi-process JAX (unavailable single-host; SURVEY.md §4's
command-construction-assertion pattern applied to a wire protocol).
"""

import threading

import numpy as np
import pytest

from horovod_tpu.torch.engine import (Adasum, Average, JaxProcessEngine,
                                      Sum, ThreadSimEngine)


class _Bus:
    """Thread-barrier allgather bus shared by fake engines."""

    def __init__(self, n):
        self.n = n
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.round = 0
        self.slots = {}
        self.results = {}

    def allgather(self, rank, arr):
        with self.cv:
            my_round = self.round + 1 if rank in self.slots else self.round
            # wait for my slot in the current round to be free
            while rank in self.slots:
                self.cv.wait(timeout=30)
            self.slots[rank] = np.asarray(arr)
            if len(self.slots) == self.n:
                out = np.stack([self.slots[r] for r in range(self.n)])
                self.results[self.round] = [out, self.n]
                self.slots = {}
                self.round += 1
                self.cv.notify_all()
            target = my_round
            while target not in self.results:
                if not self.cv.wait(timeout=30):
                    raise RuntimeError("fake bus stalled")
            out, remaining = self.results[target]
            self.results[target][1] -= 1
            if self.results[target][1] == 0:
                del self.results[target]
            self.cv.notify_all()
            return out


class _FakeJaxEngine(JaxProcessEngine):
    """JaxProcessEngine with the jax transport swapped for the bus."""

    def __init__(self, rank, size, bus):
        # bypass JaxProcessEngine.__init__ (requires process_count > 1)
        self._rank_v = rank
        self._size_v = size
        self._bus = bus
        self._lock = threading.RLock()
        self._joined = False
        self._cache_init()

    def rank(self):
        return self._rank_v

    def size(self):
        return self._size_v

    def _group(self, members):
        """(bus, my position, group size) for a member subset — the fake's
        rendering of the real engine's member-process mesh."""
        if members is None:
            return self._bus, self._rank_v, self._size_v
        key = tuple(sorted(members))
        with self._bus.lock:
            groups = getattr(self._bus, "groups", None)
            if groups is None:
                groups = self._bus.groups = {}
            bus = groups.get(key)
            if bus is None:
                bus = groups[key] = _Bus(len(key))
        return bus, key.index(self._rank_v), len(key)

    def _allgather_fixed(self, arr, members=None):
        bus, pos, _ = self._group(members)
        return bus.allgather(pos, arr)

    def _device_gather(self, arr, members):
        return self._allgather_fixed(arr, members)

    def _device_reduce(self, flat, op, scatter_shape=None, members=None):
        # The real engine runs ONE jitted XLA collective over a one-device-
        # per-(member-)process mesh; threads in one process can't form that
        # mesh, so the fake reduces over the bus with identical semantics
        # (identity contributions from joined ranks already included by the
        # caller).
        from horovod_tpu.torch.engine import (Average, Max, Min, Product,
                                              Sum)
        bus, pos, k = self._group(members)
        g = bus.allgather(pos, flat)
        fn = {Sum: np.sum, Average: np.sum, Min: np.min, Max: np.max,
              Product: np.prod}[op]
        red = fn(g, axis=0).astype(flat.dtype)
        if scatter_shape is not None:
            red = red.reshape(scatter_shape)
            return np.split(red, k)[pos].copy()
        return red


def _run_engines(n, fn):
    bus = _Bus(n)
    engines = [_FakeJaxEngine(r, n, bus) for r in range(n)]
    results = [None] * n
    errors = []

    def worker(r):
        try:
            results[r] = fn(engines[r], r)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "engine threads hung"
    if errors:
        raise errors[0]
    return results


def test_fake_allreduce_sum_and_average():
    def fn(eng, r):
        a = eng.allreduce("g", np.full((2, 3), r + 1.0), Sum)
        b = eng.allreduce("g", np.full((4,), r + 1.0), Average)
        return a, b

    for a, b in _run_engines(3, fn):
        np.testing.assert_allclose(a, np.full((2, 3), 6.0))
        np.testing.assert_allclose(b, np.full((4,), 2.0))


def test_fake_allgather_uneven_rows():
    def fn(eng, r):
        return eng.allgather("ag", np.arange((r + 1) * 2,
                                             dtype=np.float32).reshape(
                                                 r + 1, 2))

    outs = _run_engines(2, fn)
    expect = np.concatenate([np.arange(2, dtype=np.float32).reshape(1, 2),
                             np.arange(4, dtype=np.float32).reshape(2, 2)])
    for o in outs:
        np.testing.assert_allclose(o, expect)


def test_fake_broadcast_and_alltoall():
    def fn(eng, r):
        b = eng.broadcast("b", np.full((3,), float(r)), 1)
        a, splits = eng.alltoall("a", np.arange(4.0) + 10 * r, None)
        return b, a, splits

    outs = _run_engines(2, fn)
    for b, _, _ in outs:
        np.testing.assert_allclose(b, np.full((3,), 1.0))
    np.testing.assert_allclose(outs[0][1], [0.0, 1.0, 10.0, 11.0])
    np.testing.assert_allclose(outs[1][1], [2.0, 3.0, 12.0, 13.0])
    np.testing.assert_allclose(outs[0][2], [2, 2])


def test_fake_broadcast_none_receivers():
    """Receivers pass arr=None and learn the geometry from the root's
    header round — including root_rank=1, where rank 0's shape-unknown
    header must NOT be picked as the payload shape reference (the
    ``noshape`` marker; regression for the r5 watchdog-path fix)."""
    def fn(eng, r):
        if r == 1:
            return eng.broadcast("bn", np.arange(6.0).reshape(2, 3), 1)
        return eng.broadcast("bn", None, 1)

    for out in _run_engines(3, fn):
        np.testing.assert_allclose(out, np.arange(6.0).reshape(2, 3))


def test_fake_object_helpers():
    """Engine-level gather_object/broadcast_object (the transport under
    the JAX path's hvd.allgather_object/broadcast_object — they must ride
    the engine protocol so the stall watchdog covers them)."""
    def fn(eng, r):
        gathered = eng.gather_object({"rank": r, "pad": "x" * (7 * (r + 1))})
        b = eng.broadcast_object(("root-obj", r) if r == 2 else None,
                                 root_rank=2)
        return gathered, b

    for gathered, b in _run_engines(3, fn):
        assert [g["rank"] for g in gathered] == [0, 1, 2]
        assert b == ("root-obj", 2)


def test_fake_reducescatter():
    def fn(eng, r):
        return eng.reducescatter("rs", np.arange(4.0), Sum)

    outs = _run_engines(2, fn)
    np.testing.assert_allclose(outs[0], [0.0, 2.0])
    np.testing.assert_allclose(outs[1], [4.0, 6.0])


def test_fake_subgroup_allreduce_and_broadcast():
    """Process-set ops run ONLY among members (member-mesh rounds); a
    non-member rank is untouched and free to do other work — the
    reference's MPI_Comm_split semantics, previously NotImplementedError
    on this engine (VERDICT r1 missing item 5)."""
    def fn(eng, r):
        if r in (0, 2):
            a = eng.allreduce("sg", np.full((2,), float(r + 1)), Sum,
                              members=(0, 2))
            b = eng.broadcast("sb", np.full((2,), float(r)), 2,
                              members=(0, 2))
            return a, b
        return None

    outs = _run_engines(3, fn)
    for r in (0, 2):
        np.testing.assert_allclose(outs[r][0], np.full((2,), 4.0))  # 1+3
        np.testing.assert_allclose(outs[r][1], np.full((2,), 2.0))  # root 2
    assert outs[1] is None


def test_fake_subgroup_reducescatter_disjoint_concurrent():
    """Two disjoint subgroups run concurrently without cross-talk."""
    def fn(eng, r):
        if r in (0, 1):
            return eng.reducescatter(
                "rs", np.arange(4.0) * (r + 1), Sum, members=(0, 1))
        return eng.allreduce("solo", np.full((3,), 7.0), Sum, members=(2,))

    outs = _run_engines(3, fn)
    np.testing.assert_allclose(outs[0], [0.0, 3.0])   # sum [0..3]+[0,2,4,6]
    np.testing.assert_allclose(outs[1], [6.0, 9.0])
    np.testing.assert_allclose(outs[2], np.full((3,), 7.0))  # singleton


def test_fake_subgroup_average_divides_by_member_count():
    def fn(eng, r):
        if r == 1:
            return None
        return eng.allreduce("avg", np.full((2,), float(r)), Average,
                             members=(0, 2))

    outs = _run_engines(3, fn)
    np.testing.assert_allclose(outs[0], np.full((2,), 1.0))  # (0+2)/2


def test_fake_subgroup_nonmember_raises():
    def fn(eng, r):
        if r == 1:
            try:
                eng.allreduce("x", np.zeros(2), Sum, members=(0, 2))
            except ValueError as e:
                return str(e)
            return "no error"
        # members must still meet so the test ends cleanly
        return eng.allreduce("x", np.zeros(2), Sum, members=(0, 2))

    outs = _run_engines(3, fn)
    assert "not in process set" in outs[1]


def test_fake_join_uneven_steps():
    # rank 0 does 1 step then joins; rank 1 does 3 steps. Joined rank must
    # answer rank 1's collectives with zero contributions (reference
    # JoinOp), and Average must divide by the ACTIVE count.
    def fn(eng, r):
        steps = 1 if r == 0 else 3
        outs = []
        for i in range(steps):
            outs.append(eng.allreduce(f"s{i}", np.full((2,), r + 1.0),
                                      Average))
        last = eng.join()
        return outs, last

    outs = _run_engines(2, fn)
    np.testing.assert_allclose(outs[0][0][0], np.full((2,), 1.5))
    np.testing.assert_allclose(outs[1][0][1], np.full((2,), 2.0))
    np.testing.assert_allclose(outs[1][0][2], np.full((2,), 2.0))
    assert outs[0][1] == 1 and outs[1][1] == 1


def test_fake_mismatch_detection():
    # Divergent op names across processes must raise, not cross-pair.
    def fn(eng, r):
        with pytest.raises(RuntimeError, match="mismatch"):
            eng.allreduce("left" if r == 0 else "right",
                          np.ones(2), Sum)
        return True

    assert all(_run_engines(2, fn))


def test_threadsim_stall_raises():
    # One rank issues an op its peer never does: the stall inspector analog
    # must raise instead of hanging forever.
    eng = ThreadSimEngine(2, stall_timeout_s=1.5)
    eng.set_rank(0)
    with pytest.raises(RuntimeError, match="stalled"):
        eng.allreduce("lonely", np.ones(2), Sum)


# --- steady-state signature cache (VERDICT r2 #1b) ---------------------------

def _pin_cache(monkeypatch, capacity=1024, verify_every=0):
    """Pin the signature-cache config. The engine resolves it through the
    context config when one is initialized (programmatic Config wins), so
    patch both the env and any live context."""
    import horovod_tpu.core.context_api as ctx_api
    monkeypatch.setenv("HOROVOD_CACHE_CAPACITY", str(capacity))
    monkeypatch.setenv("HOROVOD_CACHE_VERIFY_EVERY", str(verify_every))
    if ctx_api.is_initialized():
        monkeypatch.setattr(ctx_api.context().config, "cache_capacity",
                            capacity)
        monkeypatch.setattr(ctx_api.context().config, "cache_verify_every",
                            verify_every)


class _CountingFakeEngine(_FakeJaxEngine):
    """Counts host-side negotiation gathers (``_allgather_fixed``)."""

    def __init__(self, rank, size, bus):
        super().__init__(rank, size, bus)
        self.host_rounds = 0

    def _allgather_fixed(self, arr, members=None):
        self.host_rounds += 1
        return super()._allgather_fixed(arr, members)


def _run_counting(n, fn):
    bus = _Bus(n)
    engines = [_CountingFakeEngine(r, n, bus) for r in range(n)]
    results = [None] * n
    errors = []

    def worker(r):
        try:
            results[r] = fn(engines[r], r)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "engine threads hung"
    if errors:
        raise errors[0]
    return results


def test_cache_allreduce_steady_state_one_host_round(monkeypatch):
    """First occurrence pays mini + full header round (3 host gathers);
    every later occurrence pays ONLY the mini round (1 host gather) before
    the device payload — the response-cache steady state."""
    _pin_cache(monkeypatch)
    def fn(eng, r):
        counts = []
        for _ in range(3):
            before = eng.host_rounds
            eng.allreduce("g", np.full(4, r + 1.0, np.float32), Sum)
            counts.append(eng.host_rounds - before)
        return counts

    for counts in _run_counting(2, fn):
        assert counts == [3, 1, 1], counts


def test_cache_allgather_steady_state(monkeypatch):
    """Gather-path ops skip the pickled header round too: 5 host gathers
    first (mini + 2 header + 2 payload), 3 after (mini + 2 payload) —
    and ragged row counts still work on the cached path."""
    _pin_cache(monkeypatch)
    def fn(eng, r):
        first = eng.host_rounds
        a = eng.allgather("ag", np.full((r + 1, 2), r, np.float32))
        first = eng.host_rounds - first
        steady = eng.host_rounds
        b = eng.allgather("ag", np.full((r + 2, 2), r, np.float32))
        steady = eng.host_rounds - steady
        return first, steady, a, b

    for first, steady, a, b in _run_counting(2, fn):
        assert first == 5 and steady == 3, (first, steady)
        assert a.shape == (3, 2) and b.shape == (5, 2)


def test_cache_steady_state_mismatch_raises(monkeypatch):
    """Two ranks issuing DIFFERENT cached ops must raise the mismatch
    error from the mini round itself, not hang or cross-pair."""
    _pin_cache(monkeypatch)
    def fn(eng, r):
        eng.allreduce("a", np.ones(2, np.float32), Sum)
        eng.allreduce("b", np.ones(2, np.float32), Sum)
        # now diverge: rank 0 re-issues "a", rank 1 re-issues "b"
        with pytest.raises(RuntimeError, match="mismatch"):
            eng.allreduce("a" if r == 0 else "b",
                          np.ones(2, np.float32), Sum)
        return True

    assert all(_run_counting(2, fn))


def test_cache_capacity_zero_disables_mini_round(monkeypatch):
    """HOROVOD_CACHE_CAPACITY=0 (reference env) restores the pre-cache
    wire protocol: no mini round, 2 host gathers per allreduce forever."""
    _pin_cache(monkeypatch, capacity=0)

    def fn(eng, r):
        counts = []
        for _ in range(2):
            before = eng.host_rounds
            eng.allreduce("g", np.ones(3, np.float32), Sum)
            counts.append(eng.host_rounds - before)
        # gather-path ops (which pass a sig to _round unconditionally)
        # must also survive capacity 0 — regression: _sig_commit used to
        # evict from an empty OrderedDict here.
        for _ in range(2):
            before = eng.host_rounds
            eng.allgather("ag", np.full((r + 1, 2), r, np.float32))
            counts.append(eng.host_rounds - before)
        return counts

    for counts in _run_counting(2, fn):
        assert counts == [2, 2, 4, 4], counts


def test_cache_verify_every_reverifies(monkeypatch):
    """HOROVOD_CACHE_VERIFY_EVERY=2 periodically re-runs the full header
    round as a divergence audit."""
    _pin_cache(monkeypatch, verify_every=2)

    def fn(eng, r):
        counts = []
        for _ in range(4):
            before = eng.host_rounds
            eng.allreduce("g", np.ones(3, np.float32), Sum)
            counts.append(eng.host_rounds - before)
        return counts

    for counts in _run_counting(2, fn):
        assert counts == [3, 1, 3, 1], counts


def test_cache_fused_adasum_bucket_steady_state(monkeypatch):
    """VERDICT r3 #4: fused Adasum buckets (segments metadata) ride the
    signature cache too — steady state is one mini round per bucket op —
    and per-segment coefficients apply (each packed tensor combines with
    its OWN Adasum coefficients, bit-identical to per-tensor ops)."""
    _pin_cache(monkeypatch)
    a0 = np.array([1.0, 0.0, 3.0], np.float32)   # tensor A, 3 elements
    b0 = np.array([2.0, 2.0], np.float32)        # tensor B, 2 elements

    def fn(eng, r):
        # rank 1 contributes different values
        a = a0 * (r + 1)
        b = b0 if r == 0 else np.array([-2.0, 2.0], np.float32)
        flat = np.concatenate([a, b])
        counts, outs = [], []
        for _ in range(3):
            before = eng.host_rounds
            outs.append(eng.allreduce("adasum_bucket", flat, Adasum,
                                      segments=(3, 2)))
            counts.append(eng.host_rounds - before)
        return counts, outs[-1]

    from horovod_tpu.core.engine import _adasum_combine
    expect_a = _adasum_combine(a0, a0 * 2)
    expect_b = _adasum_combine(b0, np.array([-2.0, 2.0], np.float32))
    for counts, out in _run_counting(2, fn):
        # Adasum rides the gather payload path (host tree combine), so
        # steady state is mini + 2 payload gathers — the header round's
        # 2 gathers are what the cache removes (same shape as the
        # allgather steady state: 5 first, 3 after).
        assert counts == [5, 3, 3], counts
        np.testing.assert_array_equal(out[:3], expect_a)
        np.testing.assert_array_equal(out[3:], expect_b)

    # differing segment layouts across ranks must NOT silently combine:
    def bad(eng, r):
        flat = np.ones(5, np.float32)
        with pytest.raises(RuntimeError):
            eng.allreduce("seg_mismatch", flat, Adasum,
                          segments=(3, 2) if r == 0 else (2, 3))
        return True

    assert all(_run_counting(2, bad))


def test_cache_capacity_one_perpetual_evict_refill(monkeypatch):
    """VERDICT r3 #8: a capacity-1 cache under a 2-op steady state is the
    worst case — each op evicts the other before its next occurrence, so
    BOTH ops pay the asymmetric want-full path on EVERY round, forever.
    Verified invariants: (a) correctness is unaffected (results match the
    uncached protocol), (b) every occurrence costs mini + 2 header
    gathers = 3 host rounds (the want-full fallback, not a hang or a
    stale hit), (c) the cache never exceeds capacity, (d) a SINGLE-op
    steady state still reaches the 1-gather cached path at capacity 1."""
    _pin_cache(monkeypatch, capacity=1)

    def fn(eng, r):
        counts, outs = [], []
        for _ in range(3):  # alternating ops: perpetual evict/refill
            for name in ("a", "b"):
                before = eng.host_rounds
                outs.append(eng.allreduce(
                    name, np.full(2, r + 1.0, np.float32), Sum))
                counts.append(eng.host_rounds - before)
                assert len(eng._sig_seen) <= 1
        solo = []
        for _ in range(3):  # single hot op: capacity 1 is enough
            before = eng.host_rounds
            eng.allreduce("solo", np.ones(2, np.float32), Sum)
            solo.append(eng.host_rounds - before)
        return counts, solo, outs

    for counts, solo, outs in _run_counting(2, fn):
        assert counts == [3] * 6, counts
        assert solo == [3, 1, 1], solo
        for o in outs:
            np.testing.assert_allclose(o, [3.0, 3.0])


def test_cache_mixed_subgroup_and_global_cycles(monkeypatch):
    """VERDICT r3 #8: subgroup and global cached ops interleaved over many
    cycles. Each reaches its own steady state (1 mini gather per op), the
    subgroup's mini round meets among MEMBERS only (non-members spend no
    gather on it), and results stay correct throughout."""
    _pin_cache(monkeypatch)
    n = 3
    sub = (0, 2)

    def fn(eng, r):
        per_cycle = []
        for cycle in range(6):
            before = eng.host_rounds
            g = eng.allreduce("glob", np.full(2, r + 1.0, np.float32), Sum)
            np.testing.assert_allclose(g, [6.0, 6.0])  # 1+2+3
            if r in sub:
                s = eng.allreduce("subg", np.full(2, r + 1.0, np.float32),
                                  Sum, members=sub)
                np.testing.assert_allclose(s, [4.0, 4.0])  # 1+3
            per_cycle.append(eng.host_rounds - before)
        return per_cycle

    outs = _run_counting(n, fn)
    for r, per_cycle in enumerate(outs):
        # steady state from cycle 1: one mini gather per op issued
        expect = 2 if r in sub else 1
        assert per_cycle[1:] == [expect] * 5, (r, per_cycle)


def test_cache_rank_rejoins_mid_steady_state(monkeypatch):
    """VERDICT r3 #8: a rank joining mid-steady-state drags cached ops
    back onto the full header round (identity contributions keep
    working), and after the join completes the SAME signatures resume
    the 1-gather cached path — the seen-counts survive the join."""
    _pin_cache(monkeypatch)

    def fn(eng, r):
        # steady state first
        for _ in range(2):
            eng.allreduce("g", np.full(2, r + 1.0, np.float32), Sum)
        if r == 0:
            eng.join()           # rank 0 out for one stretch
            during = None
        else:
            before = eng.host_rounds
            during = eng.allreduce("g", np.full(2, 5.0, np.float32), Sum)
            assert eng.host_rounds - before >= 3  # forced full round
            eng.join()
        # both back: cached path must resume at one gather
        steady = []
        outs = []
        for _ in range(2):
            before = eng.host_rounds
            outs.append(eng.allreduce(
                "g", np.full(2, r + 1.0, np.float32), Sum))
            steady.append(eng.host_rounds - before)
        return during, steady, outs

    outs = _run_counting(2, fn)
    np.testing.assert_allclose(outs[1][0], [5.0, 5.0])  # identity join
    for during, steady, res in outs:
        assert steady == [1, 1], steady
        for o in res:
            np.testing.assert_allclose(o, [3.0, 3.0])


def test_cache_join_falls_back_to_full_rounds(monkeypatch):
    """A joined rank forces cached ops back onto the full header round so
    its zero/identity contributions keep working (steady-state ops before
    the join, join-covered ops after)."""
    _pin_cache(monkeypatch)
    def fn(eng, r):
        out1 = eng.allreduce("g", np.full(2, r + 1.0, np.float32), Sum)
        out2 = eng.allreduce("g", np.full(2, r + 1.0, np.float32), Sum)
        if r == 0:
            eng.join()
            return out1, out2, None
        out3 = eng.allreduce("g", np.full(2, 5.0, np.float32), Sum)
        eng.join()
        return out1, out2, out3

    outs = _run_counting(2, fn)
    np.testing.assert_allclose(outs[0][0], [3.0, 3.0])
    np.testing.assert_allclose(outs[1][1], [3.0, 3.0])
    np.testing.assert_allclose(outs[1][2], [5.0, 5.0])
