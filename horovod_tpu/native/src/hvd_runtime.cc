// hvd_runtime — native host-side runtime for horovod_tpu.
//
// Reference parity (SURVEY.md §2.1): the reference's C++ core owns a
// background thread + queues (operations.cc), a thread pool
// (thread_pool.cc) and a timeline writer thread (timeline.cc). Under SPMD
// the collective scheduling moved into XLA, so the native layer that still
// earns its keep on a TPU host is:
//
//   * ThreadPool           — thread_pool.cc parity, used by the pipeline.
//   * Timeline             — timeline.cc parity: mutex+cv queue drained by
//                            a dedicated writer thread into chrome-trace
//                            JSON; never blocks the caller on disk.
//   * RecordPipeline       — multithreaded, double-buffered host input
//                            pipeline over fixed-size-record binary files:
//                            the memcpy/prefetch role the reference's
//                            fusion-buffer MEMCPY_IN path plays, applied to
//                            the TPU's actual host bottleneck (feeding
//                            device_put).
//
// Plain C ABI (extern "C") for ctypes binding — no pybind11 in this image.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <functional>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

// ---------------------------------------------------------------------------
// ThreadPool (reference: horovod/common/thread_pool.cc)
// ---------------------------------------------------------------------------

class ThreadPool {
 public:
  explicit ThreadPool(int n) : stop_(false) {
    if (n < 1) n = 1;
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] { Loop(); });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  void Submit(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      q_.push(std::move(fn));
    }
    cv_.notify_one();
  }

 private:
  void Loop() {
    for (;;) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !q_.empty(); });
        if (stop_ && q_.empty()) return;
        fn = std::move(q_.front());
        q_.pop();
      }
      fn();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::queue<std::function<void()>> q_;
  std::vector<std::thread> workers_;
  bool stop_;
};

// ---------------------------------------------------------------------------
// Timeline (reference: horovod/common/timeline.cc — writer-thread design)
// ---------------------------------------------------------------------------

class Timeline {
 public:
  Timeline(const char* path, long long start_us)
      : start_us_(start_us), stop_(false), first_(true) {
    file_ = std::fopen(path, "w");
    ok_ = file_ != nullptr;
    if (ok_) {
      std::fputs("[\n", file_);
      writer_ = std::thread([this] { Drain(); });
    }
  }

  ~Timeline() { Close(); }

  bool ok() const { return ok_; }

  void Event(const char* name, const char* cat, char ph, int pid, int tid,
             long long ts_us) {
    if (!ok_) return;
    char buf[512];
    // chrome-trace event; ph is one of B/E/i/X.
    std::snprintf(buf, sizeof(buf),
                  "{\"name\": \"%s\", \"cat\": \"%s\", \"ph\": \"%c\", "
                  "\"ts\": %lld, \"pid\": %d, \"tid\": %d%s}",
                  name, cat, ph, ts_us, pid, tid,
                  ph == 'i' ? ", \"s\": \"g\"" : "");
    {
      std::lock_guard<std::mutex> lk(mu_);
      q_.emplace_back(buf);
    }
    cv_.notify_one();
  }

  long long NowUs() const {
    using namespace std::chrono;
    return duration_cast<microseconds>(
               steady_clock::now().time_since_epoch()).count() - start_us_;
  }

  void Close() {
    if (!ok_) return;
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_one();
    if (writer_.joinable()) writer_.join();
    std::fputs("\n]\n", file_);
    std::fclose(file_);
    ok_ = false;
  }

 private:
  void Drain() {
    for (;;) {
      std::deque<std::string> batch;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !q_.empty(); });
        batch.swap(q_);
        if (batch.empty() && stop_) return;
      }
      for (auto& ev : batch) {
        if (!first_) std::fputs(",\n", file_);
        first_ = false;
        std::fputs(ev.c_str(), file_);
      }
      std::fflush(file_);
    }
  }

  FILE* file_;
  bool ok_;
  long long start_us_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::string> q_;
  std::thread writer_;
  std::atomic<bool> stop_;
  bool first_;
};

// ---------------------------------------------------------------------------
// RecordPipeline — prefetching reader over fixed-size-record binary files.
// ---------------------------------------------------------------------------

struct Batch {
  std::vector<uint8_t> data;
  long long n_records = 0;
};

class RecordPipeline {
 public:
  RecordPipeline(const std::vector<std::string>& paths,
                 long long record_bytes, long long batch_records,
                 int n_threads, int capacity, unsigned long long seed,
                 bool shuffle, bool drop_remainder)
      : record_bytes_(record_bytes), batch_records_(batch_records),
        capacity_(capacity < 1 ? 1 : capacity), done_producing_(false),
        error_(false), shutdown_(false), pool_(n_threads) {
    // Index every record as (file, offset), optionally shuffled globally.
    for (const auto& p : paths) {
      FILE* f = std::fopen(p.c_str(), "rb");
      if (!f) { error_ = true; err_ = "cannot open " + p; return; }
      std::fseek(f, 0, SEEK_END);
      long long sz = std::ftell(f);
      std::fclose(f);
      if (sz % record_bytes != 0) {
        error_ = true;
        err_ = p + " size not a multiple of record_bytes";
        return;
      }
      long long n = sz / record_bytes;
      for (long long i = 0; i < n; ++i) {
        index_.push_back({(int)files_.size(), i});
      }
      files_.push_back(p);
    }
    if (shuffle) {
      // Deterministic SplitMix64 Fisher-Yates, mirrored bit-for-bit by the
      // Python fallback (native/__init__.py): same seed => same batches on
      // both paths. std::shuffle's algorithm is implementation-defined, so
      // it cannot honor that contract across toolchains.
      unsigned long long state = seed;
      auto next_u64 = [&state]() {
        state += 0x9E3779B97F4A7C15ULL;
        unsigned long long z = state;
        z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
        z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
        return z ^ (z >> 31);
      };
      for (long long i = (long long)index_.size() - 1; i > 0; --i) {
        long long j = (long long)(next_u64() % (unsigned long long)(i + 1));
        std::swap(index_[i], index_[j]);
      }
    }
    // Partition the index into batches; reader tasks claim batch slots in
    // order but produce concurrently; a bounded queue applies backpressure.
    n_batches_ = (long long)(index_.size() + batch_records_ - 1)
                 / batch_records_;
    if (drop_remainder) n_batches_ = (long long)index_.size() / batch_records_;
    next_batch_.store(0);
    int tasks = n_threads < 1 ? 1 : n_threads;
    producers_live_.store(tasks);
    for (int t = 0; t < tasks; ++t) {
      pool_.Submit([this] { Produce(); });
    }
  }

  ~RecordPipeline() {
    // Unblock producers waiting for queue space so ~ThreadPool (which
    // destructs FIRST, being the last member) can join them. Member
    // destruction runs after this body, in reverse declaration order.
    {
      std::lock_guard<std::mutex> lk(mu_);
      shutdown_ = true;
    }
    cv_in_.notify_all();
  }

  // Returns n_records (0 = end of data, -1 = error). Caller's dst must hold
  // batch_records * record_bytes.
  long long Next(uint8_t* dst) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_out_.wait(lk, [this] {
      return error_ || ready_.count(next_emit_) ||
             (done_producing_ && ready_.empty());
    });
    if (error_) return -1;
    auto it = ready_.find(next_emit_);
    if (it == ready_.end()) return 0;  // done
    Batch b = std::move(it->second);
    ready_.erase(it);
    ++next_emit_;
    lk.unlock();
    cv_in_.notify_all();
    std::memcpy(dst, b.data.data(), b.data.size());
    return b.n_records;
  }

  const char* err() const { return err_.c_str(); }

 private:
  void Produce() {
    for (;;) {
      long long bi = next_batch_.fetch_add(1);
      if (bi >= n_batches_ || error_) break;
      long long lo = bi * batch_records_;
      long long hi = std::min<long long>(lo + batch_records_,
                                         (long long)index_.size());
      Batch b;
      b.n_records = hi - lo;
      b.data.resize((size_t)(b.n_records * record_bytes_));
      // Group reads by file for locality; records within a batch keep
      // their (shuffled) order.
      bool ok = true;
      for (long long i = lo; i < hi && ok; ++i) {
        auto [fi, rec] = index_[(size_t)i];
        ok = ReadRecord(fi, rec,
                        b.data.data() + (size_t)((i - lo) * record_bytes_));
      }
      std::unique_lock<std::mutex> lk(mu_);
      if (!ok) {
        error_ = true;
        err_ = "read failed in " + files_[index_[(size_t)lo].first];
        lk.unlock();
        cv_out_.notify_all();
        cv_in_.notify_all();  // wake producers parked on queue space
        break;
      }
      // Emit in batch-index order (same-seed determinism contract): each
      // producer parks its batch under its index; the consumer drains
      // next_emit_ in sequence. The bi == next_emit_ escape keeps the
      // needed batch insertable when the buffer is full of later ones
      // (no deadlock: the lowest outstanding index can always land).
      cv_in_.wait(lk, [this, bi] {
        return error_ || shutdown_ || bi == next_emit_ ||
               (long long)ready_.size() < capacity_;
      });
      if (error_ || shutdown_) break;
      ready_.emplace(bi, std::move(b));
      lk.unlock();
      cv_out_.notify_all();
    }
    if (producers_live_.fetch_sub(1) == 1) {
      std::lock_guard<std::mutex> lk(mu_);
      done_producing_ = true;
      cv_out_.notify_all();
    }
  }

  struct FileCache {
    std::vector<FILE*> fps;
    ~FileCache() {
      for (FILE* f : fps) if (f) std::fclose(f);
    }
  };

  bool ReadRecord(int file_idx, long long rec, uint8_t* dst) {
    // One FILE* per (thread,file); closed when the pool thread exits.
    thread_local FileCache cache;
    if ((int)cache.fps.size() < (int)files_.size()) {
      cache.fps.resize(files_.size(), nullptr);
    }
    FILE*& f = cache.fps[(size_t)file_idx];
    if (!f) {
      f = std::fopen(files_[(size_t)file_idx].c_str(), "rb");
      if (!f) return false;
    }
    if (std::fseek(f, (long)(rec * record_bytes_), SEEK_SET) != 0)
      return false;
    return std::fread(dst, 1, (size_t)record_bytes_, f)
           == (size_t)record_bytes_;
  }

  std::vector<std::string> files_;
  std::vector<std::pair<int, long long>> index_;
  long long record_bytes_, batch_records_, n_batches_, capacity_;
  std::atomic<long long> next_batch_;
  std::atomic<int> producers_live_;
  std::mutex mu_;
  std::condition_variable cv_in_, cv_out_;
  std::map<long long, Batch> ready_;
  long long next_emit_ = 0;
  bool done_producing_;
  bool error_;
  bool shutdown_;
  std::string err_;
  ThreadPool pool_;   // must destruct before members it uses? (last member
                      // destructs FIRST, so pool_ joins before the rest die)
};

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

// ---------------------------------------------------------------------------
// ParallelGather — fork-join row gather (batch assembly: dst[i] =
// src[idx[i]]). The memcpy half of the reference's MEMCPY_IN_FUSION_BUFFER
// stage, applied to the host input path; called from Python via ctypes
// (which drops the GIL), so shuffle-gather overlaps device compute.
// ---------------------------------------------------------------------------

static void ParallelGather(const uint8_t* src, const long long* idx,
                           long long n_idx, long long row_bytes,
                           uint8_t* dst, int n_threads) {
  long long total = n_idx * row_bytes;
  int want = n_threads < 1 ? 1 : n_threads;
  if (want > n_idx) want = static_cast<int>(n_idx > 0 ? n_idx : 1);
  if (want == 1 || total < (1 << 24)) {  // <16MB: spawn costs more than the copy
    for (long long i = 0; i < n_idx; ++i) {
      memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
             static_cast<size_t>(row_bytes));
    }
    return;
  }
  std::vector<std::thread> ts;
  long long per = (n_idx + want - 1) / want;
  for (int t = 0; t < want; ++t) {
    long long lo = t * per;
    long long hi = std::min(n_idx, lo + per);
    if (lo >= hi) break;
    ts.emplace_back([src, idx, row_bytes, dst, lo, hi] {
      for (long long i = lo; i < hi; ++i) {
        memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
               static_cast<size_t>(row_bytes));
      }
    });
  }
  for (auto& th : ts) th.join();
}

extern "C" {

// v2: hvd_pipeline_create seed widened to unsigned long long.
// v3: hvd_parallel_gather.
int hvd_runtime_abi_version() { return 3; }

// -- thread pool (exposed for tests; the pipeline uses it internally) -------

void* hvd_pool_create(int n_threads) { return new ThreadPool(n_threads); }

void hvd_pool_counter_add(void* pool, long long* counter, long long times) {
  // Submit `times` increments of an atomic counter — a self-contained
  // smoke/bench entry that avoids C->Python callbacks.
  auto* p = static_cast<ThreadPool*>(pool);
  auto* c = reinterpret_cast<std::atomic<long long>*>(counter);
  for (long long i = 0; i < times; ++i) {
    p->Submit([c] { c->fetch_add(1); });
  }
}

void hvd_pool_destroy(void* pool) { delete static_cast<ThreadPool*>(pool); }

// -- timeline ---------------------------------------------------------------

void* hvd_timeline_open(const char* path) {
  auto* t = new Timeline(path, 0);
  if (!t->ok()) { delete t; return nullptr; }
  return t;
}

void hvd_timeline_event(void* t, const char* name, const char* cat, char ph,
                        int pid, int tid) {
  auto* tl = static_cast<Timeline*>(t);
  tl->Event(name, cat, ph, pid, tid, tl->NowUs());
}

void hvd_timeline_close(void* t) {
  auto* tl = static_cast<Timeline*>(t);
  tl->Close();
  delete tl;
}

// -- record pipeline --------------------------------------------------------

void* hvd_pipeline_create(const char** paths, int n_paths,
                          long long record_bytes, long long batch_records,
                          int n_threads, int capacity,
                          unsigned long long seed,
                          int shuffle, int drop_remainder) {
  std::vector<std::string> ps;
  for (int i = 0; i < n_paths; ++i) ps.emplace_back(paths[i]);
  return new RecordPipeline(ps, record_bytes, batch_records, n_threads,
                            capacity, seed, shuffle != 0,
                            drop_remainder != 0);
}

long long hvd_pipeline_next(void* p, uint8_t* dst) {
  return static_cast<RecordPipeline*>(p)->Next(dst);
}

const char* hvd_pipeline_error(void* p) {
  return static_cast<RecordPipeline*>(p)->err();
}

void hvd_pipeline_destroy(void* p) {
  delete static_cast<RecordPipeline*>(p);
}

// -- parallel gather --------------------------------------------------------

void hvd_parallel_gather(const uint8_t* src, const long long* idx,
                         long long n_idx, long long row_bytes,
                         uint8_t* dst, int n_threads) {
  ParallelGather(src, idx, n_idx, row_bytes, dst, n_threads);
}

}  // extern "C"
