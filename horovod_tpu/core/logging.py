"""Leveled logging mirroring the reference's ``horovod/common/logging.cc``.

The reference exposes glog-style ``LOG(level)`` macros controlled by
``HOROVOD_LOG_LEVEL`` (trace/debug/info/warning/error/fatal) and
``HOROVOD_LOG_HIDE_TIME``. We map the same env surface onto Python's
``logging`` so the knob names users know keep working.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(5, "TRACE")

_logger: logging.Logger | None = None


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        logger = logging.getLogger("horovod_tpu")
        level_name = os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower()
        logger.setLevel(_LEVELS.get(level_name, logging.WARNING))
        if not logger.handlers:
            handler = logging.StreamHandler(sys.stderr)
            hide_time = os.environ.get("HOROVOD_LOG_HIDE_TIME", "0") in ("1", "true")
            fmt = "[%(levelname)s] %(message)s" if hide_time else \
                "[%(asctime)s %(levelname)s horovod_tpu] %(message)s"
            handler.setFormatter(logging.Formatter(fmt))
            logger.addHandler(handler)
        logger.propagate = False
        _logger = logger
    return _logger
