"""Training-side publisher: gate committed generations into the serving
plane.

Reference analog: upstream Horovod's elastic state broadcast
(``horovod/common/elastic``, SURVEY.md §2) re-broadcasts known-good state
to WORKERS on reset; here the same "known-good weights" predicate —
an atomically-published manifest whose blobs verify against their
content addresses, over a sentinel-clean window — pushes state OUT to
serving processes instead (docs/serving.md).

The gate, per candidate commit ``seq``:

1. **Cadence** — only every Nth committed generation is a candidate
   (``HOROVOD_PUBLISH_EVERY``).
2. **Sentinel-clean window** — zero ``steps_skipped``/``rollbacks``
   since the last candidate (core/sentinel.py counters): a window that
   contained a numeric-containment event never reaches users.
3. **Integrity** — the manifest must read back complete and EVERY blob
   it references must re-hash to its content address
   (checkpoint/store.py verify-at-read), so a publish can never point at
   torn or bit-flipped bytes.

A passing commit is pinned against GC FIRST (``BlobStore.pin_manifest``
— the pin file carries the publish record, doubling as coordinator-less
discovery for store-watch registries), then announced to the coordinator
via the journaled ``op:"publish"`` record (elastic/service.py), which is
best-effort: a dropped announcement is healed by the pin.

Wire this off the step loop with :func:`attach`: the gate's blob re-hash
runs on the commit writer's thread via ``elastic/state.py`` post-commit
hooks, so the training step never blocks on publishing.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Callable, Dict, Optional

from ..checkpoint.store import BLOB_DIGEST_SIZE, BlobIntegrityError
from ..core import telemetry as _telemetry
from ..core.logging import get_logger
from ..core import sentinel as _sentinel
from ..elastic.state import _CAS_SUBDIR, _cas_store, _path_name, \
    register_commit_hook, unregister_commit_hook
from . import constants as SC


def leaves_digest(manifest: Dict) -> str:
    """One digest over every content address a manifest references, in
    manifest order — the served-weights identity both ends compare: the
    publisher stamps it into the publish record, the registry recomputes
    it from what it actually swapped in (tests/test_serving_e2e.py
    asserts equality after every swap)."""
    h = hashlib.blake2b(digest_size=BLOB_DIGEST_SIZE)
    h.update(str(manifest.get("skeleton", "")).encode())
    for entry in manifest.get("leaves", []):
        h.update(str(entry[0]).encode())
    return h.hexdigest()


class Publisher:
    """Gate + announce published weights for one commit dir.

    ``counters``/``clock`` are injectable (tests run the gate with a fake
    sentinel and no real time); ``client`` is an optional
    ``CoordinatorClient`` — without one, publishes are discoverable only
    through the pin files (store-watch mode).

    ``shard_plan`` enables the optional per-shard blob layer
    (docs/checkpointing.md "Per-shard blobs"): ``plan(path_names, shape)
    -> (axis, n) | None`` names how a leaf is split for the serving
    topology (``serving/decode.py::tp_shard_plan`` derives it from the
    decode plane's megatron plan). Planned leaves additionally get ``n``
    part blobs and a ``shards`` manifest entry keyed by the leaf's
    digest, so a sharded registry delta-fetches only the part bytes its
    target sharding needs. Whole-leaf blobs stay authoritative — old
    readers and unsharded registries never see the difference, and
    ``leaves_digest`` (the served identity) covers only skeleton + leaf
    digests, so the shard layer does not change what is being served.
    """

    def __init__(self, commit_dir: str, client=None,
                 every: Optional[int] = None, keep: Optional[int] = None,
                 counters: Callable[[], Dict] = _sentinel.counters,
                 clock: Callable[[], float] = time.time, rank: int = 0,
                 shard_plan: Optional[Callable] = None):
        self.commit_dir = commit_dir
        self.store = _cas_store(commit_dir)
        self.client = client
        self._every = every
        self._keep = keep
        self._counters = counters
        self._clock = clock
        self._rank = int(rank)
        self._shard_plan = shard_plan
        #: leaf digest -> shards entry, reused across publishes so an
        #: unchanged leaf is never re-split/re-pickled (the CAS dedups
        #: the bytes regardless; this saves the CPU work)
        self._shard_memo: Dict[str, Dict] = {}
        self._seen = 0
        # Sentinel window baseline: counters at the LAST candidate commit
        # (cadence hit), so "zero skips/rollbacks in the window" means
        # since the previous publish decision, not since process start.
        self._window_base = self._clean_counters()
        self.last_published: Optional[Dict] = None

    def _clean_counters(self) -> Dict[str, float]:
        try:
            c = self._counters() or {}
        except Exception:       # noqa: BLE001 — a broken probe blocks, below
            return {}
        return {k: float(v) for k, v in c.items()
                if isinstance(v, (int, float))}

    def _cadence(self) -> int:
        return SC.publish_every() if self._every is None else self._every

    def _pin_keep(self) -> int:
        return SC.publish_keep() if self._keep is None \
            else max(2, int(self._keep))

    # -- the gate ------------------------------------------------------------

    def _blocked(self, cause: str, seq: int) -> None:
        _telemetry.inc("hvd_serving_publish_gate_blocked_total")
        _telemetry.record_event("publish_gate_blocked", cause=cause, seq=seq)
        get_logger().warning(
            "publish gate blocked commit seq=%d: %s", seq, cause)

    def _sentinel_dirty(self) -> Optional[str]:
        now = self._clean_counters()
        base, self._window_base = self._window_base, now
        for key in ("steps_skipped", "rollbacks"):
            delta = now.get(key, 0.0) - base.get(key, 0.0)
            if delta > 0:
                return f"sentinel window dirty: {key} +{delta:g}"
        return None

    def _verify_manifest(self, seq: int) -> Optional[Dict]:
        manifest = self.store.read_manifest(seq)
        if manifest is None:
            return None
        try:
            self.store.get_blob(manifest["skeleton"], verify=True)
            for entry in manifest.get("leaves", []):
                self.store.get_blob(entry[0], verify=True)
            for meta in (manifest.get("shards") or {}).values():
                for entry in meta.get("parts", []):
                    self.store.get_blob(entry[0], verify=True)
        except (OSError, KeyError, BlobIntegrityError):
            return None
        return manifest

    # -- per-shard blob layer --------------------------------------------------

    def _write_shards(self, seq: int, manifest: Dict) -> Dict:
        """Split each planned leaf into part blobs and republish the
        manifest (same seq — atomic overwrite) with the ``shards`` map.
        Best-effort: any failure logs and returns the original manifest,
        which is complete without shards."""
        import pickle

        import numpy as np

        try:
            import jax
            from ..elastic.state import _LeafRef
            skeleton = pickle.loads(
                self.store.get_blob(manifest["skeleton"]))
            flat, _ = jax.tree_util.tree_flatten_with_path(skeleton)
            entries = manifest.get("leaves", [])
            shards: Dict[str, Dict] = {}
            for path, ref in flat:
                if not isinstance(ref, _LeafRef):
                    continue
                digest = entries[ref.index][0]
                memo = self._shard_memo.get(digest)
                if memo is not None:
                    shards[digest] = memo
                    continue
                names = tuple(_path_name(p) for p in path)
                leaf = np.asarray(pickle.loads(self.store.get_blob(digest)))
                plan = self._shard_plan(names, leaf.shape)
                if plan is None:
                    continue
                axis, n = int(plan[0]), int(plan[1])
                if n <= 1 or axis >= leaf.ndim or leaf.shape[axis] % n:
                    continue
                parts = []
                for piece in np.split(leaf, n, axis=axis):
                    data = pickle.dumps(np.ascontiguousarray(piece),
                                        protocol=4)
                    d, _new = self.store.put_blob(data)
                    parts.append([d, len(data)])
                shards[digest] = {"axis": axis, "n": n, "parts": parts}
            if not shards:
                return manifest
            manifest = dict(manifest)
            manifest["shards"] = shards
            self.store.publish_manifest(manifest)
            self._shard_memo = dict(shards)
            _telemetry.set_gauge("hvd_serving_shard_blobs",
                                 float(sum(len(m["parts"])
                                           for m in shards.values())))
            return manifest
        except Exception as err:    # noqa: BLE001 — shards are optional
            get_logger().warning(
                "per-shard blob layer for seq=%d failed (%s) — publishing "
                "whole-leaf manifest only", seq, err)
            return self.store.read_manifest(seq) or manifest

    # -- publishing ----------------------------------------------------------

    def maybe_publish(self, seq: int) -> Optional[Dict]:
        """Run the gate on commit ``seq``; returns the publish record
        when it published, None otherwise (not a candidate / blocked)."""
        every = self._cadence()
        if every <= 0:
            return None
        self._seen += 1
        if self._seen % every != 0:
            return None
        dirty = self._sentinel_dirty()
        if dirty is not None:
            self._blocked(dirty, seq)
            return None
        manifest = self._verify_manifest(seq)
        if manifest is None:
            self._blocked("manifest unreadable or blob integrity "
                          "verification failed", seq)
            return None
        if self._shard_plan is not None:
            # Shards ride the SAME manifest (atomic re-publish, same seq)
            # and must exist before the pin/announce makes the publish
            # discoverable — a sharded registry adopting this record must
            # find its part blobs on first read.
            manifest = self._write_shards(seq, manifest)
        record = {
            "manifest_seq": int(seq),
            "step": int(seq),
            "commit_dir": self.commit_dir,
            "cas": os.path.join(self.commit_dir, _CAS_SUBDIR),
            "time": float(self._clock()),
            "leaves_digest": leaves_digest(manifest),
            "rank": self._rank,
            "published": True,
        }
        # Pin BEFORE announcing: once a serving process can learn of this
        # manifest, GC must already be unable to sweep it.
        self.store.pin_manifest(seq, meta=record)
        self._trim_pins()
        if self.client is not None:
            try:
                self.client.announce_publish(record)
            except Exception as err:    # noqa: BLE001 — pin already heals
                get_logger().warning(
                    "publish announcement for seq=%d failed (%s) — "
                    "store-watch discovery via the pin file still works",
                    seq, err)
        self.last_published = record
        _telemetry.inc("hvd_serving_published_total")
        _telemetry.set_gauge("hvd_serving_last_published_seq", float(seq))
        _telemetry.record_event("publish", seq=seq,
                                leaves_digest=record["leaves_digest"])
        get_logger().info("published commit seq=%d (leaves_digest=%s)",
                          seq, record["leaves_digest"])
        return record

    def _trim_pins(self) -> None:
        """Unpin all but the newest ``HOROVOD_PUBLISH_KEEP`` publish pins
        (>= 2, so the previously-served manifest stays fetchable during a
        swap). Only pins carrying a publish record are touched — foreign
        pins are left alone."""
        keep = self._pin_keep()
        published = [s for s in self.store.pinned_seqs()
                     if (self.store.read_pin(s) or {}).get("published")]
        for seq in published[:-keep]:
            self.store.unpin_manifest(seq)


def attach(commit_dir: str, **kwargs) -> Publisher:
    """Create a :class:`Publisher` and hook it onto the commit writer's
    post-commit seam (elastic/state.py): the gate runs on the WRITER
    thread after every manifest publish for this ``commit_dir``, keeping
    blob re-hash work off the training step loop. Detach with
    :func:`detach`."""
    pub = Publisher(commit_dir, **kwargs)

    def _hook(cd: str, seq: int) -> None:
        if cd == commit_dir:
            pub.maybe_publish(seq)

    pub._hook = _hook           # keep the callable for detach()
    register_commit_hook(_hook)
    return pub


def detach(pub: Publisher) -> bool:
    hook = getattr(pub, "_hook", None)
    return unregister_commit_hook(hook) if hook is not None else False
