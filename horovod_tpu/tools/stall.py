"""Stall inspector — the training-progress watchdog.

Reference parity: ``horovod/common/stall_inspector.cc`` (SURVEY.md §2.1) —
the reference flags tensors submitted on some ranks but not others for
>60 s (``HOROVOD_STALL_CHECK_TIME_SECONDS``) and can hard-shutdown after
``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``.

Under SPMD there is no per-tensor negotiation to diverge, so the failure
mode shifts: a lost peer / hung ICI collective freezes the WHOLE step on
every rank. The TPU-true analog is therefore a step-progress watchdog: the
loop reports progress (``record`` or the ``wrap`` decorator); a daemon
thread warns when no step completes within the warning window and invokes
the shutdown action after the shutdown window (default: raise
``HorovodInternalError`` in the loop via a poisoned flag, which under
``@elastic.run`` triggers recovery — the same escalation path the
reference's shutdown takes).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Callable, Optional

from ..core.config import Config
from ..core.exceptions import HorovodInternalError
from ..core.logging import get_logger


class StallInspector:
    def __init__(self, warning_sec: float = 60.0,
                 shutdown_sec: float = 0.0,
                 on_stall: Optional[Callable[[float], None]] = None,
                 on_shutdown: Optional[Callable[[float], None]] = None,
                 poll_interval_sec: Optional[float] = None,
                 enabled: bool = True):
        self.warning_sec = warning_sec
        self.shutdown_sec = shutdown_sec
        self.enabled = enabled
        self._on_stall = on_stall
        self._on_shutdown = on_shutdown
        self._poll = poll_interval_sec or max(0.05, min(warning_sec / 4, 5.0))
        self._last = time.monotonic()
        self._step = 0
        self._warned = False
        self._poisoned = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_config(cls, config: Optional[Config] = None) -> "StallInspector":
        cfg = config or Config.from_env()
        return cls(warning_sec=cfg.stall_check_warning_sec,
                   shutdown_sec=cfg.stall_check_shutdown_sec,
                   enabled=not cfg.stall_check_disable)

    # -- progress reporting --------------------------------------------------

    def record(self, step: Optional[int] = None) -> None:
        """Report that a step completed. Raises HorovodInternalError if the
        watchdog already declared this worker dead (so the elastic wrapper
        can recover instead of hanging forever)."""
        if self._poisoned:
            self._poisoned = False
            raise HorovodInternalError(
                f"stall inspector: no progress for >{self.shutdown_sec:.0f}s")
        self._last = time.monotonic()
        self._step = step if step is not None else self._step + 1
        self._warned = False

    def wrap(self, step_fn: Callable) -> Callable:
        """Wrap a train-step callable so every completed call records
        progress (checks the poison flag before dispatch too)."""
        @functools.wraps(step_fn)
        def wrapped(*a, **kw):
            if self._poisoned:
                self.record()      # raises
            out = step_fn(*a, **kw)
            self.record()
            return out
        return wrapped

    # -- watchdog thread -----------------------------------------------------

    def start(self) -> "StallInspector":
        if not self.enabled:
            # HOROVOD_STALL_CHECK_DISABLE: the reference's kill-switch —
            # no watchdog, record() still cheap/no-op-safe.
            return self
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._watch, daemon=True,
                                            name="hvd-stall-inspector")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _watch(self) -> None:
        while not self._stop.wait(self._poll):
            idle = time.monotonic() - self._last
            if idle > self.warning_sec and not self._warned:
                self._warned = True
                get_logger().warning(
                    "stall inspector: no step progress for %.0fs "
                    "(last step %d) — a peer or collective may be hung "
                    "(reference: stall_inspector.cc warning)", idle,
                    self._step)
                if self._on_stall:
                    self._on_stall(idle)
            if self.shutdown_sec and idle > self.shutdown_sec:
                get_logger().error(
                    "stall inspector: exceeded shutdown window (%.0fs); "
                    "poisoning the step loop", idle)
                if self._on_shutdown:
                    self._on_shutdown(idle)
                self._poisoned = True
                self._last = time.monotonic()   # don't re-fire every poll
