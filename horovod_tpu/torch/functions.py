"""Parameter/object broadcast helpers for the torch API.

Reference parity: ``horovod/torch/functions.py`` (SURVEY.md §2.4, §5.4):
``broadcast_parameters`` (state_dict or named_parameters),
``broadcast_optimizer_state`` and ``broadcast_object`` — the
rank-0-restores-then-broadcasts pattern used for checkpoint resume.
"""

from __future__ import annotations

import io
import pickle

import numpy as np
import torch

from . import mpi_ops as _ops


def broadcast_parameters(params, root_rank: int = 0,
                         process_set=None) -> None:
    """Broadcast model parameters from ``root_rank`` to every rank.

    ``params`` is a ``model.state_dict()`` or a ``named_parameters``
    iterable, as in the reference. With ``process_set``, broadcast is
    among the set's members (``root_rank`` is a GLOBAL rank and must be
    a member, reference semantics).
    """
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None:
            continue
        if not torch.is_tensor(p):
            continue  # non-tensor state_dict entries are broadcast_object's job
        handles.append(_ops.broadcast_async_(p, root_rank, name=name,
                                             process_set=process_set))
    for h in handles:
        _ops.synchronize(h)


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0,
                              process_set=None) -> None:
    """Broadcast the optimizer's state (momenta etc.) from ``root_rank``.

    Mirrors the reference's approach: state tensors are broadcast in
    place; scalar hyper-state goes through :func:`broadcast_object` so all
    ranks agree bit-exactly.
    """
    state = optimizer.state_dict()
    # Root describes the full structure first (param_groups, scalar state,
    # tensor shapes/dtypes) so ranks with EMPTY state — the
    # rank-0-restores-then-broadcasts resume pattern — can allocate
    # placeholders and participate in every tensor broadcast instead of
    # deadlocking the name-keyed rendezvous.
    meta = None
    if _ops.rank() == root_rank:
        meta = {
            "param_groups": state["param_groups"],
            "scalar_state": {
                pid: {k: v for k, v in pstate.items()
                      if not torch.is_tensor(v)}
                for pid, pstate in state["state"].items()
            },
            "tensors": {
                pid: {k: (tuple(v.shape), v.dtype)
                      for k, v in pstate.items() if torch.is_tensor(v)}
                for pid, pstate in state["state"].items()
            },
        }
    meta = broadcast_object(meta, root_rank, name="optimizer.state.meta",
                            process_set=process_set)
    handles, tensors = [], {}
    for pid, entries in meta["tensors"].items():
        tensors[pid] = {}
        for k, (shape, dtype) in entries.items():
            local = state["state"].get(pid, {}).get(k)
            if not torch.is_tensor(local) or tuple(local.shape) != shape:
                local = torch.zeros(shape, dtype=dtype)
            tensors[pid][k] = local
            handles.append(_ops.broadcast_async_(
                local, root_rank, name=f"optimizer.state.{pid}.{k}",
                process_set=process_set))
    for h in handles:
        _ops.synchronize(h)
    new_state = {
        pid: {**meta["scalar_state"].get(pid, {}), **tensors[pid]}
        for pid in meta["tensors"]
    }
    optimizer.load_state_dict(
        {"state": new_state, "param_groups": meta["param_groups"]})


def broadcast_object(obj, root_rank: int = 0,
                     name: str = "broadcast_object", process_set=None):
    """Pickle-broadcast an arbitrary Python object from ``root_rank``
    (reference ``hvd.broadcast_object``: size first, then payload).
    With ``process_set``, among the set's members only."""
    if _ops.rank() == root_rank:
        buf = io.BytesIO()
        pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
        payload = np.frombuffer(buf.getvalue(), dtype=np.uint8).copy()
        sz = np.asarray([payload.shape[0]], dtype=np.int64)
    else:
        payload = None
        sz = np.zeros(1, dtype=np.int64)
    rt = _ops._rt()
    m = _ops._members(process_set)
    sz = rt.engine.broadcast(f"{name}.size", sz, root_rank, members=m)
    if payload is None:
        payload = np.zeros(int(sz[0]), dtype=np.uint8)
    payload = rt.engine.broadcast(f"{name}.data", payload, root_rank,
                                  members=m)
    return pickle.loads(payload.tobytes())


def allgather_object(obj, name: str = "allgather_object",
                     process_set=None) -> list:
    """Gather one arbitrary picklable object per rank; every rank gets the
    rank-ordered list (reference ``hvd.allgather_object``: pickle + size
    exchange + ragged byte allgather). With ``process_set``, member-ordered
    among the set's members only."""
    payload = np.frombuffer(
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
        dtype=np.uint8).copy()
    rt = _ops._rt()
    m = _ops._members(process_set)
    sizes = rt.engine.allgather(
        f"{name}.size", np.asarray([payload.shape[0]], dtype=np.int64),
        members=m)
    data = rt.engine.allgather(f"{name}.data", payload, members=m)
    out, off = [], 0
    for s in sizes:
        out.append(pickle.loads(data[off:off + int(s)].tobytes()))
        off += int(s)
    return out
