"""Variable/object broadcast helpers for the tensorflow API.

Reference parity: ``horovod/tensorflow/functions.py`` —
``broadcast_variables`` (the startup-sync primitive behind
``BroadcastGlobalVariablesCallback``), ``broadcast_object``,
``allgather_object``.
"""

from __future__ import annotations

import pickle

import numpy as np

from . import mpi_ops as _ops


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign every variable the root's value (reference
    ``hvd.broadcast_variables``): one broadcast per variable, name-keyed
    by position so ranks match regardless of variable-name differences."""
    for i, v in enumerate(variables):
        v.assign(_ops.broadcast(v, root_rank, name=f"broadcast_vars.{i}"))


def broadcast_object(obj, root_rank: int = 0,
                     name: str = "broadcast_object"):
    """Broadcast an arbitrary picklable object (reference
    ``hvd.broadcast_object``): size round + padded byte broadcast."""
    rt = _ops._rt()
    if rt.engine.rank() == root_rank:
        blob = np.frombuffer(
            pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
            dtype=np.uint8).copy()
    else:
        blob = np.zeros(0, dtype=np.uint8)
    n = rt.engine.broadcast(f"{name}.size",
                            np.asarray([blob.shape[0]], dtype=np.int64),
                            root_rank)
    padded = np.zeros(int(n[0]), dtype=np.uint8)
    padded[:blob.shape[0]] = blob
    data = rt.engine.broadcast(f"{name}.data", padded, root_rank)
    return pickle.loads(data.tobytes())


def allgather_object(obj, name: str = "allgather_object") -> list:
    """Gather one picklable object per rank; every rank gets the
    rank-ordered list (reference ``hvd.allgather_object``)."""
    rt = _ops._rt()
    payload = np.frombuffer(
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
        dtype=np.uint8).copy()
    sizes = rt.engine.allgather(
        f"{name}.size", np.asarray([payload.shape[0]], dtype=np.int64))
    data = rt.engine.allgather(f"{name}.data", payload)
    out, off = [], 0
    for s in sizes:
        out.append(pickle.loads(data[off:off + int(s)].tobytes()))
        off += int(s)
    return out
