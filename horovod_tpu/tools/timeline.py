"""Chrome-trace timeline writer.

Reference parity: ``horovod/common/timeline.cc`` (SURVEY.md §5.1) — the
reference logs every tensor's lifecycle (NEGOTIATE → QUEUE → MEMCPY_IN →
NCCL_ALLREDUCE → MEMCPY_OUT) from a dedicated writer thread into a JSON
file loadable in ``chrome://tracing``, enabled by ``HOROVOD_TIMELINE``.

On TPU the device-side story is better served by ``jax.profiler`` (xplane →
TensorBoard/Perfetto); this writer covers the HOST-side lifecycle that the
XLA trace does not show — eager-op dispatch, elastic events, autotune trials,
checkpoint commits — in the same Chrome-trace format so both can be loaded
side by side. ``merge_chrome_traces`` below merges them.

Thread model mirrors the reference: events are queued from any thread and a
single writer thread drains to disk (crash-safe incremental JSON array).
"""

from __future__ import annotations

import atexit
import json
import os
import queue
import threading
import time
from typing import Optional


class Timeline:
    """Incremental Chrome-trace (JSON array format) event writer."""

    def __init__(self, path: str, mark_cycles: bool = False):
        self.path = path
        self.mark_cycles = mark_cycles
        self._q: "queue.Queue[Optional[dict]]" = queue.Queue()
        # Monotonic clock anchored at construction: wall-clock (time.time)
        # is NTP-steppable mid-run, which reorders/negates span timestamps
        # in the viewer; perf_counter never goes backwards.
        self._start = time.perf_counter()
        self._open_spans: dict = {}
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._file = open(path, "w")
        self._file.write("[\n")
        self._file.flush()  # header visible even if the process dies early
        self._first = True
        self._writer = threading.Thread(target=self._drain, daemon=True,
                                        name="hvd-timeline-writer")
        self._closed = False
        self._writer.start()
        # Normal interpreter exit closes the JSON array even when the
        # owner forgot stop_timeline(); close() is idempotent so an
        # explicit close first is fine. (os._exit paths skip atexit by
        # design — the flight recorder covers those, docs/telemetry.md.)
        atexit.register(self.close)

    # -- event API (mirrors timeline.cc ActivityStart/ActivityEnd/Marker) --

    def _us(self) -> int:
        return int((time.perf_counter() - self._start) * 1e6)

    def activity_start(self, name: str, activity: str, rank: int = 0,
                       tid: int = 0) -> None:
        self._q.put({"name": activity, "cat": name, "ph": "B",
                     "ts": self._us(), "pid": rank, "tid": tid})

    def activity_end(self, name: str, activity: str, rank: int = 0,
                     tid: int = 0) -> None:
        self._q.put({"name": activity, "cat": name, "ph": "E",
                     "ts": self._us(), "pid": rank, "tid": tid})

    def marker(self, name: str, rank: int = 0) -> None:
        self._q.put({"name": name, "ph": "i", "ts": self._us(),
                     "pid": rank, "tid": 0, "s": "g"})

    def mark_cycle(self) -> None:
        if self.mark_cycles:
            self.marker("CYCLE")

    def span(self, name: str, activity: str = "SPAN"):
        """Context manager convenience (host-side spans)."""
        tl = self

        class _Span:
            def __enter__(self):
                tl.activity_start(name, activity)
                return self

            def __exit__(self, *exc):
                tl.activity_end(name, activity)
                return False

        return _Span()

    # -- writer thread ----------------------------------------------------

    def _drain(self) -> None:
        while True:
            ev = self._q.get()
            if ev is None:
                return
            with self._lock:
                if self._file.closed:
                    return
                if not self._first:
                    self._file.write(",\n")
                self._first = False
                self._file.write(json.dumps(ev))
                self._file.flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._writer.join(timeout=5)
        with self._lock:
            self._file.write("\n]\n")
            self._file.close()


def merge_chrome_traces(paths, out_path, labels=None):
    """Merge chrome-trace JSON files into one (the hvd timeline + a
    ``jax.profiler`` chrome export, or several hosts' timelines — parity with
    the reference's single merged timeline from ``timeline.cc``, which wrote
    one file because all activity flowed through rank-0's controller; here
    each source writes independently and is merged after the fact).

    Each input's events keep their timestamps but get a distinct ``pid``
    namespace plus a process_name metadata row, so tracks stay separated in
    the viewer. Inputs may be ``[...]`` arrays or ``{"traceEvents": [...]}``
    (both chrome-trace flavors); gzipped files are handled; ``stackFrames``
    tables are carried over with ids renamed to stay unambiguous.
    """
    import gzip
    import json as _json

    merged, stack_frames, extra = [], {}, {}
    for i, p in enumerate(paths):
        opener = gzip.open if str(p).endswith(".gz") else open
        with opener(p, "rt") as f:
            data = _json.load(f)
        if isinstance(data, dict):
            if "traceEvents" not in data:
                raise ValueError(
                    f"{p}: not a chrome trace (object without 'traceEvents')")
            events = data["traceEvents"]
        else:
            data, events = {}, data
        label = (labels[i] if labels and i < len(labels)
                 else os.path.basename(str(p)))
        for k, frame in (data.get("stackFrames") or {}).items():
            frame = dict(frame)
            if "parent" in frame:
                frame["parent"] = f"t{i}:{frame['parent']}"
            stack_frames[f"t{i}:{k}"] = frame
        for k, v in data.items():
            if k not in ("traceEvents", "stackFrames"):
                extra.setdefault(k, v)  # e.g. displayTimeUnit: first wins
        base = (i + 1) * 100000
        pid_map, labeled = {}, set()
        for ev in events:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            orig = ev.get("pid", 0)
            if orig not in pid_map:
                # Dense remap (not modulo) so distinct source pids can never
                # collide into one track.
                pid_map[orig] = base + len(pid_map)
            ev["pid"] = pid_map[orig]
            for sf_key in ("sf", "esf"):
                if sf_key in ev:
                    ev[sf_key] = f"t{i}:{ev[sf_key]}"
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                # Prefix the input's own track names with our label so the
                # merged inputs stay distinguishable in the viewer.
                args = dict(ev.get("args") or {})
                args["name"] = f"{label}/{args.get('name', orig)}"
                ev["args"] = args
                labeled.add(ev["pid"])
            merged.append(ev)
        for orig, pid in pid_map.items():
            if pid not in labeled:
                name = label if len(pid_map) == 1 else f"{label}/p{orig}"
                merged.append({"name": "process_name", "ph": "M", "pid": pid,
                               "args": {"name": name}})
    out = {"traceEvents": merged, **extra}
    if stack_frames:
        out["stackFrames"] = stack_frames
    with open(out_path, "w") as f:
        _json.dump(out, f)
    return out_path
