"""Elastic state for TF/Keras models.

Reference parity: ``horovod/tensorflow/elastic.py`` (``TensorFlowState``
/ ``TensorFlowKerasState``, SURVEY.md §2.5, §3.4): commit/restore of
variable values (+ arbitrary scalar attributes) and ``sync()``
broadcasting from the new rank 0 after a membership change. Built on
:class:`horovod_tpu.elastic.state.FrameworkState`, so commits ALSO
persist to ``HOROVOD_ELASTIC_COMMIT_DIR`` and ``load_latest()`` resumes
a relaunched generation (the restart elastic mode). Plugs into the same
``@hvd.elastic.run`` wrapper as the JAX/torch states; the exception
protocol (``HorovodInternalError`` / ``HostsUpdatedInterrupt``) is
shared.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..elastic.state import FrameworkState
from . import functions as _fn


class TensorFlowState(FrameworkState):
    """Commit/restore/sync over a list of tf.Variables (+ scalars)."""

    _GUARDED = ("variables",)

    def __init__(self, variables=None, **kwargs: Any):
        self.variables = list(variables) if variables is not None else []
        super().__init__(**kwargs)

    def _collect(self):
        """Override point: the live variable list (re-evaluated at every
        snapshot/sync so lazily-created variables are picked up)."""
        return self.variables

    def _framework_snapshot(self):
        self.variables = list(self._collect())
        return [np.asarray(v) for v in self.variables]

    def _framework_restore(self, snap) -> None:
        # Re-collect so variables built since the snapshot are aligned;
        # ones newer than the snapshot keep their live values (zip stops
        # at the shorter list) — same behavior as restoring a checkpoint
        # into a partially-built optimizer.
        self.variables = list(self._collect())
        for v, saved in zip(self.variables, snap):
            v.assign(saved)

    def _framework_broadcast(self) -> None:
        self.variables = list(self._collect())
        _fn.broadcast_variables(self.variables, root_rank=0)

    def _broadcast_scalars(self, scalars):
        return _fn.broadcast_object(scalars, root_rank=0,
                                    name="tf_state.scalars")


class TensorFlowKerasState(TensorFlowState):
    """Reference ``TensorFlowKerasState``: tracks a Keras model's (and
    optionally its optimizer's) variables, RE-COLLECTED at every
    snapshot/sync — Keras 3 creates optimizer slot variables (momentum,
    velocity, ...) lazily at the first ``apply_gradients``, so a list
    frozen at construction would silently skip them."""

    _GUARDED = ("variables", "model", "optimizer")

    def __init__(self, model, optimizer=None, **kwargs: Any):
        self.model = model
        self.optimizer = optimizer
        super().__init__(self._collect_keras(model, optimizer), **kwargs)

    @staticmethod
    def _collect_keras(model, optimizer):
        variables = list(model.trainable_variables) \
            + list(model.non_trainable_variables)
        if optimizer is not None and getattr(optimizer, "variables", None):
            variables += list(optimizer.variables)
        return variables

    def _collect(self):
        return self._collect_keras(self.model, self.optimizer)
