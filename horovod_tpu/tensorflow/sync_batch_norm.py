"""Cross-rank SyncBatchNorm for the tensorflow/keras API.

Reference parity: ``horovod/tensorflow/sync_batch_norm.py`` (SURVEY.md
§2.4, §2.6): batch statistics combine across ranks — one packed
allreduce of (count, sum, sq-sum) so uneven batches weight correctly —
with running stats updated from the global moments. Single-rank or
inference behaves exactly like ``keras.layers.BatchNormalization``.
"""

from __future__ import annotations

import keras
import tensorflow as tf

from . import mpi_ops as _ops
from ..core.engine import Sum


class SyncBatchNormalization(keras.layers.BatchNormalization):
    """Drop-in ``BatchNormalization`` whose training statistics span all
    ranks (channels-last; the reference layer's contract)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        try:
            self._hvd_name = _ops._rt().autoname("sync_batch_norm", None)
        except RuntimeError:
            self._hvd_name = "sync_batch_norm.uninit"

    def call(self, inputs, training=None):
        # keras contract: a frozen layer (trainable=False) uses moving
        # stats and must not mutate them, even under training=True.
        if not training or not self.trainable or _ops.size() == 1:
            return super().call(inputs, training=training)

        x = tf.convert_to_tensor(inputs)
        ndim = x.shape.rank
        ax = self.axis if self.axis >= 0 else ndim + self.axis
        if ax != ndim - 1:
            raise ValueError(
                "SyncBatchNormalization supports channels-last only in "
                f"this build; got axis={self.axis} for rank-{ndim} input")
        axes = list(range(ndim - 1))  # reduce all but channels-last
        c = x.shape[-1]
        # Statistics accumulate in float32 regardless of input dtype:
        # fp16 counts/sq-sums overflow at image-sized batches.
        xs = tf.cast(x, tf.float32)
        count = tf.cast(tf.size(x) / c, tf.float32)[None]
        local_sum = tf.reduce_sum(xs, axis=axes)
        local_sqsum = tf.reduce_sum(tf.square(xs), axis=axes)

        packed = tf.concat([count, local_sum, local_sqsum], 0)
        packed = _ops.allreduce(packed, op=Sum, name=self._hvd_name)
        total = packed[0]
        mean = packed[1:1 + c] / total
        sqmean = packed[1 + c:] / total
        var = sqmean - tf.square(mean)

        if self.moving_mean is not None:
            m = self.momentum
            # Biased (population) variance for the running stat: the
            # Keras BatchNormalization convention, and what this layer's
            # own single-rank/frozen fallback through super().call uses —
            # so inference stats agree between the two code paths.
            self.moving_mean.assign(
                self.moving_mean * m
                + tf.cast(mean, self.moving_mean.dtype) * (1 - m))
            self.moving_variance.assign(
                self.moving_variance * m
                + tf.cast(var, self.moving_variance.dtype) * (1 - m))

        gamma = tf.cast(self.gamma, tf.float32) if self.scale \
            else tf.ones_like(mean)
        beta = tf.cast(self.beta, tf.float32) if self.center \
            else tf.zeros_like(mean)
        out = tf.nn.batch_normalization(xs, mean, var, beta, gamma,
                                        self.epsilon)
        return tf.cast(out, x.dtype)


#: Reference alias: ``hvd.SyncBatchNorm`` names the same layer.
SyncBatchNorm = SyncBatchNormalization
