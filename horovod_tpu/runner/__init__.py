"""horovod_tpu.runner — launcher / CLI layer (reference L6, SURVEY.md §2.5).

``python -m horovod_tpu.runner.launch`` (alias ``hvdrun``) replaces
``horovodrun``; ``runner.run()`` replaces ``horovod.run()``. The Gloo HTTP
rendezvous + per-GPU ssh workers of the reference collapse into per-host
processes joined through the JAX coordination service over DCN (§2.7).
"""

from .api import run
from .hosts import (HostAssignment, HostInfo, SlotInfo, get_host_assignments,
                    parse_host_files, parse_hosts)
from .launch import check_build, main, make_parser, parse_settings, run_commandline
from .settings import Settings

__all__ = [
    "run", "HostAssignment", "HostInfo", "SlotInfo", "get_host_assignments",
    "parse_host_files", "parse_hosts", "check_build", "main", "make_parser",
    "parse_settings", "run_commandline", "Settings",
]
