"""Compat shim: the process-collective engines are framework-neutral
(numpy payloads) and now live in ``horovod_tpu.core.engine`` so the
tensorflow binding can share them; this module re-exports the public
surface under its historical name."""

from ..core.engine import (  # noqa: F401
    Adasum, Average, CollectiveEngine, JaxProcessEngine, Max, Min, Product,
    SingleProcessEngine, Sum, ThreadSimEngine, _Rendezvous, reduce_arrays)
