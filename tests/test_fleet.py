"""Fleet unit tests (tier-1: injected clocks, no real sleeps on the hot
assertions): replica registry journal replay, health-gated pruning,
heartbeat-on-poll, the arbiter's hysteresis/cooldown/bounds and its
crash-restart reseed, and the failover client against in-process stub
replicas. The np=3 subprocess chaos companions live in
tests/test_fleet_chaos.py (marked slow).
"""

import json
import socket
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from horovod_tpu.elastic import constants as C
from horovod_tpu.elastic import journal as journal_mod
from horovod_tpu.elastic.arbiter import ArbiterPolicy, FleetArbiter
from horovod_tpu.elastic.service import CoordinatorClient, CoordinatorService
from horovod_tpu.runner import secret as _secret
from horovod_tpu.serving.fleet import (FleetClient, FleetOverloadedError,
                                       FleetRequestError, ReplicaAgent)


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture
def svc(tmp_path):
    key = _secret.make_secret_key()
    clock = _Clock()
    service = CoordinatorService(key, bind_host="127.0.0.1",
                                 journal_path=str(tmp_path / "wal.jsonl"),
                                 clock=clock)
    yield service, key, clock
    service.close()


def _client(svc_obj, key, **kw):
    return CoordinatorClient(f"127.0.0.1:{svc_obj.port}", key,
                             sleep=lambda s: None, **kw)


# ------------------------------------------------- journal replica/arbiter


def test_journal_replays_replica_lifecycle():
    st = journal_mod.empty_state()
    for rec in (
            {"op": "replica", "action": "register", "replica_id": "a",
             "addr": "127.0.0.1:1", "rank": 901},
            {"op": "replica", "action": "register", "replica_id": "b",
             "addr": "127.0.0.1:2", "rank": 902},
            {"op": "replica", "action": "drain", "replica_id": "a"},
            {"op": "replica", "action": "deregister", "replica_id": "b"},
            {"op": "arbiter", "seq": 3, "serving_target": 2,
             "training_np": 6, "reason": "overload"}):
        journal_mod.apply_record(st, rec)
    assert set(st["replicas"]) == {"a"}
    assert st["replicas"]["a"]["draining"] is True
    assert st["replicas"]["a"]["rank"] == 901
    assert st["arbiter_seq"] == 3
    assert st["fleet"] == {"serving_target": 2, "training_np": 6,
                           "reason": "overload"}
    # deregister is idempotent at replay too
    journal_mod.apply_record(st, {"op": "replica", "action": "deregister",
                                  "replica_id": "b"})
    assert set(st["replicas"]) == {"a"}


def test_service_crash_restart_restores_replicas_and_fleet(tmp_path, svc):
    service, key, clock = svc
    service._record_replica({"action": "register", "replica_id": "r1",
                             "addr": "127.0.0.1:9001", "rank": 901})
    service._record_replica({"action": "register", "replica_id": "r2",
                             "addr": "127.0.0.1:9002", "rank": 902})
    service._record_replica({"action": "drain", "replica_id": "r2"})
    seq = service.record_arbiter_decision(2, 6, "overload")
    jp = service._journal.path
    service.simulate_crash()
    rebuilt = CoordinatorService(key, bind_host="127.0.0.1",
                                 journal_path=jp, restore=True,
                                 clock=clock)
    try:
        snap = rebuilt.replicas_snapshot()
        assert set(snap) == {"r1", "r2"}
        assert snap["r2"]["draining"] is True
        assert snap["r1"]["addr"] == "127.0.0.1:9001"
        view = rebuilt.fleet_view()
        assert view["arbiter_seq"] == seq
        assert view["fleet"]["serving_target"] == 2
        assert view["fleet"]["training_np"] == 6
    finally:
        rebuilt.close()


# --------------------------------------------- lifecycle + grace pruning


def test_replica_lifecycle_over_http(svc):
    service, key, _clock = svc
    c = _client(service, key)
    assert c.register_replica("rep-a", "127.0.0.1:9001", rank=901)
    assert c.register_replica("rep-b", "127.0.0.1:9002", rank=902)
    view = c.get_replicas()
    assert [r["id"] for r in view["replicas"]] == ["rep-a", "rep-b"]
    assert all(not r["draining"] for r in view["replicas"])
    assert c.drain_replica("rep-a")
    view = c.get_replicas()
    drain_flags = {r["id"]: r["draining"] for r in view["replicas"]}
    assert drain_flags == {"rep-a": True, "rep-b": False}
    assert c.deregister_replica("rep-a", reason="drained")
    assert c.deregister_replica("rep-a", reason="drained")  # idempotent
    assert [r["id"] for r in c.get_replicas()["replicas"]] == ["rep-b"]
    # drain of an unknown id is a no-op refusal, not a crash
    assert not service._record_replica({"action": "drain",
                                        "replica_id": "ghost"})


def test_replica_grace_pruning_and_heartbeat_on_poll(svc, monkeypatch):
    service, key, clock = svc
    monkeypatch.setenv(C.REPLICA_GRACE_ENV, "10")
    hb = _client(service, key, replica_id="rep-hb")
    silent = _client(service, key)
    assert hb.register_replica("rep-hb", "127.0.0.1:9001", rank=901)
    assert silent.register_replica("rep-silent", "127.0.0.1:9002", rank=902)
    clock.t = 6.0
    # rep-hb's ordinary world poll carries replica=rep-hb -> heartbeat;
    # rep-silent never polls again.
    assert hb.get_world() is not None
    clock.t = 12.0
    # rep-silent is 12s silent (> grace); rep-hb heartbeat was 6s ago.
    view = service.replicas_view()
    assert [r["id"] for r in view["replicas"]] == ["rep-hb"]
    # the prune was journaled as a deregister: a crash-restart replays to
    # the same membership the live list served
    st = journal_mod.replay(service._journal.path)
    assert set(st["replicas"]) == {"rep-hb"}
    # a pruned replica's stale poll must NOT resurrect it
    assert silent.get_world() is not None
    clock.t = 13.0
    assert [r["id"] for r in service.replicas_view()["replicas"]] \
        == ["rep-hb"]


def test_touch_unknown_replica_ignored(svc):
    service, _key, _clock = svc
    service._touch_replica_locked("never-registered")
    assert service.replicas_snapshot() == {}


# ------------------------------------------------------------- arbiter


def _arm_signals(service, queue_depth, staleness=0.0, step_wall=0.05):
    service._record_metrics({"rank": 901, "g": {
        "hvd_serving_queue_depth": float(queue_depth),
        "hvd_serving_staleness_seconds": float(staleness)}})
    service._record_metrics({"rank": 0, "g": {
        'hvd_step_wall_seconds{what="train"}': float(step_wall)}})


def test_serving_signals_split_by_rank_band(svc):
    service, _key, _clock = svc
    _arm_signals(service, queue_depth=7.0, staleness=2.5, step_wall=0.125)
    sig = service.serving_signals()
    assert sig["queue_depth"] == 7.0
    assert sig["staleness_s"] == 2.5
    assert sig["step_wall_s"] == 0.125      # labeled gauge still matched


def test_arbiter_hysteresis_sustain_and_bounds(svc):
    service, _key, _clock = svc
    pol = ArbiterPolicy(queue_high=8.0, queue_low=1.0, sustain=2,
                        cooldown_s=30.0, min_training_np=2,
                        min_replicas=1, max_replicas=3)
    clock = _Clock()
    arb = FleetArbiter(service, total_hosts=8, policy=pol, clock=clock)
    assert arb.shape == {"serving_target": 1, "training_np": 7}
    _arm_signals(service, queue_depth=12.0)
    assert arb.evaluate() is None            # 1 eval < sustain=2
    clock.t = 1.0
    dec = arb.evaluate()                     # sustained: scale out
    assert dec is not None and dec["serving_target"] == 2
    assert dec["training_np"] == 6 and dec["seq"] == 1
    assert arb.shape["serving_target"] + arb.shape["training_np"] == 8
    # cooldown: still overloaded — the streak keeps counting but no
    # decision lands until the 30s dead time elapses
    clock.t = 10.0
    assert arb.evaluate() is None
    clock.t = 31.5
    dec = arb.evaluate()   # cooldown over + overload sustained through it
    assert dec is not None and dec["serving_target"] == 3
    # at max_replicas: overload can no longer scale out
    clock.t = 100.0
    assert arb.evaluate() is None
    clock.t = 101.0
    assert arb.evaluate() is None
    # idle traffic reclaims replicas for training, down to min_replicas
    _arm_signals(service, queue_depth=0.0)
    clock.t = 200.0
    assert arb.evaluate() is None
    clock.t = 201.0
    dec = arb.evaluate()
    assert dec is not None and dec["serving_target"] == 2
    clock.t = 300.0
    arb.evaluate()
    clock.t = 301.0
    dec = arb.evaluate()
    assert dec is not None and dec["serving_target"] == 1
    clock.t = 400.0
    arb.evaluate()
    clock.t = 401.0
    assert arb.evaluate() is None            # min_replicas floor holds


def test_arbiter_training_floor_blocks_scale_out(svc):
    service, _key, _clock = svc
    pol = ArbiterPolicy(queue_high=8.0, queue_low=1.0, sustain=1,
                        cooldown_s=0.0, min_training_np=3,
                        min_replicas=1, max_replicas=4)
    clock = _Clock()
    arb = FleetArbiter(service, total_hosts=4, policy=pol, clock=clock)
    assert arb.shape == {"serving_target": 1, "training_np": 3}
    _arm_signals(service, queue_depth=100.0)
    # training is already at its floor: overload cannot take a host
    assert arb.evaluate() is None
    assert arb.shape == {"serving_target": 1, "training_np": 3}


def test_arbiter_staleness_triggers_scale_out(svc):
    service, _key, _clock = svc
    pol = ArbiterPolicy(queue_high=1e9, queue_low=-1.0, sustain=1,
                        cooldown_s=0.0, staleness_high_s=5.0,
                        min_training_np=1, min_replicas=1, max_replicas=4)
    arb = FleetArbiter(service, total_hosts=4, policy=pol, clock=_Clock())
    _arm_signals(service, queue_depth=0.0, staleness=9.0)
    dec = arb.evaluate()
    assert dec is not None and dec["serving_target"] == 2


def test_arbiter_crash_restart_reseeds_same_shape(tmp_path, svc):
    service, key, svc_clock = svc
    pol = ArbiterPolicy(queue_high=8.0, queue_low=1.0, sustain=1,
                        cooldown_s=0.0, min_training_np=1,
                        min_replicas=1, max_replicas=4)
    arb = FleetArbiter(service, total_hosts=6, policy=pol, clock=_Clock())
    _arm_signals(service, queue_depth=50.0)
    arb.evaluate()
    arb.evaluate()
    shape_before = dict(arb.shape)
    seq_before = service.fleet_view()["arbiter_seq"]
    assert shape_before == {"serving_target": 3, "training_np": 3}
    jp = service._journal.path
    service.simulate_crash()
    rebuilt = CoordinatorService(key, bind_host="127.0.0.1",
                                 journal_path=jp, restore=True,
                                 clock=svc_clock)
    try:
        arb2 = FleetArbiter(rebuilt, total_hosts=6, policy=pol,
                            clock=_Clock())
        # the resumed arbiter continues the SAME rebalance, same seq
        assert arb2.shape == shape_before
        assert rebuilt.fleet_view()["arbiter_seq"] == seq_before
        # and its NEXT decision extends the journaled sequence
        _arm_signals(rebuilt, queue_depth=50.0)
        dec = rebuilt and arb2.evaluate()
        assert dec is not None and dec["seq"] == seq_before + 1
    finally:
        rebuilt.close()


def test_arbiter_rejects_empty_world(svc):
    service, _key, _clock = svc
    with pytest.raises(ValueError):
        FleetArbiter(service, total_hosts=0)


# ----------------------------------------------------- failover client


class _StubReplica:
    """A bare HTTP replica answering /predict with a fixed plan: each
    entry is an int status (non-200 refused with that code) or "ok"."""

    def __init__(self, plan="ok", retry_after="0.25"):
        self.plan = plan if isinstance(plan, list) else [plan]
        self.calls = 0
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                self.rfile.read(
                    int(self.headers.get("Content-Length", "0")))
                step = stub.plan[min(stub.calls, len(stub.plan) - 1)]
                stub.calls += 1
                if step == "ok":
                    body = json.dumps({"ok": True,
                                       "served_by": stub.addr}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(int(step))
                    if step == 429:
                        self.send_header("Retry-After", retry_after)
                    self.send_header("Content-Length", "0")
                    self.end_headers()

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.addr = "127.0.0.1:%d" % self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()

    def close(self):
        self._server.shutdown()
        self._server.server_close()


def _dead_addr():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"127.0.0.1:{port}"


def test_fleet_client_fails_over_dead_replica():
    good = _StubReplica("ok")
    try:
        fc = FleetClient(replicas=[_dead_addr(), good.addr], timeout_s=5)
        out = fc.predict({"x": 1.0})
        assert out["ok"] and out["served_by"] == good.addr
        assert fc.stats["failovers"] == 1
    finally:
        good.close()


def test_fleet_client_fails_over_503_and_429(monkeypatch):
    draining = _StubReplica(503)
    shedding = _StubReplica(429)
    good = _StubReplica("ok")
    try:
        fc = FleetClient(replicas=[draining.addr, shedding.addr, good.addr],
                         timeout_s=5)
        outs = [fc.predict({"x": i}) for i in range(3)]
        assert all(o["ok"] for o in outs)
        assert all(o["served_by"] == good.addr for o in outs)
        assert fc.stats["shed_seen"] >= 1
    finally:
        for s in (draining, shedding, good):
            s.close()


def test_fleet_client_all_shed_raises_overloaded():
    a, b = _StubReplica(429, retry_after="2.5"), _StubReplica(429)
    try:
        fc = FleetClient(replicas=[a.addr, b.addr], timeout_s=5)
        with pytest.raises(FleetOverloadedError) as ei:
            fc.predict({"x": 1.0})
        assert ei.value.retry_after_s == 2.5
        # backpressure surfaced as ONE pass over the set, not max_tries
        assert a.calls + b.calls == 2
    finally:
        a.close()
        b.close()


def test_fleet_client_exhaustion_raises_request_error():
    fc = FleetClient(replicas=[_dead_addr()], timeout_s=1, max_tries=2)
    with pytest.raises(FleetRequestError):
        fc.predict({"x": 1.0})


def test_fleet_client_refresh_skips_draining(svc):
    service, key, _clock = svc
    c = _client(service, key)
    assert c.register_replica("rep-a", "127.0.0.1:9001", rank=901)
    assert c.register_replica("rep-b", "127.0.0.1:9002", rank=902)
    fc = FleetClient(coord=c)
    assert sorted(fc.healthy_addrs()) == ["127.0.0.1:9001",
                                          "127.0.0.1:9002"]
    assert c.drain_replica("rep-a")
    fc.refresh(force=True)
    assert fc.healthy_addrs() == ["127.0.0.1:9002"]


def test_fleet_client_needs_a_source():
    with pytest.raises(ValueError):
        FleetClient()


# -------------------------------------------------------- replica agent


def test_replica_agent_registers_and_drain_deregisters(svc, monkeypatch):
    import numpy as np
    from horovod_tpu.serving import InferenceServer, ModelRegistry

    service, key, _clock = svc
    monkeypatch.setenv(C.REPLICA_GRACE_ENV, "9")
    monkeypatch.setenv("HOROVOD_SERVING_LONG_POLL_SECONDS", "30")
    reg = ModelRegistry()
    srv = InferenceServer(reg, lambda payload, inputs, n: [0.0] * n,
                          buckets=(1, 2), window_s=0.0,
                          request_timeout_s=5.0)
    agent = None
    try:
        client = CoordinatorClient(f"127.0.0.1:{service.port}", key,
                                   watch_publish=True,
                                   sleep=lambda s: None)
        agent = ReplicaAgent(srv, client, replica_id="rep-agent", rank=901)
        assert agent.registered
        assert client.replica_id == "rep-agent"
        view = service.replicas_view()
        assert [r["id"] for r in view["replicas"]] == ["rep-agent"]
        assert view["replicas"][0]["addr"] == srv.addr()
        # poll pacing stays inside the heartbeat grace window
        assert agent._wait_bound() == pytest.approx(3.0)
        # drain: coordinator mark -> server drain -> deregister callback
        assert agent.drain(timeout_s=5.0)
        assert service.replicas_view()["replicas"] == []
    finally:
        if agent is not None:
            agent.close(deregister=False)
        srv.close()


# ------------------------------------- replica agent: preemption drain


def _live_registry(tmp_path, name="commits", w=7.0):
    """A ModelRegistry holding one published generation (the serving
    floor a /predict needs)."""
    import os

    import numpy as np

    from horovod_tpu.checkpoint.store import BlobStore
    from horovod_tpu.elastic.state import ObjectState
    from horovod_tpu.serving import ModelRegistry, Publisher

    d = str(tmp_path / name)
    os.makedirs(d, exist_ok=True)
    state = ObjectState(commit_dir=d, commit_async=False, w=np.float32(w))
    state.commit()
    pub = Publisher(d, every=1,
                    counters=lambda: {"steps_skipped": 0, "rollbacks": 0})
    assert pub.maybe_publish(state._commit_seq) is not None
    store = BlobStore(os.path.join(d, "cas"))
    reg = ModelRegistry(store=store)
    assert reg.poll_store(store)
    return reg


def test_replica_agent_preempt_drain_completes_inflight(svc, monkeypatch,
                                                        tmp_path):
    """SIGTERM on a serving host (ISSUE 20): the agent joins the
    lifecycle plane, the in-flight request FINISHES, and deregistration
    fires only after the server drained — the reuse of the training
    workers' graceful-handoff plane on the serving side."""
    import os
    import signal
    import time

    from horovod_tpu.core import lifecycle
    from horovod_tpu.serving import InferenceServer

    service, key, _clock = svc
    monkeypatch.setenv(C.REPLICA_GRACE_ENV, "9")
    entered = threading.Event()

    def slow_forward(payload, inputs, n):
        entered.set()
        time.sleep(0.4)
        return [1.0] * n

    reg = _live_registry(tmp_path)
    srv = InferenceServer(reg, slow_forward, buckets=(1, 2), window_s=0.0,
                          request_timeout_s=10.0)
    agent = None
    lifecycle.uninstall()
    try:
        client = _client(service, key, watch_publish=True)
        agent = ReplicaAgent(srv, client, replica_id="rep-pre", rank=901)
        assert agent.registered
        assert agent.enable_preempt_drain(timeout_s=10.0)
        out = {}

        def inflight():
            req = urllib.request.Request(
                f"http://{srv.addr()}/predict",
                data=json.dumps({"x": 1.0}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10.0) as r:
                out["status"] = r.status
                out["body"] = json.loads(r.read().decode())

        th = threading.Thread(target=inflight)
        th.start()
        assert entered.wait(5.0)             # request is on the floor
        os.kill(os.getpid(), signal.SIGTERM)  # the real reclaim notice
        th.join(timeout=10.0)
        assert not th.is_alive()
        # the in-flight request completed — a reset here is the bug
        assert out["status"] == 200 and out["body"]["ok"]
        # drain-on-preempt deregistered the replica at the coordinator
        deadline = time.monotonic() + 10.0
        while (service.replicas_view()["replicas"]
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert service.replicas_view()["replicas"] == []
    finally:
        lifecycle.uninstall()
        if agent is not None:
            agent.close(deregister=False)
        srv.close()


def test_fleet_client_sees_failover_not_resets_across_preempt(svc, tmp_path):
    """Traffic across a preemption drill: 100/100 requests complete;
    the drained replica's load moves to the survivor with zero errors
    surfaced to the FleetClient caller."""
    import time

    from horovod_tpu.core import lifecycle
    from horovod_tpu.serving import InferenceServer

    service, key, _clock = svc
    srvs, agents = [], []
    lifecycle.uninstall()
    try:
        for i, rid in enumerate(("rep-a", "rep-b")):
            reg = _live_registry(tmp_path, name=f"commits-{rid}")
            srv = InferenceServer(
                reg, lambda payload, inputs, n, rid=rid: [float(i)] * n,
                buckets=(1, 2), window_s=0.0, request_timeout_s=10.0)
            client = _client(service, key, watch_publish=True)
            agent = ReplicaAgent(srv, client, replica_id=rid, rank=901 + i)
            assert agent.registered
            srvs.append(srv)
            agents.append(agent)
        # only the victim joins the plane: the drill below must drain
        # rep-a and leave rep-b serving
        assert agents[0].enable_preempt_drain(timeout_s=10.0)
        fc = FleetClient(coord=_client(service, key), timeout_s=10.0,
                         refresh_s=0.05, max_tries=8)
        done = 0
        for i in range(100):
            if i == 20:
                lifecycle.request_preempt()   # deterministic drill
            out = fc.predict({"x": float(i)})
            assert out.get("ok"), out
            done += 1
        assert done == 100                    # zero lost, zero resets
        assert fc.stats["requests"] == 100
        # the drain really happened: rep-a is gone from the registry
        deadline = time.monotonic() + 10.0
        while (len(service.replicas_view()["replicas"]) > 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        ids = [r["id"] for r in service.replicas_view()["replicas"]]
        assert ids == ["rep-b"]
        # and the survivor answers alone
        assert fc.predict({"x": 0.0}).get("ok")
    finally:
        lifecycle.uninstall()
        for a in agents:
            a.close(deregister=False)
        for s in srvs:
            s.close()
