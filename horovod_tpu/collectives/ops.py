"""In-graph collective primitives — the data plane.

Reference parity: the op layer of ``horovod/common/ops/`` (SURVEY.md §2.2)
plus the per-framework op surface (``hvd.allreduce/allgather/broadcast/
alltoall/reducescatter/grouped_*``). Where the reference routes an enqueued
tensor through negotiation → fusion buffer → NCCL (§3.2 call stack), here
every op is a jit-compatible function over a named mesh axis that lowers to a
single ``xla::AllReduce``-family HLO **inside** the compiled graph — the
thing the reference's ``tensorflow/xla_mpi_ops.cc`` CustomCall explicitly
could not do (it had to escape the graph via host callback; SURVEY.md §3.5).

Fusion: the reference's fusion buffer + cycle-time batching is replaced by
``grouped_*`` ops which pack leaves into explicit flat buckets sized by
``HOROVOD_FUSION_THRESHOLD`` (``_fused_reduce``) — a compile-time fusion
buffer with zero host involvement, emitted in reverse-layer order so XLA's
latency-hiding scheduler overlaps the first buckets' collectives with the
still-running backward (docs/fusion.md). XLA's own collective combiner
remains available as an opt-in (``HOROVOD_FUSION_APPLY_XLA_FLAGS``).

Process sets lower to ``axis_index_groups`` — a partitioned ICI collective
instead of the reference's per-set NCCL communicator (§2.1 process_set.cc).

All ops accept pytrees and operate leaf-wise (grouped ops fuse across the
tree). Every op works inside ``shard_map``/``pjit`` over a mesh axis; the
eager per-rank wrappers live in ``collectives/eager.py``.
"""

from __future__ import annotations

import contextlib as _contextlib
import threading as _threading
from typing import Any, List, Optional, Sequence, Tuple, Union

#: A rank axis is one mesh axis name, or — on a hierarchical (multi-
#: axis) mesh — a tuple of names with the ICI-contiguous axis last.
AxisName = Union[str, Tuple[str, ...]]

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.core import context_api as _ctx
from ..core import telemetry as _telemetry
from ..core.process_sets import ProcessSet
from .compression import Compression, Compressor

# --- Reduce-op constants, parity with hvd.Sum/Average/Min/Max/Product/Adasum
Sum = "sum"
Average = "average"
Min = "min"
Max = "max"
Product = "product"
Adasum = "adasum"

#: The named-axis collective primitives every op in this module lowers
#: through, mapped to the jaxpr param holding their axis names.  This is
#: the vocabulary ``analysis/jaxpr.py`` walks when it extracts the static
#: collective signature stream (the SPMD stand-in for the reference
#: controller's negotiated tensor stream) — extend it here if an op ever
#: lowers through a new primitive, and hvd-analyze follows automatically.
COLLECTIVE_PRIMITIVES = {
    "psum": "axes",
    "pmin": "axes",
    "pmax": "axes",
    "all_gather": "axis_name",
    "all_to_all": "axis_name",
    "reduce_scatter": "axis_name",
    "ppermute": "axis_name",
    "pbroadcast": "axis_name",
    "axis_index": "axis_name",
}


def _axis(axis_name: Optional[AxisName]) -> AxisName:
    if axis_name is not None:
        return tuple(axis_name) if isinstance(axis_name, (tuple, list)) \
            else axis_name
    if _ctx.is_initialized():
        return _ctx.context().axis_name
    return _ctx.RANK_AXIS


def _groups(process_set: Optional[ProcessSet], axis: AxisName,
            require_equal: bool = False) -> Optional[List[List[int]]]:
    if process_set is None or process_set.process_set_id == 0:
        return None
    # On a multi-axis (hierarchical) rank axis, ``axis_index_groups`` are
    # FLAT indices over the tuple (outer-major, the same order
    # ``lax.axis_index(tuple)`` yields) — exactly the global-rank layout
    # ``parallel/mesh.py`` builds, so process-set ranks need no remapping.
    # This composes the reference's process_set.cc (works on every backend,
    # including the hierarchical NCCL path) with zero-config
    # HOROVOD_HIERARCHICAL_ALLREDUCE's 2-axis mesh (VERDICT r2 missing #1).
    world = lax.axis_size(axis)
    members = list(process_set.ranks)
    rest = [r for r in range(world) if r not in process_set.ranks]
    if not require_equal:
        return [members] + [[r] for r in rest]
    k = len(members)
    if len(rest) % k != 0:
        raise ValueError(
            f"process set of size {k} cannot partition axis size {world} "
            "into equal groups (required for shape-changing collectives)")
    return [members] + [rest[i:i + k] for i in range(0, len(rest), k)]


def _set_size(process_set: Optional[ProcessSet], axis: AxisName) -> int:
    if process_set is None or process_set.process_set_id == 0:
        return lax.axis_size(axis)
    return process_set.size()


def _member_mask(process_set: Optional[ProcessSet], axis: AxisName):
    """Traced boolean: is this device a member of the process set?
    None for the global set (everyone is)."""
    if process_set is None or process_set.process_set_id == 0:
        return None
    idx = lax.axis_index(axis)
    member = jnp.zeros((), jnp.bool_)
    for r in process_set.ranks:
        member = member | (idx == r)
    return member


def static_axis_size(axis: AxisName) -> Optional[int]:
    """Bound size of ``axis`` at trace time, or None outside a binding
    context. Lets every op collapse to identity on a 1-member axis — XLA
    does NOT reliably elide single-participant collectives (measured: a
    1-device ResNet step kept 90 all-reduce + ~2.5k reshuffle ops), and the
    reference likewise short-circuits size-1 worlds."""
    try:
        return lax.axis_size(axis)
    except Exception:
        return None


_forced_size1 = _threading.local()


@_contextlib.contextmanager
def force_axis_size1(*axes: str):
    """Trace-time declaration that ``axes`` have exactly one member.

    Used by ``make_train_step``'s 1-device fast path, which traces the step
    WITHOUT ``shard_map`` (the SPMD partitioner costs real layout copies on
    TPU even for one device): inside this context every hvd collective on a
    listed axis collapses to identity instead of failing on the unbound
    axis name."""
    prev = getattr(_forced_size1, "axes", frozenset())
    _forced_size1.axes = prev | frozenset(axes)
    try:
        yield
    finally:
        _forced_size1.axes = prev


def effective_axis_size(axis: AxisName) -> Optional[int]:
    """``static_axis_size`` with two extra resolution steps for unbound
    axes: a ``force_axis_size1`` declaration wins, else the context world
    size when the axis IS the context's rank axis. This makes a 1-device
    world behave like the reference's 1-process run — train steps need no
    ``shard_map`` wrapper at all, and every collective inside still
    collapses to identity."""
    if isinstance(axis, tuple):
        per_axis = [effective_axis_size(a) for a in axis]
        if all(n is not None for n in per_axis):
            total = 1
            for n in per_axis:
                total *= n
            return total
        if _ctx.is_initialized() and axis == _ctx.context().axis_name:
            return _ctx.context().size
        return None
    n = static_axis_size(axis)
    if n is not None:
        return n
    if axis in getattr(_forced_size1, "axes", ()):
        return 1
    if _ctx.is_initialized() and axis == _ctx.context().axis_name:
        return _ctx.context().size
    return None


def _is_global(process_set: Optional[ProcessSet]) -> bool:
    """The explicit global set (id 0) is equivalent to passing None."""
    return process_set is None or process_set.process_set_id == 0


_REDUCE_OPS = (Sum, Average, Min, Max, Product)


def _identity_reduce(tensor, op: str, prescale_factor: float,
                     postscale_factor: float):
    """Size-1-axis allreduce. Applies the same scalar ops in the same order
    as ``_reduce_leaf`` so dtype promotion matches the multi-device path
    (e.g. int32 + Average → float32 regardless of world size)."""
    def leaf(x):
        if prescale_factor != 1.0:
            x = x * prescale_factor
        if op == Average:
            x = x / 1  # true-divide by the participant count: promotes ints
        if postscale_factor != 1.0:
            x = x * postscale_factor
        return x
    return jax.tree_util.tree_map(leaf, tensor)


def _op_identity(x, op: str):
    """The reduce op's identity element, in ``x``'s dtype — what a masked
    non-member contributes so a full-axis collective computes the member-
    only reduction."""
    if op in (Sum, Average):
        return jnp.zeros_like(x)
    if op == Product:
        return jnp.ones_like(x)
    if jnp.issubdtype(x.dtype, jnp.bool_):
        return jnp.full_like(x, op == Min)
    info = (jnp.finfo if jnp.issubdtype(x.dtype, jnp.inexact)
            else jnp.iinfo)(x.dtype)
    return jnp.full_like(x, info.max if op == Min else info.min)


def _reduce_leaf(x, op: str, axis: str, groups, nparticipants: int,
                 prescale_factor: float, postscale_factor: float,
                 mask=None):
    if prescale_factor != 1.0:
        x = x * prescale_factor
    if mask is not None:
        # Process set over a multi-axis rank axis: JAX's grouped psum is
        # unimplemented over axis tuples, so members reduce over the FULL
        # axis with non-members contributing the op identity (callers
        # restore non-member outputs). Same result, full-axis wire cost.
        x = jnp.where(mask, x, _op_identity(x, op))
    if op in (Sum, Average):
        y = lax.psum(x, axis, axis_index_groups=groups)
        if op == Average:
            y = y / nparticipants
    elif op == Min:
        y = lax.pmin(x, axis, axis_index_groups=groups)
    elif op == Max:
        y = lax.pmax(x, axis, axis_index_groups=groups)
    elif op == Product:
        # No product collective in XLA. Power-of-2 global reduces use a
        # log2(N) XOR butterfly over ppermute — O(1) extra memory; process
        # sets / ragged worlds fall back to gather+prod (O(N) memory,
        # matching the reference's host MPI_PROD in effect).
        world = static_axis_size(axis)
        if (groups is None and not isinstance(axis, tuple)
                and world is not None and world & (world - 1) == 0):
            y = x
            d = 1
            while d < world:
                recv = lax.ppermute(y, axis,
                                    [(r, r ^ d) for r in range(world)])
                y = y * recv
                d <<= 1
        else:
            g = lax.all_gather(x, axis, axis=0, axis_index_groups=groups)
            y = jnp.prod(g, axis=0)
    else:
        raise ValueError(f"unsupported reduce op: {op}")
    if postscale_factor != 1.0:
        y = y * postscale_factor
    return y


def _fused_reduce(tensors, compression: Compressor, reduce_flat,
                  member=None, max_bucket_bytes: Optional[int] = None):
    """The compile-time fusion buffer: flatten a pytree's leaves into one
    contiguous flat buffer per wire dtype, apply ``reduce_flat`` to each, and
    split/decompress back. Shared by ``grouped_allreduce`` and
    ``hierarchical_allreduce``. ``member`` (traced bool) restores each
    non-member leaf to its input (process-set passthrough semantics).

    ``max_bucket_bytes`` sizes the SCHEDULED buckets — the in-graph
    rendering of ``HOROVOD_FUSION_THRESHOLD`` (the reference's fusion-buffer
    size, fusion_buffer_manager.cc + its cycle-time batching): leaves are
    greedily packed into per-dtype buckets walking the flatten order IN
    REVERSE, because gradient pytrees flatten roughly first-layer-first
    while backward produces the LAST layer's grads first — so each bucket's
    producers are an early prefix of backward and XLA's latency-hiding
    scheduler can fly the first buckets' collectives while the rest of
    backward is still running. One giant buffer (the uncapped path)
    serializes behind its LAST producer — the first layer's dW, i.e. the
    very end of backward. A single leaf larger than the cap forms its own
    bucket unsplit (reference semantics: tensors over the fusion-buffer
    size go as one op — splitting one producer's payload buys no overlap).
    This is the knob the transparent autotuner (tools/autotune.py) searches
    and ``benchmarks/collectives.py --sweep-fusion`` sweeps.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tensors)
    if not leaves:
        return tensors
    compressed = [compression.compress(x) for x in leaves]

    def finish(i, y_flat):
        cx, cctx = compressed[i]
        y = compression.decompress(y_flat.reshape(cx.shape), cctx)
        if member is not None:
            y = jnp.where(member, y, leaves[i])
        return y

    # Trace-time telemetry: bucket count and wire bytes are static
    # properties of the traced pytree (cx.size/itemsize are Python ints
    # here), so the record fires once per TRACE, never per execution —
    # zero cost inside the compiled step.
    total_bytes = sum(cx.size * cx.dtype.itemsize for cx, _ in compressed)

    if max_bucket_bytes == 0:
        # Fusion disabled (HOROVOD_FUSION_THRESHOLD=0, reference semantics):
        # one collective per tensor.
        _telemetry.inc("hvd_collective_issues_total")
        _telemetry.record_event("collective_issue", buckets=len(compressed),
                                tensors=len(leaves), bytes=total_bytes)
        return jax.tree_util.tree_unflatten(
            treedef, [finish(i, reduce_flat(cx.ravel()))
                      for i, (cx, _) in enumerate(compressed)])
    out: List[Any] = [None] * len(leaves)
    if max_bucket_bytes:
        # Scheduled bucketing: greedy reverse-order per-dtype packing.
        cap = int(max_bucket_bytes)
        bucket_idxs: List[List[int]] = []
        open_bucket: dict = {}  # dtype -> (bucket position, bytes packed)
        for i in reversed(range(len(leaves))):
            cx = compressed[i][0]
            nbytes = cx.size * cx.dtype.itemsize
            cur = open_bucket.get(cx.dtype)
            if cur is not None and cur[1] + nbytes <= cap:
                bucket_idxs[cur[0]].append(i)
                open_bucket[cx.dtype] = (cur[0], cur[1] + nbytes)
            else:
                bucket_idxs.append([i])
                open_bucket[cx.dtype] = (len(bucket_idxs) - 1, nbytes)
    else:
        # Uncapped (no context / explicit None): one buffer per dtype.
        per_dtype: dict = {}
        for i, (cx, _) in enumerate(compressed):
            per_dtype.setdefault(cx.dtype, []).append(i)
        bucket_idxs = list(per_dtype.values())
    _telemetry.inc("hvd_collective_issues_total")
    _telemetry.record_event("collective_issue", buckets=len(bucket_idxs),
                            tensors=len(leaves), bytes=total_bytes)
    for idxs in bucket_idxs:
        if len(idxs) == 1:
            i = idxs[0]
            out[i] = finish(i, reduce_flat(compressed[i][0].ravel()))
            continue
        flat = jnp.concatenate([compressed[i][0].ravel() for i in idxs])
        red = reduce_flat(flat)
        off = 0
        for i in idxs:
            sz = compressed[i][0].size
            out[i] = finish(i, red[off:off + sz])
            off += sz
    return jax.tree_util.tree_unflatten(treedef, out)


_fusion_override = _threading.local()


@_contextlib.contextmanager
def fusion_threshold_override(bytes_: Optional[int]):
    """Thread-locally scope the fusion threshold to the traces performed
    inside this context — used by the transparent autotuner so a TRIAL
    value never contaminates other steps traced while tuning is in flight
    (and nothing leaks if the loop ends before convergence)."""
    prev = getattr(_fusion_override, "value", None)
    _fusion_override.value = bytes_
    try:
        yield
    finally:
        _fusion_override.value = prev


def _fusion_threshold() -> Optional[int]:
    """Trace-time fusion threshold (``HOROVOD_FUSION_THRESHOLD``, bytes).
    Semantics match the reference: ``0`` disables fusion (one collective
    per tensor); a positive value caps each fused buffer; None (no
    context) = one uncapped buffer. An active
    :func:`fusion_threshold_override` wins over the config."""
    ov = getattr(_fusion_override, "value", None)
    if ov is not None:
        return int(ov)
    if not _ctx.is_initialized():
        return None
    t = _ctx.context().config.fusion_threshold_bytes
    return int(t) if t is not None and t >= 0 else None


_hier_override = _threading.local()


@_contextlib.contextmanager
def hierarchical_override(value: Optional[bool]):
    """Thread-locally force HOROVOD_HIERARCHICAL_ALLREDUCE on/off for the
    traces inside this context (None = follow the config) — the
    transparent autotuner's second dimension: hierarchical vs flat is a
    pure graph-shape choice (identical numerics), so it is safe to search
    live."""
    prev = getattr(_hier_override, "value", None)
    _hier_override.value = value
    try:
        yield
    finally:
        _hier_override.value = prev


def _hierarchical_axes(axis, process_set, op: str):
    """(cross_axes, intra_axis) when HOROVOD_HIERARCHICAL_ALLREDUCE should
    reshape this reduce, else None.

    Engages only for Sum/Average on the global set over a multi-axis rank
    axis: the innermost mesh axis is the ICI-contiguous one (parallel/mesh.py
    axis ordering; ``create_hybrid_mesh`` puts DCN axes outermost), so it
    plays the reference's intra-node NCCL role and the outer axes the
    cross-node MPI role (nccl_operations.cc hierarchical path, SURVEY §2.2).
    """
    if op not in (Sum, Average):
        return None
    if not isinstance(axis, tuple) or len(axis) < 2:
        return None
    if not _is_global(process_set):
        return None
    ov = getattr(_hier_override, "value", None)
    if ov is not None:
        enabled = bool(ov)
    else:
        enabled = (_ctx.is_initialized()
                   and _ctx.context().config.hierarchical_allreduce)
    if not enabled:
        return None
    return axis[:-1], axis[-1]


def _hier_reduce_flat(flat, op: str, intra_axis: str, cross_axes,
                      n_total: int, prescale_factor: float,
                      postscale_factor: float,
                      cross_compression: Optional[Compressor] = None):
    """Hierarchical sum/average of a flat 1-D buffer: reduce-scatter over the
    ICI axis → allreduce over the DCN axes → allgather back over ICI.

    Wire cost per device vs a flat N-way allreduce: the cross-slice hop moves
    1/n_intra of the bytes (each device owns a shard), which is exactly the
    reference's reason for HOROVOD_HIERARCHICAL_ALLREDUCE — keep the
    bandwidth-hungry phase on the fast fabric. Average divides on the shard,
    before the gather, so the scale runs on 1/n_intra of the elements.

    ``cross_compression`` casts ONLY the cross-slice payload to the wire
    dtype around the cross ``psum`` (reference: compression.py's wire cast,
    applied where bytes are scarce — DCN). The ICI reduce-scatter, the
    Average divide, and the ICI all-gather stay full-precision: the lossy
    adds are bounded by n_cross − 1 (typically 1–3 slices), while the
    n_intra-way accumulate — where bf16 error would actually compound —
    keeps f32. Halves the DCN bytes for f32 gradients.
    """
    if prescale_factor != 1.0:
        flat = flat * prescale_factor
    n_intra = lax.axis_size(intra_axis)
    sz = flat.shape[0]
    pad = (-sz) % n_intra
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    shard = lax.psum_scatter(flat, intra_axis, scatter_dimension=0,
                             tiled=True)
    if cross_compression is not None:
        wire, cctx = cross_compression.compress(shard)
        wire = lax.psum(wire, cross_axes)
        shard = cross_compression.decompress(wire, cctx)
    else:
        shard = lax.psum(shard, cross_axes)
    if op == Average:
        shard = shard / n_total
    if postscale_factor != 1.0:
        shard = shard * postscale_factor
    out = lax.all_gather(shard, intra_axis, axis=0, tiled=True)
    return out[:sz] if pad else out


def _cross_compressor() -> Optional[Compressor]:
    """The config-engaged DCN-hop compressor
    (``HOROVOD_HIERARCHICAL_COMPRESSION``: none | bf16 | fp16), or None."""
    if not _ctx.is_initialized():
        return None
    name = getattr(_ctx.context().config, "hierarchical_compression", "none")
    return {"bf16": Compression.bf16, "fp16": Compression.fp16}.get(name)


def hierarchical_allreduce(tensor: Any, op: str = Average, *,
                           intra_axis: str, cross_axes,
                           compression: Compressor = Compression.none,
                           cross_compression: Optional[Compressor] = None,
                           prescale_factor: float = 1.0,
                           postscale_factor: float = 1.0) -> Any:
    """Explicit two-level allreduce over a (cross, intra) mesh decomposition.

    Parity: the reference's ``HOROVOD_HIERARCHICAL_ALLREDUCE`` data path
    (NCCL reducescatter within the node → MPI allreduce across nodes →
    NCCL allgather; ``horovod/common/ops/nccl_operations.cc``, SURVEY §2.2),
    re-expressed on the topology TPU pods actually have: ``intra_axis`` rides
    ICI within a slice, ``cross_axes`` (a name or tuple of names) rides DCN.
    ``allreduce()``/``grouped_allreduce()`` route here automatically when the
    config flag is set and the rank axis is a multi-axis tuple; call this
    directly to force the shape regardless of the flag. All leaves fuse into
    per-dtype flat buffers (one collective sequence per dtype).

    ``cross_compression`` (default: resolve ``HOROVOD_HIERARCHICAL_
    COMPRESSION`` from the context config) casts only the cross-slice (DCN)
    hop's payload to the wire dtype — see ``_hier_reduce_flat``. Pass
    ``Compression.none`` to force it off regardless of config.
    """
    if op not in (Sum, Average):
        raise ValueError("hierarchical allreduce supports Sum and Average; "
                         f"got {op!r}")
    cross = tuple(cross_axes) if isinstance(cross_axes, (tuple, list)) \
        else (cross_axes,)
    if cross_compression is None:
        cross_compression = _cross_compressor()
    elif cross_compression is Compression.none:
        cross_compression = None
    n_total = lax.axis_size((*cross, intra_axis))
    return _fused_reduce(
        tensor, compression,
        lambda flat: _hier_reduce_flat(flat, op, intra_axis, cross, n_total,
                                       prescale_factor, postscale_factor,
                                       cross_compression=cross_compression),
        max_bucket_bytes=_fusion_threshold())


def allreduce(tensor: Any, op: str = Average, *,
              process_set: Optional[ProcessSet] = None,
              axis_name: Optional[str] = None,
              compression: Compressor = Compression.none,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0) -> Any:
    """Allreduce a pytree across the rank axis.

    Parity: ``hvd.allreduce`` (torch/mpi_ops.py, tensorflow/mpi_ops.py).
    ``op=Adasum`` routes to the scale-invariant butterfly in
    ``collectives/adasum.py`` (reference: ops/adasum/adasum.h).
    """
    if op == Adasum:
        from .adasum import adasum_allreduce
        return adasum_allreduce(tensor, process_set=process_set,
                                axis_name=axis_name, compression=compression,
                                prescale_factor=prescale_factor,
                                postscale_factor=postscale_factor)
    if op not in _REDUCE_OPS:
        raise ValueError(f"unsupported reduce op: {op}")
    axis = _axis(axis_name)
    if _is_global(process_set) and effective_axis_size(axis) == 1:
        return _identity_reduce(tensor, op, prescale_factor,
                                postscale_factor)
    hier = _hierarchical_axes(axis, process_set, op)
    if hier is not None:
        cross, intra = hier
        return hierarchical_allreduce(
            tensor, op, intra_axis=intra, cross_axes=cross,
            compression=compression, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
    masked = not _is_global(process_set) and isinstance(axis, tuple)
    groups = None if masked else _groups(process_set, axis)
    n = _set_size(process_set, axis)
    member = _member_mask(process_set, axis)

    def leaf(x):
        cx, cctx = compression.compress(x)
        cy = _reduce_leaf(cx, op, axis, groups, n,
                          prescale_factor, postscale_factor,
                          mask=member if masked else None)
        y = compression.decompress(cy, cctx)
        if member is not None:
            # Non-members of a process set must see their input unchanged
            # (reference semantics: they never called the op) — undo the
            # averaging/scaling their singleton-group passthrough received.
            y = jnp.where(member, y, x)
        return y

    return jax.tree_util.tree_map(leaf, tensor)


def grouped_allreduce(tensors: Any, op: str = Average, *,
                      process_set: Optional[ProcessSet] = None,
                      axis_name: Optional[str] = None,
                      compression: Compressor = Compression.none,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0) -> Any:
    """Allreduce fusing every leaf into ONE flat buffer → ONE collective.

    This is the reference's fusion buffer (fusion_buffer_manager.cc +
    group_table.cc) reborn at compile time: leaves are flattened, concatenated
    into a single contiguous vector, reduced by a single ``xla::AllReduce``,
    and split back — no memcpy-in/out on the host, no cycle-time wait.
    Non-sum ops and mixed dtypes fall back to per-dtype buckets.
    """
    if op == Adasum:
        from .adasum import adasum_allreduce
        return adasum_allreduce(tensors, process_set=process_set,
                                axis_name=axis_name, compression=compression,
                                prescale_factor=prescale_factor,
                                postscale_factor=postscale_factor)
    if op not in _REDUCE_OPS:
        raise ValueError(f"unsupported reduce op: {op}")
    axis = _axis(axis_name)
    if _is_global(process_set) and effective_axis_size(axis) == 1:
        return _identity_reduce(tensors, op, prescale_factor,
                                postscale_factor)
    hier = _hierarchical_axes(axis, process_set, op)
    if hier is not None:
        # hierarchical_allreduce already fuses leaves into per-dtype flat
        # buffers — it IS the grouped form.
        cross, intra = hier
        return hierarchical_allreduce(
            tensors, op, intra_axis=intra, cross_axes=cross,
            compression=compression, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
    masked = not _is_global(process_set) and isinstance(axis, tuple)
    groups = None if masked else _groups(process_set, axis)
    n = _set_size(process_set, axis)
    member = _member_mask(process_set, axis)
    return _fused_reduce(
        tensors, compression,
        lambda flat: _reduce_leaf(flat, op, axis, groups, n,
                                  prescale_factor, postscale_factor,
                                  mask=member if masked else None),
        member=member, max_bucket_bytes=_fusion_threshold())


def _ragged_set(process_set: Optional[ProcessSet], axis) -> bool:
    """True when ``process_set`` is a proper subset whose complement cannot
    be partitioned into equal-size groups — the case XLA's
    ``axis_index_groups`` cannot express for shape-changing collectives."""
    if _is_global(process_set):
        return False
    world = lax.axis_size(axis)
    k = len(process_set.ranks)
    return (world - k) % k != 0


def _padded_member_groups(process_set: ProcessSet, axis):
    """Equal-size ``axis_index_groups`` with the member set padded by
    complement ranks to the smallest world-divisor >= set size, as group
    0 — the wire-cost fix for RAGGED sets (VERDICT r2 #8): a 3-of-8
    allgather then moves 4 rows/device, not 8. Returns the groups, or
    None when no divisor beats the full axis (e.g. 5 of 8). On this
    path only MEMBERS receive meaningful output (the reference leaves
    non-participant output undefined; shapes stay uniform)."""
    world = lax.axis_size(axis)
    members = sorted(process_set.ranks)
    k = len(members)
    s = next(d for d in range(k, world + 1) if world % d == 0)
    if s >= world:
        return None
    comp = [r for r in range(world) if r not in process_set.ranks]
    pad, rest = comp[:s - k], comp[s - k:]
    return [members + pad] + [rest[i:i + s]
                              for i in range(0, len(rest), s)]


def _member_pos(process_set: ProcessSet, axis):
    """Traced position of this device within the (sorted) member list;
    0 for non-members (callers mask their output)."""
    idx = lax.axis_index(axis)
    pos = jnp.zeros((), jnp.int32)
    for i, r in enumerate(sorted(process_set.ranks)):
        pos = jnp.where(idx == r, i, pos)
    return pos


def allgather(tensor: Any, *, process_set: Optional[ProcessSet] = None,
              axis_name: Optional[str] = None) -> Any:
    """Gather along dim 0 from every rank, concatenated in rank order.

    Parity: ``hvd.allgather``. Under SPMD every device contributes the same
    static shape; for per-rank varying first dims use
    ``collectives.dynamic.allgather_v`` (pad-to-max + size side channel,
    SURVEY.md §7 "hard parts").

    Process sets whose complement doesn't split into equal groups (e.g.
    5 of 8 ranks — inexpressible as ``axis_index_groups``) take a padded
    construction: the member set plus enough complement ranks to reach
    the smallest world-divisor forms group 0 (a 3-of-8 gather moves 4
    rows/device, not 8) and members slice off their rows; on this path
    non-member output is shape-correct but unspecified (reference
    semantics: non-participants never call the op). When no divisor
    beats the full axis (5 of 8), it falls back to a full-axis gather +
    member-row selection — there every device, members AND non-members,
    receives the members' concatenation.
    """
    axis = _axis(axis_name)
    if _is_global(process_set) and effective_axis_size(axis) == 1:
        return tensor
    if not _is_global(process_set) and _ragged_set(process_set, axis):
        members = sorted(process_set.ranks)
        k = len(members)
        pg = _padded_member_groups(process_set, axis)

        def ragged_leaf(x):
            m = x.shape[0]
            if pg is not None:
                g = lax.all_gather(x, axis, axis=0, tiled=True,
                                   axis_index_groups=pg)
                return g[:k * m]  # members' rows (members lead group 0)
            g = lax.all_gather(x, axis, axis=0, tiled=True)
            rows = np.concatenate(
                [np.arange(r * m, (r + 1) * m) for r in members])
            return g[rows]

        return jax.tree_util.tree_map(ragged_leaf, tensor)
    if (_is_global(process_set) and isinstance(axis, tuple) and len(axis) >= 2
            and _ctx.is_initialized()
            and _ctx.context().config.hierarchical_allgather):
        # HOROVOD_HIERARCHICAL_ALLGATHER (reference: the NCCL-intra →
        # cross-node staged gather): gather over the ICI axis first, then
        # the DCN axes — same bytes, but the DCN hop moves intra-complete
        # blocks, and XLA schedules the two phases independently. Output
        # row order (outer-major) matches the flat tuple-axis gather.
        cross, intra = axis[:-1], axis[-1]

        def hier_leaf(x):
            y = lax.all_gather(x, intra, axis=0, tiled=True)
            return lax.all_gather(y, cross, axis=0, tiled=True)

        return jax.tree_util.tree_map(hier_leaf, tensor)
    groups = _groups(process_set, axis, require_equal=True)

    def leaf(x):
        return lax.all_gather(x, axis, axis=0, tiled=True,
                              axis_index_groups=groups)

    return jax.tree_util.tree_map(leaf, tensor)


def grouped_allgather(tensors: Any, **kw) -> Any:
    return allgather(tensors, **kw)


def broadcast(tensor: Any, root_rank: int = 0, *,
              process_set: Optional[ProcessSet] = None,
              axis_name: Optional[str] = None) -> Any:
    """Broadcast from ``root_rank`` to all ranks (in the process set).

    Parity: ``hvd.broadcast``. Lowered as a masked ``psum``; ranks outside
    the process set keep their own value (singleton groups).

    Lowering verified (r2, VERDICT item 8): the select+psum emits ONE
    ``all-reduce`` in the optimized HLO (8-device CPU mesh, 4 MB/device —
    no decomposition into anything worse). Cost analysis: XLA executes
    large all-reduces as reduce-scatter + all-gather at ~2x payload ring
    cost, vs ~1x for an ideal one-to-all collective-broadcast (which lax
    does not expose) and ~log2(n)x for a ppermute tree (worse for n >= 8).
    So masked-psum is within 2x of optimal, in one schedulable HLO op —
    kept deliberately. Host-side startup parameter broadcast
    (``optimizer.broadcast_parameters``) doesn't use this path at all; it
    rides ``multihost_utils.broadcast_one_to_all``.
    """
    axis = _axis(axis_name)
    if _is_global(process_set):
        world = effective_axis_size(axis)
        if world is not None and not 0 <= root_rank < world:
            # Without this, keep=(idx==root) is False everywhere and the
            # masked psum silently broadcasts zeros.
            raise ValueError(f"root rank {root_rank} out of range for axis "
                             f"'{axis}' of size {world}")
        if world == 1:
            return tensor
    idx = lax.axis_index(axis)
    if process_set is not None and process_set.process_set_id != 0:
        if root_rank not in process_set.ranks:
            raise ValueError(
                f"root rank {root_rank} not in process set {process_set.ranks}")
        member = jnp.zeros((), jnp.bool_)
        for r in process_set.ranks:
            member = member | (idx == r)
        if isinstance(axis, tuple):
            # Grouped psum is unimplemented over axis tuples (hierarchical
            # meshes): full-axis masked psum of the root's value, then
            # non-members restore their input.
            def leaf_t(x):
                contrib = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
                y = lax.psum(contrib, axis).astype(x.dtype)
                return jnp.where(member, y, x)
            return jax.tree_util.tree_map(leaf_t, tensor)
        groups = _groups(process_set, axis)
        keep = (idx == root_rank) | ~member
    else:
        groups = None
        keep = idx == root_rank

    def leaf(x):
        contrib = jnp.where(keep, x, jnp.zeros_like(x))
        return lax.psum(contrib, axis, axis_index_groups=groups).astype(x.dtype)

    return jax.tree_util.tree_map(leaf, tensor)


def grouped_broadcast(tensors: Any, root_rank: int = 0, **kw) -> Any:
    return broadcast(tensors, root_rank, **kw)


def alltoall(tensor: Any, splits: Optional[Sequence[int]] = None, *,
             process_set: Optional[ProcessSet] = None,
             axis_name: Optional[str] = None) -> Any:
    """All-to-all exchange: dim 0 is split across ranks, chunk *i* goes to
    rank *i*; output is the concatenation of received chunks.

    Parity: ``hvd.alltoall`` (nccl ncclAllToAll / MPI_Alltoallv). Equal
    splits lower to a single ``xla::AllToAll`` over ICI. Uneven ``splits``
    need the padded variant in ``collectives.dynamic.alltoall_v``.
    """
    if splits is not None:
        from .dynamic import alltoall_v
        return alltoall_v(tensor, splits, process_set=process_set,
                          axis_name=axis_name)
    axis = _axis(axis_name)
    if _is_global(process_set) and effective_axis_size(axis) == 1:
        return tensor
    if not _is_global(process_set) and _ragged_set(process_set, axis):
        # Ragged set: gather the members' tensors (padded equal-size
        # groups when a world-divisor >= set size exists — set-size wire
        # cost, VERDICT r2 #8 — else the full axis), then each member
        # picks its own chunk from each member's contribution (shape is
        # preserved, so non-members just keep their input).
        members = sorted(process_set.ranks)
        k = len(members)
        member = _member_mask(process_set, axis)
        pos = _member_pos(process_set, axis)
        pg = _padded_member_groups(process_set, axis)

        def ragged_leaf(x):
            if x.shape[0] % k != 0:
                raise ValueError(
                    f"alltoall dim0 ({x.shape[0]}) must be divisible by the "
                    f"participant count ({k}); pass explicit splits for "
                    "uneven exchange")
            c = x.shape[0] // k
            if pg is not None:
                g = lax.all_gather(x, axis, axis=0, tiled=False,
                                   axis_index_groups=pg)  # [s, ...]
                # group 0 leads with the members in member order
                srcs = range(k)
            else:
                g = lax.all_gather(x, axis, axis=0, tiled=False)
                srcs = members
            picks = [lax.dynamic_slice_in_dim(g[r], pos * c, c, axis=0)
                     for r in srcs]
            out = jnp.concatenate(picks, axis=0)
            return jnp.where(member, out, x)

        return jax.tree_util.tree_map(ragged_leaf, tensor)
    groups = _groups(process_set, axis, require_equal=True)

    def leaf(x):
        n = _set_size(process_set, axis)
        if x.shape[0] % n != 0:
            raise ValueError(
                f"alltoall dim0 ({x.shape[0]}) must be divisible by the "
                f"participant count ({n}); pass explicit splits for uneven "
                "exchange")
        return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=True, axis_index_groups=groups)

    return jax.tree_util.tree_map(leaf, tensor)


def reducescatter(tensor: Any, op: str = Sum, *,
                  process_set: Optional[ProcessSet] = None,
                  axis_name: Optional[str] = None) -> Any:
    """Reduce across ranks then scatter dim-0 chunks: rank *i* keeps chunk *i*.

    Parity: ``hvd.reducescatter`` (ncclReduceScatter). This is also the ZeRO
    building block the reference exposes but never uses (SURVEY.md §2.6).
    """
    if op not in (Sum, Average):
        raise ValueError("reducescatter supports Sum and Average")
    axis = _axis(axis_name)
    if _is_global(process_set) and effective_axis_size(axis) == 1:
        return tensor
    if not _is_global(process_set) and _ragged_set(process_set, axis):
        # Ragged set: member-masked full-axis psum, then each member slices
        # its own chunk of the reduced tensor (non-members get chunk 0 —
        # the reference leaves non-participant output undefined).
        k = len(process_set.ranks)
        member = _member_mask(process_set, axis)
        pos = _member_pos(process_set, axis)

        def ragged_leaf(x):
            if x.shape[0] % k != 0:
                raise ValueError(
                    f"reducescatter dim0 ({x.shape[0]}) must be divisible "
                    f"by {k}")
            c = x.shape[0] // k
            contrib = jnp.where(member, x, jnp.zeros_like(x))
            s = lax.psum(contrib, axis)
            y = lax.dynamic_slice_in_dim(s, pos * c, c, axis=0)
            return y / k if op == Average else y

        return jax.tree_util.tree_map(ragged_leaf, tensor)
    groups = _groups(process_set, axis, require_equal=True)
    n = _set_size(process_set, axis)

    def leaf(x):
        if x.shape[0] % n != 0:
            raise ValueError(
                f"reducescatter dim0 ({x.shape[0]}) must be divisible by {n}")
        y = lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True,
                             axis_index_groups=groups)
        return y / n if op == Average else y

    return jax.tree_util.tree_map(leaf, tensor)


def grouped_reducescatter(tensors: Any, op: str = Sum, **kw) -> Any:
    return reducescatter(tensors, op, **kw)


def barrier(*, axis_name: Optional[str] = None) -> None:
    """Synchronisation barrier (parity: ``hvd.barrier``). Inside a compiled
    SPMD program this is a tiny psum; program-order already serialises."""
    axis = _axis(axis_name)
    lax.psum(jnp.zeros((), jnp.float32), axis)
