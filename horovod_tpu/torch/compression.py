"""fp16 wire compression for the torch API.

Reference parity: ``horovod/torch/compression.py`` (SURVEY.md §2.4) — the
same four names (``Compression.none/.fp16``, ``NoneCompressor``,
``FP16Compressor``), compressing the wire payload and casting back after
the collective.
"""

from __future__ import annotations

import torch


class Compressor:
    @staticmethod
    def compress(tensor):
        """Return (compressed_tensor, ctx)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point:
            return tensor.to(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.to(ctx)


class BF16Compressor(Compressor):
    """TPU-native wire dtype (beyond the reference's none/fp16 pair;
    the jax and tf surfaces offer the same): fp32 exponent range, so
    gradient compression never overflows the way fp16 can. Crosses the
    numpy engine boundary via the int16 view-cast in ``mpi_ops``
    (a bit-identical reinterpret; uint16 views need torch>=2.3)."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point:
            return tensor.to(torch.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.to(ctx)


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
