"""Contract-registry acceptance (ISSUE 17, tier-1).

Three layers:

1. the FULL ``--contracts --strict`` matrix runs clean on the repo
   as-is (builds are memoized module-wide, so the thin per-family
   drivers in test_wire_contracts/test_fusion/test_bench_parity/
   test_step_builder reuse these builds instead of re-compiling);
2. detection is proven by breaking one contract each way IN-PROCESS —
   drop a donation, emit a stray permute, unpin the DLRM entry layout,
   rank-gate a psum — and asserting exactly the expected finding fires;
3. the CLI exits nonzero NAMING the violated contract, and a real
   subprocess run round-trips the SARIF surface end-to-end.
"""

import json
import os
import subprocess
import sys

import horovod_tpu  # noqa: F401  (compat shims before any jax use)
from horovod_tpu.analysis import analyze_rank_divergence, contracts
from horovod_tpu.analysis.__main__ import main as analysis_main
from horovod_tpu.analysis.hlo import HloCollective, LayoutMove

ALL_FAMILIES = (
    "dp-step-fusion", "dp-step-accum", "bench-arms-parity",
    "gspmd-deferred-every1", "gspmd-deferred-programs",
    "adasum-butterfly", "ring-attention", "pipeline-handoff",
    "hierarchical-allreduce", "decode-tp", "verify-tp", "prefill-tp",
    "decode-tp8", "verify-tp8", "dlrm-layout-pin",
)


def test_registry_covers_required_families():
    fams = contracts.families()
    assert len(fams) >= 8, fams
    for expected in ALL_FAMILIES:
        assert expected in fams, f"{expected} missing from registry"


def test_full_matrix_strict_clean(capsys):
    """Every registered family's contract holds on the repo as-is."""
    rc = analysis_main(["--contracts", "--strict"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "hvd-analyze: clean" in out


# --------------------------------------------------- injected breaks
#
# Each break doctors a MEMOIZED build output (never the repo) and runs
# the family's real verify on it: detection is proven without paying a
# second build, and the break cannot leak — ``summaries()`` still holds
# the pristine dict.

def test_break_dropped_donation_fires():
    base = contracts.summaries("dp-step-accum")
    doctored = dict(base)
    doctored["donated"] = base["accum"]        # the non-donated program
    problems = contracts.get("dp-step-accum").verify(doctored)
    assert problems, "dropped donation went undetected"
    assert any("donat" in p for p in problems), problems


def test_break_stray_permute_fires():
    base = contracts.summaries("decode-tp")
    key = ("llama", 2)
    s = base["summaries"][key]
    perm = HloCollective(
        op="collective_permute", group_size=8,
        groups=(), pairs=tuple((r, (r + 1) % 8) for r in range(8)),
        n_links=8, operand_bytes=512, result_bytes=512,
        ring_bytes=512.0, line=999)
    doctored = {**base, "summaries": {
        **base["summaries"],
        key: s._replace(collectives=s.collectives + (perm,))}}
    problems = contracts.get("decode-tp").verify(doctored)
    assert problems, "stray collective_permute went undetected"
    assert any("collective_permute" in p for p in problems), problems


def test_break_dlrm_layout_unpin_fires():
    base = contracts.summaries("dlrm-layout-pin")
    shape = base["table_shapes"][1]            # per-shard table shape
    s = base["summary"]
    mv = LayoutMove(
        op="transpose", shape=shape, line=42,
        text=f"  %transpose.9 = {shape}{{0,1}} transpose(%param.2)")
    doctored = {**base,
                "summary": s._replace(layout_moves=s.layout_moves + (mv,))}
    problems = contracts.get("dlrm-layout-pin").verify(doctored)
    assert problems, "table-shaped transpose went undetected"
    assert any("entry-layout pin" in p for p in problems), problems


def test_break_rank_gated_psum_fires():
    import analysis_fixture_steps as FS
    findings = analyze_rank_divergence(FS.rank_gated_allreduce_factory, 8)
    assert len(findings) == 1, findings
    f = findings[0]
    assert f.check_id == "jax-rank-divergence"
    assert f.detail["rank_a"] == 0 and f.detail["rank_b"] == 1
    assert f.detail["stream_a"] and not f.detail["stream_b"]


# ----------------------------------------------------------- CLI layer

def test_cli_nonzero_names_violated_contract(capsys):
    """A failing family makes the CLI exit 1 with the contract named in
    the finding line (``contract-<family>``)."""
    base = contracts.summaries("dp-step-accum")
    doctored = dict(base)
    doctored["donated"] = base["accum"]
    fam = "dp-step-accum-injected-break"
    contracts.register(contracts.Contract(
        fam, "injected break (test-only)",
        "horovod_tpu/train/step_builder.py",
        lambda: doctored, contracts.get("dp-step-accum").verify))
    try:
        rc = analysis_main(["--contracts", "--family", fam])
        out = capsys.readouterr().out
        assert rc == 1, out
        assert f"contract-{fam}" in out, out
    finally:
        contracts.unregister(fam)


def test_cli_family_validation(capsys):
    assert analysis_main(["--contracts", "--family", "no-such"]) == 2
    assert "unknown contract families" in capsys.readouterr().err
    assert analysis_main(["--family", "adasum-butterfly"]) == 2
    assert "--family requires --contracts" in capsys.readouterr().err


def test_cli_subprocess_contract_sarif_end_to_end():
    """One real subprocess run (cheap family): exit 0, valid SARIF doc
    with zero results — the CI-annotator surface, end to end."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis", "--contracts",
         "--family", "adasum-butterfly", "--sarif"],
        capture_output=True, text=True, env=env, cwd=root)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"] == []
    assert doc["runs"][0]["tool"]["driver"]["name"] == "hvd-analyze"
