"""Content-addressed checkpoint subsystem tests (elastic/state.py
``_CommitWriter`` + checkpoint/store.py ``BlobStore``).

Property coverage the ISSUE names: async == sync snapshot equivalence at
every commit cadence, dedup correctness (bit-identical restores when
blobs are shared across commits and ranks), digest-mismatch loudness,
GC/retention, torn-commit containment (a rank dying between blob write
and manifest publish must leave the previous complete manifest as the
restore point).
"""

import json
import os
import pickle
import subprocess
import sys
import textwrap
import time

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import elastic
from horovod_tpu.checkpoint.store import (BlobIntegrityError, BlobStore,
                                          blob_digest, newest_manifest_seq)
from horovod_tpu.elastic import state as state_mod


# --- BlobStore unit behavior ------------------------------------------------

def test_blob_put_get_roundtrip_and_dedup(tmp_path):
    store = BlobStore(str(tmp_path / "cas"))
    data = b"x" * 1000
    digest, wrote = store.put_blob(data)
    assert wrote and digest == blob_digest(data)
    assert store.get_blob(digest) == data
    # Idempotent: the second put of identical bytes writes nothing.
    digest2, wrote2 = store.put_blob(data)
    assert digest2 == digest and not wrote2
    assert store.stats["bytes_written"] == 1000
    assert store.stats["bytes_deduped"] == 1000
    assert store.stats["blobs_written"] == 1
    assert store.stats["blobs_deduped"] == 1


def test_blob_verify_at_read_raises_loudly(tmp_path):
    store = BlobStore(str(tmp_path / "cas"))
    digest, _ = store.put_blob(b"hello world" * 10)
    path = store.blob_path(digest)
    with open(path, "r+b") as fh:
        fh.seek(3)
        fh.write(b"\xff")
    with pytest.raises(BlobIntegrityError):
        store.get_blob(digest)
    # verify=False is the explicit escape hatch (peer-fetch re-hashing
    # happens at the receiving rank's put_blob).
    assert store.get_blob(digest, verify=False)


def test_manifest_publish_atomic_and_torn_skipped(tmp_path):
    store = BlobStore(str(tmp_path / "cas"))
    store.publish_manifest({"seq": 1, "skeleton": "ab", "leaves": []})
    store.publish_manifest({"seq": 2, "skeleton": "cd", "leaves": []})
    assert store.manifest_seqs() == [1, 2]
    # Tear manifest 2 (truncate mid-JSON): read returns None, newest
    # readable falls back to 1.
    with open(store.manifest_path(2), "r+b") as fh:
        fh.truncate(9)
    assert store.read_manifest(2) is None
    assert store.newest_manifest()["seq"] == 1
    assert store.newest_seq() == 1


def test_newest_manifest_seq_never_raises(tmp_path):
    assert newest_manifest_seq(str(tmp_path / "nope")) == -1
    assert newest_manifest_seq("") == -1


# --- async == sync equivalence at every cadence -----------------------------

def _drive(state, cadence, steps=7):
    """A deterministic fake training loop: mutate array + scalar attrs
    every step, commit every ``cadence`` steps."""
    for i in range(steps):
        state.step = i + 1
        state.params = {"w": state.params["w"] + 1.0,
                        "frozen": state.params["frozen"]}
        if (i + 1) % cadence == 0:
            state.save()
    assert state.flush_commits(timeout=30)


@pytest.mark.parametrize("cadence", [1, 2, 3, 5])
def test_async_equals_sync_snapshot_every_cadence(tmp_path, cadence):
    payload0 = lambda: {"w": jnp.arange(8.0), "frozen": jnp.ones(16)}  # noqa: E731
    d_async = str(tmp_path / f"async_{cadence}")
    d_sync = str(tmp_path / f"sync_{cadence}")
    sa = elastic.JaxState(commit_dir=d_async, commit_async=True,
                          params=payload0(), step=0)
    ss = elastic.JaxState(commit_dir=d_sync, commit_async=False,
                          params=payload0(), step=0)
    _drive(sa, cadence)
    _drive(ss, cadence)
    ra = elastic.JaxState(commit_dir=d_async, params=None, step=-1)
    rs = elastic.JaxState(commit_dir=d_sync, params=None, step=-1)
    assert ra.load_latest() and rs.load_latest()
    assert ra.step == rs.step and ra._commit_seq == rs._commit_seq
    for k in ("w", "frozen"):
        a, b = np.asarray(ra.params[k]), np.asarray(rs.params[k])
        assert a.tobytes() == b.tobytes()   # bit-identical
    # In-memory rollback snapshots match the persisted commit too.
    assert np.asarray(sa._saved["params"]["w"]).tobytes() \
        == np.asarray(ra.params["w"]).tobytes()


# --- dedup ------------------------------------------------------------------

def test_frozen_leaves_dedup_across_commits(tmp_path):
    d = str(tmp_path / "commits")
    frozen = jnp.arange(4096.0)       # 16 KiB leaf, never touched
    s = elastic.JaxState(commit_dir=d, params={"w": jnp.zeros(8),
                                               "frozen": frozen}, step=0)
    for i in range(4):
        s.step = i
        s.params = {"w": s.params["w"] + 1.0, "frozen": s.params["frozen"]}
        s.save()
    assert s.flush_commits(timeout=30)
    stats = s._writer.store.stats
    # The frozen leaf's bytes were written exactly once (identity cache
    # short-circuits even the fetch after commit 1); later commits write
    # only the small changed leaves + manifest-pinned skeleton.
    frozen_bytes = len(pickle.dumps(np.asarray(frozen), protocol=4))
    assert stats["bytes_written"] < 4 * frozen_bytes
    assert stats["bytes_written"] > 0


def test_identical_content_dedups_across_ranks(tmp_path):
    """Two states sharing a commit dir (two ranks on a shared disk):
    the second rank's identical leaves land on existing addresses and
    cost zero written bytes."""
    d = str(tmp_path / "commits")
    mk = lambda: {"w": jnp.arange(1024.0)}  # noqa: E731
    a = elastic.JaxState(commit_dir=d, commit_async=False, params=mk(),
                         step=0)
    a.save()
    b = elastic.JaxState(commit_dir=d, commit_async=False, params=mk(),
                         step=0)
    b.save()
    stats = b._writer.store.stats
    assert stats["blobs_deduped"] >= 1          # the big leaf, at least
    assert stats["bytes_deduped"] > stats["bytes_written"]
    # And the shared-store restore is bit-identical.
    r = elastic.JaxState(commit_dir=d, params=None, step=-1)
    assert r.load_latest()
    assert np.asarray(r.params["w"]).tobytes() \
        == np.asarray(mk()["w"]).tobytes()


# --- GC / retention ---------------------------------------------------------

def test_gc_retention_keeps_newest_k_and_sweeps_blobs(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_CHECKPOINT_KEEP", "2")
    d = str(tmp_path / "commits")
    s = elastic.JaxState(commit_dir=d, commit_async=False,
                         params={"w": jnp.zeros(2048)}, step=0)
    digests_per_commit = []
    for i in range(5):
        s.step = i
        s.params = {"w": s.params["w"] + 1.0}
        s.save()
        store = s._writer.store
        m = store.read_manifest(s._commit_seq)
        digests_per_commit.append([e[0] for e in m["leaves"]])
        time.sleep(0.02)   # distinct mtimes for the GC age guard
    store = state_mod._cas_store(d)
    assert store.manifest_seqs() == [4, 5]
    # Blobs only the dropped manifests referenced are gone; kept ones stay.
    kept_refs = store.referenced_digests(
        [store.read_manifest(4), store.read_manifest(5)])
    for digest in digests_per_commit[0]:
        if digest not in kept_refs:
            assert not store.has_blob(digest)
    for digest in digests_per_commit[-1]:
        assert store.has_blob(digest)
    # Restores still work after the sweep.
    r = elastic.JaxState(commit_dir=d, params=None, step=-1)
    assert r.load_latest() and r._commit_seq == 5


def test_gc_never_drops_last_manifest(tmp_path):
    store = BlobStore(str(tmp_path / "cas"))
    digest, _ = store.put_blob(b"payload")
    store.publish_manifest({"seq": 1, "skeleton": digest, "leaves": []})
    stats = store.gc(0)     # keep clamps to 1
    assert stats["manifests_removed"] == 0
    assert store.manifest_seqs() == [1]
    assert store.has_blob(digest)


def test_gc_keeps_pinned_manifest_and_blobs_past_retention(tmp_path):
    """Publish pins (serving plane) hold a manifest + its blobs no matter
    how far HOROVOD_CHECKPOINT_KEEP has moved past it; unpinning releases
    it to the next sweep."""
    store = BlobStore(str(tmp_path / "cas"))
    digests = []
    for seq in range(1, 6):
        digest, _ = store.put_blob(b"gen-%d" % seq)
        digests.append(digest)
        store.publish_manifest({"seq": seq, "skeleton": digest,
                                "leaves": [[digest, 6]]})
        time.sleep(0.02)    # distinct mtimes for the GC age guard
    pin_path = store.pin_manifest(2, meta={"published": True,
                                           "leaves_digest": "ab"})
    assert os.path.exists(pin_path)
    assert store.pinned_seqs() == [2]
    assert store.read_pin(2)["leaves_digest"] == "ab"
    stats = store.gc(1)
    # pinned seq 2 + newest seq 5 survive; 1, 3, 4 are swept
    assert store.manifest_seqs() == [2, 5]
    assert stats["manifests_removed"] == 3
    assert store.has_blob(digests[1]) and store.has_blob(digests[4])
    # gen-1's blob predates every kept manifest: swept. gen-3/4 blobs are
    # NEWER than pinned manifest 2, so the concurrent-writer age guard
    # retains them (they go once the retention window moves on).
    assert not store.has_blob(digests[0])
    # pinned content is still verifiably readable (a serving process may
    # be mid-delta-fetch against it)
    assert store.get_blob(digests[1], verify=True) == b"gen-2"
    # unpin -> swept by the next pass; double-unpin reports False
    assert store.unpin_manifest(2) is True
    assert store.unpin_manifest(2) is False
    store.gc(1)
    assert store.manifest_seqs() == [5]
    assert not store.has_blob(digests[1])


# --- per-shard blob layer (ISSUE 14) ----------------------------------------

def _clean_counters():
    return {"steps_skipped": 0.0, "rollbacks": 0.0}


def _decode_like_params(seed, dim=32, hidden=64):
    """A params tree keyed like the decode plane's (attn/mlp kernels),
    small enough to publish in milliseconds — ``tp_shard_plan`` matches
    on path names and shapes, not on flax types."""
    rng = np.random.RandomState(seed)
    leaf = lambda *s: rng.randn(*s).astype(np.float32)  # noqa: E731
    return {
        "tok_embeddings": {"embedding": leaf(64, dim)},   # replicated
        "block_0": {
            "attn": {"wq": {"kernel": leaf(dim, dim)},    # column (axis 1)
                     "wo": {"kernel": leaf(dim, dim)}},   # row (axis 0)
            "mlp": {"w1": {"kernel": leaf(dim, hidden)},
                    "w2": {"kernel": leaf(hidden, dim)}},
        },
    }


def _publish_params(tmp_path, name, params, shard_plan=None):
    from horovod_tpu.elastic.state import ObjectState
    from horovod_tpu.serving.publisher import Publisher
    d = str(tmp_path / name)
    state = ObjectState(commit_dir=d, commit_async=False, params=params)
    pub = Publisher(d, every=1, counters=_clean_counters,
                    shard_plan=shard_plan)
    state.commit()
    rec = pub.maybe_publish(state._commit_seq)
    assert rec is not None
    return state, pub, rec


def test_shard_manifest_roundtrip_and_identity(tmp_path):
    """Manifest encode/decode: every planned leaf gets an
    ``shards[leaf_digest] = {axis, n, parts}`` entry whose parts
    re-concatenate bit-identically to the whole-leaf blob — and the
    shard layer does NOT change ``leaves_digest`` (the served identity
    covers skeleton + leaf digests only)."""
    from horovod_tpu.serving.decode import tp_shard_plan
    from horovod_tpu.serving.publisher import leaves_digest

    tp = 4
    params = _decode_like_params(0)
    state, pub, rec = _publish_params(tmp_path, "cas", params,
                                      shard_plan=tp_shard_plan(tp))
    manifest = pub.store.read_manifest(rec["manifest_seq"])
    shards = manifest["shards"]
    # wq/wo/w1/w2 kernels planned; the embedding is replicated (no entry).
    assert len(shards) == 4
    leaf_bytes = {e[0]: e[1] for e in manifest["leaves"]}
    for digest, meta in shards.items():
        assert meta["n"] == tp and len(meta["parts"]) == tp
        assert meta["axis"] in (0, 1)
        whole = pickle.loads(pub.store.get_blob(digest, verify=True))
        parts = [pickle.loads(pub.store.get_blob(p[0], verify=True))
                 for p in meta["parts"]]
        np.testing.assert_array_equal(
            np.concatenate(parts, axis=meta["axis"]), whole)
        for p in meta["parts"]:
            assert p[1] > 0
        assert digest in leaf_bytes                 # whole leaf stays
    # Identity: stripping the shard layer leaves the digest unchanged.
    bare = {k: v for k, v in manifest.items() if k != "shards"}
    assert leaves_digest(manifest) == leaves_digest(bare) \
        == rec["leaves_digest"]


def test_shard_read_compat_both_ways(tmp_path):
    """Old reader × new manifest and new reader × old manifest both
    restore bit-identical payloads: whole-leaf blobs stay authoritative."""
    from horovod_tpu.serving.decode import tp_shard_plan, tp_shard_selector
    from horovod_tpu.serving.registry import ModelRegistry

    params = _decode_like_params(3)
    # New manifest (with shards), plain registry (no selector).
    _, pub, rec = _publish_params(tmp_path, "new", params,
                                  shard_plan=tp_shard_plan(4))
    plain = ModelRegistry(store=pub.store)
    assert plain.adopt(rec)
    got = plain.current().payload["attrs"]["params"]
    for k in ("wq", "wo"):
        np.testing.assert_array_equal(
            np.asarray(got["block_0"]["attn"][k]["kernel"]),
            params["block_0"]["attn"][k]["kernel"])
    # Old manifest (no shards), shard-selecting registry: falls back to
    # the whole leaf and still lands the complete payload.
    _, pub2, rec2 = _publish_params(tmp_path, "old", params)
    assert "shards" not in pub2.store.read_manifest(rec2["manifest_seq"])
    sel = ModelRegistry(store=pub2.store,
                        shard_selector=tp_shard_selector(4, 1))
    assert sel.adopt(rec2)
    got2 = sel.current().payload["attrs"]["params"]
    np.testing.assert_array_equal(
        np.asarray(got2["block_0"]["mlp"]["w1"]["kernel"]),
        params["block_0"]["mlp"]["w1"]["kernel"])


def test_shard_delta_fetch_counts_and_topology_change(tmp_path):
    """A shard-selecting registry fetches only its part bytes for planned
    leaves; a selector whose tp does NOT match the manifest's shard count
    (topology changed between publish and serve) falls back to whole
    leaves — correct first, cheap second."""
    from horovod_tpu.serving.decode import tp_shard_plan, tp_shard_selector
    from horovod_tpu.serving.registry import ModelRegistry

    tp = 4
    state, pub, rec = _publish_params(tmp_path, "cas",
                                      _decode_like_params(1),
                                      shard_plan=tp_shard_plan(tp))
    full = ModelRegistry(store=pub.store)
    shard = ModelRegistry(store=pub.store,
                          shard_selector=tp_shard_selector(tp, 2))
    mismatch = ModelRegistry(store=pub.store,
                             shard_selector=tp_shard_selector(2, 1))
    assert full.adopt(rec) and shard.adopt(rec) and mismatch.adopt(rec)
    fb = full.stats["bytes_fetched"]
    sb = shard.stats["bytes_fetched"]
    mb = mismatch.stats["bytes_fetched"]
    # Sharded leaves dominate this tree, so the delta is well under 1/2.
    assert 0 < sb < fb / 2, (sb, fb)
    # n=4 manifest × tp=2 selector: every leaf falls back to whole bytes.
    assert mb == fb, (mb, fb)
    # The mismatch payload is still complete and correct.
    got = mismatch.current().payload["attrs"]["params"]
    np.testing.assert_array_equal(
        np.asarray(got["block_0"]["attn"]["wo"]["kernel"]),
        np.asarray(full.current()
                   .payload["attrs"]["params"]["block_0"]["attn"]["wo"]
                   ["kernel"]))
    # And the shard registry's planned leaves are the right slices.
    wq = np.asarray(shard.current()
                    .payload["attrs"]["params"]["block_0"]["attn"]["wq"]
                    ["kernel"])
    wq_full = np.asarray(full.current()
                         .payload["attrs"]["params"]["block_0"]["attn"]
                         ["wq"]["kernel"])
    np.testing.assert_array_equal(wq, np.split(wq_full, tp, axis=1)[2])


def test_corrupted_shard_part_rejected_keeps_generation(tmp_path):
    """One bit-flipped part blob must fail adoption LOUDLY on the shard
    registry — which keeps serving its previous generation — while the
    whole-leaf path (intact blobs) adopts the same publish fine."""
    from horovod_tpu.serving.decode import tp_shard_plan, tp_shard_selector
    from horovod_tpu.serving.registry import ModelRegistry

    tp = 4
    state, pub, rec = _publish_params(tmp_path, "cas",
                                      _decode_like_params(5),
                                      shard_plan=tp_shard_plan(tp))
    full = ModelRegistry(store=pub.store)
    shard = ModelRegistry(store=pub.store,
                          shard_selector=tp_shard_selector(tp, 0))
    assert full.adopt(rec) and shard.adopt(rec)

    state.params = _decode_like_params(6)
    state.commit()
    rec2 = pub.maybe_publish(state._commit_seq)
    manifest = pub.store.read_manifest(rec2["manifest_seq"])
    part_digest = next(iter(manifest["shards"].values()))["parts"][0][0]
    with open(pub.store.blob_path(part_digest), "r+b") as fh:
        fh.seek(10)
        fh.write(b"\xff\xff\xff")

    kept = shard.current().manifest_seq
    rejected = shard.stats["rejected"]
    assert shard.adopt(rec2) is False
    assert shard.current().manifest_seq == kept      # generation kept
    assert shard.stats["rejected"] == rejected + 1
    assert full.adopt(rec2)                          # whole leaves intact


def test_gc_keeps_shard_part_blobs_of_live_manifests(tmp_path):
    """``referenced_digests`` names part blobs, so GC cannot sweep the
    shard layer out from under a live (or pinned) manifest; dropping the
    manifest releases the parts like any other blob."""
    store = BlobStore(str(tmp_path / "cas"))
    leaf, _ = store.put_blob(b"leaf-bytes" * 100)
    p1, _ = store.put_blob(b"part-one")
    p2, _ = store.put_blob(b"part-two")
    store.publish_manifest({
        "seq": 1, "skeleton": leaf, "leaves": [[leaf, 1000]],
        "shards": {leaf: {"axis": 0, "n": 2,
                          "parts": [[p1, 8], [p2, 8]]}}})
    refs = store.referenced_digests([store.read_manifest(1)])
    assert p1 in refs and p2 in refs
    time.sleep(0.02)
    d2, _ = store.put_blob(b"gen-2")
    store.publish_manifest({"seq": 2, "skeleton": d2, "leaves": [[d2, 5]]})
    store.gc(2)                     # both manifests live: parts survive
    assert store.has_blob(p1) and store.has_blob(p2)
    time.sleep(0.02)
    store.gc(1)                     # manifest 1 swept: parts released
    assert store.manifest_seqs() == [2]
    assert not store.has_blob(p1) and not store.has_blob(p2)


# --- peer-sourced resume (ISSUE 18) -----------------------------------------

_MESH_KEY = b"k" * 32


def _peer_restore(src_store, manifest, dst_dir, shard_selector=None):
    """Simulate a fresh rank restoring over the blob mesh: an EMPTY local
    store, the need set computed from the manifest under the selector,
    every blob fetched point-to-point from a real loopback
    ``BlobPeerService`` — exactly the multi-process resume path of
    ``load_persisted_world`` minus the collectives. Returns
    ``(payload, bytes_fetched)``."""
    from horovod_tpu.elastic import blobmesh
    dst = BlobStore(str(dst_dir))
    svc = blobmesh.BlobPeerService(src_store, _MESH_KEY,
                                   bind_host="127.0.0.1", rank=0)
    addr = {0: f"127.0.0.1:{svc.port}"}
    fetched = 0
    try:
        skel = [manifest["skeleton"]]
        s = blobmesh.fetch_missing(dst, skel, {skel[0]: [0]}, addr,
                                   _MESH_KEY)
        fetched += s["bytes_fetched"]
        need = state_mod._manifest_need(dst, manifest, shard_selector)
        missing = [d for d in need if not dst.has_blob(d)]
        s = blobmesh.fetch_missing(
            dst, missing,
            blobmesh.assign_sources(missing, {0: set(missing)}, 0),
            addr, _MESH_KEY)
        fetched += s["bytes_fetched"]
    finally:
        svc.close()
    return state_mod._unpack_manifest(dst, manifest, shard_selector), fetched


def _leaves_bytes(tree):
    import jax
    return [np.asarray(l).tobytes() for l in jax.tree_util.tree_leaves(tree)]


def test_peer_resume_equals_local_restore_bit_identical(tmp_path):
    """Property (same topology AND the regrown-world case — a brand-new
    rank owns NO blobs): a payload materialized entirely over the peer
    mesh is bit-identical to the committing rank's local restore, and the
    fetched bytes account for exactly skeleton + every whole leaf."""
    d = str(tmp_path / "commits")
    s = elastic.JaxState(commit_dir=d, commit_async=False,
                         params={"w": jnp.arange(256.0),
                                 "b": jnp.ones(32)}, step=0)
    s.save()
    store = state_mod._cas_store(d)
    manifest = store.read_manifest(s._commit_seq)
    local = state_mod._unpack_manifest(store, manifest)
    peer, fetched = _peer_restore(store, manifest, tmp_path / "fresh")
    assert _leaves_bytes(peer) == _leaves_bytes(local)
    expected = len(store.get_blob(manifest["skeleton"])) \
        + sum(e[1] for e in manifest["leaves"])
    assert fetched == expected


def test_peer_resume_resharded_world_delta_and_identity(tmp_path):
    """Topology-change restore (serving-style tp reshape): each target
    shard fetches ONLY its part blobs for planned leaves — byte
    accounting proves the delta — and the selected slices re-concatenate
    bit-identically to the whole-leaf restore."""
    from horovod_tpu.serving.decode import tp_shard_plan, tp_shard_selector
    tp = 4
    params = _decode_like_params(11)
    _state, pub, rec = _publish_params(tmp_path, "cas", params,
                                       shard_plan=tp_shard_plan(tp))
    manifest = pub.store.read_manifest(rec["manifest_seq"])
    full, full_bytes = _peer_restore(pub.store, manifest,
                                     tmp_path / "full")
    got_wq = []
    for idx in range(tp):
        part, part_bytes = _peer_restore(
            pub.store, manifest, tmp_path / f"shard{idx}",
            shard_selector=tp_shard_selector(tp, idx))
        assert 0 < part_bytes < full_bytes / 2, (part_bytes, full_bytes)
        # unplanned leaves ride whole (bit-identical to the full restore)
        emb = part["attrs"]["params"]["tok_embeddings"]["embedding"]
        assert np.asarray(emb).tobytes() == np.asarray(
            full["attrs"]["params"]["tok_embeddings"]["embedding"]).tobytes()
        got_wq.append(np.asarray(
            part["attrs"]["params"]["block_0"]["attn"]["wq"]["kernel"]))
    wq_full = np.asarray(
        full["attrs"]["params"]["block_0"]["attn"]["wq"]["kernel"])
    np.testing.assert_array_equal(np.concatenate(got_wq, axis=1), wq_full)
    assert np.concatenate(got_wq, axis=1).tobytes() == wq_full.tobytes()


def test_peer_resume_topology_mismatch_whole_leaf_fallback(tmp_path):
    """A selector whose tp does not divide the manifest's shard count
    falls back to whole leaves: the need set names no part blobs, the
    fetched bytes equal the full restore, and the payload is complete."""
    from horovod_tpu.serving.decode import tp_shard_plan, tp_shard_selector
    params = _decode_like_params(12)
    _state, pub, rec = _publish_params(tmp_path, "cas", params,
                                       shard_plan=tp_shard_plan(4))
    manifest = pub.store.read_manifest(rec["manifest_seq"])
    part_digests = {p[0] for m in manifest["shards"].values()
                    for p in m["parts"]}
    need = state_mod._manifest_need(pub.store, manifest,
                                    tp_shard_selector(2, 1))
    assert not (set(need) & part_digests)
    full, full_bytes = _peer_restore(pub.store, manifest, tmp_path / "f")
    mism, mism_bytes = _peer_restore(pub.store, manifest, tmp_path / "m",
                                     shard_selector=tp_shard_selector(2, 1))
    assert mism_bytes == full_bytes
    assert _leaves_bytes(mism) == _leaves_bytes(full)


def test_load_persisted_world_single_process_selector(tmp_path):
    """``load_persisted_world`` (single-process path) honors the shard
    selector: planned leaves come back as the target shard's slice,
    bit-identical to slicing the whole-leaf restore."""
    from horovod_tpu.serving.decode import tp_shard_plan, tp_shard_selector
    tp, idx = 4, 2
    params = _decode_like_params(13)
    _state, pub, rec = _publish_params(tmp_path, "cas", params,
                                       shard_plan=tp_shard_plan(tp))
    d = str(tmp_path / "cas")
    whole = state_mod.load_persisted_world(d)
    sliced = state_mod.load_persisted_world(
        d, shard_selector=tp_shard_selector(tp, idx))
    wq_whole = np.asarray(
        whole["attrs"]["params"]["block_0"]["attn"]["wq"]["kernel"])
    wq_slice = np.asarray(
        sliced["attrs"]["params"]["block_0"]["attn"]["wq"]["kernel"])
    np.testing.assert_array_equal(
        wq_slice, np.split(wq_whole, tp, axis=1)[idx])
    assert wq_slice.tobytes() \
        == np.split(wq_whole, tp, axis=1)[idx].tobytes()


def test_load_persisted_world_legacy_single_frame_fallback(tmp_path):
    """A commit dir holding only a legacy single-frame commit (no CAS
    manifest) still restores through ``load_persisted_world`` — with or
    without a selector (the selector needs a manifest to act on)."""
    d = str(tmp_path / "legacy")
    os.makedirs(d)
    payload = {"seq": 3, "attrs": {"w": np.arange(16.0)}}
    state_mod._persist(d, payload)
    got = state_mod.load_persisted_world(d)
    assert got["seq"] == 3
    np.testing.assert_array_equal(got["attrs"]["w"], np.arange(16.0))
    got2 = state_mod.load_persisted_world(
        d, shard_selector=lambda names, meta: None)
    assert np.asarray(got2["attrs"]["w"]).tobytes() \
        == np.asarray(got["attrs"]["w"]).tobytes()


# --- torn commit (crash between blob write and manifest publish) ------------

_TORN_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import horovod_tpu
    from horovod_tpu import elastic
    from horovod_tpu.testing import faults

    commit_dir = sys.argv[1]
    s = elastic.JaxState(commit_dir=commit_dir,
                         params={"w": jnp.arange(8.0)}, step=0)
    faults.on_step(0, rank=0)
    s.step = 1
    s.params = {"w": s.params["w"] + 1.0}
    s.save()
    assert s.flush_commits(timeout=30)      # commit 1 fully published
    faults.on_step(1, rank=0)               # arms the torn fault
    s.step = 2
    s.params = {"w": s.params["w"] + 1.0}
    s.save()
    # Commit 2's writer dies between blob write and manifest publish —
    # this flush never returns.
    s.flush_commits(timeout=30)
    print("UNREACHABLE", flush=True)
    sys.exit(3)
""")


@pytest.mark.slow
def test_torn_commit_restores_previous_manifest(tmp_path):
    """Kill the committing process between blob write and manifest
    publish (``torn`` fault): the store holds commit 2's orphan blobs
    but only commit 1's manifest, and restore lands on commit 1 — never
    a mixed state."""
    script = tmp_path / "torn_worker.py"
    script.write_text(_TORN_WORKER)
    d = str(tmp_path / "commits")
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo_root + os.pathsep + env.get("PYTHONPATH", ""),
        "HOROVOD_FAULT_SPEC": "torn:rank=0,step=1",
        "HOROVOD_FAULT_MARKER_DIR": str(tmp_path / "markers"),
        "HOROVOD_RANK": "0",
    })
    r = subprocess.run([sys.executable, str(script), d], env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 1, (r.stdout, r.stderr)
    assert "UNREACHABLE" not in r.stdout
    assert "torn commit" in (r.stdout + r.stderr)
    store = state_mod._cas_store(d)
    assert store.manifest_seqs() == [1]         # commit 2 never published
    s2 = elastic.JaxState(commit_dir=d, params=None, step=-1)
    assert s2.load_latest()
    assert s2.step == 1 and s2._commit_seq == 1
    np.testing.assert_array_equal(np.asarray(s2.params["w"]),
                                  np.arange(8.0) + 1.0)


# --- telemetry / incident wiring --------------------------------------------

def test_commit_telemetry_counters_and_stall_metric(tmp_path):
    from horovod_tpu.core import telemetry as _telemetry
    sess = _telemetry.active()
    if not sess.enabled:
        pytest.skip("telemetry disabled in this session")
    d = str(tmp_path / "commits")
    s = elastic.JaxState(commit_dir=d, params={"w": jnp.zeros(512)}, step=0)
    s.params = {"w": s.params["w"] + 1.0}
    s.save()
    assert s.flush_commits(timeout=30)
    snap = sess.registry.export()
    keys = set(snap["c"]) | set(snap["g"])
    assert any(k.startswith("hvd_checkpoint_bytes_written_total")
               for k in keys)
    assert any(k.startswith("hvd_commit_stall_seconds") for k in keys)
    assert any(k.startswith("hvd_last_manifest_seq") for k in keys)


def test_incident_report_names_last_manifest(tmp_path):
    from horovod_tpu.core import telemetry as _telemetry
    path = _telemetry.assemble_incident(
        str(tmp_path), 1, failure={"generation": 0, "last_manifest": 7})
    assert path is not None
    with open(path) as fh:
        report = json.load(fh)
    assert report["last_manifest"] == 7


def test_incident_last_manifest_falls_back_to_rank_events(tmp_path):
    from horovod_tpu.core import telemetry as _telemetry
    with open(os.path.join(str(tmp_path), "flight_0.jsonl"), "w") as fh:
        fh.write(json.dumps({"t": 0.0, "kind": "manifest_publish",
                             "seq": 3}) + "\n")
        fh.write(json.dumps({"t": 1.0, "kind": "manifest_publish",
                             "seq": 5}) + "\n")
    path = _telemetry.assemble_incident(str(tmp_path), 2,
                                        failure={"generation": 1})
    with open(path) as fh:
        report = json.load(fh)
    assert report["last_manifest"] == 5
