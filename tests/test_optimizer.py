"""DistributedOptimizer tests — parity with the reference's optimizer-wrapper
cases in test/parallel/test_torch.py (grad averaging, backward_passes_per_step
local aggregation, predivide factor, process sets, join uneven data)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

import horovod_tpu as hvd
from horovod_tpu.optimizer import (DistributedOptimizer, distributed,
                                   join_allreduce)

N = 8


def run_sharded(fn, *args, out_specs=P()):
    f = shard_map(fn, mesh=hvd.mesh(),
                  in_specs=tuple(P(hvd.RANK_AXIS) for _ in args),
                  out_specs=out_specs, check_vma=False)
    return jax.jit(f)(*args)


def test_distributed_sgd_averages_grads():
    opt = distributed(optax.sgd(0.1))
    params = {"w": jnp.ones((3,))}
    grads_per_rank = np.stack(
        [np.full((3,), float(r)) for r in range(N)]).astype(np.float32)

    def step(g):
        g = {"w": g[0]}
        state = opt.init(params)
        updates, _ = opt.update(g, state, params)
        return optax.apply_updates(params, updates)["w"]

    out = np.asarray(run_sharded(step, jnp.asarray(grads_per_rank)))
    # mean grad = 3.5 → w = 1 - 0.1*3.5
    np.testing.assert_allclose(out, 1 - 0.35, rtol=1e-6)


def test_distributed_matches_single_process_large_batch():
    """DP training with grad averaging == single-process training on the
    concatenated batch — the core correctness invariant of the reference."""
    rng = np.random.RandomState(0)
    X = rng.randn(N * 4, 5).astype(np.float32)
    y = rng.randn(N * 4, 1).astype(np.float32)
    w0 = rng.randn(5, 1).astype(np.float32)

    def loss_fn(w, xb, yb):
        return jnp.mean((xb @ w - yb) ** 2)

    # single-process reference
    w_ref = jnp.asarray(w0)
    opt_ref = optax.sgd(0.05)
    st_ref = opt_ref.init(w_ref)
    for _ in range(5):
        g = jax.grad(loss_fn)(w_ref, jnp.asarray(X), jnp.asarray(y))
        up, st_ref = opt_ref.update(g, st_ref, w_ref)
        w_ref = optax.apply_updates(w_ref, up)  # hvd-analyze: ok

    # distributed: each rank sees its shard; mean-of-shard-means == full mean
    opt = distributed(optax.sgd(0.05))

    def train(xs, ys):
        w = jnp.asarray(w0)
        st = opt.init(w)
        for _ in range(5):
            g = jax.grad(loss_fn)(w, xs, ys)
            up, st = opt.update(g, st, w)
            w = optax.apply_updates(w, up)  # hvd-analyze: ok
        return w

    w_dp = np.asarray(run_sharded(train, jnp.asarray(X), jnp.asarray(y)))
    np.testing.assert_allclose(w_dp, np.asarray(w_ref), rtol=1e-5, atol=1e-6)


def test_backward_passes_per_step():
    """k micro-steps accumulate locally; collective+update at the boundary."""
    k = 4
    opt = distributed(optax.sgd(1.0), backward_passes_per_step=k)
    w0 = jnp.zeros((2,))

    def train(gs):
        # gs: [1, k, 2] per-rank sequence of k micro-grads
        w = w0
        st = opt.init(w)
        outs = []
        for i in range(k):
            up, st = opt.update(gs[0, i], st, w)
            w = optax.apply_updates(w, up)
            outs.append(w)
        return jnp.stack(outs)

    rng = np.random.RandomState(1)
    gs = rng.randn(N, k, 2).astype(np.float32)
    out = np.asarray(run_sharded(train, jnp.asarray(gs)))
    # first k-1 steps: no change
    np.testing.assert_allclose(out[0], 0.0, atol=1e-7)
    np.testing.assert_allclose(out[k - 2], 0.0, atol=1e-7)
    # boundary: w = -lr * mean-over-(ranks × micro-steps)
    expected = -gs.mean(axis=(0, 1))
    np.testing.assert_allclose(out[k - 1], expected, rtol=1e-5, atol=1e-6)


def test_gradient_predivide_factor():
    opt_pre = DistributedOptimizer(optax.sgd(1.0),
                                   gradient_predivide_factor=2.0)
    opt_avg = DistributedOptimizer(optax.sgd(1.0))
    g_per_rank = np.stack([np.full((2,), float(r + 1))
                           for r in range(N)]).astype(np.float32)
    w = jnp.zeros((2,))

    def step(opt):
        def body(g):
            st = opt.init(w)
            up, _ = opt.update(g[0], st, w)
            return optax.apply_updates(w, up)
        return np.asarray(run_sharded(body, jnp.asarray(g_per_rank)))

    # predivide path must equal plain averaging (it is an average computed
    # in two stages)
    np.testing.assert_allclose(step(opt_pre), step(opt_avg), rtol=1e-5)


def test_distributed_process_set():
    ps = hvd.add_process_set([0, 1, 2, 3])
    opt = distributed(optax.sgd(1.0), process_set=ps)
    g_per_rank = np.stack([np.full((1,), float(r))
                           for r in range(N)]).astype(np.float32)

    def body(g):
        w = jnp.zeros((1,))
        st = opt.init(w)
        up, _ = opt.update(g[0], st, w)
        return optax.apply_updates(w, up)[None]

    out = np.asarray(run_sharded(body, jnp.asarray(g_per_rank),
                                 out_specs=P(hvd.RANK_AXIS)))
    np.testing.assert_allclose(out[0, 0], -1.5, rtol=1e-5)  # mean(0..3)
    np.testing.assert_allclose(out[5, 0], -5.0, rtol=1e-5)  # own grad


def test_join_allreduce_uneven_data():
    flags = np.array([1, 1, 1, 1, 1, 0, 0, 0], np.float32)
    grads = np.stack([np.full((2,), float(r + 1))
                      for r in range(N)]).astype(np.float32)

    def body(g, f):
        return join_allreduce(g[0], f[0, 0])

    out = np.asarray(run_sharded(body, jnp.asarray(grads),
                                 jnp.asarray(flags)[:, None]))
    np.testing.assert_allclose(out, np.full((2,), (1 + 2 + 3 + 4 + 5) / 5.0),
                               rtol=1e-5)


def test_join_allreduce_no_live_ranks():
    flags = np.zeros((N,), np.float32)
    grads = np.ones((N, 3), np.float32)

    def body(g, f):
        return join_allreduce(g[0], f[0, 0])

    out = np.asarray(run_sharded(body, jnp.asarray(grads),
                                 jnp.asarray(flags)[:, None]))
    np.testing.assert_allclose(out, 0.0, atol=1e-7)


def test_broadcast_parameters_single_host_identity():
    params = {"w": jnp.arange(4.0)}
    out = hvd.optimizer.broadcast_parameters(params)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.arange(4.0))


def test_allgather_object_single_host():
    obj = {"config": [1, 2, 3]}
    assert hvd.optimizer.allgather_object(obj) == [obj]


def test_broadcast_object_single_host():
    obj = {"epoch": 3, "lr": 0.1}
    assert hvd.optimizer.broadcast_object(obj) == obj


def test_join_shim():
    assert hvd.optimizer.join() == N - 1


def test_sync_batch_norm():
    """SyncBatchNorm normalises with cross-replica statistics."""
    from horovod_tpu.optimizer import SyncBatchNorm
    rng = np.random.RandomState(3)
    x = rng.randn(N, 4, 6).astype(np.float32) + np.arange(N)[:, None, None]

    bn = SyncBatchNorm(use_running_average=False, momentum=0.9)
    variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[0]))

    def body(xb):
        y, _ = bn.apply(variables, xb[0], mutable=["batch_stats"])
        return y[None]

    out = np.asarray(run_sharded(body, jnp.asarray(x),
                                 out_specs=P(hvd.RANK_AXIS)))
    # global normalisation: per-feature mean over ALL ranks ~ 0
    flat = out.reshape(-1, 6)
    np.testing.assert_allclose(flat.mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(flat.std(0), 1.0, atol=1e-2)


def test_join_allreduce_rejects_bad_op():
    with pytest.raises(ValueError):
        join_allreduce({"g": jnp.ones(2)}, True, op=hvd.Min)


def test_unsynced_batch_stats_are_pmeaned():
    """make_train_step must return truly-replicated batch stats even when
    the model's BatchNorm does not sync (axis_name=None)."""
    from horovod_tpu.models import ResNetTiny
    from horovod_tpu.train import create_train_state, make_train_step
    from horovod_tpu.optimizer import distributed

    model = ResNetTiny(num_classes=10, dtype=jnp.float32, axis_name=None)
    opt = distributed(optax.sgd(0.1))
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(N * 2, 8, 8, 3).astype(np.float32)
                    + np.repeat(np.arange(N), 2)[:, None, None, None])
    y = jnp.asarray(rng.randint(0, 10, (N * 2,)))
    loss_fn = lambda l, t: optax.softmax_cross_entropy_with_integer_labels(l, t).mean()
    st = create_train_state(model, jax.random.PRNGKey(0), x[:1], opt)
    st, _ = make_train_step(model, opt, loss_fn)(st, x, y)
    # stats are the mean over per-device stats: finite, well-defined
    for leaf in jax.tree_util.tree_leaves(st.batch_stats):
        assert np.isfinite(np.asarray(leaf)).all()
