"""Fleet arbiter: traffic-aware capacity re-bidding between training
and serving (ROADMAP item 5; docs/fleet.md).

No upstream analog (SURVEY.md §2: ``horovod/runner/elastic/driver.py``
only ever arbitrates TRAINING hosts — serving does not exist there).
Here the coordinator already merges everything a policy needs: per-rank
training step walls and serving queue-depth/staleness gauges arrive
piggybacked on the existing polls (core/telemetry.py →
``CoordinatorService._record_metrics``), and the elastic world can
grow/shrink via the graceful reset. This module closes the loop.

Design:

- **Policy is pure hysteresis** (:class:`ArbiterPolicy`): the worst
  per-replica queue depth must stay at or above ``queue_high`` (or
  staleness above ``staleness_high_s``) for ``sustain`` consecutive
  evaluations before serving scales OUT by one replica, and at or below
  ``queue_low`` just as long before a replica is reclaimed for training
  — with a ``cooldown_s`` dead time between decisions so the fleet never
  flaps faster than a graceful reset + replica warmup can complete.
  Bounds: serving never exceeds ``max_replicas`` and training never
  shrinks below ``min_training_np``; serving never drops below
  ``min_replicas``.
- **Every decision is a journal record**: :meth:`FleetArbiter.evaluate`
  lands decisions through
  :meth:`~.service.CoordinatorService.record_arbiter_decision`, which
  appends an ``op:"arbiter"`` record (elastic/journal.py) under the
  arbiter's own monotonic ``seq``. A coordinator crash-restart replays
  the journal and the next :class:`FleetArbiter` seeds itself from
  :meth:`~.service.CoordinatorService.fleet_view` — the fleet resumes
  the SAME shape mid-rebalance instead of re-deciding from zero (chaos
  proof: tests/test_fleet_chaos.py).
- **Decide, don't enact**: the arbiter outputs a target shape
  ``{serving_target, training_np}``. Enactment — starting/draining
  replicas (``InferenceServer.drain()``), shrinking the training world
  via the existing graceful reset — belongs to the hosting harness
  (benchmarks/fleet.py, the driver), whose moves land as their own
  world/replica journal records. Keeping the decision separate from the
  move is what makes replay deterministic: the journal holds intents,
  not side effects.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..core import telemetry as _telemetry
from ..core.logging import get_logger
from . import constants as C


def _env_float(name: str, default: float) -> float:
    import os
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    import os
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        return default


@dataclass
class ArbiterPolicy:
    """Hysteresis bounds for capacity re-bidding (docs/fleet.md lists
    each knob's failure mode when mis-set)."""

    #: Scale serving OUT when the worst replica queue depth sustains here.
    queue_high: float = C.DEFAULT_ARBITER_QUEUE_HIGH
    #: Reclaim a replica for training when it sustains at or below this.
    queue_low: float = C.DEFAULT_ARBITER_QUEUE_LOW
    #: Staleness that also triggers scale-out (0 = queue depth only).
    staleness_high_s: float = C.DEFAULT_ARBITER_STALENESS_HIGH_S
    #: The training world never shrinks below this.
    min_training_np: int = C.DEFAULT_ARBITER_MIN_TRAINING_NP
    #: Serving replica-count bounds.
    min_replicas: int = C.DEFAULT_ARBITER_MIN_REPLICAS
    max_replicas: int = C.DEFAULT_ARBITER_MAX_REPLICAS
    #: Dead time between decisions (a graceful reset + replica warmup
    #: must complete before the signals are trustworthy again).
    cooldown_s: float = C.DEFAULT_ARBITER_COOLDOWN_S
    #: Consecutive evaluations a signal must sustain before it counts.
    sustain: int = C.DEFAULT_ARBITER_SUSTAIN

    @classmethod
    def from_env(cls) -> "ArbiterPolicy":
        return cls(
            queue_high=_env_float(C.ARBITER_QUEUE_HIGH_ENV,
                                  C.DEFAULT_ARBITER_QUEUE_HIGH),
            queue_low=_env_float(C.ARBITER_QUEUE_LOW_ENV,
                                 C.DEFAULT_ARBITER_QUEUE_LOW),
            staleness_high_s=_env_float(C.ARBITER_STALENESS_HIGH_ENV,
                                        C.DEFAULT_ARBITER_STALENESS_HIGH_S),
            min_training_np=max(1, _env_int(
                C.ARBITER_MIN_TRAINING_NP_ENV,
                C.DEFAULT_ARBITER_MIN_TRAINING_NP)),
            min_replicas=max(0, _env_int(C.ARBITER_MIN_REPLICAS_ENV,
                                         C.DEFAULT_ARBITER_MIN_REPLICAS)),
            max_replicas=max(1, _env_int(C.ARBITER_MAX_REPLICAS_ENV,
                                         C.DEFAULT_ARBITER_MAX_REPLICAS)),
            cooldown_s=max(0.0, _env_float(C.ARBITER_COOLDOWN_ENV,
                                           C.DEFAULT_ARBITER_COOLDOWN_S)),
            sustain=max(1, _env_int(C.ARBITER_SUSTAIN_ENV,
                                    C.DEFAULT_ARBITER_SUSTAIN)),
        )


class FleetArbiter:
    """The policy loop the coordinator hosts.

    ``total_hosts`` is the capacity being bid over: at every decision
    ``serving_target + training_np == total_hosts`` (one host per
    serving replica — the granularity the graceful reset moves in).
    ``clock`` is injectable so hysteresis/cooldown tests run on a fake
    clock, no real sleeps in tier-1.
    """

    def __init__(self, service, total_hosts: int,
                 policy: Optional[ArbiterPolicy] = None,
                 clock: Callable[[], float] = time.monotonic):
        if total_hosts < 1:
            raise ValueError(f"total_hosts must be >= 1, got {total_hosts}")
        self._service = service
        self._policy = policy or ArbiterPolicy.from_env()
        self._clock = clock
        self._total = int(total_hosts)
        self._high_streak = 0
        self._low_streak = 0
        self._last_decision_t: Optional[float] = None
        # Crash-restart seam: adopt the journal-replayed shape (and its
        # seq) so the resumed arbiter continues the SAME rebalance. A
        # fresh world starts at min_replicas serving.
        view = service.fleet_view()
        fleet = view.get("fleet")
        if fleet is not None:
            self._serving = int(fleet["serving_target"])
            self._training = int(fleet["training_np"])
        else:
            self._serving = min(self._policy.max_replicas,
                                max(self._policy.min_replicas, 1))
            self._training = max(self._policy.min_training_np,
                                 self._total - self._serving)
        _telemetry.set_gauge("hvd_fleet_serving_target",
                             float(self._serving))
        _telemetry.set_gauge("hvd_fleet_training_np", float(self._training))

    @property
    def shape(self) -> dict:
        """The current target fleet shape."""
        return {"serving_target": self._serving,
                "training_np": self._training}

    # -- the policy ----------------------------------------------------------

    def _in_cooldown(self, now: float) -> bool:
        return (self._last_decision_t is not None
                and now - self._last_decision_t < self._policy.cooldown_s)

    def evaluate(self, now: Optional[float] = None) -> Optional[dict]:
        """Run one policy evaluation against the coordinator-merged
        signals. Returns the decision dict (journaled, with its ``seq``)
        when the fleet shape changes, else None. Call on the hosting
        loop's cadence — every evaluation advances the sustain streaks,
        so cadence × ``sustain`` is the real reaction time."""
        p = self._policy
        now = self._clock() if now is None else now
        sig = self._service.serving_signals()
        overloaded = sig["queue_depth"] >= p.queue_high or (
            p.staleness_high_s > 0
            and sig["staleness_s"] > p.staleness_high_s)
        idle = sig["queue_depth"] <= p.queue_low
        self._high_streak = self._high_streak + 1 if overloaded else 0
        self._low_streak = self._low_streak + 1 if idle else 0
        if self._in_cooldown(now):
            return None
        serving, training = self._serving, self._training
        reason = ""
        if self._high_streak >= p.sustain and serving < p.max_replicas \
                and training - 1 >= p.min_training_np:
            serving, training = serving + 1, training - 1
            reason = (f"overload: queue={sig['queue_depth']:.1f} "
                      f"staleness={sig['staleness_s']:.1f}s sustained "
                      f"{self._high_streak} evals")
        elif self._low_streak >= p.sustain and serving > p.min_replicas \
                and serving - 1 >= 0:
            serving, training = serving - 1, training + 1
            reason = (f"drained: queue={sig['queue_depth']:.1f} sustained "
                      f"{self._low_streak} evals")
        if (serving, training) == (self._serving, self._training):
            return None
        seq = self._service.record_arbiter_decision(serving, training,
                                                    reason)
        self._serving, self._training = serving, training
        self._high_streak = self._low_streak = 0
        self._last_decision_t = now
        _telemetry.set_gauge("hvd_fleet_serving_target", float(serving))
        _telemetry.set_gauge("hvd_fleet_training_np", float(training))
        get_logger().info("arbiter: decision #%d serving=%d training=%d "
                          "(%s)", seq, serving, training, reason)
        return {"seq": seq, "serving_target": serving,
                "training_np": training, "reason": reason}
