"""Torch tensor collectives with async handles.

Reference parity: ``horovod/torch/mpi_ops.py`` + the C++ binding
``horovod/torch/mpi_ops_v2.cc`` / ``handle_manager.cc`` (SURVEY.md §2.3,
§2.4): sync and async variants of every op, in-place ``*_`` forms, integer
handles resolved by ``synchronize``/``poll``, name-keyed matching across
ranks, prescale/postscale factors and wire compression.

The transport is a :class:`~.engine.CollectiveEngine`; async execution uses
a per-rank worker pool, so ranks may submit differently-ordered op sets and
the name-keyed rendezvous still matches them — the job the reference's
controller negotiation does (SURVEY.md §2.1). Like the reference, a name may
not be in flight twice ("duplicate tensor name" error).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional

import numpy as np
import torch

from . import engine as _engine
from .engine import (Adasum, Average, Max, Min, Product, Sum)  # noqa: F401
from .compression import Compression
from ..core.process_sets import ProcessSet, ProcessSetTable  # noqa: F401

# --- module state -----------------------------------------------------------

_lock = threading.Lock()
_state: Optional["_TorchRuntime"] = None


class _TorchRuntime:
    """Per-process runtime: engine + handle table + ordered async worker."""

    def __init__(self, eng: _engine.CollectiveEngine):
        self.engine = eng
        self.handles: Dict[int, Future] = {}
        self.next_handle = 0
        self.hlock = threading.Lock()
        self._executors: Dict[int, ThreadPoolExecutor] = {}
        self._name_counters: Dict[int, Dict[str, int]] = {}
        self._inflight: set = set()
        self.process_sets = ProcessSetTable(eng.size())

    def executor(self) -> ThreadPoolExecutor:
        # A worker POOL per rank: ops run concurrently so ranks may submit
        # op sets in different orders and the name-keyed rendezvous still
        # matches them (the reference controller's negotiation role).
        # Engines whose transport matches by PROGRAM ORDER (JaxProcessEngine
        # over XLA collectives) get a single worker instead: submission
        # order defines the cross-process pairing, and the engine's header
        # round turns any residual divergence into an error.
        r = self.engine.rank()
        workers = 1 if getattr(self.engine, "requires_ordered_submission",
                               False) else 16
        with self.hlock:
            ex = self._executors.get(r)
            if ex is None:
                if isinstance(self.engine, _engine.ThreadSimEngine):
                    ex = ThreadPoolExecutor(
                        max_workers=workers,
                        initializer=self.engine.set_rank, initargs=(r,))
                else:
                    ex = ThreadPoolExecutor(max_workers=workers)
                self._executors[r] = ex
            return ex

    def submit(self, kind: str, name: Optional[str], fn) -> int:
        """Autoname, reject duplicate in-flight names (reference
        "Duplicate tensor name" error), run ``fn(name)`` on the rank's
        pool, return a handle."""
        name = self.autoname(kind, name)
        key = (self.engine.rank(), kind, name)
        with self.hlock:
            if key in self._inflight:
                raise ValueError(
                    f"duplicate name {name!r}: a {kind} with this name is "
                    "already in flight (reference controller restriction)")
            self._inflight.add(key)

        def run():
            # Per-op timeline span (reference timeline.cc: each collective
            # gets NEGOTIATE/EXEC activities; here the host-side engine op
            # is one span, device phases live in the xplane trace).
            tl = None
            from ..core import context_api as _ctx
            if _ctx.is_initialized():
                tl = _ctx.context().timeline
            # tid = worker-thread id: concurrent ops on the async pool
            # must not share a Chrome-trace track, or B/E pairs mis-nest
            # and spans get attributed to the wrong op.
            tid = threading.get_ident() & 0x7FFFFFFF
            if tl is not None:
                tl.activity_start(name, kind.upper(),
                                  rank=self.engine.rank(), tid=tid)
            try:
                return fn(name)
            finally:
                if tl is not None:
                    tl.activity_end(name, kind.upper(),
                                    rank=self.engine.rank(), tid=tid)
                with self.hlock:
                    self._inflight.discard(key)
        return self.alloc(self.executor().submit(run))

    def alloc(self, fut: Future) -> int:
        with self.hlock:
            h = self.next_handle
            self.next_handle += 1
            self.handles[h] = fut
            return h

    def autoname(self, kind: str, name: Optional[str]) -> str:
        from ..core.engine import next_autoname
        with self.hlock:
            return next_autoname(self._name_counters, self.engine.rank(),
                                 kind, name)

    def shutdown(self):
        # Release only what THIS binding owns (its executors).  The
        # engine is the shared process engine (context_api.process_engine,
        # also used by TF and the JAX-path object helpers); its teardown
        # belongs to core.context_api.shutdown — shutting it down here
        # would yank it from under the other frontends (ADVICE r5 #3).
        for ex in self._executors.values():
            ex.shutdown(wait=True)


def init(engine: Optional[_engine.CollectiveEngine] = None) -> None:
    """Initialize the torch API. Engine selection mirrors the reference's
    transport priority (SURVEY.md §2.2 op manager): an explicit engine wins
    (tests inject ThreadSimEngine); otherwise JaxProcessEngine on multi-host
    pods; otherwise single-process."""
    global _state
    with _lock:
        if _state is not None:
            return
        if engine is None:
            # The ONE shared process engine (context_api.process_engine):
            # torch, TF, and the JAX-path object helpers must issue rounds
            # through the same instance, or their unordered rounds over the
            # one coordination service could cross-pair (r5 review).
            from ..core.context_api import process_engine
            engine = process_engine()
        _state = _TorchRuntime(engine)


def shutdown() -> None:
    global _state
    with _lock:
        if _state is not None:
            _state.shutdown()
            _state = None


def is_initialized() -> bool:
    return _state is not None


def _rt() -> _TorchRuntime:
    if _state is None:
        raise RuntimeError(
            "horovod_tpu.torch not initialized; call hvd.init() first")
    return _state


def rank() -> int:
    return _rt().engine.rank()


def size() -> int:
    return _rt().engine.size()


def local_rank() -> int:
    return _rt().engine.local_rank()


def local_size() -> int:
    return _rt().engine.local_size()


def cross_rank() -> int:
    return _rt().engine.cross_rank()


def cross_size() -> int:
    return _rt().engine.cross_size()


# --- process sets (reference process_sets.py over the engine layer) ---------

def add_process_set(ranks) -> ProcessSet:
    """Register a subset of ranks for subgroup collectives (reference
    ``hvd.add_process_set``). Pass the returned set as ``process_set=`` to
    any op; only member ranks may call it."""
    return _rt().process_sets.add(ranks)


def remove_process_set(ps) -> None:
    _rt().process_sets.remove(ps)


def global_process_set() -> ProcessSet:
    return _rt().process_sets.global_set


def _members(process_set: Optional[ProcessSet]):
    """ProcessSet -> engine ``members`` tuple (None for the global set, so
    the non-set path stays byte-identical)."""
    if process_set is None or process_set.process_set_id == 0:
        return None
    return tuple(process_set.ranks)


# --- numpy adaptation -------------------------------------------------------

def _to_np(t: torch.Tensor) -> np.ndarray:
    if t.dtype == torch.bfloat16:
        # torch refuses bf16 .numpy(); view-cast through int16 (present
        # in every supported torch, unlike uint16 which needs >=2.3) onto
        # the ml_dtypes wire dtype — a bit-identical reinterpret — so
        # bf16 tensors and Compression.bf16 cross the engine boundary.
        import ml_dtypes
        return (t.detach().cpu().contiguous().view(torch.int16)
                .numpy().view(ml_dtypes.bfloat16))
    return t.detach().cpu().contiguous().numpy()


def _from_np(a: np.ndarray, like: torch.Tensor) -> torch.Tensor:
    import ml_dtypes
    if a.dtype == ml_dtypes.bfloat16:
        out = torch.from_numpy(
            np.ascontiguousarray(a).view(np.int16)).view(torch.bfloat16)
        return out.to(device=like.device, dtype=like.dtype)
    return torch.from_numpy(np.ascontiguousarray(a)).to(
        device=like.device, dtype=like.dtype)


# --- allreduce --------------------------------------------------------------

def _allreduce_impl(tensor: torch.Tensor, op: str, name: Optional[str],
                    compression, prescale_factor: float,
                    postscale_factor: float,
                    output: Optional[torch.Tensor],
                    members=None, segments=None) -> torch.Tensor:
    rt = _rt()
    compressed, ctx = compression.compress(tensor)
    arr = _to_np(compressed)
    if prescale_factor != 1.0:
        # keep the WIRE dtype: ml_dtypes.bfloat16 * python float promotes
        # to float32, silently doubling the compressed payload
        arr = (arr * prescale_factor).astype(arr.dtype)
    # pass segments only when set: engine subclasses predating the fused
    # Adasum metadata (tests, user fakes) keep working untouched
    out = rt.engine.allreduce(name, arr, op, members=members,
                              **({} if segments is None
                                 else {"segments": segments}))
    if postscale_factor != 1.0:
        out = out * postscale_factor
    res = compression.decompress(_from_np(out, compressed), ctx)
    res = res.to(tensor.dtype)
    if output is not None:
        output.copy_(res)
        return output
    return res


def allreduce_async(tensor: torch.Tensor, average: Optional[bool] = None,
                    name: Optional[str] = None,
                    compression=Compression.none, op: Optional[str] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set: Optional[ProcessSet] = None) -> int:
    op = _op_from_average(average, op)
    return _rt().submit("allreduce", name, lambda nm: _allreduce_impl(
        tensor, op, nm, compression, prescale_factor, postscale_factor,
        None, _members(process_set)))


def allreduce_async_(tensor: torch.Tensor, average: Optional[bool] = None,
                     name: Optional[str] = None,
                     compression=Compression.none, op: Optional[str] = None,
                     prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0,
                     process_set: Optional[ProcessSet] = None) -> int:
    op = _op_from_average(average, op)
    return _rt().submit("allreduce", name, lambda nm: _allreduce_impl(
        tensor, op, nm, compression, prescale_factor, postscale_factor,
        tensor, _members(process_set)))


def allreduce(tensor: torch.Tensor, average: Optional[bool] = None,
              name: Optional[str] = None, compression=Compression.none,
              op: Optional[str] = None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0,
              process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(allreduce_async(
        tensor, average, name, compression, op, prescale_factor,
        postscale_factor, process_set))


def allreduce_(tensor: torch.Tensor, average: Optional[bool] = None,
               name: Optional[str] = None, compression=Compression.none,
               op: Optional[str] = None, prescale_factor: float = 1.0,
               postscale_factor: float = 1.0,
               process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(allreduce_async_(
        tensor, average, name, compression, op, prescale_factor,
        postscale_factor, process_set))


def grouped_allreduce_async(tensors, average=None, name=None,
                            compression=Compression.none, op=None,
                            prescale_factor=1.0, postscale_factor=1.0,
                            process_set: Optional[ProcessSet] = None):
    """One handle for a list of tensors, reduced atomically (reference:
    grouped ops via group_table.cc, SURVEY.md §2.1)."""
    op = _op_from_average(average, op)
    m = _members(process_set)
    return _rt().submit("grouped_allreduce", name, lambda nm: [
        _allreduce_impl(t, op, f"{nm}.{i}", compression,
                        prescale_factor, postscale_factor, None, m)
        for i, t in enumerate(tensors)])


def grouped_allreduce(tensors, **kw):
    return synchronize(grouped_allreduce_async(tensors, **kw))


def grouped_allreduce_async_(tensors, average=None, name=None,
                             compression=Compression.none, op=None,
                             prescale_factor=1.0, postscale_factor=1.0,
                             process_set: Optional[ProcessSet] = None):
    op = _op_from_average(average, op)
    m = _members(process_set)
    return _rt().submit("grouped_allreduce", name, lambda nm: [
        _allreduce_impl(t, op, f"{nm}.{i}", compression,
                        prescale_factor, postscale_factor, t, m)
        for i, t in enumerate(tensors)])


def grouped_allreduce_(tensors, **kw):
    return synchronize(grouped_allreduce_async_(tensors, **kw))


def allreduce_fused_async_(tensors, op: str = Average,
                           name: Optional[str] = None,
                           compression=Compression.none,
                           prescale_factor: float = 1.0,
                           postscale_factor: float = 1.0,
                           process_set: Optional[ProcessSet] = None) -> int:
    """ONE engine collective for a list of same-dtype tensors: concatenate
    into a flat fusion buffer, allreduce it, scatter the result back into
    the tensors in place (reference ``fusion_buffer_manager.cc``'s
    MEMCPY_IN/OUT_OF_FUSION_BUFFER, SURVEY.md §2.1 — the bandwidth/latency
    form, vs ``grouped_allreduce_`` which issues one *named* engine op per
    tensor and only guarantees atomicity). On the multi-host engine this is
    what collapses a P-parameter gradient step from O(P) negotiated rounds
    to O(buckets)."""
    rt = _rt()
    m = _members(process_set)
    # Fused Adasum applies each tensor's OWN coefficient pair inside the
    # buffer (reference ops/adasum/adasum.h fused-buffer design): the
    # per-tensor segment boundaries ride the submission to the engine.
    segments = tuple(t.numel() for t in tensors) if op == Adasum else None

    def run(nm):
        flat = torch.cat([t.detach().reshape(-1) for t in tensors])
        res = _allreduce_impl(flat, op, nm, compression, prescale_factor,
                              postscale_factor, None, m, segments)
        off = 0
        for t in tensors:
            n = t.numel()
            t.copy_(res[off:off + n].view_as(t).to(t.dtype))
            off += n
        return tensors
    return rt.submit("allreduce", name, run)


def _op_from_average(average: Optional[bool], op: Optional[str]) -> str:
    if average is not None and op is not None:
        raise ValueError("specify either average or op, not both "
                         "(reference mpi_ops.py contract)")
    if op is not None:
        return op
    if average is False:
        return Sum
    return Average


def sparse_allreduce_async(tensor: torch.Tensor, op: str = Average,
                           name: Optional[str] = None,
                           process_set: Optional[ProcessSet] = None) -> int:
    """Allreduce a sparse COO tensor via the reference's gather-based
    scheme (``horovod/torch/optimizer.py`` ``_sparse_allreduce_async``):
    allgather (indices, values) across ranks — nnz may differ per rank,
    the engines' ragged allgather handles it — then rebuild;
    ``coalesce()`` sums duplicate coordinates, which IS the reduction.
    Only Sum/Average make sense for sparse.

    NOT in place (sparse storage cannot be swapped under a live tensor):
    the reduced tensor is ``synchronize(handle)``'s RETURN VALUE — assign
    it, e.g. ``p.grad = hvd.synchronize(h)``; the input is untouched."""
    if op not in (Sum, Average):
        raise ValueError(f"sparse allreduce supports Sum/Average, got {op}")
    if not tensor.is_sparse:
        raise ValueError("sparse_allreduce_async needs a sparse tensor")
    rt = _rt()
    members = _members(process_set)
    # Average must divide by the PARTICIPANT count, not the world size —
    # a future sub-world caller would otherwise get silently wrong means.
    n = len(members) if members is not None else rt.engine.size()

    def run(nm):
        t = tensor.coalesce()
        idx = t.indices().t().contiguous().cpu().numpy()  # [nnz, ndim]
        vals = t.values().contiguous()
        if op == Average:
            vals = vals / n
        g_idx = rt.engine.allgather(f"{nm}.idx", idx, members=members)
        g_vals = rt.engine.allgather(f"{nm}.vals", _to_np(vals),
                                     members=members)
        return torch.sparse_coo_tensor(
            torch.from_numpy(np.ascontiguousarray(g_idx.T)),
            _from_np(g_vals, vals).to(tensor.dtype),
            t.shape).coalesce().to(tensor.device)
    return rt.submit("sparse_allreduce", name, run)


# --- allgather --------------------------------------------------------------

def allgather_async(tensor: torch.Tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    rt = _rt()
    return rt.submit("allgather", name, lambda nm: _from_np(
        rt.engine.allgather(nm, _to_np(tensor),
                            members=_members(process_set)), tensor))


def allgather(tensor: torch.Tensor, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(allgather_async(tensor, name, process_set))


def grouped_allgather_async(tensors, name: Optional[str] = None,
                            process_set: Optional[ProcessSet] = None) -> int:
    rt = _rt()
    m = _members(process_set)
    return rt.submit("grouped_allgather", name, lambda nm: [
        _from_np(rt.engine.allgather(f"{nm}.{i}", _to_np(t), members=m), t)
        for i, t in enumerate(tensors)])


def grouped_allgather(tensors, name: Optional[str] = None,
                      process_set: Optional[ProcessSet] = None):
    return synchronize(grouped_allgather_async(tensors, name, process_set))


# --- broadcast --------------------------------------------------------------

def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    rt = _rt()
    return rt.submit("broadcast", name, lambda nm: _from_np(
        rt.engine.broadcast(nm, _to_np(tensor), root_rank,
                            members=_members(process_set)), tensor))


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None) -> int:
    rt = _rt()

    def run(nm):
        out = rt.engine.broadcast(nm, _to_np(tensor), root_rank,
                                  members=_members(process_set))
        tensor.copy_(_from_np(out, tensor))
        return tensor
    return rt.submit("broadcast", name, run)


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(broadcast_async(tensor, root_rank, name, process_set))


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None,
               process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name,
                                        process_set))


# --- alltoall ---------------------------------------------------------------

def alltoall_async(tensor: torch.Tensor,
                   splits: Optional[torch.Tensor] = None,
                   name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None) -> int:
    rt = _rt()
    want_splits = splits is not None

    def run(nm):
        sp = None if splits is None else _to_np(splits)
        out, recv = rt.engine.alltoall(nm, _to_np(tensor), sp,
                                       members=_members(process_set))
        res = _from_np(out, tensor)
        if want_splits:
            return res, torch.from_numpy(recv.astype(np.int64))
        return res
    return rt.submit("alltoall", name, run)


def alltoall(tensor: torch.Tensor, splits: Optional[torch.Tensor] = None,
             name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None):
    """Returns the received tensor, or ``(tensor, received_splits)`` when
    ``splits`` is given (reference mpi_ops.py contract)."""
    return synchronize(alltoall_async(tensor, splits, name, process_set))


# --- reducescatter ----------------------------------------------------------

def reducescatter_async(tensor: torch.Tensor, op: str = Sum,
                        name: Optional[str] = None,
                        process_set: Optional[ProcessSet] = None) -> int:
    rt = _rt()
    return rt.submit("reducescatter", name, lambda nm: _from_np(
        rt.engine.reducescatter(nm, _to_np(tensor), op,
                                members=_members(process_set)), tensor))


def reducescatter(tensor: torch.Tensor, op: str = Sum,
                  name: Optional[str] = None,
                  process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(reducescatter_async(tensor, op, name, process_set))


def grouped_reducescatter_async(tensors, op: str = Sum,
                                name: Optional[str] = None,
                                process_set: Optional[ProcessSet] = None
                                ) -> int:
    """One handle for a list of tensors, each reducescattered (reference:
    grouped ops via group_table.cc)."""
    rt = _rt()
    m = _members(process_set)
    return rt.submit("grouped_reducescatter", name, lambda nm: [
        _from_np(rt.engine.reducescatter(f"{nm}.{i}", _to_np(t), op,
                                         members=m), t)
        for i, t in enumerate(tensors)])


def grouped_reducescatter(tensors, op: str = Sum,
                          name: Optional[str] = None,
                          process_set: Optional[ProcessSet] = None):
    return synchronize(grouped_reducescatter_async(tensors, op, name,
                                                   process_set))


# --- handles ----------------------------------------------------------------

def synchronize(handle: int):
    """Block until the async op behind ``handle`` completes; return its
    output (reference: handle_manager.cc wait + exception rethrow)."""
    rt = _rt()
    with rt.hlock:
        fut = rt.handles.pop(handle, None)
    if fut is None:
        raise ValueError(f"unknown or already-synchronized handle {handle}")
    return fut.result()


def poll(handle: int) -> bool:
    """True if the op behind ``handle`` has completed (sync would not
    block)."""
    rt = _rt()
    with rt.hlock:
        fut = rt.handles.get(handle)
    if fut is None:
        raise ValueError(f"unknown or already-synchronized handle {handle}")
    return fut.done()


# --- join / barrier ---------------------------------------------------------

def join(device: int = -1) -> int:
    """Block until every rank has called join; return the last rank to join
    (reference ``hvd.join``; the device argument is accepted for signature
    parity and ignored — there is no per-GPU buffer to pin)."""
    rt = _rt()
    return rt.executor().submit(rt.engine.join).result()


def barrier(process_set: Optional[ProcessSet] = None) -> None:
    rt = _rt()
    m = _members(process_set)
    rt.executor().submit(
        lambda: rt.engine.barrier(members=m)).result()
