"""TorchEstimator: train a torch model from DataFrame-shaped data.

Reference parity: ``horovod/spark/torch/TorchEstimator`` + ``TorchModel``
(SURVEY.md §2.5) — estimator ``fit(df)`` materialises the data, runs the
training loop with ``hvd.torch.DistributedOptimizer`` active, checkpoints
through a Store, and returns a Transformer holding the trained model.

TPU-native placement: torch tensors live on host CPU in this build (see
``horovod_tpu/torch/__init__.py``); the estimator drives the same pluggable
collective engine the rest of the torch surface uses, so it works
single-process (default), thread-simulated (tests), or across the hosts of
a jax.distributed job. The TPU compute path remains ``JaxEstimator``.
"""

from __future__ import annotations

import io
import os
from typing import Callable, Optional

import numpy as np

from ..checkpoint.store import Store
from ..core.logging import get_logger
from .estimator import _materialize, _transform_df, _validation_split


def _label_tensor(labels):
    """Labels → torch tensor with a loss-friendly dtype: floating numpy
    arrives as float64 (e.g. ``X @ w``) which MSELoss rejects against
    float32 outputs; integer class labels must be int64 for NLL/CE."""
    import torch as _torch

    t = _torch.as_tensor(labels)
    if t.is_floating_point():
        return t.to(_torch.float32)
    if t.dtype in (_torch.int8, _torch.int16, _torch.int32, _torch.uint8):
        return t.to(_torch.int64)
    return t


class TorchModel:
    """The fitted Transformer (reference: ``horovod.spark.torch.TorchModel``).

    Holds the trained ``torch.nn.Module``; ``predict`` on numpy arrays,
    ``transform`` on Spark/pandas DataFrames (appends ``output_col``).
    """

    def __init__(self, model, feature_col: str = "features",
                 output_col: str = "prediction"):
        self.model = model
        self.feature_col = feature_col
        self.output_col = output_col

    def predict(self, features: np.ndarray) -> np.ndarray:
        import torch

        self.model.eval()
        with torch.no_grad():
            out = self.model(torch.as_tensor(np.asarray(features),
                                             dtype=torch.float32))
        return out.numpy()

    def transform(self, df):
        """Spark/pandas DataFrame → same DataFrame + prediction column."""
        return _transform_df(self, df)

    # -- store round trip ---------------------------------------------------

    def save(self, store: Store, run_id: str) -> str:
        import torch

        path = os.path.join(store.checkpoint_path(run_id), "torch_model.pt")
        buf = io.BytesIO()
        torch.save({"state_dict": self.model.state_dict(),
                    "feature_col": self.feature_col,
                    "output_col": self.output_col}, buf)
        store.write(path, buf.getvalue())
        return path

    @classmethod
    def load(cls, store: Store, run_id: str, model) -> "TorchModel":
        import torch

        path = os.path.join(store.checkpoint_path(run_id), "torch_model.pt")
        blob = torch.load(io.BytesIO(store.read(path)),
                          weights_only=False)
        model.load_state_dict(blob["state_dict"])
        return cls(model, feature_col=blob["feature_col"],
                   output_col=blob["output_col"])


class TorchEstimator:
    """Train a ``torch.nn.Module`` with the distributed torch surface active.

    Parameters mirror the reference estimator's essentials: ``model`` (torch
    Module), ``optimizer`` (a ``torch.optim.Optimizer`` bound to the model's
    parameters — the reference takes the same), ``loss`` (``(outputs,
    labels) -> scalar tensor``), ``batch_size`` (GLOBAL batch per step),
    ``epochs``, ``feature_col``/``label_col``, ``store``+``run_id``,
    ``validation`` (held-out fraction), ``backward_passes_per_step``.
    """

    def __init__(self, model=None, optimizer=None,
                 loss: Optional[Callable] = None,
                 feature_col: str = "features", label_col: str = "label",
                 batch_size: int = 32, epochs: int = 1,
                 validation: Optional[float] = None,
                 store: Optional[Store] = None, run_id: str = "run",
                 shuffle: bool = True, seed: int = 0,
                 backward_passes_per_step: int = 1,
                 output_col: str = "prediction"):
        if model is None or optimizer is None or loss is None:
            raise ValueError("model, optimizer and loss are required")
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_col = feature_col
        self.label_col = label_col
        self.batch_size = batch_size
        self.epochs = epochs
        self.validation = validation
        self.store = store
        self.run_id = run_id
        self.shuffle = shuffle
        self.seed = seed
        self.backward_passes_per_step = backward_passes_per_step
        self.output_col = output_col
        self.history: list = []
        self._dopt = None  # hooks register once; refitting reuses them

    def fit(self, data) -> TorchModel:
        import torch

        from .. import torch as hvd

        if not hvd.is_initialized():
            hvd.init()
        n = hvd.size()
        if self.batch_size % n:
            raise ValueError(
                f"batch_size {self.batch_size} must be divisible by the "
                f"world size {n} (global batch shards over ranks)")
        local_batch = self.batch_size // n

        from .data_store import StoreDataset
        if isinstance(data, StoreDataset):
            return self._fit_store(data, local_batch)

        feats, labels = _materialize(data, self.feature_col, self.label_col)
        rng = np.random.RandomState(self.seed)
        feats, labels, val = _validation_split(feats, labels,
                                               self.validation, rng)
        if len(feats) < self.batch_size:
            raise ValueError(
                f"need at least one global batch ({self.batch_size}) of "
                f"rows, got {len(feats)}")

        dopt = self._setup_distributed()

        log = get_logger()
        steps_per_epoch = len(feats) // self.batch_size
        ft = torch.as_tensor(feats, dtype=torch.float32)
        lt = _label_tensor(labels)
        self.model.train()
        for epoch in range(self.epochs):
            # Same shard-by-rank slicing every launcher here uses: each rank
            # takes a strided slice of the shuffled global order.
            order = rng.permutation(len(feats)) if self.shuffle \
                else np.arange(len(feats))
            epoch_loss = 0.0
            for s in range(steps_per_epoch):
                sel = order[s * self.batch_size:(s + 1) * self.batch_size]
                sel = sel[hvd.rank() * local_batch:
                          (hvd.rank() + 1) * local_batch]
                dopt.zero_grad()
                out = self.model(ft[sel])
                loss = self.loss(out, lt[sel])
                loss.backward()
                dopt.step()
                epoch_loss += float(loss.detach())
            entry = {"epoch": epoch,
                     "loss": epoch_loss / max(1, steps_per_epoch)}
            if val is not None:
                entry["val_loss"] = self._eval(val)
            self.history.append(entry)
            log.info("TorchEstimator epoch %d: %s", epoch, entry)

        fitted = TorchModel(self.model, feature_col=self.feature_col,
                            output_col=self.output_col)
        if self.store is not None and hvd.rank() == 0:
            # Rank-0-only save (reference semantics): params are identical
            # on every rank after the averaged updates, and concurrent
            # writes to one Store path would race.
            fitted.save(self.store, self.run_id)
        return fitted

    def _setup_distributed(self):
        """Reference startup sequence: broadcast params + optimizer state
        from rank 0, then hook the optimizer (optimizer.py parity). Wraps
        exactly once: DistributedOptimizer registers grad hooks on the
        model's parameters, and a second fit() must not stack a second set
        (duplicate in-flight names / double reduction)."""
        from .. import torch as hvd

        hvd.broadcast_parameters(self.model.state_dict(), root_rank=0)
        hvd.broadcast_optimizer_state(self.optimizer, root_rank=0)
        if self._dopt is None:
            self._dopt = hvd.DistributedOptimizer(
                self.optimizer,
                named_parameters=self.model.named_parameters(),
                backward_passes_per_step=self.backward_passes_per_step)
        return self._dopt

    def _fit_store(self, ds, local_batch: int) -> TorchModel:
        """Streaming fit: each rank reads ITS shard of the store's part
        files through the native RecordPipeline (reference: per-executor
        Petastorm readers); every rank runs the same step count so the
        gradient collectives stay paired."""
        import itertools

        import torch

        from .. import torch as hvd

        if self.validation:
            raise ValueError(
                "validation split is not supported with a StoreDataset; "
                "materialise a separate validation run_id")
        n = hvd.size()
        steps = ds.min_steps(local_batch, n)
        if steps < 1:
            raise ValueError(
                f"need at least one local batch ({local_batch}) per rank, "
                f"got shard rows "
                f"{[ds.shard_rows(r, n) for r in range(n)]}")

        dopt = self._setup_distributed()

        log = get_logger()
        self.model.train()
        for epoch in range(self.epochs):
            it = ds.batches(local_batch, shuffle=self.shuffle,
                            seed=self.seed + epoch, rank=hvd.rank(),
                            num_replicas=n)
            epoch_loss = 0.0
            try:
                for feats, labels in itertools.islice(it, steps):
                    dopt.zero_grad()
                    out = self.model(torch.as_tensor(feats,
                                                     dtype=torch.float32))
                    loss = self.loss(out, _label_tensor(labels))
                    loss.backward()
                    dopt.step()
                    epoch_loss += float(loss.detach())
            finally:
                it.close()  # release prefetch threads even on a failed step
            entry = {"epoch": epoch, "loss": epoch_loss / max(1, steps)}
            self.history.append(entry)
            log.info("TorchEstimator epoch %d (store-streamed): %s",
                     epoch, entry)

        fitted = TorchModel(self.model, feature_col=self.feature_col,
                            output_col=self.output_col)
        if self.store is not None and hvd.rank() == 0:
            fitted.save(self.store, self.run_id)
        return fitted

    def _eval(self, val) -> float:
        import torch

        feats, labels = val
        self.model.eval()
        with torch.no_grad():
            out = self.model(torch.as_tensor(feats, dtype=torch.float32))
            loss = float(self.loss(out, _label_tensor(labels)))
        self.model.train()
        return loss
