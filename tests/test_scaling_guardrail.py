"""Round-over-round guardrail: benchmarks/scaling.py must emit a sane DP
scaling-efficiency JSON line on the virtual 8-device CPU mesh (VERDICT r1
item 9 — collective regressions must be visible without real multi-chip)."""

import json
import os
import subprocess
import sys
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Hard rails: a reading outside these is a regression no noise explains.
HARD_LO, HARD_HI = 0.65, 1.6
# Nominal band: r2-r5 readings sat ~0.95-1.05 with per-run round spreads
# up to ~0.1 on the shared-core mesh. Inside the rails but outside nominal
# -> WARN (movement attributable to stated noise, tracked via the recorded
# per-arm noise band in scaling_history.jsonl), not a test failure.
NOMINAL_LO, NOMINAL_HI = 0.85, 1.2


def test_scaling_guardrail_emits_sane_efficiency():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    # CI runs must not pollute the committed round-over-round series —
    # the driver's per-round invocation (no env) is the one that records.
    env["HOROVOD_SCALING_NO_HISTORY"] = "1"
    env["HOROVOD_PERF_NO_HISTORY"] = "1"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "scaling.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    recs = {}
    for line in out.stdout.strip().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            recs[rec["metric"]] = rec
    assert "dp8_virtual_scaling_efficiency" in recs
    assert "dp8_hierarchical_scaling_efficiency" in recs
    # Ideal is 1.0 on the shared-core CPU mesh; fail loudly only if the
    # distributed machinery ever costs >35% of compute at this tiny size
    # (r2 measured ~1.01 flat, hierarchical similar). Inside the rails
    # but outside the nominal band -> warn: single-run movement there is
    # within the stated noise (see the recorded per-arm "noise" field).
    for name, rec in recs.items():
        if not name.endswith("_scaling_efficiency"):
            continue
        assert HARD_LO <= rec["value"] <= HARD_HI, rec
        noise = rec.get("noise") or {}
        assert noise.get("rounds", 0) >= 3, \
            f"noise band must state its repeats: {rec}"
        for k in ("ratio_min", "ratio_max", "spread"):
            assert k in noise, f"noise band incomplete: {rec}"
        if not (NOMINAL_LO <= rec["value"] <= NOMINAL_HI):
            warnings.warn(
                f"{rec['metric']}={rec['value']} outside nominal "
                f"[{NOMINAL_LO}, {NOMINAL_HI}] but inside hard rails "
                f"[{HARD_LO}, {HARD_HI}]; round spread "
                f"{noise.get('spread')} over {noise.get('rounds')} rounds "
                "— investigate if it persists round-over-round "
                "(benchmarks/scaling_history.jsonl)")
    # The accum arm (ISSUE 12) must be present. It is deliberately NOT an
    # *_scaling_efficiency metric — walking the batch as 4 sequential
    # microbatches has no ideal-1.0 contract — so it gets a presence pin
    # plus a loose sanity band only: the accumulated step must stay within
    # the same order of magnitude as the plain dp8 step.
    accum = recs.get("dp8_accum4_step_ratio")
    assert accum is not None, sorted(recs)
    assert 0.2 <= accum["value"] <= 2.5, accum
    assert (accum.get("noise") or {}).get("rounds", 0) >= 3, accum
    # The overlap record (PR 6, docs/fusion.md) rides the same run: a
    # fraction in [0, 1], or None when the trace held no collective op
    # events — either way it must be present in the series.
    assert "dp8_overlap_fraction" in recs
    frac = recs["dp8_overlap_fraction"]["value"]
    assert frac is None or 0.0 <= frac <= 1.0, frac
    assert "overlap" in recs["dp8_overlap_fraction"]
    # The step-time budget record (ISSUE 11, docs/profiling.md) rides the
    # overlap trace: categories must sum to the host-lane wall.
    from horovod_tpu.tools import perf
    budget = recs.get("dp8_step_budget")
    assert budget is not None and budget["kind"] == "perf_budget"
    assert budget["sum_check"]["rel_err"] <= perf.SUM_TOLERANCE, budget
    for key in perf.BUDGET_KEYS:
        assert key in budget["budget_s_per_step"], key
