"""End-to-end serving plane: a REAL np=3 elastic world trains and
publishes generations through the CAS while a separate serving process
(this test) hot-swaps and answers HTTP requests throughout.

Acceptance (ISSUE 10): ≥2 generations published and hot-swapped with
ZERO dropped/failed requests, and after every swap the served weights'
``leaves_digest`` equals the published pin's — the serving pointer is
provably the announced generation, not a torn mix. The slow chaos
variant grows the world np=2→3 mid-publish and injects one blob
corruption between publish and adoption: the corrupt generation is
rejected (``hvd_serving_rejected_total``), the server keeps answering on
the previous weights, and a later clean publish is adopted.

Store-watch discovery is used deliberately: the launcher generates its
own HMAC secret per job, so an external serving process authenticates
by reading publish pins from the shared commit dir (docs/serving.md);
the coordinator announce path is covered in-process by
tests/test_serving.py.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from horovod_tpu.checkpoint.store import BlobStore
from horovod_tpu.serving import InferenceServer, ModelRegistry
from horovod_tpu.serving.publisher import leaves_digest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

E2E_WORKER = """
import json
import os
import time
from horovod_tpu.platform import honor_jax_platforms_env
honor_jax_platforms_env()
import numpy as np
import horovod_tpu as hvd
from horovod_tpu import elastic, serving
from horovod_tpu.elastic import constants as C

hvd.init()
commit_dir = os.environ[C.COMMIT_DIR_ENV]
pub = None
if hvd.rank() == 0:
    pub = serving.attach(commit_dir, every=1)
    tmp = os.environ["COMMIT_DIR_OUT"] + ".tmp"
    with open(tmp, "w") as f:
        f.write(commit_dir)
    os.replace(tmp, os.environ["COMMIT_DIR_OUT"])

state = elastic.ObjectState(step=0, w=np.zeros(16, np.float32))

@elastic.run
def train(state):
    while state.step < int(os.environ.get("E2E_STEPS", "6")):
        state.step += 1
        state.w = state.w + 1.0
        gm = os.environ.get("GROW_MARKER")
        if (gm and hvd.rank() == 0 and state.step == 2
                and not os.path.exists(gm)):
            with open(gm, "w") as f:
                f.write("grown")
            with open(os.environ["GROW_HOSTS_FILE"], "w") as f:
                f.write("localhost:1\\n127.0.0.2:1\\n127.0.0.3:1\\n")
        time.sleep(0.25)
        state.commit()
    return state.step

train(state)
state.flush_commits(timeout=60)
# Hold the generation (and with it the shared commit dir, which the
# driver deletes on exit) until the serving side finished verifying.
deadline = time.time() + 120
while (not os.path.exists(os.environ["DONE_MARKER"])
       and time.time() < deadline):
    time.sleep(0.1)
print(json.dumps({"trained": True, "size": hvd.size(),
                  "rank": hvd.rank()}), flush=True)
"""


def _spawn_world(tmp_path, hosts_lines, extra_args, env_extra):
    disco = tmp_path / "discover.sh"
    hosts_file = tmp_path / "hosts"
    hosts_file.write_text(hosts_lines)
    disco.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
    disco.chmod(0o755)
    script = tmp_path / "e2e_worker.py"
    script.write_text(E2E_WORKER)
    env = dict(os.environ, **env_extra)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("HOROVOD_FAULT_SPEC", None)
    env["GROW_HOSTS_FILE"] = str(hosts_file)
    return subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         *extra_args, "--host-discovery-script", str(disco),
         sys.executable, str(script)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)


def _wait_commit_dir(out_file, proc, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(out_file):
            with open(out_file) as f:
                return f.read().strip()
        if proc.poll() is not None:
            out, err = proc.communicate(timeout=30)
            raise AssertionError(
                f"launcher died before first publish: {out[-2000:]}\n"
                f"{err[-2000:]}")
        time.sleep(0.05)
    raise AssertionError("no commit dir announced within budget")


def _predict(addr, x):
    body = json.dumps({"x": float(x)}).encode()
    req = urllib.request.Request(
        f"http://{addr}/predict", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _assert_served_digest_matches_pin(reg, store):
    cur = reg.current()
    pin = store.read_pin(cur.manifest_seq)
    assert pin is not None and pin.get("published"), pin
    assert pin["leaves_digest"] == cur.leaves_digest
    # and against the manifest itself, not just the announcement
    assert leaves_digest(
        store.read_manifest(cur.manifest_seq)) == cur.leaves_digest


def _finish(proc, done_marker, timeout=120):
    with open(done_marker, "w") as f:
        f.write("done")
    out, err = proc.communicate(timeout=timeout)
    return proc.returncode, out, err


@pytest.mark.integration
def test_e2e_elastic_world_serves_across_hot_swaps(tmp_path):
    """np=3 world publishes ≥2 generations while this process serves
    HTTP requests through every hot-swap: zero dropped, digest-equal."""
    out_file = str(tmp_path / "commit_dir.txt")
    done = str(tmp_path / "done")
    proc = _spawn_world(
        tmp_path, "localhost:1\n127.0.0.2:1\n127.0.0.3:1\n",
        ["-np", "3", "--min-np", "3", "--max-np", "3"],
        {"COMMIT_DIR_OUT": out_file, "DONE_MARKER": done,
         "E2E_STEPS": "6"})
    srv = None
    try:
        commit_dir = _wait_commit_dir(out_file, proc)
        store = BlobStore(os.path.join(commit_dir, "cas"))
        reg = ModelRegistry(store=store)

        def forward(payload, inputs, padded_n):
            w = payload["attrs"]["w"]
            return [float(w[0]) + float(q["x"]) for q in inputs]

        srv = InferenceServer(reg, forward, window_s=0.002,
                              request_timeout_s=30.0)
        sent = ok = 0
        swap_seqs = []
        seq_to_w = {}
        deadline = time.time() + 180
        while time.time() < deadline:
            if reg.poll_store(store):
                cur = reg.current()
                swap_seqs.append(cur.manifest_seq)
                seq_to_w[cur.manifest_seq] = float(
                    cur.payload["attrs"]["w"][0])
                _assert_served_digest_matches_pin(reg, store)
            if reg.current() is not None:
                out = _predict(srv.addr(), sent)
                sent += 1
                ok += bool(out.get("ok"))
                # served answer reflects the served generation's weights
                assert out["result"] == pytest.approx(
                    seq_to_w[out["model_seq"]] + (sent - 1))
            if len(swap_seqs) >= 2 and sent >= 20 \
                    and reg.current().manifest_seq >= 6:
                break
            time.sleep(0.02)
        rc, pout, perr = _finish(proc, done)
        assert rc == 0, f"{pout[-3000:]}\n{perr[-3000:]}"
        assert len(swap_seqs) >= 2, swap_seqs     # >=2 hot-swaps happened
        assert sent >= 20 and ok == sent          # zero dropped/failed
        assert reg.stats["rejected"] == 0
        # all three final-generation workers reached the end
        lines = [json.loads(l) for l in pout.splitlines()
                 if l.startswith("{")]
        assert len(lines) == 3 and all(l["size"] == 3 for l in lines)
    finally:
        if srv is not None:
            srv.close()
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)


@pytest.mark.slow
@pytest.mark.integration
def test_e2e_chaos_corrupt_publish_during_elastic_grow(tmp_path):
    """Publishes keep flowing through an np=2→3 grow; one injected blob
    corruption between publish and adoption is rejected (fallback to the
    previous weights, requests keep succeeding), and a later clean
    publish is adopted with digest equality."""
    out_file = str(tmp_path / "commit_dir.txt")
    done = str(tmp_path / "done")
    proc = _spawn_world(
        tmp_path, "localhost:1\n127.0.0.2:1\n",
        ["-np", "2", "--min-np", "2", "--max-np", "3"],
        {"COMMIT_DIR_OUT": out_file, "DONE_MARKER": done,
         "E2E_STEPS": "8", "GROW_MARKER": str(tmp_path / "grown")})
    srv = None
    try:
        commit_dir = _wait_commit_dir(out_file, proc)
        store = BlobStore(os.path.join(commit_dir, "cas"))
        reg = ModelRegistry(store=store)

        def forward(payload, inputs, padded_n):
            return [float(payload["attrs"]["w"][0]) for _ in inputs]

        srv = InferenceServer(reg, forward, window_s=0.002,
                              request_timeout_s=30.0)
        sent = ok = 0
        corrupted_seq = None
        swaps = 0
        deadline = time.time() + 240
        while time.time() < deadline:
            pins = [s for s in store.pinned_seqs()
                    if (store.read_pin(s) or {}).get("published")]
            newest = max(pins) if pins else None
            cur = reg.current()
            if (newest is not None and corrupted_seq is None
                    and cur is not None and newest > cur.manifest_seq):
                # Inject: flip bytes in a CHANGED blob of the about-to-be
                # adopted generation, adopt (must reject), then restore.
                rec = store.read_pin(newest)
                manifest = store.read_manifest(newest)
                prev = store.read_manifest(cur.manifest_seq)
                if manifest is not None and prev is not None:
                    changed = ({e[0] for e in manifest["leaves"]}
                               - {e[0] for e in prev["leaves"]})
                    if changed:
                        victim = store.blob_path(sorted(changed)[0])
                        with open(victim, "rb") as f:
                            orig = f.read()
                        with open(victim, "wb") as f:
                            f.write(b"\x00" * len(orig))
                        assert reg.adopt(rec) is False
                        assert reg.current().manifest_seq \
                            == cur.manifest_seq       # fallback held
                        with open(victim, "wb") as f:
                            f.write(orig)
                        corrupted_seq = newest
            if reg.poll_store(store):
                swaps += 1
                _assert_served_digest_matches_pin(reg, store)
            if reg.current() is not None:
                out = _predict(srv.addr(), sent)
                sent += 1
                ok += bool(out.get("ok"))
            if (corrupted_seq is not None and swaps >= 2 and sent >= 20
                    and reg.current().manifest_seq >= corrupted_seq):
                break
            time.sleep(0.02)
        rc, pout, perr = _finish(proc, done)
        assert rc == 0, f"{pout[-3000:]}\n{perr[-3000:]}"
        assert corrupted_seq is not None, "chaos injection never fired"
        assert reg.stats["rejected"] >= 1         # the corrupt generation
        assert swaps >= 2
        assert sent >= 20 and ok == sent          # zero dropped/failed
        # the rejected generation (or a newer one) was later adopted
        # clean (digest equality was asserted at each swap above — the
        # commit dir is gone once the launcher exits)
        assert reg.current().manifest_seq >= corrupted_seq
        # the grow happened: the FINAL generation ran at np=3
        lines = [json.loads(l) for l in pout.splitlines()
                 if l.startswith("{")]
        assert len(lines) == 3 and all(l["size"] == 3 for l in lines)
    finally:
        if srv is not None:
            srv.close()
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
