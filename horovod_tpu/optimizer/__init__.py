from .distributed import (DistributedOptimizer, DistributedState,
                          distributed)
from .functions import (allgather_object, broadcast_object,
                        broadcast_optimizer_state, broadcast_parameters,
                        join, join_allreduce)
from .sync_batch_norm import SyncBatchNorm

__all__ = [
    "DistributedOptimizer", "DistributedState", "distributed",
    "allgather_object", "broadcast_object", "broadcast_optimizer_state", "broadcast_parameters",
    "join", "join_allreduce", "SyncBatchNorm",
]
