"""BASELINE config 3: Llama fine-tune throughput with Adasum allreduce.

The reference recipe: grad allreduce + Adasum over rings. Here the model
trains through the DP shard_map path with ``op=Adasum`` on the gradient
combine — the ICI XOR-butterfly of collectives/adasum.py with the Pallas
fused combine on TPU. Metric: tokens/sec/chip; also reports plain-Average
throughput so the Adasum butterfly's cost is visible.

Sizing: one chip can't hold 8B params + Adam state, so the TPU config is a
mid-sized decoder (~350M) with the 8B architecture's shape ratios; CPU
meshes use llama_tiny. The parallelism mechanics are identical at any size.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from common import (emit, lm_train_flops_per_token, mfu_fields,
                    on_tpu, params_count, slope_time, sync)


def main():
    import horovod_tpu as hvd
    from horovod_tpu.models.llama import Llama, LlamaConfig, llama_tiny
    from horovod_tpu.optimizer import distributed
    from horovod_tpu.train import (create_train_state, make_train_step,
                                   next_token_loss)

    hvd.init()
    n = hvd.size()
    tpu = on_tpu()
    if tpu:
        # remat_policy="attn" + per-chip batch 8: "full" remat buys batch
        # 8 (26.9k tok/s vs 25.7k at batch 4 under "dots" — HBM-bound),
        # and saving ONLY the flash-kernel residuals on top skips the
        # fwd-kernel re-run in the backward for ~400MB: 28.9k vs 28.1k
        # (+2.6% interleaved; +5.2% at batch 12, but batch 12 is slower
        # for both). See benchmarks/llama_remat_ab.py.
        # scan_layers=False (r5): the Llama profile's 14.1% gather/scatter
        # slice was attributed to the scan's loop-carried gradient stacks
        # (dynamic-update-slice of each layer's dW into [24,...] f32
        # accumulators, ~0.5 ms per write at an effective ~33 GB/s).
        # Unrolling the layer loop removes them: 29.3k -> 33.0k tok/s
        # (+12.8%, alternated single-arm runs — the two arms' states
        # can't fit on-chip together). Cost: compile ~120 s vs ~35 s;
        # the model default stays scan_layers=True for iteration speed.
        cfg = LlamaConfig(vocab_size=32000, dim=1024, n_layers=24,
                          n_heads=16, n_kv_heads=8, hidden_dim=4096,
                          max_seq_len=2048, remat_policy="attn",
                          scan_layers=False)
        per_chip, seq = 8, 1024
    else:
        cfg = llama_tiny()
        per_chip, seq = 2, 32
    batch = per_chip * n

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    model = Llama(cfg)

    def loss_fn(logits, y):
        return next_token_loss(logits, y)

    for op_name, op in (("adasum", hvd.Adasum), ("average", hvd.Average)):
        dopt = distributed(optax.adamw(1e-4), op=op)
        state = create_train_state(model, jax.random.PRNGKey(0),
                                   tokens[:1], dopt)
        n_params = params_count(state.params)
        # donate + thread the state (r5): the unrolled 24L program's live
        # set no longer fits alongside an undonated persistent state
        box = {"s": state}
        steps = {k: make_train_step(model, dopt, loss_fn, scan_steps=k,
                                    donate=True) for k in (2, 8)}

        def run(k):
            st, loss = steps[k](box["s"], tokens, tokens)
            box["s"] = st
            sync(loss)

        tps = batch * seq / slope_time(run, 2, 8)
        del box, state
        flops_tok = lm_train_flops_per_token(
            n_params, cfg.n_layers, cfg.dim, seq)
        emit(f"llama_tokens_per_sec_per_chip_{op_name}", tps / n,
             f"tokens/sec/chip (dim {cfg.dim} x {cfg.n_layers}L, seq "
             f"{seq}, op={op_name}, {n} devices)",
             **mfu_fields(tps / n, flops_tok))


if __name__ == "__main__":
    main()
