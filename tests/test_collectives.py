"""Collective op tests — the TPU analog of the reference's
test/parallel/test_torch.py op matrix (every op × dtype × shape, grouped
ops, process sets, prescale/postscale, compression)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.collectives import eager

N = 8


def stacked(shape=(4, 3), dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(N, *shape).astype(dtype)


# ---------------- allreduce ----------------

@pytest.mark.parametrize("dtype", [np.float32, np.float16, np.int32])
def test_allreduce_sum(dtype):
    x = (stacked(dtype=np.float32) * 4).astype(dtype)
    out = eager.allreduce(jnp.asarray(x), op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=2e-3, atol=2e-2)


def test_allreduce_average():
    x = stacked()
    out = eager.allreduce(jnp.asarray(x), op=hvd.Average)
    np.testing.assert_allclose(np.asarray(out), x.mean(0), rtol=1e-5)


@pytest.mark.parametrize("op,ref", [(hvd.Min, np.min), (hvd.Max, np.max),
                                    (hvd.Product, np.prod)])
def test_allreduce_minmaxprod(op, ref):
    x = stacked()
    out = eager.allreduce(jnp.asarray(x), op=op)
    np.testing.assert_allclose(np.asarray(out), ref(x, axis=0), rtol=1e-4)


def test_allreduce_prescale_postscale():
    x = stacked()
    out = eager.allreduce(jnp.asarray(x), op=hvd.Sum,
                          prescale_factor=0.5, postscale_factor=2.0)
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-5)


def test_allreduce_pytree():
    x = {"a": jnp.asarray(stacked()), "b": jnp.asarray(stacked((2,), seed=1))}
    out = eager.allreduce(x, op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out["a"]),
                               np.asarray(x["a"]).sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["b"]),
                               np.asarray(x["b"]).sum(0), rtol=1e-5)


def test_allreduce_compression_fp16():
    x = stacked()
    out = eager.allreduce(jnp.asarray(x), op=hvd.Sum,
                          compression=hvd.Compression.fp16)
    assert np.asarray(out).dtype == np.float32  # decompressed back
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-2, atol=1e-1)


def test_allreduce_compression_bf16():
    x = stacked()
    out = eager.allreduce(jnp.asarray(x), op=hvd.Sum,
                          compression=hvd.Compression.bf16)
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=5e-2, atol=2e-1)


def test_allreduce_process_set():
    """Members reduce over the set; non-members keep their own value —
    the SPMD rendering of 'non-members don't call the op'."""
    ps = hvd.add_process_set([0, 2, 4, 6])
    x = stacked()
    out = np.asarray(eager.allreduce(jnp.asarray(x), op=hvd.Sum,
                                     process_set=ps))
    expected_members = x[[0, 2, 4, 6]].sum(0)
    for r in range(N):
        if r in (0, 2, 4, 6):
            np.testing.assert_allclose(out[r], expected_members, rtol=1e-5)
        else:
            np.testing.assert_allclose(out[r], x[r], rtol=1e-6)


# ---------------- grouped ----------------

def test_grouped_allreduce_matches_individual():
    xs = [jnp.asarray(stacked(seed=i)) for i in range(3)]
    grouped = eager.grouped_allreduce(xs, op=hvd.Sum)
    for x, g in zip(xs, grouped):
        np.testing.assert_allclose(np.asarray(g), np.asarray(x).sum(0),
                                   rtol=1e-5)


def test_grouped_allreduce_mixed_dtypes():
    xs = {"f32": jnp.asarray(stacked()),
          "f16": jnp.asarray(stacked(seed=2).astype(np.float16))}
    out = eager.grouped_allreduce(xs, op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out["f32"]),
                               np.asarray(xs["f32"]).sum(0), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out["f16"]).astype(np.float32),
        np.asarray(xs["f16"]).astype(np.float32).sum(0), rtol=2e-2, atol=1e-1)


# ---------------- allgather ----------------

def test_allgather():
    x = stacked((2, 3))  # 2 rows per rank after reshape
    flat = x.reshape(N * 2, 3)
    out = eager.allgather(jnp.asarray(flat))
    np.testing.assert_array_equal(np.asarray(out), flat)


def test_allgather_process_set_even_odd():
    ps = hvd.add_process_set([0, 2, 4, 6])
    x = np.arange(N, dtype=np.float32).reshape(N, 1)
    out = np.asarray(eager.allreduce(jnp.asarray(x), op=hvd.Max,
                                     process_set=ps))
    assert out[0, 0] == 6.0 and out[1, 0] == 1.0


# ---------------- broadcast ----------------

@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(root):
    x = stacked()
    out = eager.broadcast(jnp.asarray(x), root_rank=root)
    np.testing.assert_allclose(np.asarray(out), x[root], rtol=1e-6)


def test_broadcast_int():
    x = np.arange(N * 4, dtype=np.int32).reshape(N, 4)
    out = eager.broadcast(jnp.asarray(x), root_rank=5)
    np.testing.assert_array_equal(np.asarray(out), x[5])


def test_broadcast_process_set():
    ps = hvd.add_process_set([1, 3, 5, 7])
    x = stacked()
    out = np.asarray(eager.broadcast(jnp.asarray(x), root_rank=3,
                                     process_set=ps))
    for r in range(N):
        expect = x[3] if r in (1, 3, 5, 7) else x[r]
        np.testing.assert_allclose(out[r], expect, rtol=1e-5)


def test_broadcast_root_not_in_set():
    ps = hvd.add_process_set([1, 3])
    with pytest.raises(ValueError):
        eager.broadcast(jnp.asarray(stacked()), root_rank=0, process_set=ps)


# ---------------- alltoall ----------------

def test_alltoall():
    # rank r sends value r*N+i to rank i → rank i receives [i, N+i, 2N+i...]
    x = np.arange(N * N, dtype=np.float32).reshape(N, N, 1)
    out = np.asarray(eager.alltoall(jnp.asarray(x)))
    for i in range(N):
        np.testing.assert_array_equal(out[i, :, 0],
                                      np.arange(N) * N + i)


def test_alltoall_multi_row():
    # 2 rows per destination
    x = np.arange(N * N * 2, dtype=np.float32).reshape(N, N * 2, 1)
    out = np.asarray(eager.alltoall(jnp.asarray(x)))
    assert out.shape == (N, N * 2, 1)
    # rank 0 receives rows 0:2 of every rank
    expected = np.concatenate([x[r, 0:2] for r in range(N)])
    np.testing.assert_array_equal(out[0], expected)


# ---------------- reducescatter ----------------

def test_reducescatter_sum():
    x = stacked((N * 2, 3))
    out = np.asarray(eager.reducescatter(jnp.asarray(x), op=hvd.Sum))
    total = x.sum(0)  # [N*2, 3]
    for r in range(N):
        np.testing.assert_allclose(out[r], total[r * 2:(r + 1) * 2],
                                   rtol=1e-5)


def test_reducescatter_average():
    x = stacked((N, 3))
    out = np.asarray(eager.reducescatter(jnp.asarray(x), op=hvd.Average))
    total = x.mean(0)
    for r in range(N):
        np.testing.assert_allclose(out[r], total[r:r + 1], rtol=1e-5)


# ---------------- barrier / in-graph use ----------------

def test_ops_inside_user_shard_map():
    """In-graph ops compose with user shard_map + jit — the core product."""
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    def step(x):
        g = hvd.allreduce(x * 2.0, op=hvd.Average)
        hvd.barrier()
        return g

    f = jax.jit(shard_map(step, mesh=hvd.mesh(),
                          in_specs=P(hvd.RANK_AXIS), out_specs=P()))
    x = stacked((1,)).reshape(N)
    out = f(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), (x * 2).mean(), rtol=1e-5)


# ---------------- review-finding regressions ----------------

def test_allgather_process_set_groups():
    """Process-set allgather returns per-rank group gathers (stacked), not
    just the first group's result."""
    ps = hvd.add_process_set([0, 2, 4, 6])
    x = np.arange(N, dtype=np.float32).reshape(N, 1)
    out = np.asarray(eager.allgather(jnp.asarray(x), process_set=ps))
    assert out.shape == (N, 4, 1)
    np.testing.assert_array_equal(out[0, :, 0], [0, 2, 4, 6])
    np.testing.assert_array_equal(out[1, :, 0], [1, 3, 5, 7])
    np.testing.assert_array_equal(out[2, :, 0], [0, 2, 4, 6])


def test_allgather_ragged_process_set():
    """5-of-8 set: the complement (3 ranks) can't form equal groups, so the
    op falls back to full-gather + member-row selection; every device
    (members and non-members) receives the members' concatenation. The
    reference has no equal-partition constraint — neither do we now."""
    ps = hvd.add_process_set([0, 1, 2, 3, 4])
    x = np.arange(N, dtype=np.float32).reshape(N, 1)
    out = np.asarray(eager.allgather(jnp.asarray(x), process_set=ps))
    assert out.shape == (N, 5, 1)
    for r in range(N):
        np.testing.assert_array_equal(out[r, :, 0], [0, 1, 2, 3, 4])


def test_alltoall_ragged_process_set():
    """5-of-8 alltoall: member i receives chunk i from every member, in
    member order; non-members keep their input."""
    ps = hvd.add_process_set([0, 1, 2, 3, 4])
    k = 5
    x = np.zeros((N, k), np.float32)
    for r in range(N):
        x[r] = r * 10 + np.arange(k)
    out = np.asarray(eager.alltoall(jnp.asarray(x), process_set=ps))
    for i, r in enumerate([0, 1, 2, 3, 4]):
        np.testing.assert_array_equal(out[r], [m * 10 + i for m in range(k)])
    for r in (5, 6, 7):
        np.testing.assert_array_equal(out[r], x[r])


def test_reducescatter_ragged_process_set():
    """5-of-8 reducescatter: member i gets chunk i of the member-sum."""
    ps = hvd.add_process_set([0, 1, 2, 3, 4])
    k = 5
    x = np.arange(N * k, dtype=np.float32).reshape(N, k)
    out = np.asarray(eager.reducescatter(jnp.asarray(x), op=hvd.Sum,
                                         process_set=ps))
    assert out.shape == (N, 1)
    expect = x[:k].sum(0)   # member-sum per chunk
    for i in range(k):
        np.testing.assert_allclose(out[i, 0], expect[i])
    for r in (5, 6, 7):     # non-members: chunk 0 of the member-sum
        np.testing.assert_allclose(out[r, 0], expect[0])


def test_adasum_prescale_applied():
    x = np.random.RandomState(7).randn(N, 6).astype(np.float32)
    base = np.asarray(eager.allreduce(jnp.asarray(x), op=hvd.Adasum))
    scaled = np.asarray(eager.allreduce(jnp.asarray(x), op=hvd.Adasum,
                                        prescale_factor=100.0))
    assert not np.allclose(base, scaled)
    # Adasum is scale-invariant in direction: prescale by c scales result by c
    np.testing.assert_allclose(scaled, base * 100.0, rtol=1e-3)


def test_timeline_written(tmp_path):
    import json
    hvd.shutdown()
    import horovod_tpu.core.config as _cfgmod
    path = str(tmp_path / "tl.json")
    cfg = hvd.Config.from_env()
    cfg.timeline_path = path
    hvd.init(config=cfg)
    tl = hvd.core.context().timeline
    assert tl is not None
    with tl.span("tensor_x", "ALLREDUCE"):
        pass
    tl.marker("STEP")
    hvd.shutdown()
    events = json.load(open(path))
    names = [e["ph"] for e in events]
    assert "B" in names and "E" in names and "i" in names
    hvd.init()


def test_allreduce_process_set_average_nonmembers_unchanged():
    """Average over a process set must not scale non-members' passthrough."""
    ps = hvd.add_process_set([0, 1])
    x = np.arange(1, N + 1, dtype=np.float32).reshape(N, 1)
    out = np.asarray(eager.allreduce(jnp.asarray(x), op=hvd.Average,
                                     process_set=ps))
    np.testing.assert_allclose(out[0, 0], 1.5)
    np.testing.assert_allclose(out[1, 0], 1.5)
    for r in range(2, N):
        np.testing.assert_allclose(out[r, 0], x[r, 0])


def test_allreduce_process_set_prescale_nonmembers_unchanged():
    ps = hvd.add_process_set([0, 1])
    x = np.arange(1, N + 1, dtype=np.float32).reshape(N, 1)
    out = np.asarray(eager.allreduce(jnp.asarray(x), op=hvd.Sum,
                                     process_set=ps, prescale_factor=10.0))
    np.testing.assert_allclose(out[0, 0], 30.0)
    for r in range(2, N):
        np.testing.assert_allclose(out[r, 0], x[r, 0])


def test_grouped_allreduce_process_set_average():
    ps = hvd.add_process_set([0, 1])
    xs = [jnp.asarray(np.arange(1, N + 1, dtype=np.float32).reshape(N, 1))]
    out = np.asarray(eager.grouped_allreduce(xs, op=hvd.Average,
                                             process_set=ps)[0])
    np.testing.assert_allclose(out[0, 0], 1.5)
    np.testing.assert_allclose(out[5, 0], 6.0)


def test_adasum_process_set_prescale_nonmembers_unchanged():
    ps = hvd.add_process_set([0, 1])
    x = np.random.RandomState(11).randn(N, 4).astype(np.float32)
    out = np.asarray(eager.allreduce(jnp.asarray(x), op=hvd.Adasum,
                                     process_set=ps, prescale_factor=50.0))
    for r in range(2, N):
        np.testing.assert_allclose(out[r], x[r], rtol=1e-5)


def test_eager_jit_cache_reused():
    from horovod_tpu.collectives.eager import _jit_cache
    _jit_cache.clear()
    x = jnp.asarray(stacked())
    eager.allreduce(x, op=hvd.Sum)
    n_entries = len(_jit_cache)
    eager.allreduce(x, op=hvd.Sum)
    eager.allreduce(jnp.asarray(stacked(seed=3)), op=hvd.Sum)
    assert len(_jit_cache) == n_entries  # same key reused


def test_getattr_missing_submodule_is_attribute_error():
    """Lazy __getattr__ must translate ModuleNotFoundError into
    AttributeError so hasattr()/dir() tooling works."""
    import pytest as _pytest
    with _pytest.raises(AttributeError):
        hvd.__getattr__("utils")  # lazy-listed but not built yet
    with _pytest.raises(AttributeError):
        hvd.__getattr__("definitely_not_a_module")
    assert hasattr(hvd, "models") and hasattr(hvd, "optimizer")


# ---------------- 1-member-axis fast path / validation ----------------

def _one_mesh():
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:1]), ("one",))


def test_one_member_axis_elides_collectives():
    """On a size-1 axis every global-set op is identity and the compiled HLO
    contains NO collectives (XLA does not elide single-participant ones)."""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    def body(x):
        a = hvd.allreduce(x, op=hvd.Sum, axis_name="one", prescale_factor=2.0,
                          process_set=hvd.global_process_set())
        b = hvd.allgather(a, axis_name="one")
        c = hvd.broadcast(b, 0, axis_name="one")
        d = hvd.alltoall(c, axis_name="one")
        e = hvd.reducescatter(d, op=hvd.Sum, axis_name="one")
        (g,) = hvd.grouped_allreduce([e], op=hvd.Sum, axis_name="one")
        return g

    f = jax.jit(shard_map(body, mesh=_one_mesh(), in_specs=P(), out_specs=P()))
    hlo = f.lower(jnp.ones((4, 3))).compile().as_text()
    for bad in ("all-reduce", "all-gather", "all-to-all", "reduce-scatter",
                "collective-permute"):
        assert bad not in hlo
    np.testing.assert_allclose(np.asarray(f(jnp.ones((4, 3)))),
                               2.0 * np.ones((4, 3)))


def test_broadcast_root_out_of_range_raises():
    with pytest.raises(ValueError, match="out of range"):
        eager.broadcast(jnp.asarray(stacked()), root_rank=N)


def test_allreduce_invalid_op_raises_even_on_one_device():
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    with pytest.raises(ValueError, match="unsupported reduce op"):
        jax.jit(shard_map(lambda x: hvd.allreduce(x, op="mean",
                                                  axis_name="one"),
                          mesh=_one_mesh(), in_specs=P(),
                          out_specs=P()))(jnp.ones(3))


def test_one_member_average_promotes_int_like_multi_device():
    """Average must promote int dtypes the same on a 1-member axis as the
    psum/divide path does on N members."""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    multi = eager.allreduce(jnp.ones((N, 3), jnp.int32), op=hvd.Average)
    one = jax.jit(shard_map(lambda x: hvd.allreduce(x, op=hvd.Average,
                                                    axis_name="one"),
                            mesh=_one_mesh(), in_specs=P(),
                            out_specs=P()))(jnp.ones((3,), jnp.int32))
    assert multi.dtype == one.dtype == jnp.float32


def test_merge_chrome_traces_labels_and_stackframes(tmp_path):
    import json
    from horovod_tpu.tools import merge_chrome_traces
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps([
        {"name": "x", "ph": "X", "ts": 0, "dur": 1, "pid": 5, "tid": 0},
        {"name": "x", "ph": "X", "ts": 0, "dur": 1, "pid": 100005, "tid": 0},
    ]))
    b.write_text(json.dumps({
        "displayTimeUnit": "ns",
        "stackFrames": {"3": {"name": "f", "parent": "1"},
                        "1": {"name": "root"}},
        "traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "dev"}},
            {"name": "y", "ph": "X", "ts": 2, "dur": 1, "pid": 0, "tid": 0,
             "sf": "3"},
        ]}))
    out = tmp_path / "merged.json"
    merge_chrome_traces([a, b], out, labels=["host", "tpu"])
    m = json.loads(out.read_text())
    evs = m["traceEvents"]
    # distinct source pids stay distinct (no modulo collision)
    xs = [e["pid"] for e in evs if e.get("name") == "x"]
    assert len(set(xs)) == 2
    names = {e["args"]["name"] for e in evs
             if e.get("name") == "process_name"}
    assert "tpu/dev" in names and any(n.startswith("host") for n in names)
    # stackFrames carried over, ids+parents renamed consistently, sf rewritten
    (y,) = [e for e in evs if e.get("name") == "y"]
    assert y["sf"] == "t1:3"
    assert m["stackFrames"]["t1:3"]["parent"] == "t1:1"
    assert m["displayTimeUnit"] == "ns"
    # non-trace dict input is rejected
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"foo": 1}))
    with pytest.raises(ValueError, match="traceEvents"):
        merge_chrome_traces([bad], tmp_path / "out2.json")


def test_allgather_padded_ragged_set_wire_cost():
    """VERDICT r2 #8: a RAGGED set with a usable world-divisor (3-of-8:
    complement 5 can't form groups of 3, but padding one complement rank
    gives groups of 4) gathers group-size rows — half the world-size
    wire bytes — and members still get exactly the members' rows."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map
    from horovod_tpu.collectives import ops

    ps = hvd.add_process_set([1, 4, 6])
    x = np.arange(N * 2, dtype=np.float32).reshape(N * 2, 1)

    f = shard_map(lambda t: ops.allgather(t, process_set=ps),
                  mesh=hvd.mesh(), in_specs=P(hvd.RANK_AXIS),
                  out_specs=P(hvd.RANK_AXIS), check_vma=False)
    out = np.asarray(jax.jit(f)(jnp.asarray(x))).reshape(N, 6, 1)
    for r in (1, 4, 6):  # members see [rows of 1, rows of 4, rows of 6]
        np.testing.assert_array_equal(out[r].ravel(), [2, 3, 8, 9, 12, 13])

    txt = jax.jit(f).lower(jnp.asarray(x)).as_text()
    gathers = [l for l in txt.splitlines() if "all_gather" in l]
    assert gathers, txt[:500]
    # per-device 2 rows -> padded group of 4 gathers 8 rows; a full-axis
    # gather would produce 16.
    assert any("tensor<8x1xf32>" in l for l in gathers), gathers
    assert not any("tensor<16x1xf32>" in l for l in gathers), gathers
    hvd.remove_process_set(ps)


def test_ragged_allgather_wire_byte_accounting():
    """VERDICT r4 #6: the padded-group allgather's wire bytes match the
    ring formula analytically — group 4 (padded 3-of-8) gathers
    (g-1)/g * result_bytes per device, 3/7 of what the full-axis gather
    would move."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map
    from wire_accounting import collective_wire_costs
    from horovod_tpu.collectives import ops

    ps = hvd.add_process_set([1, 4, 6])
    x = jnp.asarray(np.arange(N * 2, dtype=np.float32).reshape(N * 2, 1))
    f = shard_map(lambda t: ops.allgather(t, process_set=ps),
                  mesh=hvd.mesh(), in_specs=P(hvd.RANK_AXIS),
                  out_specs=P(hvd.RANK_AXIS), check_vma=False)
    costs = [c for c in collective_wire_costs(
        jax.jit(f).lower(x).as_text()) if c["op"] == "all_gather"]
    assert len(costs) == 1, costs
    c = costs[0]
    # per-device 2 rows x 1 f32 = 8 B in; padded group of 4 -> 32 B out
    assert c["group_size"] == 4
    assert c["operand_bytes"] == 8 and c["result_bytes"] == 32
    assert c["ring_bytes"] == pytest.approx(3 / 4 * 32)     # = 24 B
    # a full-axis gather would be (7/8)*64 = 56 B — the ragged set pays
    # 3/7 of that
    assert c["ring_bytes"] < 7 / 8 * 64
    hvd.remove_process_set(ps)


def test_alltoall_v_wire_byte_accounting():
    """alltoall_v's pad-to-max wire contract (VERDICT r4 #6): the data
    exchange is exactly n*max_split rows regardless of actual splits, plus
    an [n]-int32 size side channel — both matched against the lowered HLO
    with the (g-1)/g ring formula."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map
    from wire_accounting import collective_wire_costs
    from horovod_tpu.collectives import dynamic

    max_split = 3
    x = jnp.asarray(np.arange(N * 6, dtype=np.float32).reshape(N * 6, 1))
    sp = jnp.asarray(np.tile([1, 2, 0, 3, 0, 0, 0, 0], (N, 1))
                     .astype(np.int32))

    def body(t, s):
        recv, counts = dynamic.alltoall_v(t, s.reshape(-1),
                                          max_split=max_split)
        return recv, counts

    f = shard_map(body, mesh=hvd.mesh(), in_specs=(P(hvd.RANK_AXIS),
                                                   P(hvd.RANK_AXIS)),
                  out_specs=(P(hvd.RANK_AXIS), P(hvd.RANK_AXIS)),
                  check_vma=False)
    costs = [c for c in collective_wire_costs(
        jax.jit(f).lower(x, sp).as_text()) if c["op"] == "all_to_all"]
    assert len(costs) == 2, costs        # data exchange + size side channel
    data = max(costs, key=lambda c: c["operand_bytes"])
    sizes = min(costs, key=lambda c: c["operand_bytes"])
    # data: n * max_split rows x 1 f32, independent of the actual splits
    assert data["group_size"] == N
    assert data["operand_bytes"] == N * max_split * 4
    assert data["ring_bytes"] == pytest.approx(
        (N - 1) / N * N * max_split * 4)
    # side channel: one int32 per destination
    assert sizes["operand_bytes"] == N * 4
    assert sizes["ring_bytes"] == pytest.approx((N - 1) / N * N * 4)


def test_alltoall_padded_ragged_set():
    """3-of-8 (ragged) alltoall rides the padded groups too: members
    exchange chunks in member order, non-members — including the
    complement rank drafted as group padding — keep their input."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map
    from horovod_tpu.collectives import ops

    ps = hvd.add_process_set([1, 4, 6])
    x = np.zeros((N, 3), np.float32)
    for r in range(N):
        x[r] = r * 10 + np.arange(3)

    f = shard_map(lambda t: ops.alltoall(t, process_set=ps),
                  mesh=hvd.mesh(), in_specs=P(hvd.RANK_AXIS),
                  out_specs=P(hvd.RANK_AXIS), check_vma=False)
    out = np.asarray(jax.jit(f)(jnp.asarray(x.reshape(N * 3, 1)))
                     ).reshape(N, 3)
    np.testing.assert_array_equal(out[1], [10, 40, 60])  # chunk 0 of each
    np.testing.assert_array_equal(out[4], [11, 41, 61])  # chunk 1
    np.testing.assert_array_equal(out[6], [12, 42, 62])  # chunk 2
    for r in (0, 2, 3, 5, 7):
        np.testing.assert_array_equal(out[r], x[r])
    hvd.remove_process_set(ps)


def test_in_graph_op_dtype_dim_matrix():
    """SURVEY §4 bulk tier on the PRODUCTION surface: the in-graph ops
    inside user shard_map + jit, swept over wire dtypes and 1-3D block
    shapes against exact numpy models (tiny values keep bf16/u8 exact).
    The eager tests above cover the stacked-array surface; this pins the
    compiled path the GSPMD trainers actually run."""
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
        smkw = {"check_vma": False}
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map as shard_map
        smkw = {"check_rep": False}

    dtypes = [jnp.bfloat16, jnp.float32, jnp.int32, jnp.uint8]
    shapes = [(8,), (8, 3), (8, 3, 2)]

    for dt in dtypes:
        for shape in shapes:
            base = (np.arange(int(np.prod(shape))).reshape(shape) % 5)
            ranks = np.stack([base + r + 1 for r in range(N)])  # [N,*s]
            x = jnp.asarray(ranks).astype(dt)

            def step(xb):
                b = xb[0]  # drop the shard_map leading block dim
                ar = hvd.allreduce(b, op=hvd.Sum)
                ag = hvd.allgather(b)
                bc = hvd.broadcast(b, root_rank=3)
                aa = hvd.alltoall(b)
                rs = hvd.reducescatter(b, op=hvd.Sum)
                g1, g2 = hvd.grouped_allreduce([b, b * 2], op=hvd.Sum)
                return tuple(t[None] for t in (ar, ag, bc, aa, rs, g1, g2))

            f = jax.jit(shard_map(
                step, mesh=hvd.mesh(), in_specs=P(hvd.RANK_AXIS),
                out_specs=tuple([P(hvd.RANK_AXIS)] * 7), **smkw))
            ar, ag, bc, aa, rs, g1, g2 = [
                np.asarray(t).astype(np.float64) for t in f(x)]
            total = ranks.sum(0).astype(np.float64)
            c = shape[0] // N
            for r in range(N):
                np.testing.assert_array_equal(ar[r], total, f"{dt} {shape}")
                np.testing.assert_array_equal(
                    ag[r], np.concatenate([ranks[s] for s in range(N)]))
                np.testing.assert_array_equal(bc[r], ranks[3])
                np.testing.assert_array_equal(
                    aa[r], np.concatenate(
                        [ranks[s][r * c:(r + 1) * c] for s in range(N)]))
                np.testing.assert_array_equal(
                    rs[r], total[r * c:(r + 1) * c])
                np.testing.assert_array_equal(g1[r], total)
                np.testing.assert_array_equal(g2[r], 2 * total)
