"""Elastic training example (reference: examples/elastic/pytorch synced to
SURVEY.md §3.4's loop shape).

Run fault-tolerant on a dynamic host set:

    python -m horovod_tpu.runner --min-np 1 --max-np 8 \
        --host-discovery-script ./discover_hosts.sh \
        python examples/train_elastic.py

The wrapper + driver handle worker crashes (rollback to the last commit)
and membership changes (graceful generation restart with state carried via
persisted commits).
"""

import os

# Honor an explicit CPU request before any computation: some images
# pre-register an accelerator plugin, where the env var alone is not enough.
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
import optax

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(
    _os.path.abspath(__file__))))  # run in-repo without pip install

import horovod_tpu as hvd
from horovod_tpu import elastic
from horovod_tpu.models import ResNetTiny
from horovod_tpu.optimizer import distributed
from horovod_tpu.train import create_train_state, make_train_step

EPOCHS = 3
STEPS_PER_EPOCH = 8
BATCH_PER_RANK = 8


def main():
    hvd.init()
    model = ResNetTiny(num_classes=10, axis_name=hvd.RANK_AXIS)
    opt = distributed(optax.sgd(0.05, momentum=0.9))

    rng = np.random.RandomState(0)
    images = jnp.asarray(
        rng.randn(BATCH_PER_RANK * hvd.size(), 8, 8, 3).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 10, size=(images.shape[0],)))

    tstate = create_train_state(model, jax.random.PRNGKey(0), images[:1], opt)
    step = make_train_step(
        model, opt,
        lambda logits, y: optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean())

    state = elastic.JaxState(params=tstate.params,
                             opt_state=tstate.opt_state,
                             epoch=0, batch=0)

    @elastic.run
    def train(state):
        nonlocal tstate
        # Adopt (possibly restored/synced) state into the train loop.
        tstate = tstate._replace(params=jax.device_put(state.params),
                                 opt_state=jax.device_put(state.opt_state))
        while state.epoch < EPOCHS:
            while state.batch < STEPS_PER_EPOCH:
                tstate, loss = step(tstate, images, labels)
                state.batch += 1
                state.params = tstate.params
                state.opt_state = tstate.opt_state
                state.commit()
            if hvd.cross_rank() == 0:
                print(f"epoch {state.epoch} done, loss={float(loss):.4f}")
            state.epoch += 1
            state.batch = 0
            state.commit()
        return float(loss)

    final = train(state)
    if hvd.cross_rank() == 0:
        print(f"final loss {final:.4f}")


if __name__ == "__main__":
    main()
