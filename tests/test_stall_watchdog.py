"""Unit tier for the JaxProcessEngine transport stall watchdog
(core/engine.py ``_bounded`` — VERDICT r4 #1).

Reference parity: ``horovod/common/stall_inspector.cc`` escalation
semantics applied at the transport boundary — a blocked collective warns
after ``HOROVOD_STALL_CHECK_TIME_SECONDS`` and errors with
``HorovodInternalError`` after ``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``
instead of hanging forever on a dead peer. The end-to-end proof (2 real
processes, one SIGKILLed mid-collective) lives in
tests/test_integration_run.py.
"""

import threading
import time

import pytest

from horovod_tpu.core.engine import JaxProcessEngine
from horovod_tpu.core.exceptions import HorovodInternalError


def make_engine(warn=0.0, shutdown=0.0):
    """A bare engine carrying only the watchdog state (the real __init__
    needs jax.process_count() > 1, which single-process tests can't have)."""
    eng = object.__new__(JaxProcessEngine)
    eng._stall_warn = warn
    eng._stall_shutdown = shutdown
    eng._stall_queue = None
    eng._stall_in_pool = threading.local()
    eng._transport_lost = None
    return eng


def test_disabled_watchdog_runs_inline():
    eng = make_engine(warn=0.0, shutdown=0.0)
    caller = threading.current_thread()
    seen = {}

    def fn():
        seen["thread"] = threading.current_thread()
        return 42

    assert eng._bounded(fn, "t") == 42
    assert seen["thread"] is caller          # no round-thread hop
    assert eng._stall_queue is None          # and none created


def test_fast_call_passes_result_and_exceptions_through():
    eng = make_engine(warn=5.0, shutdown=10.0)
    assert eng._bounded(lambda: "ok", "t") == "ok"
    with pytest.raises(ValueError, match="boom"):
        eng._bounded(lambda: (_ for _ in ()).throw(ValueError("boom")), "t")
    # errors do NOT mark the transport lost — only a stall does
    assert eng._transport_lost is None
    assert eng._bounded(lambda: "still-alive", "t") == "still-alive"


def test_stalled_call_raises_horovod_internal_error_bounded():
    eng = make_engine(warn=0.1, shutdown=0.5)
    release = threading.Event()
    t0 = time.monotonic()
    with pytest.raises(HorovodInternalError, match="stalled"):
        eng._bounded(lambda: release.wait(30), "allgather round")
    dt = time.monotonic() - t0
    assert 0.4 <= dt < 5.0, dt              # bounded, not 30s
    assert eng._transport_lost is not None
    release.set()                            # unpark the round thread


def test_transport_lost_fails_fast_afterwards():
    eng = make_engine(warn=0.1, shutdown=0.3)
    release = threading.Event()
    with pytest.raises(HorovodInternalError):
        eng._bounded(lambda: release.wait(30), "t")
    t0 = time.monotonic()
    with pytest.raises(HorovodInternalError, match="stalled"):
        eng._bounded(lambda: "never-runs", "t")
    assert time.monotonic() - t0 < 0.2       # immediate, no new round
    release.set()


def test_nested_transport_call_runs_on_round_thread():
    """_allgather_fixed(members=...) -> _device_gather nests transport
    calls; the inner one must run inline on the round thread (a second
    submit against the 1-thread pool would deadlock)."""
    eng = make_engine(warn=1.0, shutdown=5.0)

    def outer():
        return eng._bounded(lambda: "inner-ok", "inner")

    t0 = time.monotonic()
    assert eng._bounded(outer, "outer") == "inner-ok"
    assert time.monotonic() - t0 < 2.0


def test_warning_logged_before_shutdown(caplog):
    import logging
    eng = make_engine(warn=0.1, shutdown=0.6)
    release = threading.Event()
    logger = logging.getLogger("horovod_tpu")
    old_propagate = logger.propagate
    logger.propagate = True   # the package logger has its own handler
    try:
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            with pytest.raises(HorovodInternalError):
                eng._bounded(lambda: release.wait(30), "allgather round")
    finally:
        logger.propagate = old_propagate
    assert any("blocked" in r.message for r in caplog.records)
    release.set()


def test_parked_round_thread_does_not_block_exit():
    """After a stall the round thread stays parked in the dead collective
    FOREVER — it must be a daemon thread, or sys.exit(RESTART_EXIT_CODE)
    in elastic/run_fn.py would hang at interpreter shutdown joining it
    and the driver could never retire the generation."""
    import subprocess
    import sys
    import textwrap
    import time
    code = textwrap.dedent("""
        import sys, threading
        from horovod_tpu.core.engine import JaxProcessEngine
        from horovod_tpu.core.exceptions import HorovodInternalError
        eng = object.__new__(JaxProcessEngine)
        eng._stall_warn, eng._stall_shutdown = 0.1, 0.3
        eng._stall_queue = None
        eng._stall_in_pool = threading.local()
        eng._transport_lost = None
        try:
            eng._bounded(lambda: threading.Event().wait(600), "t")
        except HorovodInternalError:
            sys.exit(5)   # plain exit with the round thread still parked
        sys.exit(1)
    """)
    t0 = time.monotonic()
    r = subprocess.run([sys.executable, "-c", code], timeout=60)
    assert r.returncode == 5
    assert time.monotonic() - t0 < 30   # exited promptly, not joined forever


def test_elastic_driver_arms_default_shutdown_window(monkeypatch):
    """The driver exports DEFAULT_STALL_SHUTDOWN_S to workers it launches
    (a hung survivor is recoverable there); explicit user env wins."""
    from horovod_tpu.elastic import constants as C
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner.settings import Settings
    from horovod_tpu.runner.hosts import parse_hosts

    captured = {}

    def fake_run_host_process(a, command, settings, coord, key, stop,
                              extra_env=None, output_dir=None,
                              sweep_note=None):
        captured.update(extra_env or {})
        return 0

    monkeypatch.setattr("horovod_tpu.elastic.driver.run_host_process",
                        fake_run_host_process)
    monkeypatch.delenv("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", raising=False)
    s = Settings(num_proc=1, hosts=parse_hosts("localhost:1"))
    d = ElasticDriver(s, ["true"])
    d._launch_generation({"localhost": 1}, 0, "/tmp/nowhere",
                         threading.Event())
    assert captured["HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"] == \
        str(C.DEFAULT_STALL_SHUTDOWN_S)
    d._service.close()

    # user-provided value wins
    captured.clear()
    monkeypatch.setenv("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", "17")
    d2 = ElasticDriver(s, ["true"])
    d2._launch_generation({"localhost": 1}, 0, "/tmp/nowhere",
                          threading.Event())
    assert "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS" not in captured
    d2._service.close()
