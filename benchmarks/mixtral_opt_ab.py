"""Interleaved A/B of expert-bank optimizer variants at the Mixtral bench
config (real TPU) — VERDICT r4 #2.

The r4 profile pinned the remaining Mixtral bound: the AdamW pass over
the 8x-overprovisioned expert bank is 12.8% of the step (~7.3 ms of pure
HBM traffic updating 176M mostly-inactive f32 params + 2x f32 moments).
Arms (optimizer/moe_opt.py; dense params keep exact AdamW in all of
them):

- ``adamw``     baseline (the r4 bench optimizer)
- ``bf16_nu``   expert v stored bf16 + stochastic rounding  (-1x v traffic)
- ``bf16_munu`` expert m AND v stored bf16                  (-2x m/v traffic)
- ``factored``  Adafactor for expert tensors (factored v, no m)
- ``deferred``  expert update every 4th step at 4x LR (skip = zero
                param/m/v traffic on 3 of 4 steps)

Interleaved (``slope_time_paired``) because absolute single-run readings
swing ±10% over the tunnel; per-round ratios vs the ``adamw`` arm are
the evidence.

Usage (real chip):  python benchmarks/mixtral_opt_ab.py [per_chip_batch]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import jax
import jax.numpy as jnp
import numpy as np

from common import emit, lm_train_flops_per_token, mfu_fields, on_tpu, \
    params_count, slope_time_paired, sync

#: "deferred2" = TWO-program deferral (optimizer.deferred_pair): the
#: lax.cond "deferred" form measured ~flat because cond cannot alias the
#: pass-through moments; two jitted programs with donation CAN (the skip
#: program's expert bank aliases straight through). "deferred2_bf16nu"
#: stacks the bf16 second moment on the apply program.
VARIANTS = ("adamw", "bf16_nu", "bf16_munu", "factored", "deferred",
            "deferred2", "deferred2_bf16nu")


def main():
    import horovod_tpu as hvd
    from horovod_tpu.models.llama import LOGICAL_RULES
    from horovod_tpu.models.mixtral import (Mixtral, MixtralConfig,
                                            mixtral_tiny)
    from horovod_tpu.optimizer import moe_adamw
    from horovod_tpu.parallel import create_mesh
    from horovod_tpu.train import (create_gspmd_train_state,
                                   make_gspmd_train_step)

    hvd.init()
    n = hvd.size()
    tpu = on_tpu()
    if tpu:
        # shared bench config (scan_layers=False since r5) so variants
        # A/B at the adopted config (the r5 A/B table in
        # docs/benchmarks.md was measured on the scan config)
        from common import mixtral_bench_config
        cfg = mixtral_bench_config()
        pos = [a for a in sys.argv[1:] if not a.startswith("-")]
        per_chip, seq = (int(pos[0]) if pos else 16), 512
    else:
        cfg = mixtral_tiny()
        per_chip, seq = 2, 32
    batch = max(per_chip * n, 2)
    ep = min(cfg.n_experts, n)
    mesh = create_mesh({"dp": n // ep, "ep": ep}) if n > 1 \
        else create_mesh({"dp": 1})
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    model = Mixtral(cfg)

    def build(variant):
        if variant.startswith("deferred2"):
            from horovod_tpu.optimizer import deferred_pair
            from horovod_tpu.train import make_gspmd_deferred_train_step
            nu = jnp.bfloat16 if variant.endswith("bf16nu") else None
            pair = deferred_pair(1e-4, every=4, expert_nu_dtype=nu)
            state = create_gspmd_train_state(model, pair.apply,
                                             jax.random.PRNGKey(0), tokens,
                                             mesh, LOGICAL_RULES)
            step = make_gspmd_deferred_train_step(
                model, pair, mesh, LOGICAL_RULES,
                aux_weight=cfg.router_aux_weight, donate=True)
        else:
            opt = moe_adamw(1e-4, expert_variant=variant, every=4)
            state = create_gspmd_train_state(model, opt,
                                             jax.random.PRNGKey(0), tokens,
                                             mesh, LOGICAL_RULES)
            # donate=True (the bench setting): without donation the
            # lax.cond deferred variant COPIES the whole expert m/v
            # through on every skip step — the copy costs more than the
            # AdamW pass it skips (measured -14.6% with donate=False).
            step = make_gspmd_train_step(model, opt, mesh, LOGICAL_RULES,
                                         aux_weight=cfg.router_aux_weight,
                                         donate=True)
        box = {"state": state}

        def run(k):
            st, loss = box["state"], None
            for _ in range(k):
                st, loss = step(st, tokens)
            box["state"] = st
            sync(loss)

        return run, box

    # PAIRWISE vs the baseline (five full param+moment states at once OOM
    # a single chip): each pair interleaves {adamw, variant} so tunnel
    # drift lands on both arms; ratios are still per-round medians.
    # k=4/8 so the deferred arm's apply-step lands once per k=4 window
    # (its slope then reflects the AVERAGE step, apply + 3 skips).
    flops_tok = None
    for variant in VARIANTS[1:]:
        run_base, box_base = build("adamw")
        run_var, box_var = build(variant)
        if flops_tok is None:
            total = params_count(box_base["state"].params)
            expert = params_count(
                box_base["state"].params,
                select=lambda p: "moe" in p and p.rsplit("/", 1)[-1] in
                ("w1", "w2", "w3"))
            active = total - expert + expert * cfg.top_k / cfg.n_experts
            flops_tok = lm_train_flops_per_token(active, cfg.n_layers,
                                                 cfg.dim, seq)
        secs, rounds = slope_time_paired(
            {"adamw": run_base, variant: run_var}, 4, 8, return_rounds=True)
        ratio = float(np.median([r["adamw"] / r[variant] for r in rounds]))
        base_tps = batch * seq / secs["adamw"] / n
        tps = batch * seq / secs[variant] / n
        emit(f"mixtral_opt_{variant}_tokens_per_sec_per_chip", tps,
             f"tokens/sec/chip (seq {seq}, batch {per_chip}/chip, "
             f"expert_variant={variant}, {n} devices; paired adamw arm "
             f"{base_tps / 1000:.1f}k)",
             speedup_vs_adamw=round(ratio, 4),
             **mfu_fields(tps, flops_tok))
        del run_base, run_var, box_base, box_var
        import gc
        gc.collect()


if __name__ == "__main__":
    main()
