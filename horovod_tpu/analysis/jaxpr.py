"""jaxpr-level collective-graph analyzer.

Traces a step function abstractly (``jax.make_jaxpr`` over
``ShapeDtypeStruct`` args — no device execution; runs on CPU with zero
chips) and walks the closed jaxpr, descending into ``pjit`` / ``scan`` /
``cond`` / ``switch`` / ``while`` / ``shard_map`` sub-jaxprs, to extract
the ordered collective signature stream and run static consistency
checks.  This is the SPMD answer to the reference controller's runtime
negotiation (``horovod/common/controller.cc``): where the reference
detects that ranks submitted different collective streams *while the job
hangs*, GSPMD compiles one program for all ranks — so the only way ranks
can diverge is rank-dependent control flow, which is exactly what these
checks look for *before launch*.

Check ids (documented in docs/analysis.md):

- ``jax-cond-collective`` (ERROR): a collective primitive inside a
  ``lax.cond``/``lax.switch`` branch.  If the predicate is rank-dependent,
  some ranks enter the collective and others do not → deadlock.
- ``jax-grad-psum`` (ERROR): the transposed residue of differentiating
  ``lax.psum`` under ``shard_map(check_vma=False)`` — gradients silently
  scale by the axis size (the trap worked around in
  ``parallel/pipeline.py``: mask per-device, psum AFTER ``grad``).
- ``jax-cond-carry`` (WARNING): large state passed through a cond branch
  unchanged.  ``lax.cond`` cannot alias loop-carried state across the
  branch, so the pass-through is a COPY every step (the trap that killed
  the ``lax.cond`` deferred optimizer — ``optimizer/moe_opt.py``,
  VERDICT r5 #2).
- ``jax-donated-reuse`` (ERROR): a buffer donated to a jitted call is
  used again afterwards — XLA may already have aliased its memory.
- ``jax-unknown-axis`` (ERROR): a collective names an axis that is not in
  the enclosing mesh.
- ``jax-axis-order`` (WARNING): a multi-axis collective lists mesh axes
  out of mesh order, breaking ``collectives/ops.py``'s hierarchical
  ``(cross..., intra)`` convention (intra = last mesh axis rides ICI).
"""

from typing import List, NamedTuple, Optional, Tuple

import numpy as np

import jax
from jax import core as jax_core

try:  # location of Jaxpr/ClosedJaxpr classes is stable here, but be safe
    from jax._src import core as _src_core
except ImportError:  # pragma: no cover
    _src_core = jax_core

try:
    from jax._src import source_info_util as _source_info
except ImportError:  # pragma: no cover
    _source_info = None

from .findings import Finding, Severity

# Named-axis collective primitives and where each keeps its axis names
# (jax calls the psum-family param "axes", the gather family "axis_name").
# The registry lives next to the data plane so the two stay in lockstep.
from ..collectives.ops import COLLECTIVE_PRIMITIVES as COLLECTIVE_PRIMS
# axis_index is rank-divergent *by design*; it is part of the stream but
# exempt from the cond-collective deadlock check.
_DEADLOCKING = set(COLLECTIVE_PRIMS) - {"axis_index"}

DEFAULT_BIG_CARRY_BYTES = 1 << 20  # 1 MiB


class CollectiveCall(NamedTuple):
    """One entry of the ordered collective signature stream."""
    primitive: str
    axes: Tuple[str, ...]
    shape: Tuple[int, ...]
    dtype: str
    file: str
    line: int


def _loc(eqn) -> Tuple[str, int]:
    if _source_info is not None:
        try:
            frame = _source_info.user_frame(eqn.source_info)
            if frame is not None:
                return frame.file_name, frame.start_line
        except Exception:
            pass
    return "<unknown>", 0


def _axis_names(eqn) -> Tuple[str, ...]:
    param = COLLECTIVE_PRIMS[eqn.primitive.name]
    axes = eqn.params.get(param, ())
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    # psum's "axes" may mix named axes with positional ints — names only.
    return tuple(a for a in axes if isinstance(a, str))


def _aval_bytes(aval) -> int:
    try:
        size = int(np.prod(aval.shape)) if aval.shape else 1
        return size * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def _inner_jaxpr(obj):
    """Unwrap ClosedJaxpr → Jaxpr; pass Jaxpr through; else None."""
    if isinstance(obj, _src_core.ClosedJaxpr):
        return obj.jaxpr
    if isinstance(obj, _src_core.Jaxpr):
        return obj
    return None


def _generic_sub_jaxprs(params):
    """All sub-jaxprs reachable from an eqn's params (any primitive)."""
    subs = []
    for v in params.values():
        j = _inner_jaxpr(v)
        if j is not None:
            subs.append(j)
        elif isinstance(v, (tuple, list)):
            for item in v:
                j = _inner_jaxpr(item)
                if j is not None:
                    subs.append(j)
    return subs


class _Ctx(NamedTuple):
    # (file, line) of the innermost enclosing cond/switch, or None.
    cond_site: Optional[Tuple[str, int]]
    # Ordered axis names of the innermost enclosing mesh, or None if no
    # shard_map has been entered (GSPMD jaxprs carry no named axes).
    mesh_axes: Optional[Tuple[str, ...]]


class _Analysis:
    def __init__(self, big_carry_bytes: int):
        self.big_carry_bytes = big_carry_bytes
        self.stream: List[CollectiveCall] = []
        self.findings: List[Finding] = []

    # -- per-jaxpr dataflow helpers ------------------------------------

    @staticmethod
    def _input_derived(jaxpr) -> set:
        """Vars (transitively) derived from the jaxpr's inputs."""
        derived = {v for v in jaxpr.invars}
        for eqn in jaxpr.eqns:
            if any(isinstance(v, _src_core.Var) and v in derived
                   for v in eqn.invars):
                derived.update(eqn.outvars)
        return derived

    @staticmethod
    def _reaches_output(jaxpr) -> set:
        """Vars whose value (transitively) feeds the jaxpr's outputs."""
        live = {v for v in jaxpr.outvars if isinstance(v, _src_core.Var)}
        for eqn in reversed(jaxpr.eqns):
            if any(v in live for v in eqn.outvars):
                live.update(v for v in eqn.invars
                            if isinstance(v, _src_core.Var))
        return live

    # -- the walk ------------------------------------------------------

    def visit(self, jaxpr, ctx: _Ctx):
        input_derived = self._input_derived(jaxpr)
        reaches_out = self._reaches_output(jaxpr)
        donated_here = {}  # var -> (file, line) of the donating pjit
        psum_records = []  # for the per-scope jax-grad-psum pass

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            file, line = _loc(eqn)

            # jax-donated-reuse: a var used after some earlier pjit in
            # this scope took it as a donated input.
            for v in eqn.invars:
                if isinstance(v, _src_core.Var) and v in donated_here:
                    dfile, dline = donated_here[v]
                    self.findings.append(Finding(
                        "jax-donated-reuse", Severity.ERROR, file, line,
                        f"value used after being donated to the jitted "
                        f"call at {dfile}:{dline}; the donated buffer may "
                        f"already be aliased",
                        {"donated_at": f"{dfile}:{dline}"}))

            if name in COLLECTIVE_PRIMS:
                self._visit_collective(eqn, ctx, input_derived,
                                       reaches_out, file, line,
                                       psum_records)
            elif name == "cond":
                self._visit_cond(eqn, ctx, file, line)
            elif name == "while":
                for key in ("cond_jaxpr", "body_jaxpr"):
                    j = _inner_jaxpr(eqn.params.get(key))
                    if j is not None:
                        self.visit(j, ctx)
            elif name == "scan":
                j = _inner_jaxpr(eqn.params.get("jaxpr"))
                if j is not None:
                    self.visit(j, ctx)
            elif name == "shard_map":
                mesh = eqn.params.get("mesh")
                axes = tuple(getattr(mesh, "axis_names", ()) or ())
                j = _inner_jaxpr(eqn.params.get("jaxpr"))
                if j is not None:
                    self.visit(j, ctx._replace(mesh_axes=axes or None))
            elif name in ("pjit", "jit", "closed_call", "core_call",
                          "custom_jvp_call", "custom_vjp_call",
                          "custom_vjp_call_jaxpr", "remat", "checkpoint"):
                for j in _generic_sub_jaxprs(eqn.params):
                    self.visit(j, ctx)
                donated = eqn.params.get("donated_invars")
                if donated:
                    for v, d in zip(eqn.invars, donated):
                        if d and isinstance(v, _src_core.Var):
                            donated_here[v] = (file, line)
            else:
                for j in _generic_sub_jaxprs(eqn.params):
                    self.visit(j, ctx)

        self._grad_psum_pass(psum_records)

    def _grad_psum_pass(self, psum_records):
        """jax-grad-psum: the transposed residue of differentiating
        ``lax.psum`` under ``shard_map``.

        The backward pass seeds the scalar loss cotangent as the LITERAL
        1.0 and psum's transpose applies psum directly to it — so the
        jaxpr contains ``psum 1.0`` feeding the gradient outputs.  User
        code can never write that eqn: ``lax.psum(<python scalar>)`` is
        constant-folded at trace time, so a literal-operand psum only
        comes from the transpose.  A second signature (for seeds wrapped
        by a convert/mul) is a const-derived psum feeding the outputs
        next to a DEAD input-derived psum over the same axes — the
        orphaned forward half of the differentiated psum.  Both mean
        gradients silently scale by the axis size; ``barrier()``'s
        psum-of-constant never reaches the outputs and a legitimate
        post-grad psum (parallel/pipeline.py) consumes input-derived
        values, so neither trips this.
        """
        for rec in psum_records:
            if not rec["to_outputs"]:
                continue
            suspicious = rec["literal_operand"] or (
                not rec["from_inputs"]
                and any(s is not rec and s["axes"] == rec["axes"]
                        and s["from_inputs"] and not s["to_outputs"]
                        for s in psum_records))
            if suspicious:
                self.findings.append(Finding(
                    "jax-grad-psum", Severity.ERROR,
                    rec["file"], rec["line"],
                    f"psum over {rec['axes']} applied to the cotangent "
                    f"seed (a constant) with its result feeding the "
                    f"gradient outputs — signature of differentiating "
                    f"psum under shard_map: the seed lands once per "
                    f"device and gradients scale by the axis size. Mask "
                    f"per-device and psum AFTER grad (see "
                    f"parallel/pipeline.py)",
                    {"axes": list(rec["axes"])}))

    def _visit_collective(self, eqn, ctx, input_derived, reaches_out,
                          file, line, psum_records):
        name = eqn.primitive.name
        axes = _axis_names(eqn)
        aval = eqn.outvars[0].aval if eqn.outvars else None
        shape = tuple(getattr(aval, "shape", ()) or ())
        dtype = str(getattr(aval, "dtype", "?"))
        self.stream.append(
            CollectiveCall(name, axes, shape, dtype, file, line))

        # jax-cond-collective: deadlock if the branch predicate is
        # rank-dependent — only some ranks reach the collective.
        if ctx.cond_site is not None and name in _DEADLOCKING:
            cfile, cline = ctx.cond_site
            self.findings.append(Finding(
                "jax-cond-collective", Severity.ERROR, file, line,
                f"collective `{name}` over {axes or '(positional)'} inside "
                f"a cond/switch branch (branch at {cfile}:{cline}); if the "
                f"predicate is rank-dependent this deadlocks — hoist the "
                f"collective out of the branch",
                {"cond_at": f"{cfile}:{cline}", "primitive": name}))

        # Record psum facts for the per-scope jax-grad-psum pass.
        if name == "psum":
            operand_vars = [v for v in eqn.invars
                            if isinstance(v, _src_core.Var)]
            psum_records.append({
                "axes": axes,
                "from_inputs": any(v in input_derived
                                   for v in operand_vars),
                "literal_operand": not operand_vars,
                "to_outputs": any(v in reaches_out for v in eqn.outvars),
                "file": file, "line": line,
            })

        # Axis-name checks need a known mesh (shard_map scope).
        if ctx.mesh_axes is not None and axes:
            unknown = [a for a in axes if a not in ctx.mesh_axes]
            if unknown:
                self.findings.append(Finding(
                    "jax-unknown-axis", Severity.ERROR, file, line,
                    f"collective `{name}` names axis(es) {unknown} not in "
                    f"the enclosing mesh {list(ctx.mesh_axes)}",
                    {"unknown": unknown,
                     "mesh_axes": list(ctx.mesh_axes)}))
            elif len(axes) > 1:
                pos = [ctx.mesh_axes.index(a) for a in axes]
                if pos != sorted(pos):
                    self.findings.append(Finding(
                        "jax-axis-order", Severity.WARNING, file, line,
                        f"collective `{name}` lists axes {list(axes)} out "
                        f"of mesh order {list(ctx.mesh_axes)}; the "
                        f"hierarchical convention is (cross..., intra) "
                        f"with intra = the last (ICI-contiguous) mesh "
                        f"axis (collectives/ops.py)",
                        {"axes": list(axes),
                         "mesh_axes": list(ctx.mesh_axes)}))

    def _visit_cond(self, eqn, ctx, file, line):
        branches = eqn.params.get("branches", ())
        # jax-cond-carry: a branch outvar that IS a branch invar is a
        # pass-through — lax.cond cannot alias it, so it is copied every
        # call.  Sum bytes over the worst branch.
        worst = 0
        for br in branches:
            j = _inner_jaxpr(br)
            if j is None:
                continue
            invars = set(j.invars)
            passthrough = [v for v in j.outvars
                           if isinstance(v, _src_core.Var) and v in invars]
            worst = max(worst, sum(_aval_bytes(v.aval)
                                   for v in passthrough))
        if worst >= self.big_carry_bytes:
            self.findings.append(Finding(
                "jax-cond-carry", Severity.WARNING, file, line,
                f"cond branch passes ~{worst / (1 << 20):.1f} MiB of "
                f"carried state through unchanged; lax.cond cannot alias "
                f"across the branch, so this COPIES the state every step "
                f"(the every-k trap — use two jitted programs instead: "
                f"train.make_gspmd_deferred_train_step)",
                {"passthrough_bytes": worst}))
        sub_ctx = ctx._replace(cond_site=(file, line))
        for br in branches:
            j = _inner_jaxpr(br)
            if j is not None:
                self.visit(j, sub_ctx)


def _closed_jaxpr_of(fn, *args, **kwargs):
    return jax.make_jaxpr(fn)(*args, **kwargs)


def analyze_step(fn, *args,
                 mesh=None,
                 big_carry_bytes: int = DEFAULT_BIG_CARRY_BYTES,
                 **kwargs) -> List[Finding]:
    """Statically analyze a step function; returns the findings.

    ``fn`` is traced abstractly with ``jax.make_jaxpr`` — args may be real
    arrays, pytrees, or ``jax.ShapeDtypeStruct`` skeletons; nothing
    executes on any device.  ``mesh`` (optional) supplies the ambient axis
    names for steps whose collectives are NOT wrapped in an in-trace
    ``shard_map`` (axis names are then checked against ``mesh.axis_names``).
    """
    try:
        closed = _closed_jaxpr_of(fn, *args, **kwargs)
    except NameError as e:
        # jax raises at trace time for axis names bound nowhere at all
        # ("unbound axis name: X") — fold it into the same finding the
        # walker emits for a wrong name under a known mesh.
        msg = str(e)
        if "axis name" not in msg:
            raise
        code = getattr(fn, "__code__", None)
        return [Finding(
            "jax-unknown-axis", Severity.ERROR,
            getattr(code, "co_filename", "<unknown>"),
            getattr(code, "co_firstlineno", 0),
            f"tracing failed: {msg} — a collective names an axis no "
            f"enclosing mesh/shard_map binds",
            {"trace_error": msg})]
    ana = _Analysis(big_carry_bytes)
    axes = tuple(getattr(mesh, "axis_names", ()) or ()) if mesh is not None \
        else None
    ana.visit(closed.jaxpr, _Ctx(cond_site=None, mesh_axes=axes))
    return ana.findings


def rank_streams(factory, size: int, ranks=None):
    """Per-rank collective signature streams of a rank-parameterized step.

    ``factory(rank, size)`` must return ``(fn, args)`` (or the dict form
    ``{"fn": fn, "args": (...)}``) with the CONCRETE rank/size already
    bound — the closure a launcher builds per process.  Each rank's step
    is traced abstractly and its ordered collective stream extracted;
    a rank whose trace fails contributes the sentinel stream
    ``[("<trace-error>", message)]`` so rank-DEPENDENT trace failure
    registers as divergence while a uniform failure does not.
    """
    if ranks is None:
        ranks = range(size)
    streams = {}
    for rank in ranks:
        spec = factory(rank, size)
        if isinstance(spec, dict):
            fn, args = spec["fn"], tuple(spec.get("args", ()))
        else:
            fn, args = spec[0], tuple(spec[1])
        try:
            closed = _closed_jaxpr_of(fn, *args)
        except Exception as e:  # noqa: BLE001 — any trace failure counts
            streams[rank] = [("<trace-error>",
                              f"{type(e).__name__}: {e}")]
            continue
        ana = _Analysis(DEFAULT_BIG_CARRY_BYTES)
        ana.visit(closed.jaxpr, _Ctx(cond_site=None, mesh_axes=None))
        streams[rank] = ana.stream
    return streams


def _stream_sig(entry):
    """Comparison key for one stream entry: (primitive, axes, shape,
    dtype) — file/line excluded (identical code traced from different
    closures may report different lines)."""
    if isinstance(entry, CollectiveCall):
        return (entry.primitive, entry.axes, entry.shape, entry.dtype)
    return tuple(entry)  # the ("<trace-error>", msg) sentinel


def _stream_repr(stream) -> List[str]:
    out = []
    for entry in stream:
        if isinstance(entry, CollectiveCall):
            out.append(f"{entry.primitive}{list(entry.axes)} "
                       f"{entry.dtype}{list(entry.shape)}")
        else:
            out.append(f"{entry[0]} {entry[1]}")
    return out


def analyze_rank_divergence(factory, size: int,
                            ranks=None) -> List[Finding]:
    """Static cross-rank divergence detection — the SPMD analogue of the
    reference controller's mismatch response (``horovod/common/
    controller.cc`` builds a "who disagreed, about what" error when
    ranks negotiate different tensor streams; SURVEY.md §2).

    Evaluates the step once per simulated rank with concrete rank/size
    bindings (see :func:`rank_streams`), then diffs the per-rank
    collective signature streams pairwise against rank ``ranks[0]``.
    The first divergent op — extra, missing, or different — produces a
    ``jax-rank-divergence`` ERROR carrying BOTH ranks' full streams and
    the divergence index, catching ``if rank == 0: allreduce(...)``
    before a multi-host job hangs on it.
    """
    streams = rank_streams(factory, size, ranks)
    order = list(streams)
    base_rank = order[0]
    base = streams[base_rank]
    base_sig = [_stream_sig(e) for e in base]
    findings: List[Finding] = []
    for rank in order[1:]:
        other = streams[rank]
        other_sig = [_stream_sig(e) for e in other]
        if other_sig == base_sig:
            continue
        idx = next((i for i, (a, b)
                    in enumerate(zip(base_sig, other_sig)) if a != b),
                   min(len(base_sig), len(other_sig)))
        # Location: the first entry present at the divergence point.
        file, line = "<unknown>", 0
        for stream in (base, other):
            if idx < len(stream) \
                    and isinstance(stream[idx], CollectiveCall):
                file, line = stream[idx].file, stream[idx].line
                break
        a = base_sig[idx] if idx < len(base_sig) else None
        b = other_sig[idx] if idx < len(other_sig) else None
        findings.append(Finding(
            "jax-rank-divergence", Severity.ERROR, file, line,
            f"ranks {base_rank} and {rank} (of {size}) emit different "
            f"collective streams — first divergence at op {idx}: rank "
            f"{base_rank} issues {a}, rank {rank} issues {b}; on a real "
            f"job the minority rank never shows up and the collective "
            f"deadlocks (the mismatch the reference controller "
            f"negotiates at runtime, controller.cc)",
            {"size": size, "divergence_index": idx,
             "rank_a": base_rank, "rank_b": rank,
             "stream_a": _stream_repr(base),
             "stream_b": _stream_repr(other)}))
        break  # first divergent pair is the actionable one
    return findings


def collective_stream(fn, *args, **kwargs) -> List[CollectiveCall]:
    """The ordered collective signature stream of a traced step.

    The static analogue of what the reference controller negotiates at
    runtime: (primitive, axis names, shape, dtype) in program order.
    Comparing two ranks' streams is what ``tools/mismatch.py`` does with
    runtime digests; under GSPMD one trace serves all ranks, so the
    stream doubles as a golden signature for regression tests.
    """
    closed = _closed_jaxpr_of(fn, *args, **kwargs)
    ana = _Analysis(DEFAULT_BIG_CARRY_BYTES)
    ana.visit(closed.jaxpr, _Ctx(cond_site=None, mesh_axes=None))
    return ana.stream
