"""Perf-attribution plane (ISSUE 11, docs/profiling.md).

Reference parity: the per-op ``horovod/common/timeline.cc`` record plus
the autotuner's measure-persist-compare loop. This suite pins the TPU
rebuild's replacement surface (tools/perf.py): the step-time budget over
synthetic xplane traces (umbrella/async traps honored, categories sum to
wall), the per-model MFU ratchet over ``perf_history.jsonl``, regression
diffs that NAME the category and op, the live ``hvd_step_*`` gauges
through the watchdog, and their coordinator ``/metrics`` fleet rollup.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from horovod_tpu.core import telemetry as T
from horovod_tpu.core import watchdog
from horovod_tpu.elastic.service import CoordinatorClient, CoordinatorService
from horovod_tpu.runner import secret as _secret
from horovod_tpu.tools import perf
from horovod_tpu.tools.telemetry import parse_prometheus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: 1 ms in picoseconds — xplane event durations are ps; the record schema
#: rounds seconds to 6 places, so synthetic events must be ms-scale.
MS = 10**9


@pytest.fixture(autouse=True)
def _fresh_perf(monkeypatch):
    monkeypatch.delenv(perf.HISTORY_ENV, raising=False)
    monkeypatch.delenv(perf.NO_HISTORY_ENV, raising=False)
    monkeypatch.delenv(perf.RATCHET_BAND_ENV, raising=False)
    perf.reset_registered_flops()
    T.reset()
    yield
    perf.reset_registered_flops()
    T.reset()


# --- synthetic xplane traces -------------------------------------------------

def _tpu_space():
    """One TPU core plane exercising every budget trap:

    lane (XLA Ops):  dot.1 [0,400) copy.2 [400,500) all-reduce.3 [500,700)
                     loop_fusion.5 [700,900)  + a %while.4 umbrella [0,700)
    XLA Modules:     one 1000 ms module (the wall source)
    Async XLA Ops:   a 300 ms all-reduce-start window overlapping compute
    """
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    space = xplane_pb2.XSpace()
    plane = space.planes.add()
    plane.name = "/device:TPU:0 (pid 1)"
    names = {
        1: "%dot.1 = bf16[256,256]{1,0} dot(%p0, %p1)",
        2: "%copy.2",
        3: "%all-reduce.3",
        4: "%while.4",           # scan umbrella: covers its children
        5: "%loop_fusion.5",
        6: "jit_train_step",
        7: "%all-reduce-start.6",
    }
    for mid, nm in names.items():
        plane.event_metadata[mid].id = mid
        plane.event_metadata[mid].name = nm

    def _ev(line, mid, start_ms, dur_ms):
        ev = line.events.add()
        ev.metadata_id = mid
        ev.offset_ps = start_ms * MS
        ev.duration_ps = dur_ms * MS

    modules = plane.lines.add()
    modules.name = "XLA Modules"
    _ev(modules, 6, 0, 1000)
    ops = plane.lines.add()
    ops.name = "XLA Ops"
    _ev(ops, 1, 0, 400)
    _ev(ops, 2, 400, 100)
    _ev(ops, 3, 500, 200)
    _ev(ops, 4, 0, 700)          # umbrella — must be dropped
    _ev(ops, 5, 700, 200)
    async_line = plane.lines.add()
    async_line.name = "Async XLA Ops"
    _ev(async_line, 7, 0, 300)   # overlap window — never occupancy
    return space


def _cpu_space():
    """A /host:CPU plane: thunk lanes carry bare HLO names; client-infra
    spans (spaces/colons) and the python line must not count."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    space = xplane_pb2.XSpace()
    plane = space.planes.add()
    plane.name = "/host:CPU"
    names = {1: "dot.3", 2: "fusion.7", 3: "ExecuteHelper: run",
             4: "while.9", 5: "PyCall"}
    for mid, nm in names.items():
        plane.event_metadata[mid].id = mid
        plane.event_metadata[mid].name = nm
    lane = plane.lines.add()
    lane.name = "thunk-executor 0"
    for mid, start, dur in ((1, 0, 400), (2, 600, 400),
                            (3, 0, 5000), (4, 0, 1000)):
        ev = lane.events.add()
        ev.metadata_id = mid
        ev.offset_ps = start * MS
        ev.duration_ps = dur * MS
    py = plane.lines.add()
    py.name = "python"
    ev = py.events.add()
    ev.metadata_id = 5
    ev.offset_ps = 0
    ev.duration_ps = 9999 * MS
    return space


def test_budget_sums_to_wall_and_honors_xplane_traps():
    b = perf.budget_from_space(_tpu_space())
    cat = b["cat_ps"]
    assert b["wall_ps"] == 1000 * MS          # from XLA Modules, not ops
    assert cat["matmul/conv"] == 400 * MS
    assert cat["copy/transpose"] == 100 * MS
    assert cat["elementwise"] == 200 * MS
    # the async window hides the on-lane collective: all 200 ms of
    # all-reduce occupancy intersect concurrent compute/DMA
    assert cat["collective_hidden"] == 200 * MS
    assert cat["collective_exposed"] == 0
    assert cat["gather/scatter"] == 0 and cat["other"] == 0
    assert cat["host_gap"] == 100 * MS
    # THE property: categories + gap partition the wall exactly
    assert sum(cat.values()) == b["wall_ps"]
    # %while umbrella dropped (counting it would double the step);
    # async window feeds only the hidden intersection, never occupancy
    assert "while.4" not in b["op_n"]
    assert "all-reduce-start.6" not in b["op_n"]
    assert b["hidden_ps"] == 300 * MS
    assert b["collective_total_ps"] == 200 * MS
    assert "all-reduce.3" in b["op_ps"]["collective_exposed"]


def test_cpu_plane_filters_infra_and_python_lines():
    b = perf.budget_from_space(_cpu_space())
    cat = b["cat_ps"]
    assert b["n_lanes"] == 1
    assert b["wall_ps"] == 1000 * MS          # lane extent of real ops
    assert cat["matmul/conv"] == 400 * MS
    assert cat["elementwise"] == 400 * MS
    assert cat["host_gap"] == 200 * MS
    assert sum(cat.values()) == b["wall_ps"]
    assert "ExecuteHelper: run" not in b["op_n"]   # client infra
    assert "while.9" not in b["op_n"]              # umbrella
    assert "PyCall" not in b["op_n"]               # python line


def test_attribute_logdir_record_schema(tmp_path):
    space = _tpu_space()
    (tmp_path / "t.xplane.pb").write_bytes(space.SerializeToString())
    rec = perf.attribute_logdir(str(tmp_path), 2, model="synth",
                                flops_per_step=4e9)
    assert rec["kind"] == "perf_budget" and rec["model"] == "synth"
    assert rec["wall_s_per_step"] == 0.5
    for key in perf.BUDGET_KEYS:
        assert key in rec["budget_s_per_step"], key
    assert rec["budget_s_per_step"]["matmul/conv"] == 0.2
    assert rec["sum_check"]["rel_err"] <= perf.SUM_TOLERANCE
    assert rec["top_ops"]["matmul/conv"][0]["op"] == "dot.1"
    # CPU device peak is unknown: throughput recorded, MFU omitted
    assert rec["achieved_tflops"] == pytest.approx(0.008)
    assert "mfu" not in rec


def test_categorize_budget_taxonomy():
    cases = {"%dot.12 = f32[8,8] dot(...)": "matmul/conv",
             "convolution.3": "matmul/conv",
             "gather.1": "gather/scatter",
             "dynamic-slice.9": "gather/scatter",
             "%scatter-add.2": "gather/scatter",
             "copy.4": "copy/transpose",
             "transpose.8": "copy/transpose",
             "all-reduce.1": "collective",
             "reduce-scatter.2": "collective",
             "%loop_fusion.5": "elementwise",
             "wat.7": "other"}
    for name, want in cases.items():
        assert perf.categorize_budget(name) == want, name


# --- history + ratchet -------------------------------------------------------

def _rec(model, mfu=None, wall=0.1, rel_err=0.0, drop_key=None,
         budget=None, top_ops=None):
    b = dict(budget or {k: 0.0 for k in perf.BUDGET_KEYS})
    if drop_key:
        b.pop(drop_key)
    r = {"kind": "perf_budget", "metric": f"{model}_step_budget",
         "model": model, "steps": 1, "n_lanes": 1,
         "wall_s_per_step": wall, "budget_s_per_step": b,
         "sum_check": {"sum_s": wall, "wall_s": wall, "rel_err": rel_err},
         "top_ops": top_ops or {}}
    if mfu is not None:
        r["mfu"] = mfu
    return r


def test_history_round_trip_is_stamped(tmp_path, monkeypatch):
    hist = tmp_path / "perf.jsonl"
    monkeypatch.setenv(perf.HISTORY_ENV, str(hist))
    assert perf.append_history(_rec("m", mfu=0.4)) == str(hist)
    recs = perf.load_history()
    assert len(recs) == 1
    assert recs[0]["model"] == "m" and recs[0]["mfu"] == 0.4
    assert "date" in recs[0] and "git" in recs[0]   # provenance stamp
    ok, _ = perf.ratchet_check(recs)
    assert ok


def test_no_history_env_suppresses_append(tmp_path, monkeypatch):
    hist = tmp_path / "perf.jsonl"
    monkeypatch.setenv(perf.HISTORY_ENV, str(hist))
    monkeypatch.setenv(perf.NO_HISTORY_ENV, "1")
    assert perf.append_history(_rec("m")) is None
    assert not hist.exists()


def test_ratchet_wins_rail_the_floor_and_drops_fail():
    # a win ratchets the floor up; the next record is judged against it
    ok, msgs = perf.ratchet_check(
        [_rec("m", mfu=0.30), _rec("m", mfu=0.50), _rec("m", mfu=0.50)],
        band=0.9)
    assert ok and any("ok [m]" in m for m in msgs)
    # a drop below best*band fails even though it beats the FIRST record
    ok, msgs = perf.ratchet_check(
        [_rec("m", mfu=0.30), _rec("m", mfu=0.50), _rec("m", mfu=0.40)],
        band=0.9)
    assert not ok
    assert any("FAIL ratchet [m]" in m for m in msgs)


def test_ratchet_noise_band_warns_not_fails():
    ok, msgs = perf.ratchet_check(
        [_rec("m", mfu=0.50), _rec("m", mfu=0.47)], band=0.9)
    assert ok
    assert any(m.startswith("warn [m]") for m in msgs)


def _ratio_rec(model, arm, ratio):
    return {"kind": "perf_ratio", "metric": f"{model}_{arm}_ratio",
            "model": model, "arm": arm, "ratio": ratio,
            "noise": {"lo": ratio * 0.98, "hi": ratio * 1.02}}


def test_perf_ratio_records_rail_per_arm():
    # a measured A/B win (remat_sweep.py arm) becomes a per-(model, arm)
    # floor: later records inside the band pass, a collapse fails
    hist = [_ratio_rec("llama_tiny", "remat_none_vs_full", 1.30),
            _ratio_rec("llama_tiny", "remat_none_vs_full", 1.28)]
    ok, msgs = perf.ratchet_check(hist, band=0.9)
    assert ok
    assert any("warn [llama_tiny/remat_none_vs_full]" in m for m in msgs)
    ok, msgs = perf.ratchet_check(
        hist + [_ratio_rec("llama_tiny", "remat_none_vs_full", 1.0)],
        band=0.9)
    assert not ok
    assert any("FAIL ratchet [llama_tiny/remat_none_vs_full]" in m
               for m in msgs)
    # arms rail independently: one arm's drop does not hide behind
    # another arm's win on the same model
    ok, _ = perf.ratchet_check(
        hist + [_ratio_rec("llama_tiny", "scan_vs_unroll", 1.05)],
        band=0.9)
    assert ok


def test_perf_ratio_records_excluded_from_mfu_grouping():
    # a ratio record carries no MFU/budget — it must not drag a model
    # into (or pollute) the MFU ratchet, and a malformed one FAILs shape
    ok, msgs = perf.ratchet_check(
        [_rec("m", mfu=0.50), _ratio_rec("m", "accum4_vs_plain", 1.06)],
        band=0.9)
    assert ok
    assert any("ok [m]: MFU" in m for m in msgs)
    ok, msgs = perf.ratchet_check(
        [{"kind": "perf_ratio", "model": "m", "ratio": "fast"}])
    assert not ok and any("FAIL shape [perf_ratio]" in m for m in msgs)


def _headline_rec(value, band=None, **extra):
    rec = {"kind": "headline_vs_baseline",
           "metric": "resnet50_images_per_sec_per_chip", "value": value}
    if band is not None:
        rec["band"] = band
    rec.update(extra)
    return rec


def test_headline_vs_baseline_rails_against_parity_not_best():
    # railed against parity (ideal 1.0), NOT best-ever: the r05-style
    # 0.9631 after a 0.9999 passes — cross-session noise, not regression
    # (band derivation: BASELINE.md §"Headline vs_baseline noise band")
    ok, msgs = perf.ratchet_check([_headline_rec(0.9999),
                                   _headline_rec(0.9631)])
    assert ok
    assert any("ok headline" in m for m in msgs)
    # the noise tail warns: 1 − 2×band ≤ value < 1 − band
    ok, msgs = perf.ratchet_check([_headline_rec(0.95)])
    assert ok and any("warn headline" in m for m in msgs)
    # below 1 − 2×band is a real overhead regression
    ok, msgs = perf.ratchet_check([_headline_rec(0.91)])
    assert not ok and any("FAIL headline" in m for m in msgs)


def test_headline_vs_baseline_band_and_shape():
    # the record's own band overrides the default
    ok, msgs = perf.ratchet_check([_headline_rec(0.91, band=0.10)])
    assert ok and any("ok headline" in m for m in msgs)
    # only the LATEST reading is judged, and headline records never join
    # the MFU grouping (they carry a model-free ratio, not a budget)
    ok, msgs = perf.ratchet_check(
        [_headline_rec(0.50), _headline_rec(0.99), _rec("m", mfu=0.5)])
    assert ok
    assert any("ok [m]: MFU" in m for m in msgs)
    # a non-numeric value FAILs shape
    ok, msgs = perf.ratchet_check(
        [{"kind": "headline_vs_baseline", "value": "fast"}])
    assert not ok and any("FAIL shape [headline_vs_baseline]" in m
                          for m in msgs)


def _spec_rec(arm, ratio, **over):
    rec = {"kind": "spec_decode", "metric": "spec_decode_speedup",
           "model": "llama_tiny_serve_cpu8", "arm": arm, "ratio": ratio,
           "spec_k": 4,
           "tokens_per_s": {"plain": 1600.0, "spec": 1600.0 * ratio},
           "noise": {"rounds": 6, "ratio_min": ratio * 0.9,
                     "ratio_max": ratio * 1.1, "spread": ratio * 0.2},
           "steady_compiles": 0}
    rec.update(over)
    return rec


def test_spec_decode_rails_absolute_floors_per_arm():
    # the ISSUE 16 rails are ABSOLUTE per workload arm, not best-ever:
    # repeat_heavy >= 1.5x plain, adversarial >= 0.9x plain
    ok, msgs = perf.ratchet_check(
        [_spec_rec("repeat_heavy", 2.4), _spec_rec("adversarial", 0.96)],
        band=0.9)
    assert ok
    assert any("ok [spec_decode" in m and "repeat_heavy" in m
               for m in msgs)
    ok, msgs = perf.ratchet_check([_spec_rec("adversarial", 0.85)],
                                  band=0.9)
    assert not ok and any("FAIL floor [spec_decode" in m for m in msgs)
    ok, msgs = perf.ratchet_check([_spec_rec("repeat_heavy", 1.3)],
                                  band=0.9)
    assert not ok and any("FAIL floor [spec_decode" in m for m in msgs)


def test_spec_decode_drift_below_best_warns_not_fails():
    # acceptance-driven medians swing wider than the MFU band
    # (measured 1.95-2.52 across honest sessions): below best*band but
    # above the absolute floor is a drift WARNING, not a failure
    ok, msgs = perf.ratchet_check(
        [_spec_rec("repeat_heavy", 2.5), _spec_rec("repeat_heavy", 1.95)],
        band=0.9)
    assert ok
    assert any("warn [spec_decode" in m for m in msgs)


def test_spec_decode_shape_rails():
    # zero steady-state compiles is part of the record's SHAPE: a spec
    # arm that recompiles mid-stream is broken even at a great ratio
    ok, msgs = perf.ratchet_check(
        [_spec_rec("repeat_heavy", 2.4, steady_compiles=1)])
    assert not ok and any("FAIL shape [spec_decode]" in m for m in msgs)
    for bad in (_spec_rec("warp_drive", 2.4),          # unknown arm
                _spec_rec("repeat_heavy", 2.4, spec_k=1),
                _spec_rec("repeat_heavy", 2.4, noise={"rounds": 2}),
                _spec_rec("repeat_heavy", 2.4,
                          tokens_per_s={"plain": 1600.0})):
        ok, msgs = perf.ratchet_check([bad])
        assert not ok and any("FAIL shape [spec_decode]" in m
                              for m in msgs)
    # spec records never join the MFU grouping
    ok, msgs = perf.ratchet_check(
        [_rec("m", mfu=0.5), _spec_rec("adversarial", 0.96)], band=0.9)
    assert ok
    assert any("ok [m]: MFU" in m for m in msgs)


def test_ratchet_band_env_is_honored(monkeypatch):
    monkeypatch.setenv(perf.RATCHET_BAND_ENV, "0.5")
    ok, _ = perf.ratchet_check([_rec("m", mfu=0.50), _rec("m", mfu=0.30)])
    assert ok      # 0.30 >= 0.50 * 0.5


def test_shape_rail_missing_category_and_sum_breach():
    ok, msgs = perf.ratchet_check([_rec("m", drop_key="host_gap")])
    assert not ok and any("FAIL shape" in m and "host_gap" in m
                          for m in msgs)
    ok, msgs = perf.ratchet_check([_rec("m", rel_err=0.2)])
    assert not ok and any("FAIL shape" in m and "rel_err" in m
                          for m in msgs)


def test_mfu_free_records_are_shape_railed_only():
    # CPU-mesh records carry no MFU (peak unknown): shape rail still
    # applies, the ratchet does not — and says so
    ok, msgs = perf.ratchet_check([_rec("cpu_model")])
    assert ok
    assert any("shape-railed only" in m for m in msgs)


# --- diff: name the category AND the op --------------------------------------

def _ab_records():
    keys = {k: 0.0 for k in perf.BUDGET_KEYS}
    a = _rec("synth", wall=0.080,
             budget={**keys, "matmul/conv": 0.050, "gather/scatter": 0.010},
             top_ops={"gather/scatter": [
                 {"op": "gather.7", "ms_per_step": 8.0, "share": 0.1,
                  "n": 4}]})
    b = _rec("synth", wall=0.102,
             budget={**keys, "matmul/conv": 0.052, "gather/scatter": 0.030},
             top_ops={"gather/scatter": [
                 {"op": "gather.7", "ms_per_step": 25.0, "share": 0.25,
                  "n": 4},
                 {"op": "scatter.9", "ms_per_step": 5.0, "share": 0.05,
                  "n": 2}]})
    return a, b


def test_diff_names_regressed_category_and_top_op():
    a, b = _ab_records()
    out = perf.diff_records(a, b)
    assert out["regressed_category"] == "gather/scatter"
    assert out["top_op"] == "gather.7"     # ranked by GROWTH, not size
    assert out["wall_delta_s_per_step"] == pytest.approx(0.022)
    assert out["category_deltas_s_per_step"]["gather/scatter"] == \
        pytest.approx(0.020)


def test_cli_show_and_diff(tmp_path, capsys):
    hist = tmp_path / "perf.jsonl"
    a, b = _ab_records()
    with open(hist, "w") as f:
        f.write(json.dumps(a) + "\n" + json.dumps(b) + "\n")
    assert perf.main(["--history", str(hist), "show"]) == 0
    assert "step budget [synth]" in capsys.readouterr().out
    assert perf.main(["--history", str(hist), "diff", "0", "1",
                      "--json"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "perf_diff"
    assert out["regressed_category"] == "gather/scatter"
    assert out["top_op"] == "gather.7"
    # model:idx selectors hit the same records
    assert perf.main(["--history", str(hist), "diff", "synth:0",
                      "synth:-1", "--json"]) == 0


def test_cli_check_exit_codes(tmp_path, capsys):
    hist = tmp_path / "perf.jsonl"
    with open(hist, "w") as f:
        f.write(json.dumps(_rec("m", mfu=0.5)) + "\n")
    assert perf.main(["--history", str(hist), "check"]) == 0
    capsys.readouterr()
    with open(hist, "a") as f:
        f.write(json.dumps(_rec("m", mfu=0.3)) + "\n")
    assert perf.main(["--history", str(hist), "check", "--json"]) == 1
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] is False
    assert any("FAIL ratchet" in m for m in out["messages"])
    # empty history is ok (fresh checkout, nothing recorded yet)
    assert perf.main(["--history", str(tmp_path / "none.jsonl"),
                      "check"]) == 0


def test_cli_subprocess_entry_point(tmp_path):
    """The operator-facing spelling: ``python -m horovod_tpu.tools.perf``
    must exit 1 on a ratchet breach (the CI rail's contract)."""
    hist = tmp_path / "perf.jsonl"
    with open(hist, "w") as f:
        f.write(json.dumps(_rec("m", mfu=0.5)) + "\n")
        f.write(json.dumps(_rec("m", mfu=0.3)) + "\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.tools.perf",
         "--history", str(hist), "check"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert out.returncode == 1, out.stdout + out.stderr[-1000:]
    assert "FAIL ratchet" in out.stdout


# --- FLOPs registry + MFU proxy ----------------------------------------------

def test_mfu_proxy_math_and_fallbacks(monkeypatch):
    assert perf.mfu_proxy(1e12, 1.0, peak=2e12) == pytest.approx(0.5)
    # unknown peak: HOROVOD_PEAK_FLOPS env wins, else 1e12 (reads as
    # achieved TFLOP/s)
    monkeypatch.setenv("HOROVOD_PEAK_FLOPS", "5e11")
    assert perf.mfu_proxy(1e12, 1.0, peak=float("nan")) == pytest.approx(2.0)
    monkeypatch.delenv("HOROVOD_PEAK_FLOPS")
    assert perf.mfu_proxy(1e12, 1.0, peak=float("nan")) == pytest.approx(1.0)


def test_register_step_flops_rejects_garbage():
    for bad in (None, float("nan"), float("inf"), 0.0, -5.0):
        perf.register_step_flops(bad, what="perf_garbage")
    assert perf.registered_step_flops("perf_garbage") is None
    perf.register_step_flops(3e9, what="perf_garbage")
    assert perf.registered_step_flops("perf_garbage") == 3e9


def test_device_peak_flops_table():
    class _Dev:
        device_kind = "TPU v5p"
    assert perf.device_peak_flops(_Dev()) == 459e12
    _Dev.device_kind = "weird accelerator"
    import math
    assert math.isnan(perf.device_peak_flops(_Dev()))


# --- live gauges through the watchdog ----------------------------------------

def test_step_span_sets_wall_and_data_wait_gauges():
    mon = watchdog.monitor()
    with mon.step_span("perf_span"):
        time.sleep(0.002)
    with mon.step_span("perf_span"):
        pass
    reg = T.active().registry
    wall = reg.gauge_value("hvd_step_wall_seconds", what="perf_span")
    assert wall is not None and wall >= 0.0
    # the second span's begin sees the first span's end: the gap is the
    # host-side data wait
    wait = reg.gauge_value("hvd_step_data_wait_seconds", what="perf_span")
    assert wait is not None and wait >= 0.0


def test_monitored_call_publishes_mfu_proxy_gauge(monkeypatch):
    monkeypatch.setenv("HOROVOD_PEAK_FLOPS", "1e12")
    mon = watchdog.monitor()
    perf.register_step_flops(2e9, what="perf_mfu")
    assert mon.monitored_call(lambda: 7, what="perf_mfu") == 7
    reg = T.active().registry
    assert reg.gauge_value("hvd_step_wall_seconds",
                           what="perf_mfu") is not None
    proxy = reg.gauge_value("hvd_step_mfu_proxy", what="perf_mfu")
    assert proxy is not None and proxy > 0.0
    # no registered FLOPs for this signature -> no proxy gauge, no error
    assert mon.monitored_call(lambda: 8, what="perf_noflops") == 8
    assert reg.gauge_value("hvd_step_mfu_proxy",
                           what="perf_noflops") is None


# --- coordinator /metrics fleet rollup ---------------------------------------

def test_metrics_endpoint_serves_step_gauges_with_mean_rollup():
    """GET /metrics must carry the hvd_step_* gauges per rank AND a fleet
    rollup line — gauges AVERAGE across ranks (a summed step-wall would
    read as a slowdown every time a worker joins)."""
    key = _secret.make_secret_key()
    svc = CoordinatorService(key, bind_host="127.0.0.1")
    try:
        client = CoordinatorClient(f"127.0.0.1:{svc.port}", key)
        assert client.push_metrics(0, {"c": {}, "g": {
            'hvd_step_wall_seconds{what="t"}': 0.1,
            'hvd_step_mfu_proxy{what="t"}': 0.4,
            'hvd_step_data_wait_seconds{what="t"}': 0.01}})
        assert client.push_metrics(1, {"c": {}, "g": {
            'hvd_step_wall_seconds{what="t"}': 0.3,
            'hvd_step_mfu_proxy{what="t"}': 0.6,
            'hvd_step_data_wait_seconds{what="t"}': 0.03}})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{svc.port}/metrics", timeout=10) as resp:
            text = resp.read().decode()
    finally:
        svc.close()
    s = parse_prometheus(text)["samples"]
    assert s['hvd_step_wall_seconds{rank="0",what="t"}'] == 0.1
    assert s['hvd_step_wall_seconds{rank="1",what="t"}'] == 0.3
    assert s['hvd_step_wall_seconds{what="t"}'] == pytest.approx(0.2)
    assert s['hvd_step_mfu_proxy{what="t"}'] == pytest.approx(0.5)
    assert s['hvd_step_data_wait_seconds{what="t"}'] == pytest.approx(0.02)
    assert parse_prometheus(text)["types"]["hvd_step_mfu_proxy"] == "gauge"


def test_render_rollup_averages_gauges_sums_counters():
    per_rank = {
        0: {"c": {"hvd_steps_total": 10.0},
            "g": {"hvd_step_wall_seconds": 0.2}},
        1: {"c": {"hvd_steps_total": 30.0},
            "g": {"hvd_step_wall_seconds": 0.4}},
    }
    s = parse_prometheus(T.render_prometheus(per_rank))["samples"]
    assert s["hvd_steps_total"] == 40.0                       # summed
    assert s["hvd_step_wall_seconds"] == pytest.approx(0.3)   # averaged


# --- overhead guard (slow: excluded from tier-1) -----------------------------

@pytest.mark.slow
def test_perf_gauges_overhead_within_bound():
    """Full perf instrumentation (wall + data-wait + MFU-proxy gauges,
    FLOPs registered) vs telemetry-off A/B on the CPU mesh: median of
    per-round ratios ≤ 1.02 — the same bound and interleaved-rounds
    methodology as test_telemetry_overhead_within_bound (the perf gauges
    add two set_gauge calls and one locked dict lookup per step)."""
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh
    from common import slope_time_paired

    import horovod_tpu as hvd
    from horovod_tpu.optimizer import distributed
    from horovod_tpu.train import create_train_state, make_train_step

    class Wide(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            for _ in range(3):
                x = nn.relu(nn.Dense(512)(x))
            return nn.Dense(10)(x)

    def _xent(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    rng = np.random.RandomState(0)
    B = 512
    images = jnp.asarray(rng.randn(B, 8, 8, 4).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 10, size=(B,)))
    mesh1 = Mesh(np.asarray(jax.devices()[:1]), (hvd.RANK_AXIS,))
    mon = watchdog.monitor()

    def build(instrumented):
        model = Wide()
        dopt = distributed(optax.sgd(0.1))
        state = create_train_state(model, jax.random.PRNGKey(0),
                                   images[:1], dopt)
        step = make_train_step(model, dopt, _xent, mesh=mesh1,
                               axis_name=hvd.RANK_AXIS, sentinel=False)
        box = {"state": state}

        def fn(k):
            if instrumented:
                T.configure(enabled=True)
                perf.register_step_flops(1e9, what="bench_step")
            else:
                T.configure(enabled=False)
                perf.reset_registered_flops()
            for _ in range(k):
                with mon.step_span("bench_step"):
                    box["state"], loss = step(box["state"], images, labels)
            jax.block_until_ready(loss)
        return fn

    _slopes, rounds = slope_time_paired(
        {"off": build(False), "on": build(True)},
        s_short=6, s_long=24, rounds=9, return_rounds=True)
    ratios = sorted(r["on"] / r["off"] for r in rounds)
    median = ratios[len(ratios) // 2]
    assert median <= 1.02, f"perf gauge overhead ratio {median:.4f}"
