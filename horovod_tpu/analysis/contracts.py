"""Compiled-program contract registry (hvd-verify, ISSUE 17).

Every shipped program family registers the machine-checkable invariants
its performance story depends on — the graph-level facts the reference
stack enforces at runtime via the controller's response protocol
(``horovod/common/controller.cc``: coordinated checks that every rank
submitted the same collective over the same payload).  Here the checks
run AHEAD of time against :mod:`horovod_tpu.analysis.hlo` summaries of
the lowered stablehlo / optimized HLO:

- fusion-threshold collective counts + donation (``dp-step-fusion``),
- accumulation's single-allreduce discipline (``dp-step-accum``),
- bench-arm graph parity (``bench-arms-parity``),
- deferral inertness at ``every=1`` and probe DCE
  (``gspmd-deferred-every1`` / ``gspmd-deferred-programs``),
- ppermute topology × payload × hop-count for the adasum butterfly,
  ring attention, and the pipeline handoff,
- the hierarchical DCN-hop compression byte accounting,
- tensor-parallel decode/verify/prefill wire contracts at tp ∈
  {1, 2, 4, 8} (``2·n_layers`` activation all-reduces and NOTHING else),
- the DLRM entry-layout pin (zero table-shaped transpose/copy).

Builds are memoized per process and cache ONLY summaries and plain
numbers (never live device arrays), so the thin pytest drivers
(tests/test_wire_contracts.py, test_fusion.py, test_bench_parity.py,
test_step_builder.py) and the full ``--contracts`` matrix share one
build per family.  Violations surface as ``contract-<family>`` ERROR
findings through the same :class:`~.findings.Finding` pipeline as the
lint and jaxpr engines (``--json`` / ``--sarif`` included).
"""

import os
from typing import Any, Callable, Dict, List, NamedTuple, Optional

from .findings import Finding, Severity


class Contract(NamedTuple):
    """One registered program family.

    ``build()`` traces/lowers/compiles the family's programs and returns
    a plain dict of :class:`~.hlo.HloSummary` objects and numbers;
    ``verify(built)`` returns a list of human-readable problem strings
    (empty = contract holds).  ``where`` is the repo-relative source
    file the contract guards — findings anchor there.
    """
    family: str
    description: str
    where: str
    build: Callable[[], Dict[str, Any]]
    verify: Callable[[Dict[str, Any]], List[str]]


_REGISTRY: "Dict[str, Contract]" = {}
_CACHE: Dict[str, Dict[str, Any]] = {}
_PARTS: Dict[str, Any] = {}          # memoized model params (tiny, CPU)


def register(contract: Contract) -> Contract:
    _REGISTRY[contract.family] = contract
    return contract


def unregister(family: str) -> None:
    _REGISTRY.pop(family, None)
    _CACHE.pop(family, None)


def families() -> List[str]:
    return list(_REGISTRY)


def get(family: str) -> Contract:
    return _REGISTRY[family]


def clear_cache() -> None:
    _CACHE.clear()


def summaries(family: str) -> Dict[str, Any]:
    """The family's (memoized) build output."""
    if family not in _CACHE:
        _CACHE[family] = _REGISTRY[family].build()
    return _CACHE[family]


def check_family(family: str) -> List[Finding]:
    """Run one family's contract; each problem → one ERROR finding."""
    c = _REGISTRY[family]
    try:
        built = summaries(family)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:                     # noqa: BLE001 — reported
        return [Finding(
            f"contract-{family}", Severity.ERROR, c.where, 1,
            f"contract build failed: {type(e).__name__}: {e}",
            {"family": family})]
    return [Finding(f"contract-{family}", Severity.ERROR, c.where, 1,
                    problem, {"family": family})
            for problem in c.verify(built)]


def run_contracts(only: Optional[List[str]] = None) -> List[Finding]:
    """Run the whole matrix (or ``only`` the named families)."""
    names = list(only) if only else families()
    unknown = [n for n in names if n not in _REGISTRY]
    if unknown:
        raise KeyError(
            f"unknown contract families {unknown}; "
            f"registered: {families()}")
    _ensure_devices()
    out: List[Finding] = []
    for name in names:
        out.extend(check_family(name))
    return out


def _ensure_devices(n: int = 8) -> None:
    """The matrix traces 8-way meshes — same incantation as tier-1."""
    import jax
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # sitecustomize pre-registers the TPU backend; the env var alone
        # does not switch (CLAUDE.md) — mirror tests/conftest.py.
        jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n:
        raise SystemExit(
            f"hvd-analyze --contracts needs >= {n} devices "
            f"(got {len(jax.devices())}); run under\n"
            "  JAX_PLATFORMS=cpu "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")


def _reinit(mesh=None, config=None):
    """Fresh hvd engine state for builds that trace through hvd ops."""
    import horovod_tpu as hvd
    hvd.shutdown()
    kw = {}
    if mesh is not None:
        kw["mesh"] = mesh
    if config is not None:
        kw["config"] = config
    hvd.init(**kw)


# --------------------------------------------------------------- helpers

def _mlp64():
    """test_fusion's MLP (width 64, depth 4) — 10 grad leaves."""
    import optax
    from flax import linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            for _ in range(4):
                x = nn.relu(nn.Dense(64)(x))
            return nn.Dense(4)(x)

    def loss_fn(out, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            out, labels).mean()

    return MLP(), loss_fn


def _xent(logits, labels):
    import optax
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


def _mlp_small_parts(batch=32):
    """test_step_builder's 16→10 MLP over 4×4×1 images."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from ..optimizer import distributed
    from ..train import create_train_state

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            return nn.Dense(10)(nn.relu(nn.Dense(16)(x)))

    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(batch, 4, 4, 1).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 10, size=(batch,)))
    model = MLP()
    dopt = distributed(optax.sgd(0.1))
    state = create_train_state(model, jax.random.PRNGKey(0), images[:1],
                               dopt)
    return model, dopt, state, images, labels


def _llama8_parts():
    """Memoized decode-contract Llama: heads widened to 8/8 so every
    tp ∈ {1, 2, 4, 8} divides (llama_tiny's 4/2 rejects tp=4 at
    ``validate_tp``)."""
    if "llama8" not in _PARTS:
        import dataclasses
        import jax
        import jax.numpy as jnp
        from flax import linen as nn
        from ..models.llama import Llama, llama_tiny
        cfg = dataclasses.replace(llama_tiny(), n_heads=8, n_kv_heads=8)
        model = Llama(cfg)
        params = nn.meta.unbox(jax.jit(model.init)(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 16), jnp.int32)))["params"]
        _PARTS["llama8"] = (cfg, params)
    return _PARTS["llama8"]


def _mixtral8_parts():
    if "mixtral8" not in _PARTS:
        import dataclasses
        import jax
        import jax.numpy as jnp
        from flax import linen as nn
        from ..models.mixtral import Mixtral, mixtral_tiny
        cfg = dataclasses.replace(mixtral_tiny(), n_heads=8, n_kv_heads=8,
                                  capacity_factor=8.0)
        model = Mixtral(cfg)
        params = nn.meta.unbox(jax.jit(model.init)(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 16), jnp.int32)))["params"]
        _PARTS["mixtral8"] = (cfg, params)
    return _PARTS["mixtral8"]


def _tp_step_summaries(step_kind: str, tps) -> Dict[str, Any]:
    """Lower the tp decode/verify/prefill step per tp and per model kind,
    returning stablehlo summaries keyed ``(kind, tp)``."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from ..models import decode as MD
    from ..parallel import create_mesh
    from .hlo import summarize_stablehlo

    S, K, T, bs, bmax = 2, 4, 8, 4, 8
    out: Dict[str, Any] = {"summaries": {}}
    kinds = ("llama", "mixtral") if 8 in tps and len(tps) == 1 \
        else ("llama",)
    for kind in kinds:
        cfg, params = (_llama8_parts() if kind == "llama"
                       else _mixtral8_parts())
        out["n_layers"] = cfg.n_layers
        out["dim"] = cfg.dim
        for tp in tps:
            mesh = create_mesh({"tp": tp}, devices=jax.devices()[:tp])
            kp, vp = MD.init_kv_pools(cfg, 16, bs)
            if tp == 8:
                nd = NamedSharding(mesh, MD.kv_pool_spec())
                kp, vp = jax.device_put(kp, nd), jax.device_put(vp, nd)
            if step_kind == "decode":
                step = jax.jit(MD.make_decode_step_tp(cfg, bs, mesh))
                lowered = step.lower(
                    params, kp, vp, jnp.zeros((S,), jnp.int32),
                    jnp.zeros((S,), jnp.int32),
                    jnp.zeros((S, bmax), jnp.int32),
                    jnp.zeros((S,), jnp.bool_))
            elif step_kind == "verify":
                step = jax.jit(MD.make_verify_step_tp(cfg, bs, mesh))
                lowered = step.lower(
                    params, kp, vp, jnp.zeros((S, K), jnp.int32),
                    jnp.zeros((S,), jnp.int32),
                    jnp.zeros((S, bmax), jnp.int32),
                    jnp.zeros((S,), jnp.bool_))
            else:                                       # prefill
                step = jax.jit(MD.make_prefill_tp(cfg, bs, mesh))
                lowered = step.lower(
                    params, kp, vp, jnp.zeros((1, T), jnp.int32),
                    jnp.zeros((T // bs,), jnp.int32))
            out["summaries"][(kind, tp)] = summarize_stablehlo(
                lowered.as_text())
    return out


def _verify_tp_family(built, act_bytes: int) -> List[str]:
    """Shared decode/verify/prefill wire contract: exactly 2·n_layers
    activation all_reduces over the full tp group, nothing else."""
    problems = []
    n = 2 * built["n_layers"]
    for (kind, tp), s in sorted(built["summaries"].items(),
                                key=lambda kv: (kv[0][0], kv[0][1])):
        tag = f"{kind} tp={tp}"
        if s.ops() != ["all_reduce"] * n:
            problems.append(
                f"{tag}: collective stream must be exactly {n} "
                f"all_reduces, got {s.ops()}")
            continue
        for c in s.collectives:
            if c.group_size != tp:
                problems.append(
                    f"{tag}: all_reduce group_size {c.group_size} != "
                    f"tp {tp} (line {c.line})")
            if c.operand_bytes != act_bytes:
                problems.append(
                    f"{tag}: all_reduce operand {c.operand_bytes} B != "
                    f"activation {act_bytes} B (line {c.line})")
            if c.ring_bytes != 2 * (tp - 1) / tp * act_bytes:
                problems.append(
                    f"{tag}: ring wire bytes {c.ring_bytes} off the "
                    f"2(g-1)/g formula (line {c.line})")
        if s.permutes():
            problems.append(
                f"{tag}: {len(s.permutes())} collective_permute(s) — the "
                f"KV pool must stay head-sharded, zero permutes")
    return problems


# ------------------------------------------------------ family: fusion

def _build_dp_step_fusion():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from ..collectives.ops import fusion_threshold_override
    from ..optimizer import distributed
    from ..train import create_train_state, make_train_step
    from .hlo import summarize_stablehlo

    _reinit()
    model, loss_fn = _mlp64()
    xs = jnp.asarray(
        np.random.RandomState(0).randn(16, 8).astype(np.float32))
    ys = jnp.asarray(np.random.RandomState(1).randint(0, 4, size=(16,)))
    out = {}
    # Fresh step per threshold: jit caches lowerings, the override only
    # matters on the first trace of a given step object (test_fusion).
    for key, thr in (("mono", 1 << 62), ("bucketed", 20 << 10),
                     ("per_leaf", 0)):
        opt = distributed(optax.sgd(0.1))
        state = create_train_state(model, jax.random.PRNGKey(0), xs[:2],
                                   opt, broadcast=False)
        step = make_train_step(model, opt, loss_fn, donate=True)
        with fusion_threshold_override(thr):
            out[key] = summarize_stablehlo(
                step.lower(state, xs, ys).as_text())
    return out


def _verify_dp_step_fusion(b) -> List[str]:
    problems = []
    n_mono = b["mono"].count("all_reduce")
    n_buck = b["bucketed"].count("all_reduce")
    n_per = b["per_leaf"].count("all_reduce")
    if n_mono != 2:
        problems.append(
            f"monolithic threshold must fuse to 2 all_reduces (grads + "
            f"loss pmean), got {n_mono}")
    if n_per != 11:
        problems.append(
            f"threshold 0 must emit one all_reduce per grad leaf + loss "
            f"pmean = 11, got {n_per}")
    if not (n_mono < n_buck < n_per):
        problems.append(
            f"bucketed count must sit strictly between monolithic and "
            f"per-leaf: {n_mono} < {n_buck} < {n_per} fails")
    for key in ("mono", "bucketed", "per_leaf"):
        if not b[key].donated:
            problems.append(
                f"buffer donation lost at the {key} fusion threshold")
    return problems


# ------------------------------------------------------- family: accum

def _build_dp_step_accum():
    from ..train import make_train_step
    from .hlo import summarize_optimized

    _reinit()
    model, dopt, state, images, labels = _mlp_small_parts()
    plain = make_train_step(model, dopt, _xent, donate=False)
    accum = make_train_step(model, dopt, _xent, donate=False,
                            accum_steps=2)
    donated = make_train_step(model, dopt, _xent, donate=True,
                              accum_steps=2)
    return {key: summarize_optimized(
                step.lower(state, images, labels).compile().as_text())
            for key, step in (("plain", plain), ("accum", accum),
                              ("donated", donated))}


def _verify_dp_step_accum(b) -> List[str]:
    problems = []
    n_plain = b["plain"].count("all_reduce")
    n_accum = b["accum"].count("all_reduce")
    if n_accum != n_plain:
        problems.append(
            f"accum_steps=2 changed the compiled all-reduce count "
            f"({n_accum} vs plain {n_plain}) — a collective leaked "
            f"inside the microbatch loop (lint-accum-psum-order)")
    if not b["donated"].donated:
        problems.append(
            "donate=True accumulation step lost input_output_alias — "
            "the scan formulation forfeited buffer donation")
    if b["accum"].donated:
        problems.append(
            "donate=False accumulation step unexpectedly aliases "
            "buffers — donation flag is not being honored")
    return problems


# ------------------------------------------------- family: bench parity

def _build_bench_arms_parity():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import horovod_tpu as hvd
    from ..models import ResNetTiny
    from ..optimizer import distributed
    from ..train import create_train_state, make_train_step
    from .hlo import summarize_optimized

    _reinit()
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(4, 32, 32, 3).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 1000, size=(4,)))
    mesh1 = jax.sharding.Mesh(np.asarray(jax.devices()[:1]),
                              (hvd.RANK_AXIS,))

    model = ResNetTiny(num_classes=1000, axis_name=hvd.RANK_AXIS,
                       dtype=jnp.float32)
    dopt = distributed(optax.sgd(0.1, momentum=0.9))
    state = create_train_state(model, jax.random.PRNGKey(0), images[:1],
                               dopt)
    step_hvd = make_train_step(model, dopt, _xent, scan_steps=4,
                               mesh=mesh1, donate=False)

    model_p = ResNetTiny(num_classes=1000, axis_name=None,
                         dtype=jnp.float32)
    popt = optax.sgd(0.1, momentum=0.9)
    pstate = create_train_state(model_p, jax.random.PRNGKey(0),
                                images[:1], popt, broadcast=False)
    step_plain = make_train_step(model_p, popt, _xent, scan_steps=4,
                                 mesh=mesh1, donate=False)
    return {
        "hvd": summarize_optimized(
            step_hvd.lower(state, images, labels).compile().as_text()),
        "plain": summarize_optimized(
            step_plain.lower(pstate, images, labels).compile().as_text()),
    }


def _verify_bench_arms_parity(b) -> List[str]:
    problems = []
    for arm in ("hvd", "plain"):
        if b[arm].ops():
            problems.append(
                f"bench {arm} arm compiled with collectives on the "
                f"1-device mesh: {b[arm].ops()} — force_axis_size1 must "
                f"collapse everything to identity")
    return problems


# -------------------------------------------- family: deferred every=1

def _collective_sig(summary):
    return sorted((c.op, c.operand_bytes, c.groups)
                  for c in summary.collectives)


def _build_gspmd_deferred_every1():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models.llama import LOGICAL_RULES
    from ..models.mixtral import Mixtral, mixtral_tiny
    from ..optimizer import deferred_pair
    from ..parallel import create_mesh
    from ..train import (create_gspmd_train_state,
                         make_gspmd_deferred_train_step,
                         make_gspmd_train_step)
    from .hlo import summarize_optimized

    cfg = mixtral_tiny()
    mesh = create_mesh({"dp": 8})
    model = Mixtral(cfg)
    pair = deferred_pair(1e-3, every=1)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 16)))
    state = create_gspmd_train_state(model, pair.apply,
                                     jax.random.PRNGKey(0), tokens, mesh,
                                     LOGICAL_RULES)
    standard = make_gspmd_train_step(model, pair.apply, mesh,
                                     LOGICAL_RULES, donate=False)
    deferred = make_gspmd_deferred_train_step(model, pair, mesh,
                                              LOGICAL_RULES, donate=False)
    return {
        "standard": summarize_optimized(
            standard.lower(state, tokens).compile().as_text()),
        "deferred": summarize_optimized(
            deferred.lower_apply(state, tokens).compile().as_text()),
    }


def _verify_gspmd_deferred_every1(b) -> List[str]:
    problems = []
    sig_std = _collective_sig(b["standard"])
    sig_dfr = _collective_sig(b["deferred"])
    if not sig_std:
        problems.append(
            "8-way DP standard step compiled with NO collectives — the "
            "parity comparison is vacuous")
    if sig_dfr != sig_std:
        problems.append(
            f"deferred(every=1) apply program's collective signature "
            f"diverged from the standard step: {len(sig_dfr)} vs "
            f"{len(sig_std)} entries — the deferral is no longer "
            f"graph-level inert at k=1")
    return problems


# ------------------------------------------- family: deferred programs

def _build_gspmd_deferred_programs():
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..core.sentinel import Sentinel
    from ..optimizer import deferred_pair
    from ..parallel import create_mesh
    from ..train import (create_gspmd_train_state,
                         make_gspmd_deferred_train_step, next_token_loss)
    from .hlo import summarize_optimized

    class TinyLM(nn.Module):
        vocab: int = 13

        @nn.compact
        def __call__(self, tokens):
            x = nn.Embed(self.vocab, 8)(tokens)
            return nn.Dense(self.vocab)(nn.relu(nn.Dense(8)(x)))

    mesh = create_mesh({"dp": 8})
    model = TinyLM()
    pair = deferred_pair(1e-2, every=2)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(1, 13, size=(8, 6)))
    state = create_gspmd_train_state(model, pair.apply,
                                     jax.random.PRNGKey(0), tokens, mesh,
                                     ())
    s = Sentinel(max_skips=3, max_rollbacks=1,
                 rollback_fn=lambda st: st, evict_fn=lambda a: None)
    step = make_gspmd_deferred_train_step(
        model, pair, mesh, (),
        loss_fn=lambda lg, tk: next_token_loss(lg, tk),
        data_axes=("dp",), donate=False, sentinel=s)
    return {
        "apply": summarize_optimized(
            step.lower_apply(state, tokens).compile().as_text()),
        "skip": summarize_optimized(
            step.lower_skip(state, tokens).compile().as_text()),
        "probe": summarize_optimized(
            step.lower_probe(state, tokens).compile().as_text()),
    }


def _verify_gspmd_deferred_programs(b) -> List[str]:
    problems = []
    for key in ("apply", "skip", "probe"):
        if b[key].n_lines == 0:
            problems.append(f"{key} program compiled to empty HLO")
    if b["probe"].fusion_count > b["apply"].fusion_count:
        problems.append(
            f"probe program has MORE fusions than apply "
            f"({b['probe'].fusion_count} > {b['apply'].fusion_count}) — "
            f"the optimizer.update DCE regressed")
    if b["probe"].n_lines >= b["apply"].n_lines:
        problems.append(
            f"probe program is not strictly smaller than apply "
            f"({b['probe'].n_lines} vs {b['apply'].n_lines} lines) — "
            f"probe DCE regressed")
    return problems


# ------------------------------------------------ family: adasum ring pp

def _build_adasum_butterfly():
    import jax
    import jax.numpy as jnp
    import horovod_tpu as hvd
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from ..collectives.adasum import _butterfly
    from .hlo import summarize_stablehlo

    _reinit()
    x = jnp.ones((64,), jnp.float32)
    f = jax.jit(shard_map(lambda t: _butterfly(t, hvd.RANK_AXIS),
                          mesh=hvd.mesh(), in_specs=P(), out_specs=P(),
                          check_vma=False))
    return {"summary": summarize_stablehlo(f.lower(x).as_text()),
            "n": 8, "payload": 64 * 4}


def _verify_adasum_butterfly(b) -> List[str]:
    problems = []
    s, n, payload = b["summary"], b["n"], b["payload"]
    perms = s.permutes()
    if len(perms) != 3:                           # log2(8)
        return [f"butterfly must lower to log2({n})=3 permutes, "
                f"got {len(perms)}"]
    for d, c in zip((1, 2, 4), perms):
        if c.operand_bytes != payload or c.ring_bytes != payload:
            problems.append(
                f"butterfly round d={d} must move the FULL working "
                f"buffer ({payload} B), got operand={c.operand_bytes} "
                f"ring={c.ring_bytes}")
        if set(c.pairs) != {(r, r ^ d) for r in range(n)}:
            problems.append(
                f"butterfly round d={d} lost the XOR-partner topology: "
                f"{sorted(c.pairs)}")
        if c.n_links != n:
            problems.append(
                f"butterfly round d={d}: {c.n_links} links != {n}")
    return problems


def _build_ring_attention():
    import jax
    import jax.numpy as jnp
    import horovod_tpu as hvd
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from ..parallel.ring import ring_attention
    from .hlo import summarize_stablehlo

    _reinit()
    B, T_local, H, D = 1, 4, 2, 8
    q = jnp.ones((B, 8 * T_local, H, D), jnp.float32)
    f = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, hvd.RANK_AXIS,
                                       impl="jnp"),
        mesh=hvd.mesh(),
        in_specs=(P(None, hvd.RANK_AXIS), P(None, hvd.RANK_AXIS),
                  P(None, hvd.RANK_AXIS)),
        out_specs=P(None, hvd.RANK_AXIS), check_vma=False))
    return {"summary": summarize_stablehlo(f.lower(q, q, q).as_text()),
            "n": 8, "shard_bytes": B * T_local * H * D * 4}


def _verify_ring_attention(b) -> List[str]:
    problems = []
    s, n, shard_bytes = b["summary"], b["n"], b["shard_bytes"]
    perms = s.permutes()
    if len(perms) != 2:
        problems.append(
            f"ring attention must rotate exactly K and V (2 permutes "
            f"per trip), got {len(perms)}")
    ring = {(r, (r + 1) % n) for r in range(n)}
    for c in perms:
        if c.operand_bytes != shard_bytes:
            problems.append(
                f"KV rotation payload {c.operand_bytes} B != one local "
                f"shard {shard_bytes} B (line {c.line})")
        if set(c.pairs) != ring:
            problems.append(
                f"KV rotation left the +1 ring: {sorted(c.pairs)}")
    others = [c for c in s.collectives
              if c.op != "collective_permute"]
    if others:
        problems.append(
            f"non-permute collectives ride the ring-attention step: "
            f"{[c.op for c in others]}")
    return problems


def _build_pipeline_handoff():
    import jax
    import jax.numpy as jnp
    import horovod_tpu as hvd
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from ..parallel.pipeline import pipeline
    from .hlo import summarize_stablehlo

    _reinit()
    M, F = 4, 16
    x = jnp.ones((M, 2, F), jnp.float32)
    params = jnp.ones((F, F), jnp.float32)

    def stage(p, t):
        return jnp.tanh(t @ p)

    f = jax.jit(shard_map(
        lambda p, t: pipeline(stage, p, t, hvd.RANK_AXIS),
        mesh=hvd.mesh(), in_specs=(P(), P()), out_specs=P(),
        check_vma=False))
    return {"summary": summarize_stablehlo(
                f.lower(params, x).as_text()),
            "n": 8, "act_bytes": 2 * F * 4}


def _verify_pipeline_handoff(b) -> List[str]:
    problems = []
    s, n, act = b["summary"], b["n"], b["act_bytes"]
    perms = s.permutes()
    if len(perms) != 1:
        return [f"one handoff permute per schedule tick, "
                f"got {len(perms)}"]
    c = perms[0]
    if c.operand_bytes != act:
        problems.append(
            f"handoff payload {c.operand_bytes} B != one microbatch "
            f"activation {act} B")
    if set(c.pairs) != {(r, (r + 1) % n) for r in range(n)}:
        problems.append(
            f"handoff left the stage i -> i+1 ring: {sorted(c.pairs)}")
    return problems


# ------------------------------------------- family: hierarchical bf16

def _build_hierarchical_allreduce():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    import horovod_tpu as hvd
    from ..collectives import ops
    from ..core.config import Config
    from .hlo import summarize_stablehlo

    out = {"B": 64 * 4}
    x = jnp.asarray(
        np.random.RandomState(5).randn(8, 64).astype(np.float32))
    for key, name in (("off", "none"), ("on", "bf16")):
        m2 = Mesh(np.array(jax.devices()).reshape(2, 4),
                  ("cross", "intra"))
        _reinit(mesh=m2, config=Config(
            hierarchical_allreduce=True, hierarchical_compression=name))
        f = shard_map(lambda t: ops.allreduce(t, hvd.Sum), mesh=m2,
                      in_specs=P(("cross", "intra")),
                      out_specs=P(("cross", "intra")))
        out[key] = summarize_stablehlo(jax.jit(f).lower(x).as_text())
    return out


def _one(summary, op):
    cs = [c for c in summary.collectives if c.op == op]
    return cs[0] if len(cs) == 1 else None


def _verify_hierarchical_allreduce(b) -> List[str]:
    problems = []
    B = b["B"]
    for key in ("off", "on"):
        if set(b[key].ops()) != {"reduce_scatter", "all_reduce",
                                 "all_gather"}:
            return [f"hierarchical ({key}) must lower to exactly "
                    f"reduce_scatter + cross all_reduce + all_gather, "
                    f"got {b[key].ops()}"]
    ar_off, ar_on = _one(b["off"], "all_reduce"), _one(b["on"],
                                                       "all_reduce")
    if ar_off.operand_bytes != B // 4:
        problems.append(
            f"uncompressed DCN hop must carry B/n_intra = {B // 4} B "
            f"f32, got {ar_off.operand_bytes}")
    if ar_on.operand_bytes != B // 4 // 2:
        problems.append(
            f"bf16 compression must halve ONLY the DCN hop to "
            f"{B // 8} B, got {ar_on.operand_bytes}")
    for key in ("off", "on"):
        rs, ag = _one(b[key], "reduce_scatter"), _one(b[key],
                                                      "all_gather")
        if rs.operand_bytes != B or ag.result_bytes != B:
            problems.append(
                f"ICI phases ({key}) must stay f32-sized ({B} B): "
                f"reduce_scatter operand {rs.operand_bytes}, "
                f"all_gather result {ag.result_bytes}")
    return problems


# --------------------------------------------------- family: dlrm pins

def _build_dlrm_layout_pin():
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models.dlrm import DLRM, build_sparse_training, dlrm_tiny
    from ..models.llama import LOGICAL_RULES
    from ..parallel import create_mesh
    from ..train import rules_for_mesh
    from .hlo import summarize_optimized

    cfg = dlrm_tiny()
    model = DLRM(cfg)
    rng = np.random.RandomState(0)
    B, n = 16, 8
    dense = jnp.asarray(
        rng.randn(B, cfg.dense_features).astype(np.float32))
    sparse = jnp.asarray(
        rng.randint(0, cfg.rows_per_table, (B, cfg.num_tables)))
    labels = jnp.asarray((rng.rand(B) < 0.3).astype(np.float32))
    mesh = create_mesh({"ep": n})
    rules = rules_for_mesh(mesh, LOGICAL_RULES)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), dense, sparse)["params"])
    jitted, dp, tables, accum, opt_state = build_sparse_training(
        model, cfg, mesh, rules, params)
    txt = jitted.lower(dp, tables, accum, opt_state, dense, sparse,
                       labels).compile().as_text()
    nrows = cfg.num_tables * cfg.rows_per_table
    return {"summary": summarize_optimized(txt),
            "table_shapes": (f"f32[{nrows},{cfg.embed_dim}]",
                             f"f32[{nrows // n},{cfg.embed_dim}]")}


def _verify_dlrm_layout_pin(b) -> List[str]:
    problems = []
    s, shapes = b["summary"], b["table_shapes"]
    table_moves = [m for m in s.layout_moves
                   if any(t in m.text for t in shapes)]
    if table_moves:
        problems.append(
            f"{len(table_moves)} table-sized transpose/copy crept back "
            f"into the sparse step (entry-layout pin regressed), first "
            f"at line {table_moves[0].line}: "
            f"{table_moves[0].text.strip()[:120]}")
    n_t = sum(1 for m in s.layout_moves if m.op == "transpose")
    n_c = sum(1 for m in s.layout_moves if m.op == "copy")
    if n_t > 102:
        problems.append(
            f"whole-program transpose budget blown: {n_t} > 102")
    if n_c > 34:
        problems.append(f"whole-program copy budget blown: {n_c} > 34")
    return problems


# --------------------------------------------------------- registration

def _register_builtin() -> None:
    for fam, desc, where, build, verify in (
        ("dp-step-fusion",
         "fusion threshold reshapes the DP gradient collective stream "
         "(2 / bucketed / 11) with donation intact at every threshold",
         "horovod_tpu/collectives/ops.py",
         _build_dp_step_fusion, _verify_dp_step_fusion),
        ("dp-step-accum",
         "gradient accumulation keeps the single-allreduce discipline "
         "and donate=True survives the microbatch scan",
         "horovod_tpu/train/step_builder.py",
         _build_dp_step_accum, _verify_dp_step_accum),
        ("bench-arms-parity",
         "bench.py's hvd arm vs plain arm compile to identical (empty) "
         "collective sets on the 1-device mesh",
         "bench.py",
         _build_bench_arms_parity, _verify_bench_arms_parity),
        ("gspmd-deferred-every1",
         "make_gspmd_deferred_train_step(every=1) emits collective HLO "
         "signature-identical to the standard GSPMD step",
         "horovod_tpu/train/gspmd.py",
         _build_gspmd_deferred_every1, _verify_gspmd_deferred_every1),
        ("gspmd-deferred-programs",
         "the deferred x sentinel three-program set keeps probe DCE: "
         "probe strictly smaller than apply",
         "horovod_tpu/train/gspmd.py",
         _build_gspmd_deferred_programs, _verify_gspmd_deferred_programs),
        ("adasum-butterfly",
         "log2(n) full-buffer XOR-partner permute rounds",
         "horovod_tpu/collectives/adasum.py",
         _build_adasum_butterfly, _verify_adasum_butterfly),
        ("ring-attention",
         "exactly the K and V shards rotate the +1 ring, nothing else "
         "rides the step",
         "horovod_tpu/parallel/ring.py",
         _build_ring_attention, _verify_ring_attention),
        ("pipeline-handoff",
         "one activation permute per schedule tick around the stage ring",
         "horovod_tpu/parallel/pipeline.py",
         _build_pipeline_handoff, _verify_pipeline_handoff),
        ("hierarchical-allreduce",
         "bf16 compression halves ONLY the cross-slice (DCN) hop; ICI "
         "reduce-scatter/all-gather stay f32-sized",
         "horovod_tpu/collectives/ops.py",
         _build_hierarchical_allreduce, _verify_hierarchical_allreduce),
        ("decode-tp",
         "tp in {1,2,4}: decode lowers to exactly 2*n_layers [S,D] "
         "activation all_reduces over the full tp group, zero permutes",
         "horovod_tpu/models/decode.py",
         lambda: _tp_step_summaries("decode", (1, 2, 4)),
         lambda b: _verify_tp_family(b, 2 * b["dim"] * 4)),
        ("verify-tp",
         "tp in {1,2,4}: K-wide verify keeps the decode wire contract "
         "at the [S*K,D] window activation",
         "horovod_tpu/models/decode.py",
         lambda: _tp_step_summaries("verify", (1, 2, 4)),
         lambda b: _verify_tp_family(b, 2 * 4 * b["dim"] * 4)),
        ("prefill-tp",
         "tp in {1,2,4}: prefill emits the same 2-per-layer activation "
         "all_reduces at the [1,T,D] width, zero permutes",
         "horovod_tpu/models/decode.py",
         lambda: _tp_step_summaries("prefill", (1, 2, 4)),
         lambda b: _verify_tp_family(b, 8 * b["dim"] * 4)),
        ("decode-tp8",
         "llama + mixtral at tp=8 with device_put pools: the full-mesh "
         "decode wire contract",
         "horovod_tpu/models/decode.py",
         lambda: _tp_step_summaries("decode", (8,)),
         lambda b: _verify_tp_family(b, 2 * b["dim"] * 4)),
        ("verify-tp8",
         "llama + mixtral at tp=8: the K-wide verify wire contract",
         "horovod_tpu/models/decode.py",
         lambda: _tp_step_summaries("verify", (8,)),
         lambda b: _verify_tp_family(b, 2 * 4 * b["dim"] * 4)),
        ("dlrm-layout-pin",
         "compiled sparse DLRM step has zero table-shaped transpose/copy "
         "and stays under the whole-program move budget",
         "horovod_tpu/models/dlrm.py",
         _build_dlrm_layout_pin, _verify_dlrm_layout_pin),
    ):
        register(Contract(fam, desc, where, build, verify))


_register_builtin()
