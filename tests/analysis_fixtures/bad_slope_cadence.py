"""lint-slope-cadence fixture: the deferred arm applies every k=4 steps
but the slope windows (3, 8) aren't both multiples of 4 — min-over-
repeats then cherry-picks windows that dodge the expensive apply step."""
from benchmarks.common import slope_time_paired

from horovod_tpu.optimizer import deferred_pair


def main():
    pair = deferred_pair(1e-4, every=4)
    runs = {"deferred": lambda s: None}
    del pair
    return slope_time_paired(runs, 3, 8)  # <- lint-slope-cadence
