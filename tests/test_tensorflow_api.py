"""``horovod_tpu.tensorflow`` API tests — parity with the reference's TF
cases in test/parallel/test_tensorflow.py / test_tensorflow_keras.py
(op correctness over dtypes, ragged allgather, alltoall splits,
DistributedGradientTape averaging, keras DistributedOptimizer step
parity, broadcast_variables, callbacks), run over ThreadSimEngine ranks
like the reference's CPU/Gloo tier (SURVEY.md §4).
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.tensorflow as hvd  # noqa: E402
from horovod_tpu.tensorflow.testing import run_parallel  # noqa: E402


def test_single_process_basics():
    hvd.shutdown()
    hvd.init()
    assert hvd.size() == 1 and hvd.rank() == 0
    out = hvd.allreduce(tf.constant([1.0, 2.0]), op=hvd.Sum)
    np.testing.assert_allclose(out.numpy(), [1.0, 2.0])
    assert not hvd.mpi_enabled() and not hvd.nccl_built()
    hvd.shutdown()


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32])
def test_allreduce_sum_dtypes(dtype):
    n = 3

    def fn(r):
        t = tf.constant(np.full((2, 3), r + 1, dtype=dtype))
        return hvd.allreduce(t, op=hvd.Sum, name="ar").numpy()

    for o in run_parallel(n, fn):
        np.testing.assert_allclose(o, np.full((2, 3), 6, dtype=dtype))


def test_allreduce_average_and_scales():
    n = 2

    def fn(r):
        t = tf.constant([2.0 * (r + 1)])
        a = hvd.allreduce(t, name="avg").numpy()  # default Average
        b = hvd.allreduce(t, op=hvd.Sum, name="scaled",
                          prescale_factor=0.5,
                          postscale_factor=10.0).numpy()
        return a, b

    for a, b in run_parallel(n, fn):
        np.testing.assert_allclose(a, [3.0])
        np.testing.assert_allclose(b, [30.0])  # (1+2)*10


def test_allgather_ragged_rows():
    n = 2

    def fn(r):
        t = tf.constant(np.arange((r + 1) * 2, dtype=np.float32
                                  ).reshape(r + 1, 2))
        return hvd.allgather(t, name="ag").numpy()

    expect = np.concatenate([np.arange(2, dtype=np.float32).reshape(1, 2),
                             np.arange(4, dtype=np.float32).reshape(2, 2)])
    for o in run_parallel(n, fn):
        np.testing.assert_allclose(o, expect)


def test_broadcast_and_alltoall_splits():
    n = 2

    def fn(r):
        b = hvd.broadcast(tf.constant([float(r)] * 3), root_rank=1,
                          name="b").numpy()
        out, recv = hvd.alltoall(tf.constant(np.arange(3.0) + 10 * r),
                                 splits=tf.constant([1, 2]), name="a2a")
        return b, out.numpy(), recv.numpy()

    outs = run_parallel(n, fn)
    for b, _, _ in outs:
        np.testing.assert_allclose(b, [1.0, 1.0, 1.0])
    np.testing.assert_allclose(outs[0][1], [0.0, 10.0])
    np.testing.assert_allclose(outs[1][1], [1.0, 2.0, 11.0, 12.0])
    np.testing.assert_allclose(outs[0][2], [1, 1])


def test_reducescatter_and_process_set():
    n = 2

    def fn(r):
        rs = hvd.reducescatter(tf.constant(np.arange(4.0)),
                               op=hvd.Sum, name="rs").numpy()
        ps = hvd.add_process_set([0])
        # only the member calls the subgroup op (reference semantics)
        sub = hvd.allreduce(tf.constant([5.0]), op=hvd.Sum, name="solo",
                            process_set=ps).numpy() if r == 0 else None
        return rs, sub

    outs = run_parallel(n, fn)
    np.testing.assert_allclose(outs[0][0], [0.0, 2.0])
    np.testing.assert_allclose(outs[1][0], [4.0, 6.0])
    np.testing.assert_allclose(outs[0][1], [5.0])
    assert outs[1][1] is None


def test_allreduce_inside_tf_function():
    """Graph mode: the op lowers through tf.py_function (the reference's
    custom-op boundary). Multi-rank graph mode can't be thread-simulated —
    TF serializes py_function bodies on one executor thread, so two
    blocked simulated ranks would deadlock; real deployments run one
    process per rank (covered by the hvdrun TF integration case in
    test_integration_run.py). Here: the single-process graph path."""
    hvd.shutdown()
    hvd.init()

    @tf.function
    def step(x):
        return hvd.allreduce(x, op=hvd.Sum, name="graph_ar") * 2.0

    np.testing.assert_allclose(step(tf.constant([2.0])).numpy(), [4.0])
    hvd.shutdown()


def test_distributed_gradient_tape_averages():
    n = 2

    def fn(r):
        v = tf.Variable([1.0, 2.0])
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = tf.reduce_sum(v * (r + 1.0))
        g = tape.gradient(loss, [v])[0]
        return np.asarray(g)

    for g in run_parallel(n, fn):
        np.testing.assert_allclose(g, [1.5, 1.5])  # mean of 1 and 2


def test_distributed_gradient_tape_indexed_slices():
    n = 2

    def fn(r):
        emb = tf.Variable(np.zeros((4, 2), np.float32))
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            row = tf.nn.embedding_lookup(emb, [r])  # rank r touches row r
            loss = tf.reduce_sum(row) * (r + 1.0)
        g = tape.gradient(loss, [emb])[0]
        assert isinstance(g, tf.IndexedSlices)
        dense = tf.math.unsorted_segment_sum(
            g.values, g.indices, 4).numpy()
        return dense

    for dense in run_parallel(n, fn):
        np.testing.assert_allclose(dense[0], [0.5, 0.5])  # 1/2 avg divisor
        np.testing.assert_allclose(dense[1], [1.0, 1.0])


def _make_keras_model():
    import keras
    m = keras.Sequential([keras.layers.Dense(
        1, use_bias=False, input_shape=(2,))])
    m.build((None, 2))
    m.set_weights([np.array([[1.0], [2.0]], np.float32)])
    return m


def test_keras_distributed_optimizer_step_parity():
    import keras
    n = 2

    def fn(r):
        m = _make_keras_model()
        opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.1))
        x = tf.constant(np.full((2, 2), float(r + 1), np.float32))
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(m(x))
        grads = tape.gradient(loss, m.trainable_variables)
        opt.apply_gradients(zip(grads, m.trainable_variables))
        assert isinstance(opt, keras.optimizers.SGD)  # subclass adoption
        return m.get_weights()[0]

    outs = run_parallel(n, fn)
    np.testing.assert_allclose(outs[0], outs[1])
    # grad = sum over batch of x = 2*(r+1) per input dim; mean over ranks=3
    np.testing.assert_allclose(outs[0], [[1.0 - 0.3], [2.0 - 0.3]],
                               atol=1e-6)


def test_broadcast_variables_and_objects():
    n = 2

    def fn(r):
        v = tf.Variable(np.full((3,), float(r), np.float32))
        hvd.broadcast_variables([v], root_rank=1)
        obj = hvd.broadcast_object({"rank": r} if r == 0 else None,
                                   root_rank=0)
        gathered = hvd.allgather_object(("r", r))
        return np.asarray(v), obj, gathered

    outs = run_parallel(n, fn)
    for v, obj, gathered in outs:
        np.testing.assert_allclose(v, [1.0, 1.0, 1.0])
        assert obj == {"rank": 0}
        assert gathered == [("r", 0), ("r", 1)]


def test_broadcast_callback_divergent_builtness_no_deadlock():
    """Rank 0 built (checkpoint restored), rank 1 lazy/unbuilt: the
    broadcast-now-or-defer choice is agreed via a min-allreduce, so
    collective order never splits across ranks — everyone defers to the
    first on_train_batch_end and converges (no deadlock/mismatch)."""
    import keras
    from horovod_tpu.tensorflow.keras import BroadcastGlobalVariablesCallback

    X = np.random.RandomState(3).randn(8, 2).astype(np.float32)
    y = np.zeros(8, np.float32)

    def fn(r):
        tf.config.run_functions_eagerly(True)
        model = keras.Sequential([keras.layers.Dense(
            1, kernel_initializer=keras.initializers.Constant(r + 1.0))])
        opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.0))
        model.compile(optimizer=opt, loss="mse")
        if r == 0:
            model.build((None, 2))  # only rank 0 is built pre-fit
        model.fit(X, y, batch_size=4, epochs=1, verbose=0,
                  callbacks=[BroadcastGlobalVariablesCallback(0)])
        return [np.asarray(w) for w in model.get_weights()]

    try:
        r0, r1 = run_parallel(2, fn)
    finally:
        tf.config.run_functions_eagerly(False)
    for a, b in zip(r0, r1):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_metric_average_callback():
    from horovod_tpu.tensorflow.keras import MetricAverageCallback
    n = 2

    def fn(r):
        cb = MetricAverageCallback()
        logs = {"loss": float(r), "acc": float(r * 2)}
        cb.on_epoch_end(0, logs)
        return logs

    for logs in run_parallel(n, fn):
        assert logs["loss"] == 0.5 and logs["acc"] == 1.0


def test_fused_tape_op_count(monkeypatch):
    """The TF gradient path fuses like the torch one: 3 same-dtype grads
    -> ONE engine allreduce (VERDICT r2 #1 applied to the TF binding)."""
    import threading as _threading
    from horovod_tpu.core.engine import ThreadSimEngine

    class Counting(ThreadSimEngine):
        def __init__(self, k):
            super().__init__(k)
            self.names = []
            self._cl = _threading.Lock()

        def allreduce(self, name, arr, op, members=None):
            with self._cl:
                self.names.append(name)
            return super().allreduce(name, arr, op, members=members)

    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", str(64 << 20))
    eng = Counting(2)

    def fn(r):
        vs = [tf.Variable(np.full((4,), 1.0, np.float32))
              for _ in range(3)]
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = tf.add_n([tf.reduce_sum(v) * (r + 1) for v in vs])
        gs = tape.gradient(loss, vs)
        return [np.asarray(g) for g in gs]

    outs = run_parallel(2, fn, engine=eng)
    assert len(eng.names) == 2, eng.names  # one fused op per rank
    # slot-pool prefix (gradtape.<slot>) — same name on both ranks
    assert all(".fused.float32." in nm and nm.startswith("gradtape.")
               for nm in eng.names), eng.names
    assert len(set(eng.names)) == 1, eng.names
    for g in outs[0]:
        np.testing.assert_allclose(g, np.full((4,), 1.5))


def test_tape_slot_pool_stable_and_distinct(monkeypatch):
    """The gradient-tape prefix slot pool: per-step reconstructed tapes
    reuse slot 0 (stable names -> engine signature-cache hits), while two
    tapes ALIVE at once (persistent) hold distinct slots so concurrent
    models cannot cross-pair buckets."""
    import threading as _threading
    from horovod_tpu.core.engine import ThreadSimEngine

    class Recording(ThreadSimEngine):
        def __init__(self, k):
            super().__init__(k)
            self.names = []
            self._cl = _threading.Lock()

        def allreduce(self, name, arr, op, members=None):
            with self._cl:
                self.names.append(name)
            return super().allreduce(name, arr, op, members=members)

    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", str(64 << 20))
    eng = Recording(2)

    def fn(r):
        v = tf.Variable(np.ones(4, np.float32))
        # canonical eager loop: a FRESH wrapper every step — including a
        # fresh PERSISTENT tape (the WGAN-GP shape, multiple gradient
        # calls per step)
        for _ in range(2):
            with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
                loss = tf.reduce_sum(v)
            tape.gradient(loss, [v])
        pt = hvd.DistributedGradientTape(tf.GradientTape(persistent=True))
        with pt:
            lp = tf.reduce_sum(v)
        pt.gradient(lp, [v])
        pt.gradient(lp, [v])

        # direct pool semantics (per-rank pool): overlapping claims get
        # distinct slots; released slots are reused smallest-first
        import horovod_tpu.tensorflow.mpi_ops as _mo
        rt = _mo._rt()
        a = rt.claim_slot("slotpool_test")
        b = rt.claim_slot("slotpool_test")
        assert (a, b) == (0, 1)
        rt.release_slot("slotpool_test", a)
        assert rt.claim_slot("slotpool_test") == 0
        rt.release_slot("slotpool_test", 0)
        rt.release_slot("slotpool_test", b)
        return None

    run_parallel(2, fn, engine=eng)
    seq = [n for n in eng.names if ".fused." in n]
    # every call claimed-and-released slot 0: one stable name, no growth
    assert set(seq) == {"gradtape.0.fused.float32.0"}, seq


def test_tape_traced_prefix_distinct_per_instance(monkeypatch):
    """Under tf.function the tape's collective names are baked at TRACE
    time, so the eager slot pool (claim/release around gradient()) cannot
    keep two concurrently-executing compiled steps apart — a traced tape
    mints a permanent per-instance prefix instead: distinct across tapes
    (no cross-pairing between models), stable across executions
    (signature-cache hits on the baked name)."""
    import threading as _threading
    from horovod_tpu.core.engine import ThreadSimEngine

    class Recording(ThreadSimEngine):
        def __init__(self, k):
            super().__init__(k)
            self.names = []
            self._cl = _threading.Lock()

        def allreduce(self, name, arr, op, members=None):
            with self._cl:
                self.names.append(name)
            return super().allreduce(name, arr, op, members=members)

    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", str(64 << 20))
    eng = Recording(1)

    def fn(r):
        v1 = tf.Variable(np.ones(4, np.float32))
        v2 = tf.Variable(2 * np.ones(4, np.float32))

        @tf.function
        def step_a():
            with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
                loss = tf.reduce_sum(v1 * v1)
            return tape.gradient(loss, [v1])

        @tf.function
        def step_b():
            with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
                loss = tf.reduce_sum(v2)
            return tape.gradient(loss, [v2])

        (ga,) = step_a()
        (gb,) = step_b()
        np.testing.assert_allclose(np.asarray(ga), 2 * np.ones(4))
        np.testing.assert_allclose(np.asarray(gb), np.ones(4))
        step_a()  # re-execution reuses the baked (stable) names
        step_b()
        return None

    run_parallel(1, fn, engine=eng)
    seq = [n for n in eng.names if ".fused." in n]
    assert len(seq) == 4, eng.names
    prefixes = {n.split(".fused.")[0] for n in seq}
    # two tapes -> two distinct baked prefixes, each seen twice
    assert len(prefixes) == 2, seq
    assert all(p.startswith("gradtape.traced.") for p in prefixes), seq
    from collections import Counter
    assert set(Counter(seq).values()) == {2}, seq


def test_grouped_ops_fuse_engine_rounds(monkeypatch):
    """VERDICT r3 #3: the public grouped_* ops fuse like the gradient
    paths — a 50-tensor grouped_allreduce costs ONE engine round per
    dtype bucket (reference group_table.cc atomic groups), not 50;
    grouped_allgather costs one dims round + one payload per dtype;
    grouped_reducescatter one round per dtype. Results must equal the
    per-tensor ops."""
    import threading as _threading
    from horovod_tpu.core.engine import ThreadSimEngine

    class Recording(ThreadSimEngine):
        def __init__(self, k):
            super().__init__(k)
            self.calls = []
            self._cl = _threading.Lock()

        def _note(self, kind, name):
            with self._cl:
                self.calls.append((kind, name))

        def allreduce(self, name, arr, op, members=None):
            self._note("allreduce", name)
            return super().allreduce(name, arr, op, members=members)

        def allgather(self, name, arr, members=None):
            self._note("allgather", name)
            return super().allgather(name, arr, members=members)

        def reducescatter(self, name, arr, op, members=None):
            self._note("reducescatter", name)
            return super().reducescatter(name, arr, op, members=members)

    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", str(64 << 20))
    eng = Recording(2)
    n_t = 50

    def fn(r):
        f32 = [tf.constant(np.full((3,), float(r + 1) * (i + 1),
                                   np.float32)) for i in range(n_t)]
        i64 = [tf.constant(np.array([r + 1, 2 * (r + 1)], np.int64))]
        red = hvd.grouped_allreduce(f32 + i64, op=hvd.Sum)
        gat = hvd.grouped_allgather(
            [tf.constant(np.full((r + 1, 2), float(r), np.float32)),
             tf.constant(np.arange(2 * (r + 1), dtype=np.int64))])
        rs = hvd.grouped_reducescatter(
            [tf.constant(np.full((4, 2), float(r + 1), np.float32)),
             tf.constant(np.full((2,), float(r + 1), np.float32))])
        return ([np.asarray(t) for t in red],
                [np.asarray(t) for t in gat],
                [np.asarray(t) for t in rs])

    outs = run_parallel(2, fn, engine=eng)
    for red, gat, rs in outs:
        # allreduce sums: (1+2)*(i+1) for f32; [3, 6] for the i64 tensor
        for i in range(n_t):
            np.testing.assert_allclose(red[i], np.full((3,),
                                                       3.0 * (i + 1)))
        np.testing.assert_array_equal(red[n_t], [3, 6])
        # allgather: ragged rows rank0 (1 row of 0s) + rank1 (2 rows 1s)
        np.testing.assert_allclose(
            gat[0], np.concatenate([np.zeros((1, 2)), np.ones((2, 2))]))
        np.testing.assert_array_equal(gat[1], [0, 1, 0, 1, 2, 3])
        # reducescatter sum: each rank gets its dim-0 chunk of 1+2=3
        np.testing.assert_allclose(rs[0], np.full((2, 2), 3.0))
        np.testing.assert_allclose(rs[1], np.full((1,), 3.0))

    per_rank = len(eng.calls) // 2
    kinds = [k for k, _ in eng.calls]
    # 51-tensor allreduce (2 dtypes) = 2 rounds; allgather (2 dtypes) =
    # 1 dims + 2 payloads; reducescatter (1 dtype... 2 tensors f32) = 1
    assert kinds.count("allreduce") == 2 * 2, eng.calls
    assert kinds.count("allgather") == 3 * 2, eng.calls
    assert kinds.count("reducescatter") == 1 * 2, eng.calls
    assert per_rank == 6, eng.calls


def test_learning_rate_callbacks_exist():
    from horovod_tpu.tensorflow.keras import (
        BroadcastGlobalVariablesCallback, LearningRateScheduleCallback,
        LearningRateWarmupCallback)
    assert BroadcastGlobalVariablesCallback(0).root_rank == 0
    LearningRateWarmupCallback(initial_lr=0.1, warmup_epochs=2)
    LearningRateScheduleCallback(initial_lr=0.1, multiplier=0.5,
                                 start_epoch=1)


def test_gradient_tape_predivide_scales_sparse_like_dense():
    """gradient_predivide_factor must reach IndexedSlices too: with it,
    the op arrives at the sparse branch as Sum + pre/post factors, and
    the embedding gradient must still come out averaged like the dense
    one (regression: values were allgathered unscaled)."""
    n = 2

    def fn(r):
        emb = tf.Variable(np.zeros((2, 2), np.float32))
        w = tf.Variable([1.0])
        with hvd.DistributedGradientTape(
                tf.GradientTape(),
                gradient_predivide_factor=2.0) as tape:
            row = tf.nn.embedding_lookup(emb, [0])
            loss = tf.reduce_sum(row) * (r + 1.0) + w[0] * (r + 1.0)
        gd, gs = tape.gradient(loss, [w, emb])
        dense = np.asarray(gd)
        assert isinstance(gs, tf.IndexedSlices)
        sp = tf.math.unsorted_segment_sum(gs.values, gs.indices, 2).numpy()
        return dense, sp

    for dense, sp in run_parallel(n, fn):
        np.testing.assert_allclose(dense, [1.5])       # mean of 1, 2
        np.testing.assert_allclose(sp[0], [1.5, 1.5])  # sparse matches


@pytest.mark.parametrize("average", [False, True])
def test_keras_optimizer_backward_passes_per_step(average):
    """backward_passes_per_step=2: calls 1..k-1 aggregate locally (still
    advancing optimizer.iterations, so iteration-keyed LR schedules track
    batches) and apply nothing; call k applies the rank-averaged SUM of
    the accumulated gradients by default — the reference's
    average_aggregated_gradients=False default — or the mean with the
    flag set."""
    import keras
    n = 2

    def fn(r):
        m = _make_keras_model()
        opt = hvd.DistributedOptimizer(
            keras.optimizers.SGD(0.1), backward_passes_per_step=2,
            average_aggregated_gradients=average)
        for i in range(2):
            x = tf.constant(np.full((2, 2), float(r + i + 1), np.float32))
            with tf.GradientTape() as tape:
                loss = tf.reduce_sum(m(x))
            grads = tape.gradient(loss, m.trainable_variables)
            opt.apply_gradients(zip(grads, m.trainable_variables))
            if i == 0:  # nothing applied yet, but iterations advanced
                np.testing.assert_allclose(m.get_weights()[0],
                                           [[1.0], [2.0]])
                assert int(opt.iterations) == 1
        return m.get_weights()[0], int(opt.iterations)

    outs = run_parallel(n, fn)
    np.testing.assert_allclose(outs[0][0], outs[1][0])
    assert outs[0][1] == 2
    # grads per call: 2*(r+i+1) per weight-row. Local SUM over i then
    # rank mean: r=0: 6, r=1: 10 -> 8 -> w -= 0.8; averaged: half that.
    expect = [[0.6], [1.6]] if average else [[0.2], [1.2]]
    np.testing.assert_allclose(outs[0][0], expect, atol=1e-6)


def test_tensorflow_elastic_state_roundtrip():
    """TensorFlowKerasState commit/restore/sync — the reference's
    horovod.tensorflow.elastic state contract over the shared engine."""
    import keras
    from horovod_tpu.tensorflow.elastic import TensorFlowKerasState
    n = 2

    def fn(r):
        m = _make_keras_model()
        m.set_weights([np.full((2, 1), float(r), np.float32)])
        state = TensorFlowKerasState(m, batch=10 * r, epoch=r)
        state.sync()  # rank 0's weights + scalars win
        synced = m.get_weights()[0].copy()
        batch_after_sync = state.batch
        # mutate, then restore to the committed snapshot
        m.set_weights([np.full((2, 1), 99.0, np.float32)])
        state.batch = 77
        state.restore()
        return (synced, batch_after_sync, m.get_weights()[0], state.batch)

    for synced, batch, restored, batch2 in run_parallel(n, fn):
        np.testing.assert_allclose(synced, 0.0)   # root 0's value
        assert batch == 0
        np.testing.assert_allclose(restored, 0.0)
        assert batch2 == 0


def test_tensorflow_state_persists_and_resumes(tmp_path, monkeypatch):
    """FrameworkState persistence: commits land in
    HOROVOD_ELASTIC_COMMIT_DIR and a FRESH state (new process after a
    relaunch) adopts them via load_latest — the restart elastic mode."""
    from horovod_tpu.tensorflow.elastic import TensorFlowState
    hvd.shutdown()
    hvd.init()
    v = tf.Variable([1.0, 2.0])
    state = TensorFlowState([v], commit_dir=str(tmp_path), step=0)
    v.assign([5.0, 6.0])
    state.step = 9
    state.commit()

    v.assign([0.0, 0.0])
    fresh = TensorFlowState([v], commit_dir=str(tmp_path), step=0)
    assert fresh.load_latest()
    np.testing.assert_allclose(np.asarray(v), [5.0, 6.0])
    assert fresh.step == 9
    hvd.shutdown()


def test_keras_state_picks_up_lazy_optimizer_slots():
    """Keras 3 creates momentum slots at the first apply_gradients: the
    state must re-collect variables at snapshot time, or restored ranks
    keep divergent momentum buffers."""
    import keras
    from horovod_tpu.tensorflow.elastic import TensorFlowKerasState
    hvd.shutdown()
    hvd.init()
    m = _make_keras_model()
    opt = keras.optimizers.SGD(0.1, momentum=0.9)
    state = TensorFlowKerasState(m, optimizer=opt, epoch=0)
    n_before = len(state.variables)

    x = tf.constant(np.ones((2, 2), np.float32))
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(m(x))
    opt.apply_gradients(zip(tape.gradient(loss, m.trainable_variables),
                            m.trainable_variables))
    state.commit()  # must now include the momentum slot
    assert len(state.variables) > n_before
    mom = [v for v in opt.variables if "momentum" in v.path.lower()
           or "velocity" in v.path.lower()]
    if not mom:  # keras names vary; fall back to any new optimizer var
        mom = list(opt.variables)[-1:]
    snap_val = np.asarray(mom[0]).copy()
    mom[0].assign(np.full_like(snap_val, 123.0))
    state.restore()
    np.testing.assert_allclose(np.asarray(mom[0]), snap_val)
    hvd.shutdown()


def test_keras_bpps_compiled_apply_matches_eager():
    """bpps=2 under tf.function (r3's NotImplementedError became the
    reference's gradient_aggregation pattern in r4): tf.Variable
    accumulators + a traced tf.cond — calls 1..k-1 accumulate and
    advance iterations, call k allreduces the sum and applies. Single
    rank here (branch logic + numerics); the cross-process compiled
    model.fit case lives in test_integration_run.py."""
    import keras
    hvd.shutdown()
    hvd.init()
    m = _make_keras_model()
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.1),
                                   backward_passes_per_step=2)

    @tf.function
    def step(x):
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(m(x))
        grads = tape.gradient(loss, m.trainable_variables)
        opt.apply_gradients(zip(grads, m.trainable_variables))

    # grads per call: 2*scale per weight-row
    step(tf.constant(np.ones((2, 2), np.float32)))      # accumulate only
    np.testing.assert_allclose(m.get_weights()[0], [[1.0], [2.0]])
    assert int(opt.iterations) == 1
    step(tf.constant(np.full((2, 2), 2.0, np.float32)))  # 2+4=6 -> apply
    np.testing.assert_allclose(m.get_weights()[0],
                               [[1.0 - 0.6], [2.0 - 0.6]], atol=1e-6)
    assert int(opt.iterations) == 2
    # second cycle reuses the SAME reset accumulators
    step(tf.constant(np.ones((2, 2), np.float32)))
    np.testing.assert_allclose(m.get_weights()[0],
                               [[0.4], [1.4]], atol=1e-6)
    step(tf.constant(np.ones((2, 2), np.float32)))       # 2+2=4 -> apply
    np.testing.assert_allclose(m.get_weights()[0],
                               [[0.0], [1.0]], atol=1e-6)
    assert int(opt.iterations) == 4
    hvd.shutdown()


def test_sync_batch_norm_spans_ranks():
    """SyncBatchNormalization: training statistics combine across ranks
    (count-weighted), so normalized outputs use the GLOBAL batch mean."""
    n = 2

    def fn(r):
        bn = hvd.SyncBatchNormalization(momentum=0.5)
        # rank 0 contributes zeros, rank 1 fours: global mean 2, var 4
        x = tf.constant(np.full((2, 3), 4.0 * r, np.float32))
        bn.build((None, 3))
        out = bn(x, training=True)
        return (np.asarray(out), np.asarray(bn.moving_mean),
                np.asarray(bn.moving_variance))

    outs = run_parallel(n, fn)
    for out, mm, mv in outs:
        np.testing.assert_allclose(mm, np.full(3, 1.0), rtol=1e-5)
        # biased (population) var — the Keras BatchNormalization moving-
        # stat convention, matching the layer's single-rank fallback:
        # var 4; moving = 1*0.5 + 4*0.5
        np.testing.assert_allclose(mv, np.full(3, 0.5 + 0.5 * 4.0),
                                   rtol=1e-5)
    # outputs: (x - 2) / sqrt(4 + eps) -> rank0 ~ -1, rank1 ~ +1
    np.testing.assert_allclose(outs[0][0], np.full((2, 3), -1.0), atol=1e-2)
    np.testing.assert_allclose(outs[1][0], np.full((2, 3), 1.0), atol=1e-2)


def test_sync_batch_norm_fp16_stats_do_not_overflow():
    """Statistics accumulate in float32: fp16 counts/sq-sums overflow at
    image-sized batches (regression guard)."""
    # 70k rows: an fp16 count/sq-sum would overflow (65504 max)
    x = tf.constant(np.random.RandomState(0).randn(70000, 4)
                    .astype(np.float16))

    def fn(r):
        layer = hvd.SyncBatchNormalization(momentum=0.5, dtype="float16")
        layer.build((None, 4))
        out = layer(x, training=True)
        return np.asarray(layer.moving_mean), np.asarray(out)

    outs = run_parallel(2, fn)
    for mm, out in outs:
        assert np.all(np.isfinite(mm)), mm
        assert np.all(np.isfinite(out))


def test_sync_batch_norm_rejects_non_channels_last_when_syncing():
    def fn(r):
        bn = hvd.SyncBatchNormalization(axis=1)
        bn.build((None, 3, 8))
        with pytest.raises(ValueError, match="channels-last"):
            bn(tf.constant(np.zeros((2, 3, 8), np.float32)), training=True)
        return True

    assert all(run_parallel(2, fn))


def test_join_and_barrier():
    """hvd.join over the TF surface: uneven step counts — early-finishing
    ranks join and answer the stragglers' collectives with zeros; barrier
    synchronizes (reference join/barrier contract)."""
    n = 2

    def fn(r):
        hvd.barrier()
        outs = []
        steps = 1 + r  # rank 1 takes one extra step
        for i in range(steps):
            outs.append(hvd.allreduce(tf.constant([float(r + 1)]),
                                      op=hvd.Sum, name="j").numpy())
        last = hvd.join()
        return outs, last

    res = run_parallel(n, fn)
    np.testing.assert_allclose(res[0][0][0], [3.0])  # both active: 1+2
    np.testing.assert_allclose(res[1][0][1], [2.0])  # rank 0 joined: 2+0
    assert res[0][1] == res[1][1] == 1  # last joiner is rank 1


def test_allreduce_bf16_compression():
    """Compression.bf16 — the TPU-native wire dtype (same exponent range
    as fp32): values survive the cast round-trip where fp16 would
    overflow (tested at 1e5 > fp16 max 65504)."""
    n = 2

    def fn(r):
        t = tf.constant([1e5 * (r + 1), 0.5])
        return hvd.allreduce(t, op=hvd.Sum, name="bf",
                             compression=hvd.Compression.bf16).numpy()

    for o in run_parallel(n, fn):
        np.testing.assert_allclose(o, [3e5, 1.0], rtol=1e-2)


def test_op_dtype_dim_matrix():
    """SURVEY §4 bulk tier (reference test/parallel/test_tensorflow.py:
    every op x dtype x dim): one 2-rank run sweeps the TF op surface over
    the wire dtypes and 1-3D shapes against exact numpy-model
    expectations (tiny values keep f16/bf16/uint8 sums exact)."""
    n = 2
    dtypes = [tf.float16, tf.bfloat16, tf.float32, tf.float64,
              tf.uint8, tf.int8, tf.int32, tf.int64]
    shapes = [(4,), (4, 3), (4, 3, 2)]

    def fn(r):
        for dt in dtypes:
            npdt = dt.as_numpy_dtype
            for shape in shapes:
                tag = f"{dt.name}.{len(shape)}"
                base = np.arange(int(np.prod(shape))).reshape(shape) % 5
                of_rank = lambda s: (base + s + 1).astype(np.float64)
                t = tf.constant((base + r + 1).astype(npdt))
                total = of_rank(0) + of_rank(1)

                o = hvd.allreduce(t, op=hvd.Sum, name=f"mx.ar.{tag}")
                assert o.dtype == dt and tuple(o.shape) == shape
                np.testing.assert_array_equal(
                    np.asarray(o).astype(np.float64), total,
                    err_msg=f"{tag} allreduce")

                g = hvd.allgather(t, name=f"mx.ag.{tag}")
                assert tuple(g.shape) == (shape[0] * n, *shape[1:])
                for s, p in enumerate(np.split(
                        np.asarray(g).astype(np.float64), n, axis=0)):
                    np.testing.assert_array_equal(p, of_rank(s))

                b = hvd.broadcast(t, root_rank=1, name=f"mx.bc.{tag}")
                assert b.dtype == dt
                np.testing.assert_array_equal(
                    np.asarray(b).astype(np.float64), of_rank(1))

                a, _ = hvd.alltoall(
                    t, splits=tf.constant([shape[0] // n] * n),
                    name=f"mx.a2a.{tag}")
                exp = np.concatenate([np.split(of_rank(s), n, axis=0)[r]
                                      for s in range(n)])
                np.testing.assert_array_equal(
                    np.asarray(a).astype(np.float64), exp,
                    err_msg=f"{tag} alltoall")

                rs = hvd.reducescatter(t, op=hvd.Sum, name=f"mx.rs.{tag}")
                np.testing.assert_array_equal(
                    np.asarray(rs).astype(np.float64),
                    np.split(total, n, axis=0)[r],
                    err_msg=f"{tag} reducescatter")
        return True

    assert all(run_parallel(n, fn))


def test_sentinel_counter_callback_surfaces_counters(monkeypatch):
    """SentinelCounterCallback merges the numeric-integrity counters
    (core/sentinel.py) into the keras logs stream as ``sentinel/<k>``
    keys — and is a no-op when no sentinel is active, so installing it
    unconditionally is safe."""
    from horovod_tpu.core import sentinel as sentinel_mod
    from horovod_tpu.tensorflow.keras import SentinelCounterCallback

    cb = SentinelCounterCallback()
    monkeypatch.setattr(sentinel_mod, "_active", None)
    logs = {"loss": 1.0}
    cb.on_train_batch_end(0, logs)
    assert logs == {"loss": 1.0}                 # inactive: untouched
    cb.on_train_batch_end(0, None)               # None logs: no crash

    s = sentinel_mod.Sentinel(max_skips=1, clock=lambda: 0.0)
    sentinel_mod.install(s)
    s.steps_skipped = 2
    s.rollbacks = 1
    cb.on_train_batch_end(1, logs)
    assert logs["sentinel/steps_skipped"] == 2
    assert logs["sentinel/rollbacks"] == 1
    assert logs["sentinel/evictions"] == 0
    assert logs["sentinel/last_fingerprint_mismatch_step"] == -1
    # user-provided keys win over the merge (setdefault semantics)
    epoch_logs = {"sentinel/steps_skipped": 99}
    cb.on_epoch_end(0, epoch_logs)
    assert epoch_logs["sentinel/steps_skipped"] == 99
    monkeypatch.setattr(sentinel_mod, "_active", None)
