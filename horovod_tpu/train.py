"""Data-parallel training harness — the minimum end-to-end slice.

Reference parity: the training loop every Horovod example script assembles
by hand (``examples/pytorch/pytorch_imagenet_resnet50.py``: init → broadcast
params → per-step backward → DistributedOptimizer allreduce → step). Here the
whole step is ONE compiled XLA program over the mesh: forward, backward,
fused gradient allreduce, and the optimizer update all inside ``jit`` +
``shard_map`` — data rides ICI, nothing bounces through the host.

This module is deliberately small: models plug in as flax Modules, optimizers
as optax transforms wrapped by ``horovod_tpu.optimizer.distributed``.
"""

from __future__ import annotations

import os
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from .core import context_api as _ctx
from .core import sentinel as _sentinel
from .core import telemetry as _telemetry
from .core.watchdog import monitored_step
from .collectives import ops as _ops
from .collectives.ops import effective_axis_size, force_axis_size1
from .optimizer import broadcast_parameters


#: Opt-in: AOT-compile the step once on first call to read XLA
#: cost-analysis FLOPs and feed the live ``hvd_step_mfu_proxy`` gauge.
#: Off by default — the extra compile costs minutes on big models;
#: benches register FLOPs explicitly via ``tools.perf``.
STEP_COST_ANALYSIS_ENV = "HOROVOD_STEP_COST_ANALYSIS"


def _maybe_register_step_flops(lower, what, steps, args, kwargs):
    """First-call hook behind ``HOROVOD_STEP_COST_ANALYSIS``: compile the
    step's AOT lowering, read cost-analysis FLOPs via the shared
    ``tools.perf`` accounting, and register them so the watchdog's
    ``_note_step_done`` can export the MFU proxy every step. Best-effort:
    any failure (no cost analysis on this backend, donation/lowering
    mismatch) is logged and skipped, never raised into the step."""
    if os.environ.get(STEP_COST_ANALYSIS_ENV, "").lower() \
            not in ("1", "true"):
        return
    from .core.logging import get_logger
    from .tools import perf
    try:
        compiled = lower(*args, **kwargs).compile()
        flops = perf.step_flops(compiled, steps=steps)
    except Exception as e:  # noqa: BLE001 — observability must not kill
        get_logger().debug("step cost analysis unavailable: %s", e)
        return
    if flops:
        perf.register_step_flops(flops, what=what)
        get_logger().info("registered %s cost-analysis FLOPs/step: %.3e",
                          what, flops)


class TrainState(NamedTuple):
    step: Any
    params: Any
    opt_state: Any
    batch_stats: Any  # {} for models without BatchNorm


def create_train_state(model, rng, sample_input,
                       optimizer: optax.GradientTransformation,
                       broadcast: bool = True) -> TrainState:
    """Init variables + optimizer state; broadcast from rank-0's process so
    all hosts agree (reference: ``hvd.broadcast_parameters`` at startup)."""
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    if broadcast:
        params = broadcast_parameters(params)
        batch_stats = broadcast_parameters(batch_stats)
    opt_state = optimizer.init(params)
    return TrainState(jnp.zeros((), jnp.int32), params, opt_state,
                      batch_stats)


def make_train_step(model, optimizer: optax.GradientTransformation,
                    loss_fn: Callable[[Any, Any], Any], *,
                    axis_name: Optional[str] = None,
                    mesh=None,
                    donate: bool = True,
                    scan_steps: Optional[int] = None,
                    autotune: Optional[bool] = None,
                    sentinel=None):
    """Build the jitted DP train step: ``step(state, batch, labels) ->
    (state, loss)``. ``batch``/``labels`` are sharded over the rank axis,
    state is replicated; the gradient allreduce happens inside ``optimizer``
    (a ``horovod_tpu.optimizer.distributed`` transform).

    ``scan_steps=k`` wraps k consecutive steps in a device-side ``lax.scan``
    over the same batch (one dispatch, one sync) — used by benchmarks to
    measure pure device throughput without host dispatch in the loop.

    ``autotune``: when True — or by default when ``HOROVOD_AUTOTUNE=1`` is
    set (the reference's zero-user-code transparent tuning,
    parameter_manager.cc) — the returned step is a
    :class:`~horovod_tpu.tools.autotune.StepAutotuner` that tunes the
    gradient-fusion bucket size (``HOROVOD_FUSION_THRESHOLD``) against live
    throughput while training, logging trials to ``HOROVOD_AUTOTUNE_LOG``
    and locking in the best knobs after convergence. Same call contract;
    the chosen knobs are readable as ``step.chosen``.

    ``sentinel``: a :class:`~horovod_tpu.core.sentinel.Sentinel`, True, or
    (default) the ``HOROVOD_SENTINEL`` env/config switch. When engaged the
    step ALSO computes the fused in-graph health vector (one extra small
    all_gather, docs/numeric_integrity.md) and a where-guard that keeps
    params/opt_state untouched on a globally non-finite step, plus a
    second no-update probe program for consecutive bad steps (donated
    state aliases through, the update work is DCE'd — the deferred-pair
    two-program trick). The call contract is unchanged; the policy
    object is readable as ``step.sentinel``."""
    sentinel = _sentinel.resolve(sentinel)
    if sentinel is not None and scan_steps is not None:
        raise ValueError(
            "sentinel and scan_steps are mutually exclusive: the health "
            "vector must reach the host policy engine every step, but "
            "scan_steps folds k steps into one dispatch")
    if autotune is None:
        autotune = _ctx.is_initialized() and _ctx.context().config.autotune
    if autotune:
        return _autotuned_train_step(
            model, optimizer, loss_fn, axis_name=axis_name, mesh=mesh,
            donate=donate, scan_steps=scan_steps, sentinel=sentinel)
    mesh = mesh if mesh is not None else _ctx.mesh()
    if axis_name is not None:
        axis = tuple(axis_name) if isinstance(axis_name, (tuple, list)) \
            else axis_name
    elif _ctx.is_initialized() and mesh is _ctx.mesh():
        axis = _ctx.context().axis_name
    else:
        # A custom multi-axis mesh (e.g. create_hybrid_mesh for hierarchical
        # allreduce): the rank axis is the tuple of its axes — batch shards
        # over all of them, collectives reduce over all of them.
        axis = mesh.axis_names[0] if len(mesh.axis_names) == 1 \
            else tuple(mesh.axis_names)

    def make_sharded_step(apply_update: bool):
        # Two bodies, one source of truth: the probe variant
        # (apply_update=False) never traces optimizer.update, so the
        # donated params/opt_state alias straight through and the dW
        # work whose only consumer was the update is DCE'd — the same
        # two-program trick as make_gspmd_deferred_train_step (a
        # lax.cond would copy the pass-through state instead).
        def sharded_step(state: TrainState, batch, labels):
            def loss_of(params):
                variables = {"params": params}
                stats = state.batch_stats
                use_stats = len(jax.tree_util.tree_leaves(stats)) > 0
                if use_stats:
                    variables["batch_stats"] = stats
                    out, mutated = model.apply(variables, batch, train=True,
                                               mutable=["batch_stats"])
                    new_stats = mutated["batch_stats"]
                else:
                    out = model.apply(variables, batch, train=True)
                    new_stats = stats
                return loss_fn(out, labels), new_stats

            (loss, new_stats), grads = jax.value_and_grad(
                loss_of, has_aux=True)(state.params)
            multi = effective_axis_size(axis) != 1  # known at trace time
            health = None
            if sentinel is not None:
                health = _sentinel.health_vector(
                    grads, state.params, axis=axis if multi else None)
            if multi:
                loss = jax.lax.pmean(loss, axis)
            if apply_update:
                updates, opt_state = optimizer.update(grads, state.opt_state,
                                                      state.params)
                params = optax.apply_updates(state.params, updates)
                if multi:
                    # TrainState is declared replicated (out_specs P()); if
                    # the model's BatchNorm does not itself sync
                    # (axis_name=None), per-device stats would silently
                    # diverge — averaging makes them truly replicated (a
                    # no-op when the model already synced them). Routed
                    # through grouped_allreduce, NOT a per-leaf pmean
                    # tree_map: the stats ride the same fused/bucketed
                    # collective path as the gradients (one collective per
                    # bucket instead of one tiny all-reduce per BN moment —
                    # the exact pattern lint-monolithic-psum flags).
                    # Skipped on a 1-member axis: XLA does not reliably
                    # elide single-participant all-reduces.
                    new_stats = _ops.grouped_allreduce(
                        new_stats, _ops.Average, axis_name=axis)
                if sentinel is not None:
                    # In-graph skip guard: a globally non-finite step must
                    # not touch params/opt_state/stats on ANY rank. The
                    # global verdict comes from the already-gathered health
                    # vector (no second collective); jnp.where is an
                    # elementwise select, free of the lax.cond copy trap.
                    ok = health[:, 0].min() >= 1.0

                    def guard(new, old):
                        return jnp.where(ok, new, old)
                    params = jax.tree_util.tree_map(guard, params,
                                                    state.params)
                    opt_state = jax.tree_util.tree_map(guard, opt_state,
                                                       state.opt_state)
                    new_stats = jax.tree_util.tree_map(guard, new_stats,
                                                       state.batch_stats)
            else:
                params, opt_state, new_stats = (
                    state.params, state.opt_state, state.batch_stats)
            out_state = TrainState(state.step + 1, params, opt_state,
                                   new_stats)
            if sentinel is not None:
                return out_state, loss, health
            return out_state, loss

        if scan_steps is not None:
            inner = sharded_step

            def sharded_step(state, batch, labels):  # noqa: F811
                def body(st, _):
                    st, loss = inner(st, batch, labels)
                    return st, loss
                state, losses = jax.lax.scan(body, state, None,
                                             length=scan_steps)
                return state, losses[-1]

        if mesh.devices.size == 1:
            # 1-device world: no shard_map. The SPMD partitioner costs real
            # layout copies on TPU even with one participant (measured ~10%
            # on ResNet-50); under force_axis_size1 the collectives inside
            # (optimizer allreduce, pmean, BN stat sync) collapse to
            # identity, so the compiled program is bit-identical to plain
            # single-device training — the reference's 1-process behavior.
            inner_step = sharded_step

            def step(state, batch, labels):
                axes = axis if isinstance(axis, tuple) else (axis,)
                with force_axis_size1(*axes):
                    return inner_step(state, batch, labels)
        else:
            step = _shard_map(
                sharded_step, mesh=mesh,
                in_specs=(P(), P(axis), P(axis)),
                out_specs=(P(), P(), P()) if sentinel is not None
                else (P(), P()),
                check_vma=False)
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    jitted = make_sharded_step(apply_update=True)
    if sentinel is None:
        dispatch = jitted
    else:
        probe = make_sharded_step(apply_update=False)
        dispatch = _sentinel_dispatch(sentinel, jitted, probe)

    _flops_hook = []  # once-latch for the opt-in cost-analysis hook

    def marked(*args, **kwargs):
        if not _flops_hook:
            _flops_hook.append(True)
            _maybe_register_step_flops(jitted.lower, "train_step",
                                       scan_steps or 1, args, kwargs)
        # Per-step host-side timeline record (the reference's MARK_CYCLES):
        # dispatch span + cycle marker; device phases live in the
        # jax.profiler xplane (tools/profiler.py merges both views). The
        # timeline is read PER CALL (a runtime check, like the reference's)
        # so start_timeline/stop_timeline work in any order relative to
        # building the step, and a closed timeline is never written to.
        # Registry counter, not a device read: the dispatch is async and
        # the loss is still a future here — step timing/loss reads belong
        # to the watchdog span and the Keras callback, which see values
        # the host already fetched.
        _telemetry.inc("hvd_dispatches_total", what="train_step")
        tl = _ctx.context().timeline if _ctx.is_initialized() else None
        if tl is None or getattr(tl, "_closed", False):
            return dispatch(*args, **kwargs)
        tl.activity_start("TRAIN_STEP", "DISPATCH")
        out = dispatch(*args, **kwargs)
        tl.activity_end("TRAIN_STEP", "DISPATCH")
        tl.mark_cycle()
        return out

    marked.lower = jitted.lower  # keep AOT introspection available
    if sentinel is not None:
        marked.lower_probe = probe.lower
        marked.sentinel = sentinel
    # Jit-step deadline monitor (core/watchdog.py, docs/failure_model.md):
    # unarmed this is a passthrough; armed, the blocking device fetch runs
    # on a watcher-visible thread so a step blocked inside an XLA
    # collective against a dead peer can be abandoned on deadline or
    # peer-death notification instead of hanging the process forever.
    return monitored_step(marked, what="train_step")


def _sentinel_dispatch(sentinel, step_apply, step_skip):
    """Host-side sentinel wrapper shared by the DP and GSPMD step
    factories: picks the apply program (in-graph where-guard) or, while
    in containment, the no-update probe program; decodes the health
    vector the jitted step already produced; and applies the policy
    ladder's verdict. Preserves the public ``(state, loss)`` contract.

    The step number is a host counter seeded from ``state.step`` on the
    first call (the deferred-pair phase-seed pattern) — no device fetch
    beyond the health read the policy needs anyway."""
    counter = {"n": None}

    def dispatch(state, *rest):
        if counter["n"] is None:
            try:
                counter["n"] = int(state.step)
            except jax.errors.ConcretizationTypeError:
                # Abstract tracing (hvd-analyze / make_jaxpr): no policy
                # decisions are made on tracers — fall back to 0.
                counter["n"] = 0
        counter["n"] += 1
        fn = step_skip if sentinel.in_containment else step_apply
        new_state, loss, health = fn(state, *rest)
        if isinstance(health, jax.core.Tracer):
            # Abstract trace: the health vector has no concrete value and
            # the ladder must not run.
            return new_state, loss
        action = sentinel.observe(_sentinel.decode_health(health),
                                  counter["n"])
        if action.kind == "rollback":
            new_state = sentinel.do_rollback(new_state)
        elif action.kind in ("evict", "abort"):
            sentinel.do_evict(action)
        return new_state, loss

    return dispatch


def _autotuned_train_step(model, optimizer, loss_fn, **build_kw):
    """HOROVOD_AUTOTUNE=1 engagement: wrap the step in a StepAutotuner
    that searches the GRAPH-SHAPE knobs live (the reference tunes fusion
    buffer + cycle time + hierarchical flags the same
    propose→measure→report way, parameter_manager.cc):

    - ``fusion_threshold_bytes`` — gradient bucket size;
    - ``hierarchical`` — staged reducescatter/allgather vs flat allreduce
      (only on a multi-axis rank mesh, where the choice exists).

    Both change ONLY the emitted HLO (identical numerics and step
    contract), so they are safe to search under a live training loop.
    ``scan_steps`` is deliberately NOT in this space: it changes how many
    optimizer updates one call performs — a caller-visible contract — so
    it remains an explicit ``StepAutotuner`` dimension for callers who
    own their loop (see tools/autotune.py's usage example)."""
    from .core.logging import get_logger
    from .collectives.ops import (fusion_threshold_override,
                                  hierarchical_override)
    from .tools.autotune import Autotuner, CatDim, LogIntDim, StepAutotuner

    cfg = _ctx.context().config
    ctx_axis = _ctx.context().axis_name

    def build(fusion_threshold_bytes, hierarchical=None):
        inner = make_train_step(model, optimizer, loss_fn, autotune=False,
                                **build_kw)
        thr = int(fusion_threshold_bytes)

        def stepped(*args, **kwargs):
            # jit traces lazily (on first call), so the trial knobs are
            # scoped around every invocation — they reach THIS step's
            # trace and never leak into other functions traced while
            # tuning.
            with fusion_threshold_override(thr), \
                    hierarchical_override(hierarchical):
                return inner(*args, **kwargs)

        def lowered(*args, **kwargs):
            # AOT introspection must trace under the SAME knobs the step
            # executes with — lowering outside the overrides would show
            # the config-default program, not the tuned one.
            with fusion_threshold_override(thr), \
                    hierarchical_override(hierarchical):
                return inner.lower(*args, **kwargs)
        stepped.lower = lowered
        return stepped

    space = {"fusion_threshold_bytes": LogIntDim(1 << 20, 1 << 28)}
    if isinstance(ctx_axis, tuple) and len(ctx_axis) >= 2:
        space["hierarchical"] = CatDim((False, True))
    tuner = Autotuner(space, warmup_trials=cfg.autotune_warmup_samples,
                      max_trials=cfg.autotune_max_samples,
                      log_path=cfg.autotune_log)
    get_logger().info(
        "HOROVOD_AUTOTUNE: tuning fusion threshold live "
        "(%d warmup / %d max samples, %d steps each%s)",
        cfg.autotune_warmup_samples, cfg.autotune_max_samples,
        cfg.autotune_steps_per_sample,
        f", log={cfg.autotune_log}" if cfg.autotune_log else "")
    return StepAutotuner(build, space,
                         steps_per_trial=cfg.autotune_steps_per_sample,
                         tuner=tuner)


# ---------------------------------------------------------------------------
# GSPMD path: multi-axis (dp/fsdp/sp/tp/ep) training by sharding annotation.
#
# The shard_map path above is the hvd-parity explicit-collective design (DP
# only, like the reference). For tensor/sequence/expert parallelism the
# TPU-idiomatic route is GSPMD: params carry logical axis names
# (models/llama.py LOGICAL_RULES), activations carry constraints, and XLA
# inserts every collective — including the DP gradient psum the reference
# needed its whole runtime for. Use a PLAIN optax optimizer here (not
# optimizer.distributed): the grad sync is implicit in the sharding.
# ---------------------------------------------------------------------------

from flax.linen import partitioning as nn_partitioning  # noqa: E402
from flax import linen as nn  # noqa: E402
from jax.sharding import NamedSharding  # noqa: E402


class GSPMDTrainState(NamedTuple):
    step: Any
    params: Any
    opt_state: Any


def next_token_loss(logits, tokens, mask=None):
    """Shifted next-token cross entropy (standard LM objective).

    Written as ``logsumexp - target_logit`` rather than materializing the
    full ``log_softmax`` tensor: at LM-head sizes the [B,T,V] f32
    log-probs cost an extra HBM write+read per step for values that are
    immediately reduced away (profile_mixtral.py, r4)."""
    targets = tokens[:, 1:]
    logits = logits[:, :-1].astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = lse - tgt
    if mask is not None:
        m = mask[:, 1:].astype(nll.dtype)
        return (nll * m).sum() / jnp.maximum(m.sum(), 1.0)
    return nll.mean()


def rules_for_mesh(mesh, rules):
    """Drop mesh axes a rule names that this mesh doesn't have, so one rule
    table serves any mesh shape (dp-only, dp×tp, dp×fsdp×sp×tp, ...)."""
    out = []
    for logical, target in rules:
        if target is None:
            out.append((logical, None))
            continue
        t = target if isinstance(target, tuple) else (target,)
        t = tuple(a for a in t if a in mesh.axis_names)
        out.append((logical, t if len(t) > 1 else (t[0] if t else None)))
    return tuple(out)


def gspmd_shardings(model, optimizer, rng, sample_tokens, mesh, rules):
    """Abstract-init the model and derive NamedShardings for params and
    optimizer state from the logical annotations."""
    rules = rules_for_mesh(mesh, rules)
    with nn_partitioning.axis_rules(rules):
        abs_vars = jax.eval_shape(model.init, rng, sample_tokens)
    abs_params = abs_vars["params"]
    abs_opt = jax.eval_shape(optimizer.init, abs_params)
    param_sharding = nn.logical_to_mesh_sharding(
        nn.get_partition_spec(abs_params), mesh, rules)
    opt_sharding = nn.logical_to_mesh_sharding(
        nn.get_partition_spec(abs_opt), mesh, rules)

    def _fit_rank(sh, leaf):
        # Rank-CHANGING optimizer states (Adafactor's factored v_row/v_col,
        # SM3 diagonals, ...) inherit the full param's axis names from the
        # flax box; a spec longer than the leaf's rank is invalid — store
        # those small reduced moments replicated instead.
        ndim = getattr(leaf, "ndim", None)
        if ndim is None:
            # the spec tree's leaf pairs with a still-BOXED abs subtree
            # (nn.Partitioned around one ShapeDtypeStruct)
            inner = jax.tree_util.tree_leaves(leaf)
            ndim = getattr(inner[0], "ndim", None) if len(inner) == 1 \
                else None
        if ndim is not None and isinstance(sh, NamedSharding) \
                and len(sh.spec) > ndim:
            return NamedSharding(mesh, P())
        return sh

    opt_sharding = jax.tree_util.tree_map(_fit_rank, opt_sharding, abs_opt)
    return param_sharding, opt_sharding


def create_gspmd_train_state(model, optimizer, rng, sample_tokens, mesh,
                             rules) -> GSPMDTrainState:
    """Initialise params/opt state already laid out per the rule table."""
    param_sharding, opt_sharding = gspmd_shardings(
        model, optimizer, rng, sample_tokens, mesh, rules)
    rules = rules_for_mesh(mesh, rules)

    def init_all(rng, sample):
        with nn_partitioning.axis_rules(rules):
            variables = model.init(rng, sample)
        params = variables["params"]
        return params, optimizer.init(params)

    with jax.sharding.set_mesh(mesh):
        params, opt_state = jax.jit(
            init_all, out_shardings=(param_sharding, opt_sharding))(
                rng, sample_tokens)
    params = nn.meta.unbox(params)
    opt_state = nn.meta.unbox(opt_state)
    return GSPMDTrainState(jnp.zeros((), jnp.int32), params, opt_state)


def make_gspmd_train_step(model, optimizer, mesh, rules, *,
                          loss_fn: Callable = None,
                          data_axes=("dp", "fsdp"), seq_axis: str = "sp",
                          donate: bool = True, aux_weight: float = 0.0,
                          sentinel=None):
    """Jitted LM train step: ``step(state, tokens) -> (state, loss)``.
    ``tokens`` [B, T] is sharded batch-over-data-axes, seq-over-sp; all
    tp/sp/ep/fsdp collectives AND the dp grad psum are inserted by XLA from
    the sharding annotations.

    ``sentinel`` engages the numeric-integrity ladder exactly as in
    :func:`make_train_step`. GSPMD has no named rank axis, so the health
    vector is the ``[1, 3]`` global form (global finiteness/norm/digest
    via XLA's implicit reductions): skip and rollback work; per-rank
    fingerprint eviction needs the shard_map DP step."""
    sentinel = _sentinel.resolve(sentinel)
    loss_fn = loss_fn or next_token_loss
    rules = rules_for_mesh(mesh, rules)
    present = [a for a in data_axes if a in mesh.axis_names]
    seq = seq_axis if seq_axis in mesh.axis_names else None
    token_sharding = NamedSharding(mesh, P(tuple(present) or None, seq))

    def make_step(apply_update: bool):
        # Probe variant (apply_update=False): optimizer.update is never
        # traced, donated state aliases through, update work is DCE'd —
        # see make_gspmd_deferred_train_step for the two-program rationale.
        def step(state: GSPMDTrainState, tokens):
            tokens = jax.lax.with_sharding_constraint(tokens,
                                                      token_sharding)

            def loss_of(params):
                with nn_partitioning.axis_rules(rules):
                    logits, mods = model.apply({"params": params}, tokens,
                                               mutable=["losses"])
                loss = loss_fn(logits, tokens)
                if aux_weight and "losses" in mods:
                    aux = sum(jnp.sum(v) for v in
                              jax.tree_util.tree_leaves(mods["losses"]))
                    loss = loss + aux_weight * aux
                return loss

            loss, grads = jax.value_and_grad(loss_of)(state.params)
            health = None
            if sentinel is not None:
                health = _sentinel.health_vector(grads, state.params)
            if apply_update:
                updates, opt_state = optimizer.update(grads,
                                                      state.opt_state,
                                                      state.params)
                params = optax.apply_updates(state.params, updates)
                if sentinel is not None:
                    ok = health[:, 0].min() >= 1.0

                    def guard(new, old):
                        return jnp.where(ok, new, old)
                    params = jax.tree_util.tree_map(guard, params,
                                                    state.params)
                    opt_state = jax.tree_util.tree_map(guard, opt_state,
                                                       state.opt_state)
            else:
                params, opt_state = state.params, state.opt_state
            out_state = GSPMDTrainState(state.step + 1, params, opt_state)
            if sentinel is not None:
                return out_state, loss, health
            return out_state, loss

        return jax.jit(step, donate_argnums=(0,) if donate else ())

    jitted = make_step(apply_update=True)
    if sentinel is None:
        inner = jitted
    else:
        probe = make_step(apply_update=False)
        inner = _sentinel_dispatch(sentinel, jitted, probe)

    _flops_hook = []  # once-latch for the opt-in cost-analysis hook

    def run(state, tokens):
        if not _flops_hook:
            _flops_hook.append(True)
            _maybe_register_step_flops(lower, "gspmd_train_step", 1,
                                       (state, tokens), {})
        with jax.sharding.set_mesh(mesh):
            return inner(state, tokens)

    def lower(state, tokens):
        # AOT introspection must trace under the SAME mesh the step
        # executes with (tests/test_bench_parity.py compares the
        # post-SPMD-partitioning collective HLO of two such lowerings).
        with jax.sharding.set_mesh(mesh):
            return jitted.lower(state, tokens)

    run.lower = lower
    if sentinel is not None:
        def lower_probe(state, tokens):
            with jax.sharding.set_mesh(mesh):
                return probe.lower(state, tokens)
        run.lower_probe = lower_probe
        run.sentinel = sentinel
    return monitored_step(run, what="gspmd_train_step")


def make_gspmd_deferred_train_step(model, pair, mesh, rules, **kw):
    """Two-PROGRAM expert-update deferral: ``pair`` is the
    ``optimizer.deferred_pair`` result (apply/skip optimizers + cadence
    in ONE value, so the k baked into the apply program's update scale
    and the k used for dispatch cannot disagree). Compiles one step per
    optimizer and dispatches by a host-side step counter — k-1 skip
    steps, then one apply step. The skip program's untouched expert
    param/m/v are donated jit inputs returned unchanged, so XLA aliases
    their buffers (zero optimizer HBM for the bank) AND dead-code-
    eliminates the bank's dL/dW einsums (their only consumer was the
    skipped update) — which a ``lax.cond`` inside ONE program cannot
    achieve (its pass-through copies measured the saving away —
    docs/benchmarks.md r5). Both optimizers share a state structure;
    init with ``pair.apply``. Requires ``donate=True`` (the default)
    for the aliasing to exist."""
    # Resolve the sentinel ONCE so both programs share a single policy
    # object — two ladders independently counting the same bad steps must
    # not happen. Env-default engagement (HOROVOD_SENTINEL=1 with no
    # explicit kwarg) is pinned here for the same reason.
    resolved = _sentinel.resolve(kw.get("sentinel"))
    if resolved is not None:
        kw["sentinel"] = resolved
    step_apply = make_gspmd_train_step(model, pair.apply, mesh, rules, **kw)
    step_skip = make_gspmd_train_step(model, pair.skip, mesh, rules, **kw)
    every = int(pair.every)
    # Seeded from state.step on first call (not 0) so a checkpoint /
    # elastic resume keeps the apply-vs-skip cadence PHASE: a job that
    # restarts mid-window must not stretch the window, or the apply
    # program's update scale (k baked in by deferred_pair) and the real
    # number of accumulated skip steps disagree.
    counter = {"n": None}

    def step(state, tokens):
        if counter["n"] is None:
            try:
                counter["n"] = int(state.step)
            except jax.errors.ConcretizationTypeError:
                # Abstract tracing (hvd-analyze / make_jaxpr): this
                # host-side dispatcher picks ONE program per call, so the
                # phase seed is moot — fall back to 0.
                counter["n"] = 0
        counter["n"] += 1
        fn = step_apply if counter["n"] % every == 0 else step_skip
        return fn(state, tokens)

    # AOT introspection per program (the dispatcher itself has no single
    # lowering): tests/test_bench_parity.py pins that at every=1 the apply
    # program's collective HLO is byte-identical to the standard step's.
    # getattr: stubbed step factories (tests) carry no .lower.
    step.lower_apply = getattr(step_apply, "lower", None)
    step.lower_skip = getattr(step_skip, "lower", None)
    return step
