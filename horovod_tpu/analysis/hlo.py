"""HLO-level program analysis: collective streams, donation, layouts.

Promotion of ``tests/wire_accounting.py`` (VERDICT r4 #6) into a real
analysis layer.  Parses BOTH program texts a jitted step can produce —

- **lowered stablehlo** (``fn.lower(...).as_text()``): what the trace
  emitted, before SPMD partitioning.  Collectives here are the ones the
  user's code issued (``shard_map`` bodies, explicit psums);
- **optimized HLO** (``fn.lower(...).compile().as_text()``): the
  post-GSPMD, post-layout program.  GSPMD *inserts* collectives during
  partitioning and XLA's entry-layout heuristic can insert whole-tensor
  ``transpose``/``copy`` ops (the r4 DLRM killer), so contracts about
  sharded train steps and layout pins must look here —

into one typed :class:`HloSummary`: the ordered collective stream with
per-device ring wire bytes (NCCL-tests convention, the north-star
formulas of ``benchmarks/collectives.py``)::

    all_reduce:     2(g-1)/g * operand_bytes
    reduce_scatter:  (g-1)/g * operand_bytes
    all_gather:      (g-1)/g * result_bytes
    all_to_all:      (g-1)/g * operand_bytes
    collective_permute: operand_bytes per (s, t) link (point-to-point)

plus the ``input_output_alias`` donation map, the layout-changing
``copy``/``transpose`` instructions with their shapes, and fusion/line
counts.  ``analysis/contracts.py`` evaluates every shipped program
family's invariants against this summary; the legacy dict API
(:func:`collective_wire_costs`) is preserved verbatim for the
``tests/wire_accounting.py`` shim.
"""

import re
from typing import List, NamedTuple, Optional, Tuple

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2,
                "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
                "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1}

# Optimized-HLO primitive types (s/u spellings, pred for bool).
_HLO_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2,
                    "s64": 8, "u64": 8, "s32": 4, "u32": 4,
                    "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1}

_COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all")

# Optimized-HLO opcode -> normalized stablehlo-style name.
_HLO_OPCODES = {"all-reduce": "all_reduce", "all-gather": "all_gather",
                "reduce-scatter": "reduce_scatter",
                "all-to-all": "all_to_all",
                "collective-permute": "collective_permute"}


class HloCollective(NamedTuple):
    """One entry of a program's ordered collective stream."""
    op: str                       # normalized snake_case kind
    group_size: int               # replica-group size (permute: n_links)
    groups: Tuple[Tuple[int, ...], ...]   # replica groups (permute: ())
    pairs: Tuple[Tuple[int, int], ...]    # source_target_pairs (else ())
    n_links: int                  # permute links with s != t (else 0)
    operand_bytes: int
    result_bytes: int
    ring_bytes: float             # per-device wire bytes (formulas above)
    line: int                     # 1-based line in the parsed text


class DonationAlias(NamedTuple):
    """One ``input_output_alias`` entry of an optimized HloModule."""
    output_index: str             # e.g. "{}" or "{1}"
    param_number: int
    param_index: str
    kind: str                     # "may-alias" / "must-alias"


class LayoutMove(NamedTuple):
    """A data-moving ``transpose``/``copy`` instruction (optimized HLO) —
    the instruction class the DLRM entry-layout pin exists to keep away
    from table-shaped operands (CLAUDE.md, r4)."""
    op: str                       # "transpose" / "copy"
    shape: str                    # result shape, e.g. "f32[128,16]"
    line: int
    text: str                     # the full instruction line


class HloSummary(NamedTuple):
    flavor: str                   # "stablehlo" / "optimized"
    collectives: Tuple[HloCollective, ...]
    donation: Tuple[DonationAlias, ...]   # optimized only
    donated: bool                 # any donation evidence in either flavor
    layout_moves: Tuple[LayoutMove, ...]  # optimized only
    fusion_count: int             # optimized only (0 for stablehlo)
    n_lines: int

    def ops(self) -> List[str]:
        return [c.op for c in self.collectives]

    def count(self, op: str) -> int:
        return sum(1 for c in self.collectives if c.op == op)

    def permutes(self) -> List[HloCollective]:
        return [c for c in self.collectives
                if c.op == "collective_permute"]


# ------------------------------------------------------------ stablehlo

def _tensor_bytes(spec: str) -> int:
    """'16xf32' / '2x4xi64' / 'f32' (scalar) -> total bytes."""
    parts = spec.split("x")
    elems = 1
    for p in parts[:-1]:
        elems *= int(p)
    return elems * _DTYPE_BYTES[parts[-1]]


def _signature_at(lines: List[str], i: int):
    """The op's function signature ": (operands) -> results" sits on the
    same line (region-free ops) or on the region-closing line a few lines
    below; region bodies (add/min/...) carry no "->"."""
    for j in range(i, min(i + 16, len(lines))):
        sm = re.search(r":\s*\(([^)]*)\)\s*->\s*(.+)$", lines[j])
        if sm and "tensor<" in sm.group(1):
            return sm
    return None


def _stablehlo_collectives(hlo_text: str) -> List[HloCollective]:
    lines = hlo_text.splitlines()
    out = []
    for i, line in enumerate(lines):
        if re.search(r'"stablehlo\.collective_permute"', line):
            out.append(_stablehlo_permute(lines, i))
            continue
        m = re.search(r'"stablehlo\.(%s)"' % "|".join(_COLLECTIVES), line)
        if not m:
            continue
        op = m.group(1)
        gm = re.search(
            r"replica_groups = dense<(.*?)> : tensor<(\d+)x(\d+)xi64>",
            line)
        assert gm, f"no replica_groups on collective line: {line[:200]}"
        group_size = int(gm.group(3))
        groups = tuple(tuple(int(v) for v in grp.split(","))
                       for grp in re.findall(r"\[([\d,\s]+)\]", gm.group(1)))
        sig = _signature_at(lines, i)
        assert sig, f"no signature found for {op} at line {i}"
        operand_bytes = sum(_tensor_bytes(s) for s in
                            re.findall(r"tensor<([^>]+)>", sig.group(1)))
        result_bytes = sum(_tensor_bytes(s) for s in
                           re.findall(r"tensor<([^>]+)>", sig.group(2)))
        out.append(HloCollective(
            op, group_size, groups, (), 0, operand_bytes, result_bytes,
            _ring_bytes(op, group_size, operand_bytes, result_bytes),
            i + 1))
    return out


def _stablehlo_permute(lines: List[str], i: int) -> HloCollective:
    """``source_target_pairs = dense<[[s, t], ...]> : tensor<Nx2xi64>``
    (a single pair prints as ``dense<[s, t]> : tensor<1x2xi64>``); wire
    cost per participating device = the full operand (point-to-point:
    no ring discount, a device sends its whole buffer to its target)."""
    line = lines[i]
    pm = re.search(
        r"source_target_pairs = dense<(.*?)> : tensor<(\d+)x2xi64>", line)
    assert pm, f"no source_target_pairs on permute line: {line[:200]}"
    pairs = [tuple(int(v) for v in grp.split(","))
             for grp in re.findall(r"\[([\d,\s]+)\]", pm.group(1))]
    if not pairs:               # tensor<1x2xi64> prints without inner []
        flat = [int(v) for v in pm.group(1).split(",")]
        pairs = [tuple(flat[:2])]
    assert len(pairs) == int(pm.group(2)), (pairs, line[:200])
    sig = _signature_at(lines, i)
    assert sig, f"no signature found for collective_permute at line {i}"
    operand_bytes = sum(_tensor_bytes(s) for s in
                        re.findall(r"tensor<([^>]+)>", sig.group(1)))
    result_bytes = sum(_tensor_bytes(s) for s in
                       re.findall(r"tensor<([^>]+)>", sig.group(2)))
    n_links = sum(1 for s, t in pairs if s != t)
    return HloCollective(
        "collective_permute", n_links, (), tuple(pairs), n_links,
        operand_bytes, result_bytes, float(operand_bytes), i + 1)


def _ring_bytes(op, g, operand_bytes, result_bytes) -> float:
    if g <= 0:
        return 0.0
    return {"all_reduce": 2 * (g - 1) / g * operand_bytes,
            "reduce_scatter": (g - 1) / g * operand_bytes,
            "all_gather": (g - 1) / g * result_bytes,
            "all_to_all": (g - 1) / g * operand_bytes}[op]


# Stablehlo donation evidence: jax marks donated params with either
# attribute spelling depending on version.
_STABLEHLO_DONOR_MARKERS = ("jax.buffer_donor", "tf.aliasing_output")


def summarize_stablehlo(hlo_text: str) -> HloSummary:
    """Typed summary of a lowered (pre-partitioning) stablehlo module."""
    donated = any(m in hlo_text for m in _STABLEHLO_DONOR_MARKERS)
    return HloSummary(
        flavor="stablehlo",
        collectives=tuple(_stablehlo_collectives(hlo_text)),
        donation=(), donated=donated, layout_moves=(),
        fusion_count=0, n_lines=len(hlo_text.splitlines()))


# -------------------------------------------------------- optimized HLO

def _hlo_shape_bytes(spec: str) -> int:
    """'f32[2,4]{1,0}' / 'pred[]' / 'f32[8]' -> total bytes.  Tuples and
    token/opaque types return 0 (they carry no wire payload of their
    own; tuple elements are accounted when listed individually)."""
    m = re.match(r"([a-z]+\d*)\[([\d,\s]*)\]", spec.strip())
    if not m or m.group(1) not in _HLO_DTYPE_BYTES:
        return 0
    elems = 1
    dims = m.group(2).strip()
    if dims:
        for d in dims.split(","):
            elems *= int(d)
    return elems * _HLO_DTYPE_BYTES[m.group(1)]


_HLO_SHAPE_RE = r"[a-z]+\d*\[[\d,\s]*\](?:\{[^}]*\})?"

_HLO_COLLECTIVE_RE = re.compile(
    r"=\s+(\((?:[^()]|\([^)]*\))*\)|" + _HLO_SHAPE_RE + r")\s+"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(-start)?\(")


def _parse_replica_groups(line: str):
    """Brace form ``replica_groups={{0,1},{2,3}}`` or iota form
    ``replica_groups=[2,4]<=[8]`` (2 groups of 4).  Returns
    (group_size, groups)."""
    bm = re.search(r"replica_groups=\{(\{[^=]*?\})\}", line)
    if bm:
        groups = tuple(tuple(int(v) for v in grp.split(",") if v.strip())
                       for grp in re.findall(r"\{([\d,\s]*)\}", bm.group(1)))
        groups = tuple(g for g in groups if g)
        size = len(groups[0]) if groups else 0
        return size, groups
    im = re.search(r"replica_groups=\[(\d+),(\d+)\]<=", line)
    if im:
        n_groups, size = int(im.group(1)), int(im.group(2))
        groups = tuple(tuple(range(g * size, (g + 1) * size))
                       for g in range(n_groups))
        return size, groups
    return 0, ()


def _optimized_collectives(hlo_text: str) -> List[HloCollective]:
    out = []
    for i, line in enumerate(hlo_text.splitlines()):
        m = _HLO_COLLECTIVE_RE.search(line)
        if not m:
            continue
        op = _HLO_OPCODES[m.group(2)]
        result_bytes = sum(_hlo_shape_bytes(s)
                           for s in re.findall(_HLO_SHAPE_RE, m.group(1)))
        # Operand shapes print inside the call parens:
        # all-reduce(f32[2,4]{1,0} %x, f32[8]{0} %y)
        operands = line[m.end():]
        depth, j = 1, 0
        while j < len(operands) and depth:
            if operands[j] == "(":
                depth += 1
            elif operands[j] == ")":
                depth -= 1
            j += 1
        operand_bytes = sum(
            _hlo_shape_bytes(s)
            for s in re.findall(_HLO_SHAPE_RE, operands[:j - 1]))
        if op == "collective_permute":
            pairs = tuple(
                (int(a), int(b)) for a, b in re.findall(
                    r"\{(\d+)\s*,\s*(\d+)\}",
                    _braced_span(line, "source_target_pairs=")))
            n_links = sum(1 for s, t in pairs if s != t)
            out.append(HloCollective(
                op, n_links, (), pairs, n_links, operand_bytes,
                result_bytes, float(operand_bytes), i + 1))
        else:
            size, groups = _parse_replica_groups(line)
            out.append(HloCollective(
                op, size, groups, (), 0, operand_bytes, result_bytes,
                _ring_bytes(op, size, operand_bytes, result_bytes), i + 1))
    return out


_ALIAS_ENTRY_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{([\d,\s]*)\},\s*(\w+-alias)\)")


def _braced_span(text: str, marker: str) -> str:
    """The brace-balanced span following ``marker={`` (inner braces
    included, outer braces stripped); "" when the marker is absent."""
    start = text.find(marker + "{")
    if start < 0:
        return ""
    i = start + len(marker)
    depth, j = 0, i
    while j < len(text):
        if text[j] == "{":
            depth += 1
        elif text[j] == "}":
            depth -= 1
            if depth == 0:
                return text[i + 1:j]
        j += 1
    return text[i + 1:]


def _parse_donation(hlo_text: str) -> Tuple[DonationAlias, ...]:
    for line in hlo_text.splitlines():
        if "input_output_alias=" not in line:
            continue
        body = _braced_span(line, "input_output_alias=")
        return tuple(
            DonationAlias("{%s}" % out_ix.strip(), int(param),
                          "{%s}" % p_ix.strip(), kind)
            for out_ix, param, p_ix, kind in _ALIAS_ENTRY_RE.findall(body))
    return ()


_LAYOUT_MOVE_RE = re.compile(
    r"=\s+(" + _HLO_SHAPE_RE + r")\s+(transpose|copy)\(")


def _layout_moves(hlo_text: str) -> List[LayoutMove]:
    out = []
    for i, line in enumerate(hlo_text.splitlines()):
        m = _LAYOUT_MOVE_RE.search(line)
        if m:
            shape = re.match(r"[a-z]+\d*\[[\d,\s]*\]", m.group(1))
            out.append(LayoutMove(m.group(2), shape.group(0), i + 1,
                                  line.strip()))
    return out


def summarize_optimized(hlo_text: str) -> HloSummary:
    """Typed summary of an optimized (post-GSPMD) HLO module text
    (``fn.lower(...).compile().as_text()``)."""
    donation = _parse_donation(hlo_text)
    return HloSummary(
        flavor="optimized",
        collectives=tuple(_optimized_collectives(hlo_text)),
        donation=donation,
        donated=bool(donation) or "input_output_alias" in hlo_text,
        layout_moves=tuple(_layout_moves(hlo_text)),
        fusion_count=hlo_text.count("fusion("),
        n_lines=len(hlo_text.splitlines()))


def summarize(hlo_text: str,
              flavor: Optional[str] = None) -> HloSummary:
    """Dispatching entry point: sniffs stablehlo vs optimized HLO when
    ``flavor`` is not given (stablehlo text is full of ``stablehlo.``
    qualified ops; optimized HLO is not)."""
    if flavor is None:
        flavor = "stablehlo" if "stablehlo." in hlo_text else "optimized"
    if flavor == "stablehlo":
        return summarize_stablehlo(hlo_text)
    if flavor == "optimized":
        return summarize_optimized(hlo_text)
    raise ValueError(f"unknown HLO flavor {flavor!r}")


# ------------------------------------------- legacy dict API (the shim)

def collective_wire_costs(hlo_text: str) -> list:
    """Find every stablehlo collective; return a list (program order) of
    dicts: op, group_size, groups (list of device-id lists),
    operand_bytes, result_bytes, ring_bytes — permutes carry pairs /
    n_links instead of group_size / groups.  This is the original
    ``tests/wire_accounting.py`` API, preserved verbatim; that module
    now re-exports from here."""
    out = []
    for c in _stablehlo_collectives(hlo_text):
        if c.op == "collective_permute":
            out.append({"op": c.op,
                        "pairs": [list(p) for p in c.pairs],
                        "n_links": c.n_links,
                        "operand_bytes": c.operand_bytes,
                        "result_bytes": c.result_bytes,
                        "ring_bytes": c.ring_bytes})
        else:
            out.append({"op": c.op, "group_size": c.group_size,
                        "groups": [list(g) for g in c.groups],
                        "operand_bytes": c.operand_bytes,
                        "result_bytes": c.result_bytes,
                        "ring_bytes": c.ring_bytes})
    return out
