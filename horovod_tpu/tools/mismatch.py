"""Debug-mode collective-signature mismatch detector.

Reference parity (SURVEY.md §5.2): the reference has no sanitizer harness;
its only cross-rank divergence tooling is the stall inspector plus the
controller's shape/dtype mismatch errors raised during negotiation
(controller.cc builds an error Response when ranks disagree). Under SPMD
there is no negotiation to catch disagreement, so divergence (different
shapes fed on different hosts, drifted step counts, different op sequences)
surfaces as a hang or garbage numerics instead.

This detector is the XLA-world replacement the survey prescribes: each
process appends a signature per collective/step — ``(name, shape, dtype,
op)`` — into a rolling digest; :func:`verify` compares digests across all
processes (one tiny allgather) and raises with the divergent processes
listed. Enable via ``HOROVOD_MISMATCH_CHECK=1`` (eager ops record
automatically) and call ``verify()`` at step/epoch boundaries, or use it
standalone around any suspect region.

This is the RUNTIME half of the story; the STATIC half is hvd-analyze
(``horovod_tpu/analysis``), which extracts the same per-collective
signature stream from the jaxpr before launch — run it first
(``python -m horovod_tpu.analysis``, or ``HOROVOD_PREFLIGHT_ANALYZE=1``
on the launcher) and reach for this digest when divergence is
data-dependent and only reproduces live.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, List, Optional

import jax
import numpy as np

from ..core.logging import get_logger


class MismatchError(RuntimeError):
    pass


class MismatchDetector:
    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._digest = hashlib.sha256()
        self._count = 0
        self._recent: List[str] = []
        self._capacity = capacity

    @staticmethod
    def enabled() -> bool:
        return os.environ.get("HOROVOD_MISMATCH_CHECK", "").lower() in (
            "1", "true", "yes", "on")

    def record(self, name: str, shape: Any = None, dtype: Any = None,
               op: str = "") -> None:
        sig = f"{name}|{tuple(shape) if shape is not None else ()}|" \
              f"{np.dtype(dtype).name if dtype is not None else ''}|{op}"
        with self._lock:
            self._digest.update(sig.encode())
            self._count += 1
            self._recent.append(sig)
            if len(self._recent) > self._capacity:
                del self._recent[: len(self._recent) - self._capacity]

    def record_tree(self, name: str, tree: Any, op: str = "") -> None:
        for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
            self.record(f"{name}.{i}", getattr(leaf, "shape", ()),
                        getattr(leaf, "dtype", None), op)

    def fingerprint(self) -> bytes:
        with self._lock:
            return self._digest.digest() + self._count.to_bytes(8, "little")

    def verify(self, context: str = "") -> None:
        """Raise :class:`MismatchError` if any process's collective history
        diverges from process 0's. Cheap: allgathers 40 bytes."""
        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils
        fp = np.frombuffer(self.fingerprint(), np.uint8)
        all_fp = np.asarray(multihost_utils.process_allgather(fp))
        all_fp = all_fp.reshape(jax.process_count(), -1)
        bad = [p for p in range(all_fp.shape[0])
               if not np.array_equal(all_fp[p], all_fp[0])]
        if bad:
            with self._lock:
                tail = self._recent[-5:]
            raise MismatchError(
                f"collective signature mismatch {context or ''}: processes "
                f"{bad} diverge from process 0 after {self._count} recorded "
                f"collectives; this process's last signatures: {tail} "
                f"(reference analog: controller.cc shape-mismatch error)")

    def reset(self) -> None:
        with self._lock:
            self._digest = hashlib.sha256()
            self._count = 0
            self._recent.clear()


#: process-global instance the eager layer records into when enabled.
detector = MismatchDetector()


def maybe_record(name: str, tensor: Any, op: str = "") -> None:
    """Hook for the collectives layer: no-op unless
    ``HOROVOD_MISMATCH_CHECK`` is on."""
    if MismatchDetector.enabled():
        detector.record_tree(name, tensor, op)
