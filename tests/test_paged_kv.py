"""Paged KV-cache allocator properties + the slot admit/retire state
machine (serving/decode.py — ISSUE 13).

The allocator half is pure Python: free-list discipline over blocks
``1..n-1`` with block 0 reserved as the null block, all-or-nothing
admission allocation, and the no-fragmentation-by-construction property
(any free block serves any slot, so allocation fails only on genuine
exhaustion). The engine half drives ``DecodeEngine`` inline
(``decode_once``/``run_until_idle``) on llama_tiny: admission into free
slots, queueing past the slot width, requeue on pool exhaustion,
mid-decode block-extension stalls that recover when a retire frees
capacity, and the bounded compile counts the serving guardrail pins.
"""

import numpy as np
import pytest

from horovod_tpu.serving.decode import ACTIVE, FREE, BlockAllocator


# -- allocator properties -----------------------------------------------------


def test_allocator_reserves_null_block():
    a = BlockAllocator(8)
    assert a.free_blocks == 7
    got = [a.alloc() for _ in range(7)]
    assert sorted(got) == list(range(1, 8))      # block 0 never handed out
    assert a.alloc() is None                     # exhausted, not an error


def test_allocator_rejects_degenerate_pool():
    with pytest.raises(ValueError):
        BlockAllocator(1)                        # only the null block


def test_alloc_many_all_or_nothing():
    a = BlockAllocator(6)
    first = a.alloc_many(3)
    assert len(first) == 3
    assert a.alloc_many(3) is None               # only 2 left: no partial
    assert a.free_blocks == 2                    # nothing half-taken
    rest = a.alloc_many(2)
    assert sorted(first + rest) == list(range(1, 6))


def test_free_rejects_double_and_foreign():
    a = BlockAllocator(4)
    b = a.alloc()
    a.free([b])
    with pytest.raises(ValueError):
        a.free([b])                              # double free
    with pytest.raises(ValueError):
        a.free([3])                              # never allocated
    with pytest.raises(ValueError):
        a.free([0])                              # the null block


def test_allocator_churn_property():
    """Random alloc/free churn: handed-out ids stay unique and in
    ``1..n-1``, ``free + held == n-1`` at every step, and after total
    release the FULL pool is allocatable in one all-or-nothing grab —
    the no-fragmentation property."""
    rng = np.random.RandomState(0)
    n = 32
    a = BlockAllocator(n)
    held = []
    for _ in range(500):
        if held and rng.rand() < 0.45:
            k = rng.randint(1, len(held) + 1)
            batch = [held.pop(rng.randint(len(held))) for _ in range(k)]
            a.free(batch)
        else:
            got = a.alloc_many(rng.randint(1, 5))
            if got is None:
                assert a.free_blocks < 4         # only genuine exhaustion
                continue
            held.extend(got)
        assert len(set(held)) == len(held)
        assert all(1 <= b < n for b in held)
        assert a.free_blocks + len(held) == n - 1
    a.free(held)
    assert len(a.alloc_many(n - 1)) == n - 1


# -- the slot state machine ---------------------------------------------------


@pytest.fixture(scope="module")
def llama():
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from horovod_tpu.models.llama import Llama, llama_tiny

    cfg = llama_tiny()
    model = Llama(cfg)
    params = nn.meta.unbox(jax.jit(model.init)(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)))["params"]
    return cfg, model, params


def _engine(cfg, params, **kw):
    from horovod_tpu.serving.decode import DecodeEngine
    kw.setdefault("slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("pool_blocks", 16)
    kw.setdefault("max_blocks_per_slot", 4)
    kw.setdefault("prefill_buckets", (4, 8))
    return DecodeEngine(cfg, params=params, **kw)


def test_submit_validation(llama):
    cfg, _, params = llama
    eng = _engine(cfg, params)
    for bad in ([], list(range(20))):            # empty / beyond top bucket
        req = eng.submit(bad, 2)
        assert req.error is not None and req.event.is_set()
    req = eng.submit([1, 2], 0)                  # max_new < 1
    assert req.error is not None
    req = eng.submit([1, 2], 99)                 # overflows slot context
    assert req.error is not None
    assert eng.active_slots == 0 and not eng.has_work()


def test_admit_retire_roundtrip(llama):
    cfg, _, params = llama
    eng = _engine(cfg, params)
    free0 = eng.allocator.free_blocks
    req = eng.submit([5, 6, 7], 4)
    eng.run_until_idle()
    assert req.event.is_set() and req.error is None
    assert len(req.tokens) == 3 + 4 and req.tokens[:3] == [5, 6, 7]
    assert not req.truncated and req.ttft_s > 0
    assert eng.allocator.free_blocks == free0    # every block returned
    assert all(s.state == FREE for s in eng.slots)
    assert eng.active_slots == 0


def test_queueing_beyond_slot_width(llama):
    cfg, _, params = llama
    eng = _engine(cfg, params)
    reqs = [eng.submit([1 + i, 2 + i], 3) for i in range(5)]
    assert len(eng._pending) == 5
    eng.run_until_idle()
    for r in reqs:
        assert r.error is None and len(r.tokens) == 5
    assert eng.allocator.free_blocks == 15       # 16-block pool, null held


def test_admission_requeues_on_pool_exhaustion(llama):
    """Bucket 8 = 2 blocks; pool holds 2 free: the second request must
    requeue (all-or-nothing), then admit after the first retires."""
    cfg, _, params = llama
    eng = _engine(cfg, params, slots=2, pool_blocks=3,
                  prefill_buckets=(8,), max_blocks_per_slot=2)
    a = eng.submit([1, 2, 3, 4, 5], 3)
    b = eng.submit([6, 7, 8, 9, 10], 3)
    eng.decode_once()
    assert eng.active_slots == 1                 # b back on the queue
    assert len(eng._pending) == 1
    eng.run_until_idle()
    assert a.error is None and len(a.tokens) == 8
    assert b.error is None and len(b.tokens) == 8
    assert eng.allocator.free_blocks == 2


def test_extension_stall_recovers_after_retire(llama):
    """A live slot that cannot allocate its next block STALLS (masked
    out, no recompile, no OOM) and resumes once a retire frees capacity."""
    cfg, _, params = llama
    eng = _engine(cfg, params, slots=2, pool_blocks=4,
                  prefill_buckets=(4, 8), max_blocks_per_slot=2)
    a = eng.submit([1, 2], 6)                    # bucket 4: 1 block, extends
    b = eng.submit([3, 4, 5, 6], 4)              # bucket 8: 2 blocks, never
    eng.decode_once()                            # admits both: pool empty
    assert eng.allocator.free_blocks == 0
    stalled_seen = False
    for _ in range(50):
        if not eng.has_work():
            break
        eng.decode_once()
        stalled_seen = stalled_seen or eng.slots[0].stalled
    assert stalled_seen, "slot A never hit the block-extension stall"
    assert a.error is None and len(a.tokens) == 8
    assert b.error is None and len(b.tokens) == 8
    assert eng.allocator.free_blocks == 3
    assert not any(s.stalled for s in eng.slots)


def test_all_stalled_deadlock_breaks(llama):
    """Every active slot stalled on a block extension with the free list
    empty: no retire could ever happen on its own, so the engine must
    break the deadlock (retire the longest sequence truncated) instead of
    hanging forever and leaking slots + blocks (REVIEW: livelock)."""
    cfg, _, params = llama
    eng = _engine(cfg, params, slots=2, pool_blocks=3,
                  prefill_buckets=(4,), max_blocks_per_slot=4)
    a = eng.submit([1, 2, 3], 8)                 # 1 block each: pool empty
    b = eng.submit([4, 5, 6], 8)
    eng.run_until_idle(max_steps=200)            # would raise if deadlocked
    for r in (a, b):
        assert r.error is None and r.event.is_set()
        assert r.truncated                       # pool too small: partial
        assert len(r.tokens) > 3                 # but tokens were delivered
    assert eng.allocator.free_blocks == 2        # nothing leaked
    assert all(s.state == FREE for s in eng.slots)
    assert not eng.has_work()


def test_pool_smaller_than_bucket_fails_fast(llama):
    """A prompt bucket needing more blocks than the whole pool can never
    admit — submit fails it immediately instead of queueing forever."""
    cfg, _, params = llama
    eng = _engine(cfg, params, slots=1, pool_blocks=2,
                  prefill_buckets=(4, 8), max_blocks_per_slot=2)
    req = eng.submit([1, 2, 3, 4, 5], 1)         # bucket 8 = 2 blocks > 1
    assert req.error is not None and req.event.is_set()
    assert not eng.has_work()


def test_compile_counts_bounded_by_buckets(llama):
    """Steady state: ONE decode compile ever; prefill compiles == number
    of distinct buckets traffic touched — never per-request."""
    cfg, _, params = llama
    eng = _engine(cfg, params, slots=4)
    for i in range(3):                           # bucket 4
        eng.submit([1 + i, 2], 2)
    eng.run_until_idle()
    assert eng.compile_counts == {"decode": 1, "prefill": 1}
    for i in range(4):                           # mixed buckets 4 and 8
        eng.submit([1 + i] * (3 if i % 2 else 6), 3)
    eng.run_until_idle()
    assert eng.compile_counts == {"decode": 1, "prefill": 2}


def test_slot_bookkeeping_during_flight(llama):
    cfg, _, params = llama
    eng = _engine(cfg, params)
    eng.submit([9, 8, 7], 5)
    eng.decode_once()
    (slot,) = [s for s in eng.slots if s.state == ACTIVE]
    assert slot.pos > 3 and slot.gen >= 1
    assert slot.table and all(b != 0 for b in slot.table)
    eng.run_until_idle()
    assert slot.state == FREE and slot.table == [] and slot.pos == 0
