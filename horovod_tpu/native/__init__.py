"""horovod_tpu.native — C++ host runtime (ctypes-bound).

Reference parity (SURVEY.md §2.1): the pieces of the reference's native
core that still belong on the host under SPMD — thread pool
(thread_pool.cc), timeline writer thread (timeline.cc), and the
prefetch/memcpy machinery (the fusion buffer's MEMCPY_IN role) applied to
the TPU's real host bottleneck: the input pipeline. See
``src/hvd_runtime.cc``.

Everything degrades gracefully: if no C++ toolchain is present,
:func:`available` is False and :class:`RecordPipeline` transparently uses
the pure-numpy fallback with identical semantics (the tests run both).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_lib = None
_lib_lock = threading.Lock()
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lib_lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("HOROVOD_DISABLE_NATIVE", "").lower() in (
                "1", "true", "yes", "on"):
            return None
        from .build import build
        path = build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        lib.hvd_runtime_abi_version.restype = ctypes.c_int
        if lib.hvd_runtime_abi_version() != 3:
            return None
        # signatures
        lib.hvd_pool_create.restype = ctypes.c_void_p
        lib.hvd_pool_create.argtypes = [ctypes.c_int]
        lib.hvd_pool_counter_add.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_longlong]
        lib.hvd_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.hvd_timeline_open.restype = ctypes.c_void_p
        lib.hvd_timeline_open.argtypes = [ctypes.c_char_p]
        lib.hvd_timeline_event.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char, ctypes.c_int, ctypes.c_int]
        lib.hvd_timeline_close.argtypes = [ctypes.c_void_p]
        lib.hvd_pipeline_create.restype = ctypes.c_void_p
        lib.hvd_pipeline_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_int,
            ctypes.c_int, ctypes.c_ulonglong, ctypes.c_int, ctypes.c_int]
        lib.hvd_pipeline_next.restype = ctypes.c_longlong
        lib.hvd_pipeline_next.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(ctypes.c_uint8)]
        lib.hvd_pipeline_error.restype = ctypes.c_char_p
        lib.hvd_pipeline_error.argtypes = [ctypes.c_void_p]
        lib.hvd_pipeline_destroy.argtypes = [ctypes.c_void_p]
        lib.hvd_parallel_gather.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
            ctypes.c_longlong, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native runtime built & loaded."""
    return _load() is not None


class NativeTimeline:
    """C++ writer-thread Chrome-trace timeline (drop-in for the hot path;
    same file format as tools.timeline.Timeline)."""

    def __init__(self, path: str):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._h = lib.hvd_timeline_open(path.encode())
        if not self._h:
            raise OSError(f"cannot open timeline file {path}")

    def activity_start(self, name: str, activity: str, rank: int = 0) -> None:
        self._lib.hvd_timeline_event(self._h, activity.encode(),
                                     name.encode(), b"B", rank, 0)

    def activity_end(self, name: str, activity: str, rank: int = 0) -> None:
        self._lib.hvd_timeline_event(self._h, activity.encode(),
                                     name.encode(), b"E", rank, 0)

    def marker(self, name: str, rank: int = 0) -> None:
        self._lib.hvd_timeline_event(self._h, name.encode(), b"", b"i",
                                     rank, 0)

    def close(self) -> None:
        if self._h:
            self._lib.hvd_timeline_close(self._h)
            self._h = None


def _splitmix64_shuffle(items, seed: int) -> None:
    """Fisher-Yates with a SplitMix64 stream — bit-for-bit the shuffle in
    native/src/hvd_runtime.cc, so native and fallback pipelines yield the
    SAME batches for the same seed (the documented contract)."""
    mask = (1 << 64) - 1
    state = seed & mask

    def next_u64():
        nonlocal state
        state = (state + 0x9E3779B97F4A7C15) & mask
        z = state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & mask
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & mask
        return z ^ (z >> 31)

    for i in range(len(items) - 1, 0, -1):
        j = next_u64() % (i + 1)
        items[i], items[j] = items[j], items[i]


class RecordPipeline:
    """Prefetching batch reader over fixed-size-record binary files.

    Yields ``np.ndarray`` batches of shape ``(batch_size, *record_shape)``.
    Native path: multithreaded C++ readers with a bounded prefetch queue.
    Fallback path: single-threaded numpy with identical ordering semantics
    (same seed ⇒ same batches).
    """

    def __init__(self, paths: Sequence[str], record_shape: Tuple[int, ...],
                 dtype, batch_size: int, shuffle: bool = True, seed: int = 0,
                 n_threads: int = 4, prefetch: int = 4,
                 drop_remainder: bool = True,
                 force_fallback: bool = False):
        self.paths = [os.path.abspath(p) for p in paths]
        self.record_shape = tuple(record_shape)
        self.dtype = np.dtype(dtype)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.record_bytes = int(np.prod(self.record_shape)) * \
            self.dtype.itemsize
        self.drop_remainder = drop_remainder
        self._n_threads = n_threads
        self._prefetch = prefetch
        self._lib = None if force_fallback else _load()
        self._h = None
        self._fallback_iter = None
        self._start()

    @property
    def native(self) -> bool:
        return self._lib is not None

    def _start(self) -> None:
        if self._lib is not None:
            arr = (ctypes.c_char_p * len(self.paths))(
                *[p.encode() for p in self.paths])
            self._h = self._lib.hvd_pipeline_create(
                arr, len(self.paths), self.record_bytes, self.batch_size,
                self._n_threads, self._prefetch, self.seed,
                1 if self.shuffle else 0, 1 if self.drop_remainder else 0)
            err = self._lib.hvd_pipeline_error(self._h).decode()
            if err:
                self.close()
                raise OSError(f"pipeline init failed: {err}")
        else:
            self._fallback_iter = self._fallback_batches()

    # -- fallback (identical semantics, pure numpy) --------------------------

    def _fallback_batches(self):
        index: List[Tuple[str, int]] = []
        for p in self.paths:
            sz = os.path.getsize(p)
            if sz % self.record_bytes:
                raise OSError(f"{p} size not a multiple of record_bytes")
            index.extend((p, i) for i in range(sz // self.record_bytes))
        if self.shuffle:
            _splitmix64_shuffle(index, self.seed)
        files = {p: open(p, "rb") for p in self.paths}
        try:
            n_full = len(index) // self.batch_size
            total = n_full if self.drop_remainder else \
                -(-len(index) // self.batch_size)
            for b in range(total):
                chunk = index[b * self.batch_size:(b + 1) * self.batch_size]
                out = np.empty((len(chunk), self.record_bytes), np.uint8)
                for j, (p, rec) in enumerate(chunk):
                    f = files[p]
                    f.seek(rec * self.record_bytes)
                    out[j] = np.frombuffer(f.read(self.record_bytes),
                                           np.uint8)
                yield out
        finally:
            for f in files.values():
                f.close()

    # -- iteration -----------------------------------------------------------

    def next_batch(self) -> Optional[np.ndarray]:
        """Next batch, or None at end of data."""
        if self._lib is not None:
            buf = np.empty(self.batch_size * self.record_bytes, np.uint8)
            n = self._lib.hvd_pipeline_next(
                self._h, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
            if n < 0:
                raise OSError("pipeline error: "
                              + self._lib.hvd_pipeline_error(self._h)
                              .decode())
            if n == 0:
                return None
            raw = buf[: n * self.record_bytes]
        else:
            try:
                raw = next(self._fallback_iter)
            except StopIteration:
                return None
            n = raw.shape[0]
            raw = raw.reshape(-1)
        return raw.view(self.dtype).reshape((n,) + self.record_shape)

    def __iter__(self):
        while True:
            b = self.next_batch()
            if b is None:
                return
            yield b

    def close(self) -> None:
        if self._h:
            self._lib.hvd_pipeline_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


__all__ = ["NativeTimeline", "RecordPipeline", "available",
           "parallel_gather"]


def parallel_gather(src: np.ndarray, indices: np.ndarray,
                    out: Optional[np.ndarray] = None,
                    threads: int = 0) -> np.ndarray:
    """``src[indices]`` along axis 0 (1-D integer ``indices``) with native
    threaded memcpy.

    The batch-assembly hot op of the input pipeline (the reference's
    MEMCPY_IN role): ctypes releases the GIL, so gathering the next batch
    overlaps device compute inside :class:`~horovod_tpu.data.Prefetcher`.
    Falls back to numpy fancy indexing when the native lib is unavailable,
    ``src`` is not plain C-contiguous numeric data, or ``indices`` uses
    numpy-only semantics (negative values) — identical results either way.
    """
    indices = np.asarray(indices)
    if indices.ndim != 1:
        raise ValueError(f"indices must be 1-D, got shape {indices.shape}")
    if not np.issubdtype(indices.dtype, np.integer):
        raise TypeError(f"indices must be integers, got {indices.dtype}")
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    n = src.shape[0] if src.ndim else 0
    if idx.size and (int(idx.max()) >= n or int(idx.min()) < -n):
        raise IndexError(
            f"index out of bounds for axis 0 with size {n}")
    lib = _load()
    use_native = (lib is not None and src.ndim >= 1
                  and src.flags.c_contiguous and not src.dtype.hasobject
                  and (not idx.size or int(idx.min()) >= 0))
    if not use_native:
        result = src[idx]
        if out is not None:
            out[...] = result
            return out
        return result
    row_bytes = src.dtype.itemsize
    for d in src.shape[1:]:
        row_bytes *= d
    want_shape = (idx.shape[0],) + src.shape[1:]
    if out is None:
        out = np.empty(want_shape, dtype=src.dtype)
    elif (out.shape != want_shape or out.dtype != src.dtype
          or not out.flags.c_contiguous):
        raise ValueError(
            f"out must be C-contiguous {want_shape} {src.dtype}, got "
            f"{out.shape} {out.dtype}")
    if threads <= 0:
        threads = min(8, os.cpu_count() or 1)
    lib.hvd_parallel_gather(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        idx.shape[0], row_bytes,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), threads)
    return out
