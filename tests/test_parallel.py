"""Parallelism-primitive tests: ring attention, Ulysses, MoE dispatch,
pipeline — each checked against a single-device oracle (SURVEY.md §4 pattern:
CPU mesh as the universal fake backend)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

import horovod_tpu as hvd
from horovod_tpu.parallel import (create_mesh, local_attention, pipeline,
                                  ring_attention, routed_experts,
                                  topk_router, ulysses_attention)

N = 8


def sp_mesh():
    return create_mesh({"sp": N})


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_local(causal):
    rng = np.random.RandomState(0)
    B, T, H, D = 2, 32, 4, 8
    q = rng.randn(B, T, H, D).astype(np.float32) * 0.5
    k = rng.randn(B, T, H, D).astype(np.float32) * 0.5
    v = rng.randn(B, T, H, D).astype(np.float32)
    ref = np.asarray(local_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), causal=causal))

    mesh = sp_mesh()

    def body(qb, kb, vb):
        return ring_attention(qb, kb, vb, "sp", causal=causal)

    f = jax.jit(shard_map(body, mesh=mesh,
                          in_specs=(P(None, "sp"),) * 3,
                          out_specs=P(None, "sp"), check_vma=False))
    out = np.asarray(f(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ulysses_attention_matches_local():
    rng = np.random.RandomState(1)
    B, T, H, D = 2, 32, 8, 4
    q = rng.randn(B, T, H, D).astype(np.float32) * 0.5
    k = rng.randn(B, T, H, D).astype(np.float32) * 0.5
    v = rng.randn(B, T, H, D).astype(np.float32)
    ref = np.asarray(local_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), causal=True))
    mesh = sp_mesh()

    def body(qb, kb, vb):
        return ulysses_attention(qb, kb, vb, "sp", causal=True)

    f = jax.jit(shard_map(body, mesh=mesh,
                          in_specs=(P(None, "sp"),) * 3,
                          out_specs=P(None, "sp"), check_vma=False))
    out = np.asarray(f(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ulysses_head_count_validation():
    mesh = sp_mesh()

    def body(q):
        from horovod_tpu.parallel import seq_to_heads
        return seq_to_heads(q, "sp")

    f = shard_map(body, mesh=mesh, in_specs=P(None, "sp"),
                  out_specs=P(None, "sp"), check_vma=False)
    with pytest.raises(ValueError):
        f(jnp.zeros((2, 16, 6, 4)))  # 6 heads not divisible by 8


# ---------------- MoE ----------------

def test_topk_router_shapes_and_capacity():
    rng = np.random.RandomState(2)
    T, E, C = 16, 4, 3
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    r = topk_router(logits, E, C, top_k=2)
    d = np.asarray(r.dispatch)
    assert d.shape == (T, E, C)
    # no slot double-booked
    assert (d.sum(0) <= 1.0 + 1e-6).all()
    # each token dispatched at most twice (may be dropped on overflow)
    assert (d.sum((1, 2)) <= 2 + 1e-6).all()
    assert np.isfinite(float(r.aux_loss))


def test_sorted_router_matches_onehot_router():
    """The sort-based dispatch plan (r4: replaces the [T,E,C] one-hot
    einsums on the MoE hot path) is numerically equivalent to
    ``topk_router`` — same dispatch result, combine weights, aux loss,
    and gradients — across ample/tight/heavy-drop capacities."""
    from horovod_tpu.parallel.moe import (sorted_combine, sorted_dispatch,
                                          topk_router_sorted)
    rng = np.random.RandomState(7)
    T, E, D, k = 64, 8, 16, 2
    for cap_factor in (2.0, 0.5, 0.15):
        cap = max(1, int(cap_factor * k * T / E))
        logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
        x = jnp.asarray(rng.randn(T, D).astype(np.float32))
        r1 = topk_router(logits, E, cap, k)
        r2 = topk_router_sorted(logits, E, cap, k)
        np.testing.assert_allclose(
            np.asarray(jnp.einsum("tec,td->ecd", r1.dispatch, x)),
            np.asarray(sorted_dispatch(x, r2, E, cap)),
            rtol=1e-5, atol=1e-6)
        out = jnp.asarray(rng.randn(E, cap, D).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(jnp.einsum("tec,ecd->td", r1.combine, out)),
            np.asarray(sorted_combine(out, r2, T)),
            rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(r1.aux_loss),
                                   np.asarray(r2.aux_loss), rtol=1e-6)

    cap = max(1, int(0.5 * k * T / E))
    w = jnp.asarray(rng.randn(D, D).astype(np.float32))
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))

    def loss_onehot(x, logits, w):
        r = topk_router(logits, E, cap, k)
        d = jnp.einsum("tec,td->ecd", r.dispatch, x)
        o = jnp.tanh(jnp.einsum("ecd,df->ecf", d, w))
        return (jnp.einsum("tec,ecd->td", r.combine, o) ** 2).sum() \
            + r.aux_loss

    def loss_sorted(x, logits, w):
        r = topk_router_sorted(logits, E, cap, k)
        o = jnp.tanh(jnp.einsum("ecd,df->ecf",
                                sorted_dispatch(x, r, E, cap), w))
        return (sorted_combine(o, r, T) ** 2).sum() + r.aux_loss

    g1 = jax.grad(loss_onehot, argnums=(0, 1, 2))(x, logits, w)
    g2 = jax.grad(loss_sorted, argnums=(0, 1, 2))(x, logits, w)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)


def test_routed_experts_single_device_identity_expert():
    """With identity experts and top-1 routing (no drops), MoE output ==
    input (combine weights renormalised to 1)."""
    rng = np.random.RandomState(3)
    T, D, E = 8, 4, 2
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    y, aux = routed_experts(x, logits, lambda e: e, axis_name=None,
                            num_experts=E, capacity_factor=8.0, top_k=1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-5,
                               atol=1e-6)


def test_routed_experts_ep_matches_single_device():
    """Expert-parallel dispatch over 8 devices == single-device MoE."""
    rng = np.random.RandomState(4)
    Tl, D, E = 8, 6, 8  # per-device tokens; one expert per device
    x = rng.randn(N, Tl, D).astype(np.float32)
    logits = rng.randn(N, Tl, E).astype(np.float32)
    # per-expert scale weights: expert e multiplies by (e+1)
    scales = np.arange(1, E + 1, dtype=np.float32)

    def single_device_moe(xl, ll):
        def expert_fn(einp):  # [E, C, D]
            return einp * scales[:, None, None]
        return routed_experts(jnp.asarray(xl), jnp.asarray(ll), expert_fn,
                              axis_name=None, num_experts=E,
                              capacity_factor=8.0, top_k=2)[0]

    ref = np.stack([np.asarray(single_device_moe(x[r], logits[r]))
                    for r in range(N)])

    mesh = create_mesh({"ep": N})

    def body(xb, lb):
        local_scales = jnp.asarray(scales).reshape(N, 1)[
            jax.lax.axis_index("ep")]

        def expert_fn(einp):  # [E/n=1, n*C, D]
            return einp * local_scales[:, None, None]

        y, aux = routed_experts(xb[0], lb[0], expert_fn, axis_name="ep",
                                num_experts=E, capacity_factor=8.0, top_k=2)
        return y[None]

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("ep"), P("ep")),
                          out_specs=P("ep"), check_vma=False))
    out = np.asarray(f(jnp.asarray(x), jnp.asarray(logits)))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


# ---------------- pipeline ----------------

def test_pipeline_matches_sequential():
    """8-stage pipeline of affine stages == sequential composition."""
    rng = np.random.RandomState(5)
    D, M = 4, 6  # feature dim, microbatches
    Ws = rng.randn(N, D, D).astype(np.float32) * 0.3
    bs = rng.randn(N, D).astype(np.float32) * 0.1
    xs = rng.randn(M, 3, D).astype(np.float32)  # [M, B, D]

    def stage_fn(params, x):
        W, b = params
        return jnp.tanh(x @ W + b)

    # sequential oracle
    ref = xs.copy()
    for s in range(N):
        ref = np.tanh(ref @ Ws[s] + bs[s])

    mesh = create_mesh({"pp": N})

    def body(W, b, x):
        out = pipeline(stage_fn, (W[0], b[0]), x, "pp")
        return out[None]

    f = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P("pp"), P("pp"), P()),
        out_specs=P("pp"), check_vma=False))
    out = np.asarray(f(jnp.asarray(Ws), jnp.asarray(bs), jnp.asarray(xs)))
    # result lands on the last stage (rank N-1)
    np.testing.assert_allclose(out[N - 1], ref, rtol=2e-4, atol=2e-5)


def test_pipeline_grads_match_sequential():
    """Reverse-mode AD through the pipeline scan+ppermute equals the
    gradient of the sequential composition, per stage."""
    from horovod_tpu.parallel.pipeline import pipeline_value_and_grad
    rng = np.random.RandomState(6)
    D, M = 3, 5
    Ws = rng.randn(N, D, D).astype(np.float32) * 0.4
    xs = rng.randn(M, 2, D).astype(np.float32)
    ts = rng.randn(M, 2, D).astype(np.float32)

    def stage_fn(W, x):
        return jnp.tanh(x @ W)

    def loss_fn(outs, targets):
        return jnp.mean((outs - targets) ** 2)

    # oracle: sequential composition, grad per stage weight
    def seq_loss(Ws_all):
        h = jnp.asarray(xs)
        for s in range(N):
            h = jnp.tanh(h @ Ws_all[s])
        return jnp.mean((h - jnp.asarray(ts)) ** 2)

    ref_loss, ref_grads = jax.value_and_grad(seq_loss)(jnp.asarray(Ws))

    mesh = create_mesh({"pp": N})
    vg = pipeline_value_and_grad(stage_fn, loss_fn, "pp")

    def body(W, x, t):
        loss, g = vg(W[0], x, t)
        return loss[None], g[None]

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("pp"), P(), P()),
        out_specs=(P("pp"), P("pp")), check_vma=False))
    loss, grads = f(jnp.asarray(Ws), jnp.asarray(xs), jnp.asarray(ts))
    np.testing.assert_allclose(np.asarray(loss), ref_loss, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_grads),
                               rtol=2e-4, atol=1e-5)


def test_pipeline_1f1b_grads_match_sequential():
    """The hand-scheduled 1F1B interleave (O(n) activation memory,
    recompute-in-backward) produces the same per-stage gradients and loss
    as the AD-derived GPipe path and the sequential oracle."""
    from horovod_tpu.parallel.pipeline import pipeline_1f1b_value_and_grad
    rng = np.random.RandomState(11)
    D, M = 3, 40            # M > K = 2(n-1)+1 so the input ring WRAPS
    Ws = rng.randn(N, D, D).astype(np.float32) * 0.4
    xs = rng.randn(M, 2, D).astype(np.float32)
    ts = rng.randn(M, 2, D).astype(np.float32)

    def stage_fn(W, x):
        return jnp.tanh(x @ W)

    def mb_loss(y, t):
        return jnp.mean((y - t) ** 2)

    # oracle: mean over microbatches of the sequential composition loss
    def seq_loss(Ws_all):
        h = jnp.asarray(xs)
        for s in range(N):
            h = jnp.tanh(h @ Ws_all[s])
        return jnp.mean((h - jnp.asarray(ts)) ** 2, axis=(1, 2)).mean()

    ref_loss, ref_grads = jax.value_and_grad(seq_loss)(jnp.asarray(Ws))

    mesh = create_mesh({"pp": N})
    vg = pipeline_1f1b_value_and_grad(stage_fn, mb_loss, "pp")

    def body(W, x, t):
        loss, g = vg(W[0], x, t)
        return loss[None], g[None]

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("pp"), P(), P()),
        out_specs=(P("pp"), P("pp")), check_vma=False))
    loss, grads = f(jnp.asarray(Ws), jnp.asarray(xs), jnp.asarray(ts))
    np.testing.assert_allclose(np.asarray(loss)[0], ref_loss, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_grads),
                               rtol=3e-4, atol=1e-5)


def test_pipeline_training_loss_decreases():
    """3 SGD steps through the pipelined value-and-grad: loss decreases
    (the dryrun's pp case runs the same shape)."""
    from horovod_tpu.parallel.pipeline import pipeline_value_and_grad
    rng = np.random.RandomState(7)
    D, M = 4, 6
    Ws = rng.randn(N, D, D).astype(np.float32) * 0.3
    xs = rng.randn(M, 2, D).astype(np.float32)
    ts = rng.randn(M, 2, D).astype(np.float32)

    def stage_fn(W, x):
        return jnp.tanh(x @ W)

    def loss_fn(outs, targets):
        return jnp.mean((outs - targets) ** 2)

    mesh = create_mesh({"pp": N})
    vg = pipeline_value_and_grad(stage_fn, loss_fn, "pp")

    def train(W, x, t):
        def body(carry, _):
            Wc = carry
            loss, g = vg(Wc, x, t)
            return Wc - 2.0 * g, loss
        Wf, losses = jax.lax.scan(body, W[0], None, length=8)
        return Wf[None], losses[None]

    f = jax.jit(shard_map(
        train, mesh=mesh, in_specs=(P("pp"), P(), P()),
        out_specs=(P("pp"), P("pp")), check_vma=False))
    _, losses = f(jnp.asarray(Ws), jnp.asarray(xs), jnp.asarray(ts))
    losses = np.asarray(losses)[0]  # replicated scalar per step
    # 8 stacked tanh stages gradient-starve the early ranks, so progress
    # per step is small; monotone decrease is the training signal.
    assert np.all(np.diff(losses) < 0), losses
    assert losses[-1] < losses[0], losses


# --- hybrid (multi-slice) mesh construction ---------------------------------

class _FakeSliceDevice:
    """Device stub carrying the slice/process topology attributes
    ``mesh_utils.create_hybrid_device_mesh`` keys on — lets the REAL
    multi-slice branch of ``create_hybrid_mesh`` run in a unit test
    (VERDICT r2 #6: that branch had only ever executed its fallback)."""

    def __init__(self, i, slice_index):
        self.id = i
        self.slice_index = slice_index
        self.process_index = slice_index
        self.platform = "cpu"
        self.device_kind = "fake-slice-dev"

    def __repr__(self):
        return f"fake(id={self.id},slice={self.slice_index})"


def test_hybrid_mesh_real_slice_branch():
    from horovod_tpu.parallel import create_hybrid_mesh

    devs = [_FakeSliceDevice(i, i // 4) for i in range(8)]
    mesh = create_hybrid_mesh(ici_axes={"dp": 2, "tp": 2},
                              dcn_axes={"dp": 2}, devices=devs)
    assert mesh.shape == {"dp": 4, "tp": 2}
    arr = mesh.devices
    # outer dp halves = the two slices; tp stays within a slice
    slices = np.vectorize(lambda d: d.slice_index)(arr)
    assert set(slices[:2].ravel()) == {0} and set(slices[2:].ravel()) == {1}
    for row in arr:
        assert len({d.slice_index for d in row}) == 1


def test_hybrid_mesh_user_dcn_axis_is_outermost():
    """ADVICE r2: a NON-canonical DCN axis name must still order
    outermost — the hierarchical paths assume axis[-1] is ICI-contiguous,
    and 'extras last' used to put a custom DCN axis innermost (silent
    bandwidth inversion)."""
    from horovod_tpu.parallel import create_hybrid_mesh

    devs = [_FakeSliceDevice(i, i // 4) for i in range(8)]
    mesh = create_hybrid_mesh(ici_axes={"tp": 4},
                              dcn_axes={"cross": 2}, devices=devs)
    assert mesh.axis_names == ("cross", "tp")
    slices = np.vectorize(lambda d: d.slice_index)(mesh.devices)
    assert set(slices[0].ravel()) == {0} and set(slices[1].ravel()) == {1}


def test_hybrid_mesh_fallback_raises_value_error_without_slices():
    """Real CPU devices carry no slice_index: the ValueError contract the
    dryrun's fallback branch catches (keep that honest print working)."""
    from horovod_tpu.parallel import create_hybrid_mesh

    with pytest.raises(ValueError):
        create_hybrid_mesh(ici_axes={"dp": 4}, dcn_axes={"dp": 2},
                           devices=jax.devices())
