"""Unit tests for the jit-step deadline monitor (core/watchdog.py) and the
peer-liveness push plumbing (elastic/service.py failure feed).

All deadline scenarios here are DETERMINISTIC in outcome: the blocked
"step" is an event-wait that can never complete, so the deadlines are the
only exit path — wall-clock bounds only how fast the rescue lands (each
asserted to stay well under the test timeout). The cross-process versions
live in tests/test_integration_run.py.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time

import pytest

from horovod_tpu.core.exceptions import HorovodInternalError
from horovod_tpu.core.watchdog import (ACTION_ENV, COMPILE_MULT_ENV,
                                       PEER_GRACE_ENV, STEP_TIMEOUT_ENV,
                                       StepMonitor, monitored_step)


def _clear_env(monkeypatch):
    for var in (STEP_TIMEOUT_ENV, PEER_GRACE_ENV, ACTION_ENV,
                COMPILE_MULT_ENV, "HOROVOD_ELASTIC_COORD_ADDR"):
        monkeypatch.delenv(var, raising=False)


def test_unarmed_is_direct_call(monkeypatch):
    _clear_env(monkeypatch)
    m = StepMonitor()
    assert not m.armed()
    out = m.monitored_call(lambda: 41 + 1, what="t")
    assert out == 42
    hb = m.heartbeat()
    assert hb["steps_completed"] == 1
    assert not hb["in_flight"]


def test_step_timeout_rescues_blocked_step(monkeypatch):
    _clear_env(monkeypatch)
    monkeypatch.setenv(STEP_TIMEOUT_ENV, "0.6")
    monkeypatch.setenv(COMPILE_MULT_ENV, "1")   # steady-state deadline
    m = StepMonitor()
    assert m.armed()
    t0 = time.monotonic()
    with pytest.raises(HorovodInternalError, match="STEP_TIMEOUT"):
        m.monitored_call(lambda: threading.Event().wait(), what="t")
    assert time.monotonic() - t0 < 10.0
    hb = m.heartbeat()
    assert not hb["in_flight"]


def test_monitor_recovers_after_expiry(monkeypatch):
    """In-process elastic recovery keeps training in THIS process after a
    deadline expiry: the wedged fetch thread must be orphaned, not block
    the next monitored step."""
    _clear_env(monkeypatch)
    monkeypatch.setenv(STEP_TIMEOUT_ENV, "0.5")
    monkeypatch.setenv(COMPILE_MULT_ENV, "1")
    m = StepMonitor()
    with pytest.raises(HorovodInternalError):
        m.monitored_call(lambda: threading.Event().wait(), what="t")
    assert m.monitored_call(lambda: "ok", what="t") == "ok"
    assert m.heartbeat()["steps_completed"] == 1


def test_expiry_marks_registered_engines_transport_lost(monkeypatch):
    _clear_env(monkeypatch)
    monkeypatch.setenv(STEP_TIMEOUT_ENV, "0.5")
    monkeypatch.setenv(COMPILE_MULT_ENV, "1")

    class FakeEngine:
        _transport_lost = None

    eng = FakeEngine()
    m = StepMonitor()
    m.register_engine(eng)
    with pytest.raises(HorovodInternalError):
        m.monitored_call(lambda: threading.Event().wait(), what="t")
    assert eng._transport_lost is not None
    assert "abandoned" in eng._transport_lost


def test_peer_failure_arms_grace_deadline(monkeypatch):
    """A peer-death notification rescues a blocked step with NO step
    timeout configured — the STALL=0 'blocked forever' scenario."""
    _clear_env(monkeypatch)
    monkeypatch.setenv(PEER_GRACE_ENV, "0.4")
    m = StepMonitor()
    # Deterministic ordering: the failure is known BEFORE the step blocks.
    m.notify_peer_failure("hostX(exit 137)")
    # peer deadline applies even though no coordinator is configured —
    # notify_peer_failure is the push's landing point either way.
    t0 = time.monotonic()
    with pytest.raises(HorovodInternalError, match="peer died"):
        m.monitored_call(lambda: threading.Event().wait(), what="t")
    assert time.monotonic() - t0 < 10.0


def test_membership_reset_arms_grace_deadline(monkeypatch):
    """A GRACEFUL membership bump (version moved past the launch version,
    nobody died) must also rescue a blocked round: the cooperative reset
    relies on commit-time polls, but a worker already parked inside a
    collective its peers abandoned never reaches another commit — the
    host-add deadlock this pins down (resetter wedged in the runtime's
    shutdown barrier against the survivor's dead round)."""
    from horovod_tpu.elastic import constants as C
    _clear_env(monkeypatch)
    monkeypatch.setenv(PEER_GRACE_ENV, "0.4")
    monkeypatch.setenv(C.WORLD_VERSION_ENV, "1")
    m = StepMonitor()
    # What the /world watcher sees mid-round: the driver bumped to v2.
    m._maybe_notify_membership_reset({"version": 2, "failure_seq": 0})
    t0 = time.monotonic()
    with pytest.raises(HorovodInternalError, match="hosts updated"):
        m.monitored_call(lambda: threading.Event().wait(), what="t")
    assert time.monotonic() - t0 < 10.0
    # Same-or-older versions must NOT arm.
    m2 = StepMonitor()
    m2._maybe_notify_membership_reset({"version": 1, "failure_seq": 0})
    assert not m2.armed()
    m2.reset_for_recovery()


def test_reset_for_recovery_clears_membership_reset(monkeypatch):
    """The in-process recovery path re-enters the NEW world: the old
    generation's membership-reset flag must not abandon its steps."""
    from horovod_tpu.elastic import constants as C
    _clear_env(monkeypatch)
    monkeypatch.setenv(PEER_GRACE_ENV, "0.1")
    monkeypatch.setenv(C.WORLD_VERSION_ENV, "1")
    m = StepMonitor()
    m._maybe_notify_membership_reset({"version": 2})
    time.sleep(0.2)   # grace long expired
    assert m.armed()
    m.reset_for_recovery()
    assert not m.armed()
    assert m.monitored_call(lambda: "ok", what="t") == "ok"


def test_peer_push_rescues_blocked_step(monkeypatch):
    """End-to-end push through the real CoordinatorService: driver marks a
    failure on /world, the monitor's watcher polls it up and abandons the
    in-flight step within poll interval + grace."""
    from horovod_tpu.elastic import constants as C
    from horovod_tpu.elastic.service import CoordinatorService
    from horovod_tpu.runner import secret as _secret

    _clear_env(monkeypatch)
    key = _secret.make_secret_key()
    svc = CoordinatorService(key, bind_host="127.0.0.1")
    try:
        monkeypatch.setenv(C.COORD_ADDR_ENV, svc.addr("127.0.0.1"))
        monkeypatch.setenv(_secret.ENV_VAR, _secret.encode(key))
        monkeypatch.setenv(C.POLL_INTERVAL_ENV, "0.1")
        monkeypatch.setenv(PEER_GRACE_ENV, "0.3")
        svc.update_world({"localhost": 2}, 2)
        m = StepMonitor()
        assert m.peer_watch_available() and m.armed()
        started = threading.Event()

        def blocked_step():
            started.set()
            threading.Event().wait()

        # The driver-side event: a worker process exited non-zero.
        svc.mark_failure("localhost", 137)
        t0 = time.monotonic()
        with pytest.raises(HorovodInternalError, match="peer died"):
            m.monitored_call(blocked_step, what="t")
        assert started.is_set()
        assert time.monotonic() - t0 < 15.0
    finally:
        svc.close()


def test_relaunched_survivor_ignores_stale_failure_seq(monkeypatch):
    """The coordinator's failure_seq is monotonic across generations; its
    failure LIST is generation-scoped. A relaunched survivor whose first
    poll sees a nonzero seq with an EMPTY list (its predecessor's death,
    already handled by the relaunch that created it) must NOT arm the
    grace deadline — arming it would abandon every step longer than the
    poll tick and restart-loop the job."""
    from horovod_tpu.elastic import constants as C
    from horovod_tpu.elastic.service import CoordinatorService
    from horovod_tpu.runner import secret as _secret

    _clear_env(monkeypatch)
    key = _secret.make_secret_key()
    svc = CoordinatorService(key, bind_host="127.0.0.1")
    try:
        monkeypatch.setenv(C.COORD_ADDR_ENV, svc.addr("127.0.0.1"))
        monkeypatch.setenv(_secret.ENV_VAR, _secret.encode(key))
        monkeypatch.setenv(C.POLL_INTERVAL_ENV, "0.05")
        monkeypatch.setenv(PEER_GRACE_ENV, "0.15")
        # Generation 0 died: a failure was recorded, then the driver
        # published the relaunched generation's world (clearing the list).
        svc.update_world({"a": 1, "b": 1}, 2)
        svc.mark_failure("b", 137)
        svc.update_world({"a": 1, "c": 1}, 2)
        # This monitor plays the relaunched survivor: a step far longer
        # than poll+grace must complete untouched.
        m = StepMonitor()
        assert m.peer_watch_available()
        out = m.monitored_call(lambda: time.sleep(1.0) or "ok", what="t")
        assert out == "ok"
        assert m.heartbeat()["peer_failure"] is None
    finally:
        svc.close()


def test_reset_for_recovery_clears_stale_peer_failure(monkeypatch):
    """In-process elastic recovery must disarm the old world's
    peer-failure flag: its grace deadline is long expired, so left set it
    would instantly abandon every step of the recovered run."""
    _clear_env(monkeypatch)
    monkeypatch.setenv(PEER_GRACE_ENV, "0.1")
    m = StepMonitor()
    m.notify_peer_failure("hostX(exit 137)")
    time.sleep(0.2)   # grace long expired
    assert m.armed()
    m.reset_for_recovery()
    assert m.heartbeat()["peer_failure"] is None
    assert not m.armed()
    assert m.monitored_call(lambda: "ok", what="t") == "ok"


def test_reinitialize_resets_step_monitor(monkeypatch):
    """The product wiring for the above: elastic run_fn's in-process
    re-init path resets the process-wide monitor."""
    import horovod_tpu as hvd
    from horovod_tpu.core import watchdog
    from horovod_tpu.elastic import run_fn

    _clear_env(monkeypatch)
    monkeypatch.setattr(hvd, "shutdown", lambda: None)
    monkeypatch.setattr(hvd, "init", lambda: None)
    m = watchdog.monitor()
    m.notify_peer_failure("hostX(exit 137)")
    try:
        run_fn._reinitialize()
        assert m.heartbeat()["peer_failure"] is None
    finally:
        m.reset_for_recovery()   # leave the global monitor clean


def test_late_completing_step_orphans_old_fetch_thread(monkeypatch):
    """A SPURIOUS expiry (the step completes after the deadline fired)
    must retire the old fetch thread: it may neither crash on the cleared
    queue nor keep consuming the replacement queue's items."""
    _clear_env(monkeypatch)
    monkeypatch.setenv(STEP_TIMEOUT_ENV, "0.4")
    monkeypatch.setenv(COMPILE_MULT_ENV, "1")
    m = StepMonitor()
    release = threading.Event()
    before = set(threading.enumerate())   # other tests' wedged workers
    with pytest.raises(HorovodInternalError):
        m.monitored_call(lambda: release.wait(), what="t")
    old = [t for t in threading.enumerate()
           if t.name == "hvd-step-fetch" and t not in before]
    assert old
    release.set()   # the "wedged" step now completes late
    for t in old:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in old)
    # The replacement worker owns the new queue alone.
    monkeypatch.setenv(STEP_TIMEOUT_ENV, "30")
    for i in range(3):
        assert m.monitored_call(lambda i=i: i, what="t") == i


def test_first_call_per_signature_gets_compile_allowance(monkeypatch):
    """The first monitored call of a signature includes XLA compilation:
    it gets STEP_TIMEOUT x COMPILE_MULTIPLIER, so a steady-state-tuned
    deadline does not abandon the compile step. Later calls run under the
    raw deadline."""
    _clear_env(monkeypatch)
    monkeypatch.setenv(STEP_TIMEOUT_ENV, "0.5")
    monkeypatch.setenv(COMPILE_MULT_ENV, "10")
    m = StepMonitor()
    # 1.2s "compile" step: over the raw 0.5s deadline, well under the 5s
    # first-call allowance.
    assert m.monitored_call(lambda: time.sleep(1.2) or "ok",
                            what="t") == "ok"
    # Steady state: the raw deadline applies again.
    t0 = time.monotonic()
    with pytest.raises(HorovodInternalError, match="STEP_TIMEOUT"):
        m.monitored_call(lambda: threading.Event().wait(), what="t")
    assert time.monotonic() - t0 < 4.0
    # reset_for_recovery re-grants the allowance (post-resize recompile).
    m.reset_for_recovery()
    assert m.monitored_call(lambda: time.sleep(1.2) or "ok",
                            what="t") == "ok"


def test_update_world_clears_failures():
    """Failures are scoped to one generation: publishing the next world
    view must clear them, or a relaunched survivor would immediately
    re-arm on its predecessor's death."""
    from horovod_tpu.elastic.service import CoordinatorService
    from horovod_tpu.runner import secret as _secret

    key = _secret.make_secret_key()
    svc = CoordinatorService(key, bind_host="127.0.0.1")
    try:
        svc.update_world({"a": 1, "b": 1}, 2)
        svc.mark_failure("b", 137)
        from horovod_tpu.elastic.service import CoordinatorClient
        client = CoordinatorClient(svc.addr("127.0.0.1"), key)
        world = client.get_world()
        assert world["failure_seq"] == 1
        assert world["failures"] == [{"host": "b", "code": 137}]
        svc.update_world({"a": 1}, 1)
        world = client.get_world()
        assert world["failure_seq"] == 1   # monotonic across generations
        assert world["failures"] == []
    finally:
        svc.close()


def test_runtime_error_translates_to_internal_error(monkeypatch):
    """A dead peer that ERRORS the collective (gloo connection reset /
    XlaRuntimeError) instead of hanging must reach @elastic.run as
    HorovodInternalError."""
    _clear_env(monkeypatch)
    monkeypatch.setenv(STEP_TIMEOUT_ENV, "30")   # armed, far from expiry

    class XlaRuntimeError(Exception):   # matched by name, like jaxlib's
        pass

    def exploding_step():
        raise XlaRuntimeError("connection reset by peer")

    m = StepMonitor()
    with pytest.raises(HorovodInternalError, match="runtime error"):
        m.monitored_call(exploding_step, what="t")
    # Non-runtime errors pass through untranslated (user bugs must not be
    # retried by the elastic loop).
    with pytest.raises(ValueError):
        m.monitored_call(lambda: (_ for _ in ()).throw(ValueError("x")),
                         what="t")


def test_monitored_step_preserves_attrs_and_results(monkeypatch):
    _clear_env(monkeypatch)

    def fn(a, b):
        return a + b
    fn.lower = lambda *a: "lowered"
    wrapped = monitored_step(fn, what="t")
    assert wrapped(2, 3) == 5
    assert wrapped.lower() == "lowered"


def test_exit_action_hard_exits_with_restart_code(monkeypatch):
    """HOROVOD_STEP_TIMEOUT_ACTION=exit: the process dies with
    RESTART_EXIT_CODE so the driver's fate-sharing takes over. Run in a
    subprocess — os._exit is not mockable meaningfully."""
    code = (
        "import os\n"
        f"os.environ['{ACTION_ENV}'] = 'exit'\n"
        "from horovod_tpu.core.watchdog import StepMonitor\n"
        "StepMonitor()._fail('test deadline')\n"
        "raise SystemExit(99)  # unreachable\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, timeout=120)
    from horovod_tpu.elastic import constants as C
    assert proc.returncode == C.RESTART_EXIT_CODE, proc.stderr.decode()
