"""BASELINE config 5: DLRM throughput with sharded embedding exchange.

The reference path is sparse allgather/allreduce of embedding gradients
(SURVEY.md §6). Here embedding tables shard over the ``ep`` axis and XLA
inserts the gather/exchange from the sharding annotations (GSPMD); metric
is examples/sec/chip.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from common import (emit, mfu_fields, on_tpu, params_count,
                    slope_time, sync)


def main():
    import flax.linen as nn
    from flax.linen import partitioning as nn_partitioning

    import horovod_tpu as hvd
    from horovod_tpu.models.dlrm import DLRM, bce_loss, dlrm_criteo, dlrm_tiny
    from horovod_tpu.models.llama import LOGICAL_RULES
    from horovod_tpu.parallel import create_mesh
    from horovod_tpu.train import rules_for_mesh

    hvd.init()
    n = hvd.size()
    tpu = on_tpu()
    cfg = dlrm_criteo() if tpu else dlrm_tiny()
    per_chip = 2048 if tpu else 16
    B = per_chip * n

    ep = min(8, n)
    mesh = create_mesh({"dp": n // ep, "ep": ep}) if n > 1 \
        else create_mesh({"dp": 1})
    rules = rules_for_mesh(mesh, LOGICAL_RULES)

    rng = np.random.RandomState(0)
    dense = jnp.asarray(rng.randn(B, cfg.dense_features).astype(np.float32))
    sparse = jnp.asarray(rng.randint(0, cfg.rows_per_table,
                                     (B, cfg.num_tables)))
    labels = jnp.asarray((rng.rand(B) < 0.3).astype(np.float32))

    model = DLRM(cfg)
    opt = optax.adagrad(1e-2)

    with nn_partitioning.axis_rules(rules):
        abs_vars = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                                  dense, sparse)
    sharding = nn.logical_to_mesh_sharding(
        nn.get_partition_spec(abs_vars["params"]), mesh, rules)

    def init_all(rng_):
        with nn_partitioning.axis_rules(rules):
            variables = model.init(rng_, dense, sparse)
        return variables["params"]

    with jax.sharding.set_mesh(mesh):
        params = jax.jit(init_all, out_shardings=sharding)(
            jax.random.PRNGKey(0))
    params = nn.meta.unbox(params)

    # Sparse embedding training (r4): the reference's defining DLRM
    # semantics — only looked-up rows update. The previous dense path
    # spent ~87% of the step materializing [26,100000,64] gradient
    # tables + dense Adagrad + table copies (profile_dlrm.py); sparse
    # Adagrad is numerically identical (zero-grad rows don't move) and
    # touches B*26 rows instead of 2.6M. Setup (flat tables, pinned
    # layouts, donation) is SHARED with profile_dlrm.py — see
    # dlrm_common.build_sparse_training for the rationale.
    from dlrm_common import build_sparse_training
    # count dense params BEFORE dropping the table buffer
    n_dense_params = params_count({k: v for k, v in params.items()
                                   if k != "embedding_tables"})
    jitted, dense_params, tables, accum, opt_state = build_sparse_training(
        model, cfg, mesh, rules, params)
    del params

    def run(k):
        nonlocal dense_params, tables, accum, opt_state
        loss = None
        with jax.sharding.set_mesh(mesh):
            for _ in range(k):
                dense_params, tables, accum, opt_state, loss = jitted(
                    dense_params, tables, accum, opt_state, dense,
                    sparse, labels)
        sync(loss)

    ex_per_sec = B / slope_time(run, 2, 8)
    # DLRM FLOPs/example: 6x the DENSE (MLP + interaction-projection)
    # params — embedding tables are lookups, not FLOPs; the pairwise
    # feature interaction adds 3 * 2 * F^2 * d (train = 3x fwd batched
    # dot of the F x d feature matrix).
    n_feats = cfg.num_tables + 1
    flops_ex = 6.0 * n_dense_params \
        + 6.0 * n_feats * n_feats * cfg.embed_dim
    emit("dlrm_examples_per_sec_per_chip", ex_per_sec / n,
         f"examples/sec/chip ({cfg.num_tables} tables x "
         f"{cfg.rows_per_table} rows, {n} devices)",
         **mfu_fields(ex_per_sec / n, flops_ex))


if __name__ == "__main__":
    main()
