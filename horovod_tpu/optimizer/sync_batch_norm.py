"""SyncBatchNorm — cross-replica batch normalisation.

Reference parity: ``horovod/torch/sync_batch_norm.py`` (SURVEY.md §2.4,
§2.6) — the reference allgathers per-worker batch statistics (sum, sum of
squares, count) and normalises with the global mean/var.

TPU-native: ``flax.linen.BatchNorm`` already supports exactly this via its
``axis_name`` argument (a ``psum`` of the statistics inside the compiled
graph — cheaper than the reference's allgather since only the reduced
moments travel). ``SyncBatchNorm`` pins ``axis_name`` to the Horovod rank
axis so a ported model gets cross-replica stats by default, and keeps the
reference's constructor knobs.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn

from ..core.context_api import RANK_AXIS


class SyncBatchNorm(nn.BatchNorm):
    """``flax.linen.BatchNorm`` that syncs batch statistics across the
    Horovod rank axis (and any extra axes given in ``axis_name``).

    Use inside a model traced under ``shard_map``/``pjit`` with the rank
    axis in scope, exactly where the reference's module replaces
    ``torch.nn.BatchNorm*d``.
    """

    axis_name: Optional[str] = RANK_AXIS
