"""Fleet chaos (ISSUE 19 acceptance, np=3, marked slow): the two
crash proofs the unit layer cannot give.

1. Coordinator SIGKILL mid-rebalance: a subprocess coordinator hosts a
   FleetArbiter oscillating the fleet shape under a synthetic overload/
   drain cycle, confirming each decision to a side file only AFTER the
   journal append returned. The parent SIGKILLs it mid-stream, replays
   the journal into a fresh coordinator, and proves (a) every CONFIRMED
   decision is in the journal verbatim (fsync-per-record — nothing
   acknowledged is lost), (b) the replayed fleet shape IS the last
   journaled decision, and (c) a new arbiter seeded from the replay
   continues the same rebalance at seq+1.

2. replica_kill / replica_hang mid-traffic: three REAL replica
   subprocesses (InferenceServer + ReplicaAgent, registered through the
   coordinator) serve a published model; the victim carries
   ``HOROVOD_FAULT_SPEC`` so the fault harness SIGKILLs (or wedges) it
   on its Nth admitted request. A FleetClient drives traffic through
   the coordinator's /replicas list: every accepted request completes
   via failover — no hangs, no 500s surfacing, no lost answers.

The in-process (fake-clock, fast) versions of these behaviors live in
tests/test_fleet.py; this file is the subprocess ground truth.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from horovod_tpu.elastic import constants as C
from horovod_tpu.elastic import journal as journal_mod
from horovod_tpu.elastic.arbiter import ArbiterPolicy, FleetArbiter
from horovod_tpu.elastic.service import CoordinatorClient, CoordinatorService
from horovod_tpu.runner import secret as _secret
from horovod_tpu.serving import Publisher
from horovod_tpu.elastic.state import ObjectState
from horovod_tpu.serving.fleet import FleetClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.slow, pytest.mark.integration]


def _sub_env(tmp_path, **extra):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("HOROVOD_FAULT_SPEC", None)
    env["HOROVOD_FAULT_MARKER_DIR"] = str(tmp_path / "fault_markers")
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _wait_for(pred, timeout=60, what="condition", proc=None):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        if proc is not None and proc.poll() is not None:
            out, err = proc.communicate(timeout=30)
            raise AssertionError(
                f"subprocess died waiting for {what}: "
                f"{out[-2000:]}\n{err[-2000:]}")
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


# ------------------------------------------- coordinator SIGKILL replay

ARBITER_VICTIM = """
import json
import os
import time
from horovod_tpu.platform import honor_jax_platforms_env
honor_jax_platforms_env()
from horovod_tpu.elastic.arbiter import ArbiterPolicy, FleetArbiter
from horovod_tpu.elastic.service import CoordinatorService

key = bytes.fromhex(os.environ["KEY_HEX"])
svc = CoordinatorService(key, bind_host="127.0.0.1",
                         journal_path=os.environ["JOURNAL"])
policy = ArbiterPolicy(queue_high=10.0, queue_low=1.0, staleness_high_s=0,
                       min_training_np=1, min_replicas=1, max_replicas=6,
                       cooldown_s=0.0, sustain=1)
arb = FleetArbiter(svc, total_hosts=8, policy=policy)
dec_path = os.environ["DECISIONS"]
t, direction = 0.0, "up"
while True:
    serving = arb.shape["serving_target"]
    if direction == "up" and serving >= policy.max_replicas:
        direction = "down"
    elif direction == "down" and serving <= policy.min_replicas:
        direction = "up"
    q = 99.0 if direction == "up" else 0.0
    svc._record_metrics({"rank": 901,
                         "g": {"hvd_serving_queue_depth": q}})
    d = arb.evaluate(now=t)
    t += 1.0
    if d is not None:
        # CONFIRM only after record_arbiter_decision returned: anything
        # in this file must survive the SIGKILL via the journal.
        with open(dec_path, "a") as f:
            f.write(json.dumps(d) + "\\n")
            f.flush()
            os.fsync(f.fileno())
    time.sleep(0.02)
"""


def _journal_arbiter_records(path):
    out = {}
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue        # torn trailing line from the SIGKILL
            if rec.get("op") == "arbiter":
                out[int(rec["seq"])] = (int(rec["serving_target"]),
                                        int(rec["training_np"]))
            elif rec.get("op") == "snapshot":
                st = rec.get("state") or {}
                if st.get("fleet") is not None:
                    out[int(st.get("arbiter_seq", 0))] = (
                        int(st["fleet"]["serving_target"]),
                        int(st["fleet"]["training_np"]))
    return out


def test_coordinator_sigkill_mid_rebalance_replays_same_fleet(tmp_path):
    """Kill the coordinator mid-rebalance; journal replay must restore
    the exact confirmed fleet shape and the arbiter must continue the
    SAME sequence, not restart it."""
    key = _secret.make_secret_key()
    journal = str(tmp_path / "wal.jsonl")
    decisions = str(tmp_path / "decisions.jsonl")
    script = tmp_path / "arbiter_victim.py"
    script.write_text(ARBITER_VICTIM)
    env = _sub_env(tmp_path, KEY_HEX=key.hex(), JOURNAL=journal,
                   DECISIONS=decisions)
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, env=env)
    try:
        _wait_for(lambda: os.path.exists(decisions)
                  and len(open(decisions).read().splitlines()) >= 3,
                  timeout=120, what=">=3 confirmed decisions", proc=proc)
        os.kill(proc.pid, signal.SIGKILL)
        proc.communicate(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)

    confirmed = [json.loads(l) for l in open(decisions)
                 if l.strip()]
    assert len(confirmed) >= 3
    last = confirmed[-1]

    # (a) every confirmed decision is in the journal verbatim
    jarb = _journal_arbiter_records(journal)
    for d in confirmed:
        assert jarb[d["seq"]] == (d["serving_target"], d["training_np"]), \
            f"confirmed decision {d} lost or mangled in the journal"

    # (b) replay restores the last journaled shape; at most ONE decision
    # can be journaled-but-unconfirmed (killed between fsync and confirm)
    svc = CoordinatorService(key, bind_host="127.0.0.1",
                             journal_path=journal, restore=True)
    try:
        view = svc.fleet_view()
        assert view["fleet"] is not None
        assert view["arbiter_seq"] == max(jarb)
        assert last["seq"] <= view["arbiter_seq"] <= last["seq"] + 1
        if view["arbiter_seq"] == last["seq"]:
            assert view["fleet"]["serving_target"] == last["serving_target"]
            assert view["fleet"]["training_np"] == last["training_np"]
        assert (view["fleet"]["serving_target"]
                + view["fleet"]["training_np"]) == 8

        # (c) a new arbiter adopts the replayed shape and continues the
        # sequence: its next decision is seq+1, shifted by exactly one
        policy = ArbiterPolicy(queue_high=10.0, queue_low=1.0,
                               staleness_high_s=0, min_training_np=1,
                               min_replicas=1, max_replicas=6,
                               cooldown_s=0.0, sustain=1)
        arb = FleetArbiter(svc, total_hosts=8, policy=policy)
        assert arb.shape == {
            "serving_target": view["fleet"]["serving_target"],
            "training_np": view["fleet"]["training_np"]}
        grow = arb.shape["serving_target"] < policy.max_replicas
        q = 99.0 if grow else 0.0
        svc._record_metrics({"rank": 901,
                             "g": {"hvd_serving_queue_depth": q}})
        d = arb.evaluate(now=0.0)
        assert d is not None
        assert d["seq"] == view["arbiter_seq"] + 1
        step = 1 if grow else -1
        assert d["serving_target"] == view["fleet"]["serving_target"] + step
        assert d["serving_target"] + d["training_np"] == 8
    finally:
        svc.close()


# ------------------------------------------- replica faults mid-traffic

REPLICA_WORKER = """
import os
import time
from horovod_tpu.platform import honor_jax_platforms_env
honor_jax_platforms_env()
import numpy as np
from horovod_tpu.checkpoint.store import BlobStore
from horovod_tpu.elastic.service import CoordinatorClient
from horovod_tpu.serving import InferenceServer, ModelRegistry
from horovod_tpu.serving.fleet import ReplicaAgent

key = bytes.fromhex(os.environ["KEY_HEX"])
store = BlobStore(os.path.join(os.environ["COMMIT_DIR"], "cas"))
reg = ModelRegistry(store=store)
assert reg.poll_store(store), "no published generation to adopt"


def forward(payload, inputs, padded_n):
    w = float(np.asarray(payload["attrs"]["w"]).reshape(-1)[0])
    return [w + float(q["x"]) for q in inputs]


srv = InferenceServer(reg, forward, window_s=0.002,
                      request_timeout_s=30.0,
                      rank=int(os.environ["REPLICA_RANK"]))
client = CoordinatorClient(os.environ["COORD_ADDR"], key,
                           watch_publish=True)
agent = ReplicaAgent(srv, client, replica_id=os.environ["REPLICA_ID"],
                     rank=int(os.environ["REPLICA_RANK"]))
assert agent.registered
agent.start()
if os.environ.get("ENABLE_PREEMPT_DRAIN") == "1":
    # Join the graceful-handoff plane: SIGTERM -> drain -> exit 0.
    assert agent.enable_preempt_drain(timeout_s=30.0)
print("ready", flush=True)
while not agent._closing:
    time.sleep(0.2)
print("drained", flush=True)
"""


def _published_commit_dir(tmp_path, w=7.0):
    d = str(tmp_path / "commits")
    os.makedirs(d, exist_ok=True)
    state = ObjectState(commit_dir=d, commit_async=False, w=np.float32(w))
    state.commit()
    pub = Publisher(d, every=1,
                    counters=lambda: {"steps_skipped": 0, "rollbacks": 0})
    assert pub.maybe_publish(state._commit_seq) is not None
    return d


def _spawn_fleet(tmp_path, service, key, commit_dir, n=3,
                 victim_idx=1, victim_fault=None, victim_env=None):
    script = tmp_path / "replica_worker.py"
    script.write_text(REPLICA_WORKER)
    procs = []
    for i in range(n):
        env = _sub_env(tmp_path, KEY_HEX=key.hex(),
                       COORD_ADDR=f"127.0.0.1:{service.port}",
                       COMMIT_DIR=commit_dir,
                       REPLICA_ID=f"chaos-{i}", REPLICA_RANK=901 + i)
        env[C.REPLICA_GRACE_ENV] = "60"
        if i == victim_idx and victim_fault:
            env["HOROVOD_FAULT_SPEC"] = victim_fault
        if i == victim_idx and victim_env:
            env.update(victim_env)
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env))
    return procs


def _registered_count(client):
    view = client.get_replicas()
    if view is None:
        return 0
    return len([r for r in view.get("replicas", [])
                if not r.get("draining")])


def _teardown(procs, service):
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            pass
    service.close()


def test_replica_kill_mid_traffic_completes_all_requests(tmp_path,
                                                         monkeypatch):
    """The ISSUE acceptance: one of three replicas is SIGKILLed by the
    fault harness mid-traffic; all 100 accepted requests still complete
    via client failover — none hang, none surface a 5xx."""
    monkeypatch.setenv(C.REPLICA_GRACE_ENV, "60")
    key = _secret.make_secret_key()
    commit_dir = _published_commit_dir(tmp_path)
    service = CoordinatorService(key, bind_host="127.0.0.1",
                                 journal_path=str(tmp_path / "wal.jsonl"))
    procs = _spawn_fleet(tmp_path, service, key, commit_dir,
                         victim_fault="replica_kill:req=10")
    try:
        client = CoordinatorClient(f"127.0.0.1:{service.port}", key)
        _wait_for(lambda: _registered_count(client) == 3,
                  timeout=90, what="3 registered replicas")
        fc = FleetClient(coord=client, timeout_s=15.0, refresh_s=0.2,
                         max_tries=12)
        done = 0
        for i in range(100):
            out = fc.predict({"x": float(i)})
            assert out.get("ok"), out
            assert out["result"] == pytest.approx(7.0 + i)
            done += 1
        assert done == 100                      # 100/100, zero lost
        assert fc.stats["requests"] == 100
        # the victim really died (SIGKILL from the fault harness) and
        # the client really absorbed it
        victim = procs[1]
        _wait_for(lambda: victim.poll() is not None, timeout=30,
                  what="victim death")
        assert victim.returncode == -signal.SIGKILL
        assert fc.stats["failovers"] >= 1
        # the survivors are still alive and serving
        assert procs[0].poll() is None and procs[2].poll() is None
    finally:
        _teardown(procs, service)


def test_replica_hang_mid_traffic_times_out_and_fails_over(tmp_path,
                                                           monkeypatch):
    """A wedged replica (alive at the socket, never answers — the mode
    liveness probes miss) costs each hit one client timeout, never a
    lost request: all 20 requests complete via failover."""
    monkeypatch.setenv(C.REPLICA_GRACE_ENV, "60")
    key = _secret.make_secret_key()
    commit_dir = _published_commit_dir(tmp_path)
    service = CoordinatorService(key, bind_host="127.0.0.1",
                                 journal_path=str(tmp_path / "wal.jsonl"))
    procs = _spawn_fleet(tmp_path, service, key, commit_dir,
                         victim_fault="replica_hang:req=3")
    try:
        client = CoordinatorClient(f"127.0.0.1:{service.port}", key)
        _wait_for(lambda: _registered_count(client) == 3,
                  timeout=90, what="3 registered replicas")
        fc = FleetClient(coord=client, timeout_s=2.0, refresh_s=0.2,
                         max_tries=12)
        done = 0
        for i in range(20):
            out = fc.predict({"x": float(i)})
            assert out.get("ok"), out
            assert out["result"] == pytest.approx(7.0 + i)
            done += 1
        assert done == 20
        assert fc.stats["failovers"] >= 1       # the wedge was absorbed
        # wedged, not dead: the victim process is still running — the
        # failure mode only client-side timeouts catch
        assert procs[1].poll() is None
    finally:
        _teardown(procs, service)


def test_replica_sigterm_drains_gracefully_under_traffic(tmp_path,
                                                         monkeypatch):
    """The ISSUE 20 serving acceptance (np=2, real processes, real
    SIGTERM): the victim replica catches the reclaim signal through the
    lifecycle plane, drains — routing stops at the coordinator, in-flight
    requests finish — and exits 0. All 100 accepted requests complete;
    the FleetClient never sees a reset, only (at most) failover."""
    monkeypatch.setenv(C.REPLICA_GRACE_ENV, "60")
    key = _secret.make_secret_key()
    commit_dir = _published_commit_dir(tmp_path)
    service = CoordinatorService(key, bind_host="127.0.0.1",
                                 journal_path=str(tmp_path / "wal.jsonl"))
    procs = _spawn_fleet(tmp_path, service, key, commit_dir, n=2,
                         victim_idx=1,
                         victim_env={"ENABLE_PREEMPT_DRAIN": "1"})
    try:
        client = CoordinatorClient(f"127.0.0.1:{service.port}", key)
        _wait_for(lambda: _registered_count(client) == 2,
                  timeout=90, what="2 registered replicas")
        fc = FleetClient(coord=client, timeout_s=15.0, refresh_s=0.2,
                         max_tries=12)
        victim = procs[1]
        done = 0
        for i in range(100):
            if i == 20:
                victim.send_signal(signal.SIGTERM)   # the reclaim notice
            out = fc.predict({"x": float(i)})
            assert out.get("ok"), out
            assert out["result"] == pytest.approx(7.0 + i)
            done += 1
        assert done == 100                           # 100/100, zero lost
        assert fc.stats["requests"] == 100
        # graceful exit, not a kill: drain completed and the worker left
        # its loop with status 0
        _wait_for(lambda: victim.poll() is not None, timeout=30,
                  what="victim graceful exit")
        assert victim.returncode == 0
        # the victim deregistered itself (drain -> deregister-on-drained):
        # the registry converges to the lone survivor with no pruning
        _wait_for(lambda: _registered_count(client) == 1, timeout=30,
                  what="survivor-only registry")
        assert procs[0].poll() is None               # survivor serving
        assert fc.predict({"x": 1.0}).get("ok")
    finally:
        _teardown(procs, service)
