"""Observability/tuning tools tests (SURVEY.md §5.1/§5.2/§2.1)."""

import json
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.core.exceptions import HorovodInternalError
from horovod_tpu.tools import (Autotuner, GaussianProcess, IntDim, LogIntDim,
                               CatDim, MismatchDetector, StallInspector,
                               StepAutotuner, Timeline, expected_improvement)


# --- timeline ----------------------------------------------------------------

def test_timeline_writes_valid_chrome_trace(tmp_path):
    p = str(tmp_path / "t.json")
    tl = Timeline(p, mark_cycles=True)
    tl.activity_start("ALLREDUCE", "DISPATCH", rank=0)
    tl.activity_end("ALLREDUCE", "DISPATCH", rank=0)
    tl.marker("EPOCH_END")
    tl.mark_cycle()
    with tl.span("CHECKPOINT"):
        pass
    tl.close()
    events = json.load(open(p))
    phases = [e["ph"] for e in events]
    assert phases.count("B") == phases.count("E") == 2
    assert "i" in phases
    names = {e["name"] for e in events}
    assert {"DISPATCH", "EPOCHEND" if "EPOCHEND" in names else "EPOCH_END",
            "CYCLE"} <= names


def test_timeline_via_env_records_eager_dispatch(tmp_path, monkeypatch):
    p = str(tmp_path / "hvd.json")
    monkeypatch.setenv("HOROVOD_TIMELINE", p)
    hvd.shutdown()
    hvd.init()
    hvd.eager.allreduce(jnp.ones((8, 2)))
    hvd.shutdown()   # closes the timeline
    events = json.load(open(p))
    cats = {e.get("cat") for e in events}
    assert "ALLREDUCE" in cats


# --- stall inspector ---------------------------------------------------------

def test_stall_inspector_warns_and_poisons():
    warned = []
    si = StallInspector(warning_sec=0.08, shutdown_sec=0.2,
                        on_stall=lambda idle: warned.append(idle),
                        poll_interval_sec=0.02)
    with si:
        time.sleep(0.45)
        assert warned, "warning callback never fired"
        with pytest.raises(HorovodInternalError):
            si.record()
    # after the poison is consumed, recording works again
    si.record(5)
    assert si._step == 5


def test_stall_inspector_wrap_records():
    si = StallInspector(warning_sec=100)
    calls = []
    stepped = si.wrap(lambda x: calls.append(x) or x * 2)
    assert stepped(3) == 6
    assert si._step == 1 and calls == [3]


def test_stall_inspector_from_config(monkeypatch):
    monkeypatch.setenv("HOROVOD_STALL_CHECK_TIME_SECONDS", "7")
    monkeypatch.setenv("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", "21")
    si = StallInspector.from_config()
    assert si.warning_sec == 7.0 and si.shutdown_sec == 21.0


# --- mismatch detector -------------------------------------------------------

def test_mismatch_detector_fingerprint_sensitivity():
    a, b = MismatchDetector(), MismatchDetector()
    a.record("allreduce", (4, 4), np.float32, "Average")
    b.record("allreduce", (4, 4), np.float32, "Average")
    assert a.fingerprint() == b.fingerprint()
    b.record("allreduce", (4, 8), np.float32, "Average")   # shape diverges
    assert a.fingerprint() != b.fingerprint()


def test_mismatch_detector_single_process_verify_noop():
    d = MismatchDetector()
    d.record("x", (1,), np.float32)
    d.verify("step 3")          # process_count()==1: never raises
    d.reset()
    assert d._count == 0


def test_mismatch_records_eager_ops_when_enabled(monkeypatch):
    from horovod_tpu.tools import detector
    detector.reset()
    monkeypatch.setenv("HOROVOD_MISMATCH_CHECK", "1")
    hvd.eager.allreduce(jnp.ones((8, 2)))
    assert detector._count >= 1
    assert any("allreduce" in s for s in detector._recent)
    detector.reset()


# --- autotuner ---------------------------------------------------------------

def test_gp_fits_and_predicts():
    X = np.linspace(0, 1, 8)[:, None]
    y = np.sin(3 * X[:, 0])
    gp = GaussianProcess()
    gp.fit(X, y)
    mu, sigma = gp.predict(X)
    np.testing.assert_allclose(mu, y, atol=0.05)     # interpolates
    mu2, sigma2 = gp.predict(np.asarray([[0.5]]))
    assert sigma2[0] < 0.5


def test_expected_improvement_prefers_uncertain_high_mean():
    mu = np.asarray([0.0, 1.0, 1.0])
    sigma = np.asarray([0.01, 0.01, 0.5])
    ei = expected_improvement(mu, sigma, best=1.0)
    assert ei[2] > ei[1] > ei[0] - 1e-12


def test_autotuner_finds_optimum_of_quadratic(tmp_path):
    """BO must beat random warmup on a smooth objective."""
    log = str(tmp_path / "autotune.csv")
    tuner = Autotuner({"x": IntDim(0, 100)}, warmup_trials=4, max_trials=20,
                      log_path=log, seed=3)
    while not tuner.converged():
        p = tuner.propose()
        score = -((p["x"] - 70) / 100.0) ** 2       # peak at x=70
        tuner.report(p, score)
    assert abs(tuner.best_params()["x"] - 70) <= 10
    rows = open(log).read().strip().splitlines()
    assert rows[0] == "trial,x,score" and len(rows) >= 5
    tuner.close()


def test_autotuner_dims_roundtrip():
    d = LogIntDim(1 << 20, 1 << 28)
    assert d.from_unit(0.0) == 1 << 20 and d.from_unit(1.0) == 1 << 28
    assert d.from_unit(d.to_unit(1 << 24)) == 1 << 24
    c = CatDim(("none", "minimal", "full"))
    assert c.from_unit(c.to_unit("minimal")) == "minimal"
    i = IntDim(1, 16)
    assert i.from_unit(i.to_unit(7)) == 7


def test_autotuner_patience_stops_early():
    tuner = Autotuner({"x": IntDim(0, 10)}, warmup_trials=2, max_trials=100,
                      patience=5, seed=0)
    n = 0
    while not tuner.converged():
        tuner.report(tuner.propose(), 0.0)          # flat: never improves
        n += 1
    assert n < 100


def test_autotuner_empty_space_rejected():
    with pytest.raises(ValueError):
        Autotuner({})


def test_stall_inspector_disable_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_STALL_CHECK_DISABLE", "1")
    si = StallInspector.from_config()
    si.start()
    assert si._thread is None           # kill-switch honored


def test_mismatch_enabled_case_insensitive(monkeypatch):
    monkeypatch.setenv("HOROVOD_MISMATCH_CHECK", "TRUE")
    assert MismatchDetector.enabled()


def test_autotuner_context_manager(tmp_path):
    with Autotuner({"x": IntDim(0, 4)}, warmup_trials=1, max_trials=2,
                   log_path=str(tmp_path / "l.csv")) as t:
        t.report(t.propose(), 1.0)
    assert t._log_writer is None        # closed on exit


def test_eager_adasum_cache_key_stable_with_process_set():
    """ProcessSet in the eager adasum key must not embed an address repr
    (permanent jit-cache miss + false cross-process mismatch)."""
    from horovod_tpu.collectives import eager as eager_mod
    ps = hvd.add_process_set([0, 1, 2, 3])
    before = len(eager_mod._jit_cache)
    hvd.eager.adasum_allreduce(jnp.ones((8, 2)), process_set=ps)
    mid = len(eager_mod._jit_cache)
    hvd.eager.adasum_allreduce(jnp.ones((8, 2)), process_set=ps)
    after = len(eager_mod._jit_cache)
    assert mid == before + 1 and after == mid   # second call: cache hit


def test_step_autotuner_trains_while_tuning():
    """StepAutotuner (reference parameter_manager role): real training
    progress during trials, convergence to the best knob set, best step
    used afterwards."""
    import numpy as np
    import optax

    from horovod_tpu.optimizer import distributed
    from horovod_tpu.train import create_train_state, make_train_step
    from horovod_tpu.models import ResNetTiny

    model = ResNetTiny(num_classes=10, axis_name=hvd.RANK_AXIS)
    opt = distributed(optax.sgd(0.1))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 8, 8, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, (16,)))

    def loss_fn(lg, yy):
        return optax.softmax_cross_entropy_with_integer_labels(
            lg, yy).mean()

    import jax

    state = create_train_state(model, jax.random.PRNGKey(0), x[:1], opt)
    builds = []

    def build(**kn):
        builds.append(dict(kn))
        return make_train_step(model, opt, loss_fn, donate=False, **kn)

    tuner = StepAutotuner(
        build, {"scan_steps": CatDim((1, 2))}, steps_per_trial=2,
        tuner=Autotuner({"scan_steps": CatDim((1, 2))},
                        warmup_trials=2, max_trials=3, patience=2))
    step0 = int(state.step)
    for _ in range(25):
        state, loss = tuner.step(state, x, y)
    assert tuner.chosen is not None and tuner.chosen["scan_steps"] in (1, 2)
    assert len(tuner.tuner._y) == 3          # all trials scored
    assert int(state.step) > step0           # trials made real progress
    assert np.isfinite(float(np.asarray(loss)))
    assert builds[-1] == tuner.chosen        # final step uses best knobs


def test_step_autotuner_skip_first_zero_times_correctly():
    from horovod_tpu.tools import StepAutotuner

    def build(**kn):
        def fn(x):
            return x + kn["k"]
        return fn

    tuner = StepAutotuner(
        build, {"k": IntDim(0, 3)}, steps_per_trial=2, skip_first=0,
        tuner=Autotuner({"k": IntDim(0, 3)}, warmup_trials=2,
                        max_trials=3, patience=2))
    for _ in range(12):
        tuner.step(jnp.zeros(()))
    assert len(tuner.tuner._y) == 3
    # Scores are steps/sec from a per-trial window, not seconds-since-epoch
    # garbage: all positive and sane.
    assert all(0 < y < 1e9 for y in tuner.tuner._y)


def test_train_step_marks_timeline(tmp_path):
    """With a timeline attached, make_train_step records a per-step
    dispatch span + cycle marker (the reference's MARK_CYCLES)."""
    import json
    import optax
    from flax import linen as nn
    from horovod_tpu.optimizer import distributed
    from horovod_tpu.train import create_train_state, make_train_step

    import jax

    path = str(tmp_path / "tl.json")
    hvd.start_timeline(path, mark_cycles=True)

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return nn.Dense(2)(x)

    def loss_fn(out, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            out, labels).mean()

    opt = distributed(optax.sgd(0.1))
    xs = jnp.asarray(np.random.RandomState(0).randn(8, 3).astype(np.float32))
    ys = jnp.asarray(np.random.RandomState(1).randint(0, 2, size=(8,)))
    state = create_train_state(M(), jax.random.PRNGKey(0), xs[:1], opt,
                               broadcast=False)
    step = make_train_step(M(), opt, loss_fn, donate=False)
    for _ in range(3):
        state, _ = step(state, xs, ys)
    hvd.stop_timeline()

    events = [e for e in json.load(open(path)) if isinstance(e, dict)]
    spans = [e for e in events if e.get("cat") == "TRAIN_STEP"
             and e.get("ph") == "B"]
    cycles = [e for e in events if e.get("name") == "CYCLE"]
    assert len(spans) >= 3, events[:20]
    assert len(cycles) >= 3, events[:20]
