"""Torch multi-host engine payload-path microbench: device-backed XLA
reduction vs the pre-r2 gather-everything path (VERDICT r1 "what's weak" #2).

Run under a REAL multi-process launch:

    hvdrun -np 2 -H localhost:1,127.0.0.1:1 python benchmarks/torch_engine_bw.py

Rank 0 prints one JSON line per message size:
  {"metric": "torch_engine_allreduce", "size_mb": S,
   "device_ms": ..., "gather_ms": ..., "speedup": ...}

The device path runs ONE jitted XLA psum over the process mesh (ring wire
cost, on-device reduce); the gather path allgathers every rank's full
payload (size + padded-bytes rounds, N x wire bytes) and reduces in numpy.
The crossover to device-path wins moves down with process count and
payload size.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import numpy as np
import torch  # noqa: F401  (torch API init expects it importable)

SIZES_MB = [0.25, 1, 4, 16]
REPEATS = 5


def time_op(fn) -> float:
    fn()  # warm (compile/cache)
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import horovod_tpu as hvd
    from horovod_tpu import torch as thvd

    hvd.init()
    thvd.init()
    rt = thvd.mpi_ops._rt()
    eng = rt.engine
    if not hasattr(eng, "_gather_allreduce"):
        print(json.dumps({"error": "needs the multi-process JaxProcessEngine"
                          " (run under hvdrun -np 2)"}))
        return

    for i, mb in enumerate(SIZES_MB):
        n = int(mb * 1024 * 1024 / 4)
        arr = np.random.RandomState(i).randn(n).astype(np.float32)
        dev = time_op(lambda: eng.allreduce(f"bw.dev.{i}", arr, "sum"))
        gat = time_op(lambda: eng._gather_allreduce(f"bw.gat.{i}", arr,
                                                    "sum"))
        if thvd.rank() == 0:
            print(json.dumps({
                "metric": "torch_engine_allreduce", "size_mb": mb,
                "device_ms": round(dev * 1e3, 2),
                "gather_ms": round(gat * 1e3, 2),
                "speedup": round(gat / dev, 2),
            }), flush=True)


if __name__ == "__main__":
    main()
