"""Tests for the ``horovod_tpu.torch`` compat API.

Reference parity: ``test/parallel/test_torch.py`` (SURVEY.md §4) — ops ×
dtypes, in-place/async variants, handles, grouped ops, DistributedOptimizer
behavior, broadcast of parameters/optimizer state/objects, SyncBatchNorm,
join. Multi-rank execution uses the thread-simulated engine
(horovod_tpu/torch/testing.py), the analog of the reference's CPU/Gloo
2-process tier.
"""

import numpy as np
import pytest
import torch

import horovod_tpu.torch as hvd
from horovod_tpu.torch.testing import run_parallel


@pytest.fixture(autouse=True)
def _clean():
    hvd.shutdown()
    yield
    hvd.shutdown()


# --- single-process (size 1) semantics --------------------------------------

def test_single_process_basics():
    hvd.init()
    assert hvd.size() == 1
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    t = torch.arange(6, dtype=torch.float32)
    assert torch.equal(hvd.allreduce(t, op=hvd.Sum), t)
    assert torch.equal(hvd.allgather(t), t)
    assert torch.equal(hvd.broadcast(t, 0), t)


def test_single_process_build_flags():
    assert not hvd.mpi_enabled()
    assert not hvd.nccl_built()


# --- multi-rank collectives -------------------------------------------------

@pytest.mark.parametrize("dtype", [torch.float32, torch.float64,
                                   torch.int32, torch.int64])
def test_allreduce_sum_dtypes(dtype):
    n = 4

    def fn(r):
        t = torch.full((3, 2), float(r + 1)).to(dtype)
        out = hvd.allreduce(t, op=hvd.Sum, name="x")
        assert out.dtype == dtype
        return out

    outs = run_parallel(n, fn)
    expect = torch.full((3, 2), 10.0).to(dtype)
    for o in outs:
        assert torch.equal(o, expect)


def test_allreduce_average():
    n = 4
    outs = run_parallel(
        n, lambda r: hvd.allreduce(torch.full((2,), float(r)), name="a"))
    for o in outs:
        assert torch.allclose(o, torch.full((2,), 1.5))


def test_allreduce_min_max_product():
    n = 3

    def fn(r):
        t = torch.tensor([float(r + 1), float(3 - r)])
        return (hvd.allreduce(t, op=hvd.Min, name="mn"),
                hvd.allreduce(t, op=hvd.Max, name="mx"),
                hvd.allreduce(t, op=hvd.Product, name="pr"))

    for mn, mx, pr in run_parallel(n, fn):
        assert torch.equal(mn, torch.tensor([1.0, 1.0]))
        assert torch.equal(mx, torch.tensor([3.0, 3.0]))
        assert torch.equal(pr, torch.tensor([6.0, 6.0]))


def test_allreduce_inplace_and_async():
    n = 2

    def fn(r):
        t = torch.full((4,), float(r + 1))
        h = hvd.allreduce_async_(t, op=hvd.Sum, name="ip")
        assert isinstance(h, int)
        out = hvd.synchronize(h)
        assert out is t  # in-place
        return t

    for o in run_parallel(n, fn):
        assert torch.equal(o, torch.full((4,), 3.0))


def test_poll_and_unknown_handle():
    hvd.init()
    t = torch.ones(2)
    h = hvd.allreduce_async(t, op=hvd.Sum)
    # completes quickly; poll must flip to True and synchronize returns
    hvd.synchronize(h)
    with pytest.raises(ValueError):
        hvd.poll(h)
    with pytest.raises(ValueError):
        hvd.synchronize(h)


def test_allreduce_prescale_postscale():
    n = 2

    def fn(r):
        t = torch.full((2,), 2.0)
        return hvd.allreduce(t, op=hvd.Sum, name="s",
                             prescale_factor=0.5, postscale_factor=3.0)

    for o in run_parallel(n, fn):
        assert torch.equal(o, torch.full((2,), 6.0))


def test_allreduce_fp16_compression():
    n = 2

    def fn(r):
        t = torch.full((8,), 1.5, dtype=torch.float32)
        out = hvd.allreduce(t, op=hvd.Sum, name="c",
                            compression=hvd.Compression.fp16)
        assert out.dtype == torch.float32
        return out

    for o in run_parallel(n, fn):
        assert torch.equal(o, torch.full((8,), 3.0))


def test_adasum_two_identical_ranks():
    # Identical gradients: dot = |g|² so each coefficient is 1 - 1/2 = 1/2
    # and the combine returns g — scale invariance in its purest form.
    n = 2

    def fn(r):
        t = torch.tensor([2.0, -1.0, 0.5])
        return hvd.allreduce(t, op=hvd.Adasum, name="ad")

    for o in run_parallel(n, fn):
        assert torch.allclose(o, torch.tensor([2.0, -1.0, 0.5]))


def test_adasum_orthogonal_ranks_sum():
    # Orthogonal gradients: dot = 0 → plain sum (reference property).
    n = 2

    def fn(r):
        t = torch.tensor([1.0, 0.0] if r == 0 else [0.0, 1.0])
        return hvd.allreduce(t, op=hvd.Adasum, name="ad2")

    for o in run_parallel(n, fn):
        assert torch.allclose(o, torch.tensor([1.0, 1.0]))


def test_allgather_uneven():
    n = 3

    def fn(r):
        t = torch.arange(r + 1, dtype=torch.float32) + 10 * r
        return hvd.allgather(t, name="g")

    expect = torch.cat([torch.arange(r + 1, dtype=torch.float32) + 10 * r
                        for r in range(n)])
    for o in run_parallel(n, fn):
        assert torch.equal(o, expect)


def test_broadcast_root_value():
    n = 4

    def fn(r):
        t = torch.full((3,), float(r))
        out = hvd.broadcast(t, root_rank=2, name="b")
        assert torch.equal(t, torch.full((3,), float(r)))  # input untouched
        return out

    for o in run_parallel(n, fn):
        assert torch.equal(o, torch.full((3,), 2.0))


def test_alltoall_even_and_splits():
    n = 2

    def fn(r):
        t = torch.arange(4, dtype=torch.float32) + 10 * r
        out = hvd.alltoall(t, name="a2a")
        sp = torch.tensor([1, 3])
        out2, recv = hvd.alltoall(torch.arange(4, dtype=torch.float32)
                                  + 10 * r, splits=sp, name="a2av")
        return out, out2, recv

    outs = run_parallel(n, fn)
    # even: rank0 gets [0,1, 10,11]; rank1 gets [2,3, 12,13]
    assert torch.equal(outs[0][0], torch.tensor([0.0, 1.0, 10.0, 11.0]))
    assert torch.equal(outs[1][0], torch.tensor([2.0, 3.0, 12.0, 13.0]))
    # splits [1,3]: rank0 receives first 1 of each; rank1 remaining 3
    assert torch.equal(outs[0][1], torch.tensor([0.0, 10.0]))
    assert torch.equal(outs[0][2], torch.tensor([1, 1]))
    assert torch.equal(outs[1][1],
                       torch.tensor([1.0, 2.0, 3.0, 11.0, 12.0, 13.0]))
    assert torch.equal(outs[1][2], torch.tensor([3, 3]))


def test_reducescatter():
    n = 2

    def fn(r):
        t = torch.arange(4, dtype=torch.float32)
        return hvd.reducescatter(t, op=hvd.Sum, name="rs")

    outs = run_parallel(n, fn)
    assert torch.equal(outs[0], torch.tensor([0.0, 2.0]))
    assert torch.equal(outs[1], torch.tensor([4.0, 6.0]))


def test_grouped_allreduce():
    n = 2

    def fn(r):
        ts = [torch.full((2,), float(r + 1)), torch.full((3,), float(r))]
        outs = hvd.grouped_allreduce(ts, op=hvd.Sum, name="grp")
        return outs

    for a, b in run_parallel(n, fn):
        assert torch.equal(a, torch.full((2,), 3.0))
        assert torch.equal(b, torch.full((3,), 1.0))


def test_barrier_and_out_of_order_names():
    # Ranks issue differently-ordered named ops; name matching resolves.
    n = 2

    def fn(r):
        if r == 0:
            a = hvd.allreduce_async(torch.tensor([1.0]), op=hvd.Sum,
                                    name="op_a")
            b = hvd.allreduce_async(torch.tensor([2.0]), op=hvd.Sum,
                                    name="op_b")
        else:
            b = hvd.allreduce_async(torch.tensor([20.0]), op=hvd.Sum,
                                    name="op_b")
            a = hvd.allreduce_async(torch.tensor([10.0]), op=hvd.Sum,
                                    name="op_a")
        return hvd.synchronize(a), hvd.synchronize(b)

    for a, b in run_parallel(n, fn):
        assert torch.equal(a, torch.tensor([11.0]))
        assert torch.equal(b, torch.tensor([22.0]))


def test_join_uneven_ranks():
    n = 3

    def fn(r):
        total = torch.zeros(1)
        steps = r + 1  # rank r has r+1 batches
        for i in range(steps):
            out = hvd.allreduce(torch.ones(1), op=hvd.Sum,
                                name=f"step.{i}")
            total += out
        last = hvd.join()
        return total, last

    outs = run_parallel(n, fn)
    # step 0: 3 ranks → 3; step 1: 2 ranks → 2; step 2: 1 rank → 1
    assert torch.equal(outs[0][0], torch.tensor([3.0]))
    assert torch.equal(outs[1][0], torch.tensor([5.0]))
    assert torch.equal(outs[2][0], torch.tensor([6.0]))
    assert all(last == 2 for _, last in outs)


# --- DistributedOptimizer ---------------------------------------------------

def _make_model(seed):
    torch.manual_seed(seed)
    return torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.ReLU(),
                               torch.nn.Linear(8, 1))


def test_distributed_optimizer_grad_averaging():
    n = 2
    # Threads share torch's global RNG, so per-rank seeded construction
    # races; distribute one canonical init instead (real users call
    # broadcast_parameters for the same reason).
    sd0 = _make_model(0).state_dict()

    def fn(r):
        model = _make_model(0)
        model.load_state_dict(sd0)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        x = torch.full((2, 4), float(r + 1))
        loss = model(x).sum()
        opt.zero_grad()
        loss.backward()
        opt.step()
        return [p.detach().clone() for p in model.parameters()]

    outs = run_parallel(n, fn)
    # After one averaged-gradient step both ranks must hold identical params.
    for p0, p1 in zip(*outs):
        assert torch.allclose(p0, p1)

    # And they must equal a single-process run on the concatenated batch
    # (grad of mean-over-ranks == grad on combined data here because each
    # rank's loss is a sum; average of the two sums = half the total).
    model = _make_model(0)
    model.load_state_dict(sd0)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    x = torch.cat([torch.full((2, 4), 1.0), torch.full((2, 4), 2.0)])
    loss = model(x).sum() / 2
    opt.zero_grad()
    loss.backward()
    opt.step()
    for p_ref, p_dist in zip(model.parameters(), outs[0]):
        assert torch.allclose(p_ref.detach(), p_dist, atol=1e-6)


def test_distributed_optimizer_backward_passes_per_step():
    n = 2
    sd1 = _make_model(1).state_dict()

    def fn(r):
        model = _make_model(1)
        model.load_state_dict(sd1)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2)
        for i in range(2):  # two backwards, one allreduce at the 2nd
            x = torch.full((2, 4), float(r + i + 1))
            model(x).sum().backward()
        opt.step()
        return [p.detach().clone() for p in model.parameters()]

    outs = run_parallel(n, fn)
    for p0, p1 in zip(*outs):
        assert torch.allclose(p0, p1)


def test_distributed_optimizer_zero_grad_guard():
    n = 2

    def fn(r):
        model = _make_model(2)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        model(torch.ones(1, 4)).sum().backward()
        try:
            opt.zero_grad()
        except AssertionError:
            opt.step()  # release outstanding handles
            return True
        return False

    assert all(run_parallel(n, fn))


def test_distributed_optimizer_isinstance_preserved():
    hvd.init()
    model = _make_model(3)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    assert isinstance(opt, torch.optim.SGD)
    sched = torch.optim.lr_scheduler.StepLR(opt, step_size=1)
    model(torch.ones(1, 4)).sum().backward()
    opt.step()
    sched.step()


# --- broadcast functions ----------------------------------------------------

def test_broadcast_parameters():
    n = 2

    def fn(r):
        model = _make_model(seed=r)  # deliberately different inits
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        return [p.detach().clone() for p in model.parameters()]

    outs = run_parallel(n, fn)
    for p0, p1 in zip(*outs):
        assert torch.equal(p0, p1)


def test_broadcast_optimizer_state():
    n = 2

    def fn(r):
        # The rank-divergent randn below is only flavor; the assertions
        # don't depend on which values each rank drew.
        torch.manual_seed(r)  # hvd-analyze: ok
        model = _make_model(seed=0)
        opt = torch.optim.SGD(model.parameters(), lr=0.1 * (r + 1),
                              momentum=0.9)
        # build momentum state, different per rank
        model(torch.randn(2, 4)).sum().backward()
        opt.step()
        hvd.broadcast_optimizer_state(opt, root_rank=0)
        st = opt.state_dict()
        return st["param_groups"][0]["lr"], [
            v["momentum_buffer"].clone()
            for v in st["state"].values()]

    outs = run_parallel(n, fn)
    assert outs[0][0] == outs[1][0] == pytest.approx(0.1)
    for m0, m1 in zip(outs[0][1], outs[1][1]):
        assert torch.equal(m0, m1)


def test_broadcast_optimizer_state_empty_workers():
    # The advertised resume pattern: rank 0 restores a checkpoint (has
    # momentum state), workers start FRESH (empty state) — must not
    # deadlock and must leave every rank with rank 0's state.
    n = 2
    sd = _make_model(0).state_dict()

    def fn(r):
        model = _make_model(0)
        model.load_state_dict(sd)
        opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        if r == 0:  # only root builds momentum state
            model(torch.ones(2, 4)).sum().backward()
            opt.step()
            opt.zero_grad()
        hvd.broadcast_optimizer_state(opt, root_rank=0)
        st = opt.state_dict()
        return [v["momentum_buffer"].clone() for v in st["state"].values()]

    outs = run_parallel(n, fn)
    assert len(outs[1]) == len(outs[0]) > 0
    for m0, m1 in zip(outs[0], outs[1]):
        assert torch.equal(m0, m1)


def test_broadcast_object():
    n = 3

    def fn(r):
        obj = {"epoch": r * 5, "name": f"rank{r}"} if r == 1 else None
        return hvd.broadcast_object(obj, root_rank=1)

    for o in run_parallel(n, fn):
        assert o == {"epoch": 5, "name": "rank1"}


def test_allgather_object():
    n = 3

    def fn(r):
        return hvd.allgather_object({"rank": r, "data": list(range(r))})

    for out in run_parallel(n, fn):
        assert out == [{"rank": i, "data": list(range(i))}
                       for i in range(n)]


# --- process sets -----------------------------------------------------------

def test_process_set_allreduce_disjoint_sets():
    n = 4

    def fn(r):
        lo = hvd.add_process_set([0, 1])
        hi = hvd.add_process_set([2, 3])
        ps = lo if r < 2 else hi
        out = hvd.allreduce(torch.tensor([float(r)]), process_set=ps,
                            name="ps_ar")
        return float(out)

    outs = run_parallel(n, fn)
    # {0,1} average to 0.5; {2,3} average to 2.5 — sets never mix.
    assert outs == [0.5, 0.5, 2.5, 2.5]


def test_process_set_allgather_and_broadcast():
    n = 4

    def fn(r):
        evens = hvd.add_process_set([0, 2])
        if r in (0, 2):
            g = hvd.allgather(torch.tensor([[r]]), process_set=evens,
                              name="ps_ag")
            b = hvd.broadcast(torch.tensor([r * 10]), root_rank=2,
                              process_set=evens, name="ps_bc")
            return g.flatten().tolist(), int(b)
        return None

    outs = run_parallel(n, fn)
    assert outs[0] == ([0, 2], 20) and outs[2] == ([0, 2], 20)
    assert outs[1] is None and outs[3] is None


def test_process_set_non_member_call_raises():
    n = 2

    def fn(r):
        ps = hvd.add_process_set([0])
        if r == 1:
            with pytest.raises(ValueError, match="not in process set"):
                hvd.allreduce(torch.tensor([1.0]), process_set=ps,
                              name="ps_bad")
        else:
            out = hvd.allreduce(torch.tensor([5.0]), process_set=ps,
                                name="ps_ok")
            assert float(out) == 5.0
        return True

    assert run_parallel(n, fn) == [True, True]


def test_process_set_registry_roundtrip():
    def fn(r):
        gs = hvd.global_process_set()
        assert gs.process_set_id == 0 and gs.size() == 2
        ps = hvd.add_process_set([0, 1])
        assert ps.process_set_id == 0  # same ranks as global -> same set
        ps2 = hvd.add_process_set([1])
        assert ps2.included(1) and not ps2.included(0)
        hvd.remove_process_set(ps2)
        return True

    assert run_parallel(2, fn) == [True, True]


# --- SyncBatchNorm ----------------------------------------------------------

def test_sync_batch_norm_matches_global_batch():
    n = 2
    torch.manual_seed(0)
    full = torch.randn(8, 3, 4, 4)

    def fn(r):
        bn = hvd.SyncBatchNorm(3, momentum=0.5)
        bn.train()
        local = full[r * 4:(r + 1) * 4]
        out = bn(local)
        return out.detach(), bn.running_mean.clone(), bn.running_var.clone()

    outs = run_parallel(n, fn)

    ref_bn = torch.nn.BatchNorm2d(3, momentum=0.5)
    ref_bn.train()
    ref_out = ref_bn(full)
    got = torch.cat([outs[0][0], outs[1][0]])
    assert torch.allclose(got, ref_out.detach(), atol=1e-5)
    assert torch.allclose(outs[0][1], ref_bn.running_mean, atol=1e-5)
    assert torch.allclose(outs[0][2], ref_bn.running_var, atol=1e-5)


def test_sync_batch_norm_backward():
    n = 2
    torch.manual_seed(1)
    full = torch.randn(4, 2, 3, 3)

    def fn(r):
        bn = hvd.SyncBatchNorm(2)
        bn.train()
        local = full[r * 2:(r + 1) * 2].clone().requires_grad_(True)
        bn(local).sum().backward()
        return bn.weight.grad.clone(), bn.bias.grad.clone()

    outs = run_parallel(n, fn)

    ref_bn = torch.nn.BatchNorm2d(2)
    ref_bn.train()
    x = full.clone().requires_grad_(True)
    ref_bn(x).sum().backward()
    # Each rank's weight/bias grad is local; their sum equals the global.
    wsum = outs[0][0] + outs[1][0]
    bsum = outs[0][1] + outs[1][1]
    assert torch.allclose(wsum, ref_bn.weight.grad, atol=1e-4)
    assert torch.allclose(bsum, ref_bn.bias.grad, atol=1e-4)


def test_sync_batch_norm_eval_is_local():
    hvd.init()
    bn = hvd.SyncBatchNorm(3)
    bn.eval()
    x = torch.randn(2, 3, 4, 4)
    out = bn(x)
    assert out.shape == x.shape


# --- TorchState (elastic) ---------------------------------------------------

def test_torch_state_commit_restore():
    hvd.init()
    from horovod_tpu.torch.elastic import TorchState
    model = _make_model(0)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    state = TorchState(model=model, optimizer=opt, epoch=0, batch=0)
    before = [p.detach().clone() for p in model.parameters()]
    model(torch.ones(2, 4)).sum().backward()
    opt.step()
    state.epoch = 7
    state.restore()
    assert state.epoch == 0
    for p, b in zip(model.parameters(), before):
        assert torch.equal(p.detach(), b)


def test_torch_state_sync_broadcasts_rank0():
    n = 2

    def fn(r):
        from horovod_tpu.torch.elastic import TorchState
        model = _make_model(seed=r)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        state = TorchState(model=model, optimizer=opt, epoch=r)
        state.sync()
        return state.epoch, [p.detach().clone()
                             for p in model.parameters()]

    outs = run_parallel(n, fn)
    assert outs[0][0] == outs[1][0] == 0
    for p0, p1 in zip(outs[0][1], outs[1][1]):
        assert torch.equal(p0, p1)


# --- sparse gradients -------------------------------------------------------

def test_sparse_allreduce_matches_dense_sum():
    n = 2

    def fn(r):
        dense = torch.zeros(6, 3)
        dense[r] = 1.0 + r          # rank-distinct rows
        dense[4] = 2.0              # overlapping row: coalesce must SUM
        sp = dense.to_sparse_coo()
        out = hvd.synchronize(hvd.sparse_allreduce_async(sp, op="sum",
                                                         name="sp"))
        return out.to_dense()

    for out in run_parallel(n, fn):
        expect = torch.zeros(6, 3)
        expect[0] = 1.0
        expect[1] = 2.0
        expect[4] = 4.0             # 2.0 from each rank
        torch.testing.assert_close(out, expect)


@pytest.mark.parametrize("sparse_as_dense", [False, True])
def test_distributed_optimizer_sparse_embedding(sparse_as_dense):
    """nn.Embedding(sparse=True) grads flow through the sparse path and all
    ranks converge to identical weights, matching a dense-grad run."""
    n = 2

    def fit(rank, sparse):
        emb = torch.nn.Embedding(8, 4, sparse=sparse)
        with torch.no_grad():
            # Deterministic init WITHOUT the global RNG: rank threads run
            # concurrently, so manual_seed would interleave draws.
            emb.weight.copy_(torch.arange(32, dtype=torch.float32)
                             .reshape(8, 4) / 10)
        opt = torch.optim.SGD(emb.parameters(), lr=0.1)
        hvd.broadcast_parameters(emb.state_dict(), root_rank=0)
        dopt = hvd.DistributedOptimizer(
            opt, named_parameters=emb.named_parameters(),
            sparse_as_dense=sparse_as_dense and sparse)
        for step in range(3):
            dopt.zero_grad()
            ids = torch.tensor([rank, 2 + rank, 5])  # rank-distinct + shared
            loss = emb(ids).sum()
            loss.backward()
            dopt.step()
        return emb.weight.detach().clone()

    sparse_out = run_parallel(n, lambda r: fit(r, True))
    torch.testing.assert_close(sparse_out[0], sparse_out[1])
    dense_out = run_parallel(n, lambda r: fit(r, False))
    torch.testing.assert_close(sparse_out[0], dense_out[0])


class _EmbLin(torch.nn.Module):
    """Sparse embedding + dense linear with deterministic init (rank threads
    run concurrently, so torch.manual_seed would interleave draws)."""

    def __init__(self):
        super().__init__()
        self.emb = torch.nn.Embedding(6, 3, sparse=True)
        self.lin = torch.nn.Linear(3, 1)
        with torch.no_grad():
            self.emb.weight.copy_(torch.arange(18, dtype=torch.float32)
                                  .reshape(6, 3))
            self.lin.weight.fill_(0.5)
            self.lin.bias.zero_()


def test_sparse_param_unused_on_one_rank_no_deadlock():
    """Rank 1 skips the sparse embedding for a step: its fill-in must be an
    EMPTY sparse contribution (same collective type as rank 0), not dense
    zeros — and both ranks still agree afterwards."""
    n = 2

    def fit(rank):
        m = _EmbLin()
        opt = torch.optim.SGD(m.parameters(), lr=0.1)
        dopt = hvd.DistributedOptimizer(
            opt, named_parameters=m.named_parameters())
        for step in range(2):
            dopt.zero_grad()
            if rank == 0 or step == 0:       # rank 1 skips emb on step 1
                loss = m.lin(m.emb(torch.tensor([rank, 3]))).sum()
            else:
                loss = m.lin(torch.ones(2, 3)).sum()
            loss.backward()
            dopt.step()
        return m.emb.weight.detach().clone(), m.lin.weight.detach().clone()

    outs = run_parallel(n, fit)
    torch.testing.assert_close(outs[0][0], outs[1][0])
    torch.testing.assert_close(outs[0][1], outs[1][1])


def test_sparse_param_unused_from_first_step_no_deadlock():
    """Rank 1 NEVER uses the sparse embedding: its per-rank sparse history
    is empty at the first synchronize, so only the up-front sparse-param
    metadata exchange can tell it to contribute an EMPTY sparse gradient
    instead of dense zeros (which would never rendezvous with rank 0's
    indices/values allgathers — collective-type mismatch → stall)."""
    n = 2

    def fit(rank):
        m = _EmbLin()
        opt = torch.optim.SGD(m.parameters(), lr=0.1)
        dopt = hvd.DistributedOptimizer(
            opt, named_parameters=m.named_parameters())
        for step in range(2):
            dopt.zero_grad()
            if rank == 0:                    # rank 1 never touches emb
                loss = m.lin(m.emb(torch.tensor([0, 3]))).sum()
            else:
                loss = m.lin(torch.ones(2, 3)).sum()
            loss.backward()
            dopt.step()
        return m.emb.weight.detach().clone(), m.lin.weight.detach().clone()

    outs = run_parallel(n, fit)
    torch.testing.assert_close(outs[0][0], outs[1][0])
    torch.testing.assert_close(outs[0][1], outs[1][1])


def test_sparse_param_activated_midrun_no_deadlock():
    """A sparse param unused by EVERY rank at step 0 and first touched at
    step 1 (and only by rank 0): the per-step metadata exchange must tell
    rank 1 before its fill-in, or it would contribute dense zeros against
    rank 0's sparse allgathers."""
    n = 2

    def fit(rank):
        m = _EmbLin()
        opt = torch.optim.SGD(m.parameters(), lr=0.1)
        dopt = hvd.DistributedOptimizer(
            opt, named_parameters=m.named_parameters())
        for step in range(3):
            dopt.zero_grad()
            if rank == 0 and step >= 1:      # emb activates at step 1
                loss = m.lin(m.emb(torch.tensor([0, 3]))).sum()
            else:
                loss = m.lin(torch.ones(2, 3)).sum()
            loss.backward()
            dopt.step()
        return m.emb.weight.detach().clone(), m.lin.weight.detach().clone()

    outs = run_parallel(n, fit)
    torch.testing.assert_close(outs[0][0], outs[1][0])
    torch.testing.assert_close(outs[0][1], outs[1][1])


def test_ordered_engine_deferred_submission_alignment():
    """Order-matched engines (``requires_ordered_submission``, e.g. the
    multi-host JaxProcessEngine) pair collectives POSITIONALLY across
    ranks, so every rank must submit the identical sequence even when
    backward-ready order and op sets diverge (param unused on one rank,
    sparse fill-ins). Hooks defer; synchronize() replays in canonical
    param-group order — this asserts the per-rank submission logs match."""
    from horovod_tpu.torch.engine import ThreadSimEngine
    n = 2

    class OrderedSim(ThreadSimEngine):
        requires_ordered_submission = True

        def __init__(self, n):
            super().__init__(n)
            self.log = {r: [] for r in range(n)}

        def allreduce(self, name, arr, op, members=None):
            self.log[self.rank()].append(("allreduce", name))
            return super().allreduce(name, arr, op, members)

        def allgather(self, name, arr, members=None):
            self.log[self.rank()].append(("allgather", name))
            return super().allgather(name, arr, members)

    eng = OrderedSim(n)

    def fit(rank):
        m = _EmbLin()
        opt = torch.optim.SGD(m.parameters(), lr=0.1)
        dopt = hvd.DistributedOptimizer(
            opt, named_parameters=m.named_parameters())
        for step in range(2):
            dopt.zero_grad()
            if rank == 0:                    # rank 1 never touches emb
                loss = m.lin(m.emb(torch.tensor([0, 3]))).sum()
            else:
                loss = m.lin(torch.ones(2, 3)).sum()
            loss.backward()
            dopt.step()
        return m.emb.weight.detach().clone(), m.lin.weight.detach().clone()

    outs = run_parallel(n, fit, engine=eng)
    torch.testing.assert_close(outs[0][0], outs[1][0])
    torch.testing.assert_close(outs[0][1], outs[1][1])
    assert eng.log[0] == eng.log[1], (
        "ranks submitted different collective sequences — positional "
        "pairing would cross-match ops on a real ordered engine")
    assert len(eng.log[0]) > 0


def test_grouped_reducescatter():
    n = 2

    def fn(r):
        ts = [torch.ones(4, 2) * (r + 1), torch.ones(2, 3) * (r + 1)]
        outs = hvd.grouped_reducescatter(ts, name="grs")
        return [o for o in outs]

    r0, r1 = run_parallel(n, fn)
    # sum over ranks = 3; first dim scattered across the 2 ranks
    torch.testing.assert_close(r0[0], torch.full((2, 2), 3.0))
    torch.testing.assert_close(r1[0], torch.full((2, 2), 3.0))
    torch.testing.assert_close(r0[1], torch.full((1, 3), 3.0))


# --- gradient tensor fusion (VERDICT r2 #1) ---------------------------------

class _CountingEngine:
    """ThreadSimEngine recording every engine-level allreduce name."""

    def __new__(cls, n):
        import threading as _threading
        from horovod_tpu.torch.engine import ThreadSimEngine

        class _Impl(ThreadSimEngine):
            def __init__(self, k):
                super().__init__(k)
                self.allreduce_names = []
                self._count_lock = _threading.Lock()

            def allreduce(self, name, arr, op, members=None, **kw):
                with self._count_lock:
                    self.allreduce_names.append(name)
                return super().allreduce(name, arr, op, members=members,
                                         **kw)
        return _Impl(n)


def _set_fusion_threshold(monkeypatch, value):
    """The optimizer resolves the threshold through the in-graph chain
    (override > context config > env), so a live context's config must be
    patched too — env alone is only read when no context exists."""
    import horovod_tpu.core.context_api as ctx_api
    if value is None:
        monkeypatch.delenv("HOROVOD_FUSION_THRESHOLD", raising=False)
    else:
        monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", str(value))
    if ctx_api.is_initialized():
        monkeypatch.setattr(
            ctx_api.context().config, "fusion_threshold_bytes",
            64 * 1024 * 1024 if value is None else value)


def _fusion_step(sd, r, lr=0.1):
    model = _make_model(3)
    model.load_state_dict(sd)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=lr),
        named_parameters=model.named_parameters())
    x = torch.full((2, 4), float(r + 1))
    model(x).sum().backward()
    opt.step()
    return [p.detach().clone() for p in model.parameters()]


def test_fused_gradient_hot_path_op_count(monkeypatch):
    """The gradient hot path is O(buckets), not O(parameters): the default
    64 MiB HOROVOD_FUSION_THRESHOLD packs all four gradients of the test
    model into ONE engine allreduce per step, while threshold 0 restores
    the per-parameter path (reference fusion_buffer_manager.cc semantics),
    and both produce identical parameters."""
    n = 2
    sd = _make_model(3).state_dict()

    def run(threshold):
        _set_fusion_threshold(monkeypatch, threshold)
        eng = _CountingEngine(n)
        outs = run_parallel(n, lambda r: _fusion_step(sd, r), engine=eng)
        return eng.allreduce_names, outs

    names_fused, outs_fused = run(None)
    assert len(names_fused) == n * 1, names_fused
    assert all(nm.startswith("fused_grad.float32.") for nm in names_fused)

    names_unfused, outs_unfused = run(0)
    assert len(names_unfused) == n * 4, names_unfused

    for a, b in zip(outs_fused[0], outs_unfused[0]):
        torch.testing.assert_close(a, b)
    for a, b in zip(*outs_fused):
        torch.testing.assert_close(a, b)


@pytest.mark.parametrize("threshold", [None, 0])
def test_distributed_optimizer_process_set(monkeypatch, threshold):
    """Reference optimizer `process_set=` kwarg (r4): gradients reduce
    among the set's MEMBERS only — member ranks average over the member
    count, the outside rank trains independently — on BOTH the fused
    and per-tensor paths (incl. the sparse-meta round, which must meet
    among members or the step deadlocks)."""
    _set_fusion_threshold(monkeypatch, threshold)
    n = 3
    sub = (0, 2)
    sd = _make_model(3).state_dict()

    def fn(r):
        import horovod_tpu.torch as thvd
        model = _make_model(3)
        model.load_state_dict(sd)
        # rank 1 gets a singleton set: 1 participant -> purely local
        # training (a global optimizer would wait on ranks 0/2 forever)
        ps = thvd.add_process_set(sub if r in sub else (1,))
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(), process_set=ps)
        # members feed 1 and 5 (mean 3) - distinct from rank 1's own 2
        x = torch.full((2, 4), float(r * r + 1))
        model(x).sum().backward()
        opt.step()
        return [p.detach().clone() for p in model.parameters()]

    outs = run_parallel(n, fn)
    # members 0 and 2 averaged grads over THE SET (inputs 1 and 5)
    for a, b in zip(outs[0], outs[2]):
        torch.testing.assert_close(a, b)
    # the singleton rank trained on its own data -> different params
    assert any(not torch.allclose(a, b)
               for a, b in zip(outs[0], outs[1]))
    # member result == a 2-process global run on the same member data
    sd2 = dict(sd)

    def member_global(r):
        model = _make_model(3)
        model.load_state_dict(sd2)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        m_rank = (0, 2)[r]
        x = torch.full((2, 4), float(m_rank * m_rank + 1))
        model(x).sum().backward()
        opt.step()
        return [p.detach().clone() for p in model.parameters()]

    ref = run_parallel(2, member_global)
    for a, b in zip(outs[0], ref[0]):
        torch.testing.assert_close(a, b)


def test_broadcast_helpers_and_allgather_object_process_set():
    """broadcast_parameters / broadcast_object / allgather_object accept
    process_set (r4 — reference functions.py parity): member-only
    rendezvous, member-ordered results, the outside rank untouched."""
    n = 3
    sub = (0, 2)

    def fn(r):
        import horovod_tpu.torch as thvd
        if r == 1:
            return ("outside", None, None)
        ps = thvd.add_process_set(sub)
        t = torch.full((3,), float(r))
        hvd.broadcast_parameters([("w", t)], root_rank=0, process_set=ps)
        obj = hvd.broadcast_object({"root": r} if r == 0 else None,
                                   root_rank=0, process_set=ps)
        gathered = hvd.allgather_object(("m", r), process_set=ps)
        return (t.clone(), obj, gathered)

    outs = run_parallel(n, fn)
    assert outs[1] == ("outside", None, None)
    for i in (0, 2):
        t, obj, gathered = outs[i]
        torch.testing.assert_close(t, torch.zeros(3))  # root 0's value
        assert obj == {"root": 0}
        assert gathered == [("m", 0), ("m", 2)]  # member order


def test_fused_adasum_matches_per_parameter(monkeypatch):
    """VERDICT r3 #4: op=Adasum fuses like Sum/Average — O(buckets)
    engine ops with each tensor's OWN coefficient pair applied inside
    the flat buffer via segment metadata (reference ops/adasum/adasum.h
    fused-buffer design). Fused and per-parameter runs must agree
    BIT-FOR-BIT (same combine arithmetic on the same slices)."""
    n = 2
    sd = _make_model(3).state_dict()

    def step(r):
        model = _make_model(3)
        model.load_state_dict(sd)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(), op=hvd.Adasum)
        x = torch.full((2, 4), float(r + 1))
        model(x).sum().backward()
        opt.step()
        return [p.detach().clone() for p in model.parameters()]

    def run(threshold):
        _set_fusion_threshold(monkeypatch, threshold)
        eng = _CountingEngine(n)
        outs = run_parallel(n, step, engine=eng)
        return eng.allreduce_names, outs

    names_fused, outs_fused = run(None)
    # one fused op per rank — previously Adasum paid P per-param rounds
    assert len(names_fused) == n * 1, names_fused
    assert all(nm.startswith("fused_grad.float32.") for nm in names_fused)

    names_unfused, outs_unfused = run(0)
    assert len(names_unfused) == n * 4, names_unfused

    for a, b in zip(outs_fused[0], outs_unfused[0]):
        torch.testing.assert_close(a, b, rtol=0, atol=0)  # bit-for-bit
    for a, b in zip(*outs_fused):
        torch.testing.assert_close(a, b, rtol=0, atol=0)


def test_fusion_threshold_shapes_buckets(monkeypatch):
    """Grads in canonical order are 128/32/32/4 bytes; a 130-byte cap must
    yield exactly two buckets with stable (cache-friendly) names."""
    n = 2
    sd = _make_model(3).state_dict()
    _set_fusion_threshold(monkeypatch, 130)
    eng = _CountingEngine(n)
    outs = run_parallel(n, lambda r: _fusion_step(sd, r), engine=eng)
    per_rank = sorted(nm for nm in eng.allreduce_names)[::n]
    assert per_rank == ["fused_grad.float32.0", "fused_grad.float32.1"], (
        eng.allreduce_names)
    for a, b in zip(*outs):
        torch.testing.assert_close(a, b)


def test_fused_matches_predivide_and_local_aggregation(monkeypatch):
    """Fusion composes with gradient_predivide_factor and
    backward_passes_per_step: fused and unfused runs agree."""
    n = 2
    sd = _make_model(4).state_dict()

    def fn(r):
        model = _make_model(4)
        model.load_state_dict(sd)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2, gradient_predivide_factor=2.0)
        for i in range(2):
            x = torch.full((2, 4), float(r + i + 1))
            model(x).sum().backward()
        opt.step()
        return [p.detach().clone() for p in model.parameters()]

    _set_fusion_threshold(monkeypatch, 64 * 1024 * 1024)
    fused = run_parallel(n, fn)
    _set_fusion_threshold(monkeypatch, 0)
    unfused = run_parallel(n, fn)
    for a, b in zip(fused[0], unfused[0]):
        torch.testing.assert_close(a, b)


def test_torch_ops_record_timeline_spans(tmp_path):
    """Engine ops write per-op spans into the HOROVOD_TIMELINE trace
    (reference timeline.cc records each collective's activities)."""
    import json as _json
    import horovod_tpu as hvdj
    from horovod_tpu.core.config import Config

    path = tmp_path / "tl.json"
    hvdj.shutdown()
    hvdj.init(config=Config(timeline_path=str(path)))
    hvd.shutdown()
    hvd.init()
    hvd.allreduce(torch.ones(3), name="tl_op")
    hvd.shutdown()
    hvdj.shutdown()  # closes the timeline writer

    events = _json.loads(path.read_text())
    if isinstance(events, dict):
        events = events["traceEvents"]
    # activity name is the event name; the tensor name rides "cat"
    # (timeline.cc convention mirrored by tools/timeline.py)
    assert any(e.get("name") == "ALLREDUCE" and e.get("cat") == "tl_op"
               for e in events), events[:10]


def test_allreduce_bf16_tensor_and_compression():
    """bf16 torch tensors cross the numpy engine boundary (view-cast —
    torch refuses bf16 .numpy()), and Compression.bf16 keeps an fp32
    gradient's wire payload at half size without fp16's overflow (1e5 >
    fp16 max)."""
    n = 2

    def fn(r):
        raw = hvd.allreduce(torch.tensor([1.0, 2.0], dtype=torch.bfloat16)
                            * (r + 1), op=hvd.Sum, name="bfraw")
        comp = hvd.allreduce(torch.tensor([1e5 * (r + 1), 0.5]),
                             op=hvd.Sum, name="bfc",
                             compression=hvd.Compression.bf16)
        return raw, comp

    for raw, comp in run_parallel(n, fn):
        assert raw.dtype == torch.bfloat16
        torch.testing.assert_close(
            raw.float(), torch.tensor([3.0, 6.0]), rtol=1e-2, atol=1e-2)
        torch.testing.assert_close(
            comp, torch.tensor([3e5, 1.0]), rtol=1e-2, atol=1e-2)


def test_op_dtype_dim_matrix():
    """SURVEY §4 bulk tier (reference test/parallel/test_torch.py: every
    op x dtype x dim): one 2-rank run sweeps the op surface over all wire
    dtypes and 1-3D shapes against exact numpy-model expectations. Values
    stay tiny so f16/bf16/uint8 sums are exact."""
    n = 2
    dtypes = [torch.float16, torch.bfloat16, torch.float32, torch.float64,
              torch.uint8, torch.int8, torch.int16, torch.int32,
              torch.int64]
    shapes = [(4,), (4, 3), (4, 3, 2)]

    def fn(r):
        f64 = torch.float64
        for dt in dtypes:
            for shape in shapes:
                tag = f"{str(dt).split('.')[-1]}.{len(shape)}"
                base = (torch.arange(int(np.prod(shape)))
                        .reshape(shape) % 5)
                t = (base + r + 1).to(dt)
                mine = (base + r + 1).to(f64)
                of_rank = lambda s: (base + s + 1).to(f64)
                total = of_rank(0) + of_rank(1)

                o = hvd.allreduce(t, op=hvd.Sum, name=f"mx.ar.{tag}")
                assert o.dtype == dt and o.shape == t.shape, (dt, shape)
                assert torch.equal(o.to(f64), total), (dt, shape)

                g = hvd.allgather(t, name=f"mx.ag.{tag}")
                assert g.shape == (shape[0] * n, *shape[1:]), (dt, shape)
                for s, p in enumerate(torch.chunk(g.to(f64), n, dim=0)):
                    assert torch.equal(p, of_rank(s)), (dt, shape, s)

                b = hvd.broadcast(t, root_rank=1, name=f"mx.bc.{tag}")
                assert b.dtype == dt, (dt, shape)
                assert torch.equal(b.to(f64), of_rank(1)), (dt, shape)

                a = hvd.alltoall(t, name=f"mx.a2a.{tag}")
                # even split: output = concat over ranks s of s's chunk r
                exp = torch.cat([torch.chunk(of_rank(s), n, dim=0)[r]
                                 for s in range(n)])
                assert torch.equal(a.to(f64), exp), (dt, shape)

                rs = hvd.reducescatter(t, op=hvd.Sum,
                                       name=f"mx.rs.{tag}")
                assert torch.equal(rs.to(f64),
                                   torch.chunk(total, n, dim=0)[r]), \
                    (dt, shape)
            # grouped op: once per dtype (2-D), list stays one fused round
            ts = [(base2 % 5 + r + 1).to(dt)
                  for base2 in (torch.arange(6).reshape(2, 3),
                                torch.arange(8).reshape(4, 2))]
            outs = hvd.grouped_allreduce(ts, op=hvd.Sum,
                                         name=f"mx.gar.{tag}")
            for t_in, o in zip(ts, outs):
                assert o.dtype == dt and o.shape == t_in.shape
        return True

    assert all(run_parallel(n, fn))
