"""Driver↔worker coordination service for elastic training.

Reference parity: this one HMAC-authenticated HTTP service collapses three
reference components (SURVEY.md §2.5/§3.4):

- ``runner/elastic/rendezvous.py`` (the re-init rendezvous KV store),
- ``runner/elastic/registration.py`` (worker registration/last-seen),
- ``runner/elastic/worker.py`` (WorkerNotificationService — driver→worker
  host-update pushes).

The push direction is inverted: instead of every worker hosting a
notification server the driver registers with, workers cheaply poll the
driver's ``/world`` for a monotonically-increasing membership *version* at
``state.commit()`` (rate-limited). A version newer than the generation a
worker was launched with means "hosts updated" → the state machinery raises
``HostsUpdatedInterrupt``. This removes two RPC surfaces and all
registration races while keeping the observable semantics: workers learn of
membership changes at commit boundaries, exactly where the reference's
interrupt lands (its notification also only takes effect at
commit/check points).

Wire format: JSON body + ``X-HVD-Sig`` HMAC (runner/secret.py) over the
body, both directions. Replay within a job is harmless (monotonic version).

Pod-scale protocol (benchmarks/control_plane.py measures each piece; the
upstream analog — the gloo rendezvous KV store — is SURVEY.md's flagged
melt mode at O(1000) workers):

- **Versioned deltas**: a client that has seen the world before sends its
  cursor (``since_v``/``since_s`` — the two monotonic counters; their sum
  is the event id, since every mutation bumps exactly one by 1). Unchanged
  → a tiny not-modified reply. Changed within the retained event window →
  only the events since the cursor, in the SAME record format the journal
  uses, replayed client-side through journal.apply_record. Too far behind
  (or incoherent) → full-snapshot fallback, counted client-side.
- **Bounded long-poll**: ``wait=<s>`` parks the request server-side (one
  thread per parked poll, ``ThreadingHTTPServer``) until the event id
  moves or the bound expires (clamped to ``LONG_POLL_CAP_S``). Background
  watchers get event-driven notification — failure push latency drops from
  "next tick" to immediate — while steady-state request rate drops to ~one
  per client per bound.
- **Advertised pacing**: every ``/world`` reply carries
  ``poll_s = max(DEFAULT_POLL_INTERVAL_S, np / TARGET_RPS)`` and plain
  pollers stretch their cadence to it, so aggregate request rate stays
  ~flat as the world grows instead of linear in np.
- **Coalesced registration**: ``/register`` accepts ``process_ids`` (one
  request + ONE journal fsync per host) alongside single ``process_id``.
- **Journal compaction**: after ``HOROVOD_COORDINATOR_JOURNAL_COMPACT_EVERY``
  journaled mutations the live state is folded into one snapshot record
  (elastic/journal.py) so crash-restart replay is O(live state).

Control-plane hardening (docs/failure_model.md "control plane" rows):

- **Retrying client**: every logical call makes up to
  ``HOROVOD_COORDINATOR_RPC_RETRIES`` attempts under exponential backoff
  with decorrelated jitter (:class:`RetryPolicy`), each attempt bounded by
  ``HOROVOD_COORDINATOR_RPC_TIMEOUT_SECONDS``. Transient unreachability is
  therefore absorbed; *persistent* loss — continuous failure for
  ``HOROVOD_COORDINATOR_LOST_TIMEOUT_SECONDS`` — raises
  :class:`CoordinatorLostError` so callers escalate instead of treating a
  dead driver as "no change" forever. HMAC-signature failures are counted
  (``sig_failures``) and logged distinctly from ``OSError`` — a tampered
  response is not a network blip.
- **Crash-restart**: the service journals every state mutation
  (elastic/journal.py); the driver rebuilds a dead service from the journal
  with both monotonic counters intact and republishes the new port via the
  address file (``HOROVOD_ELASTIC_COORD_ADDR_FILE``), which the client
  re-reads on connect failure. The rebuilt service starts with an empty
  event window, so surviving delta clients land exactly once on the
  snapshot fallback and resume deltas from there.
- **Fault seam**: when ``HOROVOD_FAULT_SPEC`` is armed, each client attempt
  consults testing/faults.py for call-count-scheduled ``rpc_*`` faults
  (drop/delay/refuse/garble/badsig) — chaos tests inject control-plane
  failures deterministically at this one seam, delta and snapshot replies
  alike.
"""

from __future__ import annotations

import collections
import json
import os
import random
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Iterable, Iterator, Optional
from urllib.parse import parse_qs, urlsplit

from ..core import telemetry as _telemetry
from ..core.logging import get_logger
from ..runner import secret as _secret
from . import constants as C
from .journal import (CoordinatorJournal, apply_record as _apply_record,
                      replay as _journal_replay)

SIG_HEADER = "X-HVD-Sig"

#: The exact keys of the canonical world payload — what ``get_world``
#: returns regardless of which wire shape (full/nm/delta/snapshot)
#: produced it. Frozen by tests/test_elastic.py's dict-equality asserts.
WORLD_KEYS = ("version", "hosts", "np", "failures", "failure_seq")


class CoordinatorLostError(RuntimeError):
    """The coordinator has been continuously unreachable past
    ``HOROVOD_COORDINATOR_LOST_TIMEOUT_SECONDS`` — the control plane is
    considered lost and the worker must escalate (not an ``OSError``
    subclass on purpose: callers that absorb transient ``OSError`` must
    not absorb this)."""


class _SignatureError(Exception):
    """A response failed HMAC verification (tampered/corrupt — tracked
    separately from transport errors)."""


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        return default


@dataclass
class RetryPolicy:
    """Bounded-retry schedule: exponential backoff with decorrelated
    jitter (each sleep is uniform over [base, 3×previous], capped), the
    schedule that avoids retry synchronization across a fleet of workers
    all hammering a recovering coordinator at once."""

    attempts: int = C.DEFAULT_RPC_RETRIES
    timeout_s: float = C.DEFAULT_RPC_TIMEOUT_S      # per-attempt deadline
    backoff_base_s: float = C.DEFAULT_RPC_BACKOFF_BASE_S
    backoff_cap_s: float = C.DEFAULT_RPC_BACKOFF_CAP_S

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        return cls(
            attempts=max(1, _env_int(C.RPC_RETRIES_ENV,
                                     C.DEFAULT_RPC_RETRIES)),
            timeout_s=_env_float(C.RPC_TIMEOUT_ENV, C.DEFAULT_RPC_TIMEOUT_S),
            backoff_base_s=_env_float(C.RPC_BACKOFF_BASE_ENV,
                                      C.DEFAULT_RPC_BACKOFF_BASE_S),
        )

    @classmethod
    def for_resume(cls) -> "RetryPolicy":
        """The peer-blob-fetch variant (elastic/blobmesh.py): same
        attempt/backoff schedule as coordinator RPCs, but a per-attempt
        deadline sized for shipping blobs (a whole model shard), not a
        JSON world view (``HOROVOD_RESUME_FETCH_TIMEOUT_SECONDS``)."""
        return cls(
            attempts=max(1, _env_int(C.RPC_RETRIES_ENV,
                                     C.DEFAULT_RPC_RETRIES)),
            timeout_s=_env_float(C.RESUME_FETCH_TIMEOUT_ENV,
                                 C.DEFAULT_RESUME_FETCH_TIMEOUT_S),
            backoff_base_s=_env_float(C.RPC_BACKOFF_BASE_ENV,
                                      C.DEFAULT_RPC_BACKOFF_BASE_S),
        )

    def delays(self, rng: Optional[random.Random] = None) -> Iterator[float]:
        """The ``attempts - 1`` sleeps between attempts. Deterministic
        under an injected seeded ``rng`` (the fake-clock unit tests);
        process-global randomness otherwise."""
        uniform = (rng or random).uniform
        prev = self.backoff_base_s
        for _ in range(max(self.attempts - 1, 0)):
            prev = min(self.backoff_cap_s,
                       uniform(self.backoff_base_s, prev * 3))
            yield prev


class CoordinatorService:
    """Launcher-side service holding the current membership view.

    With ``journal_path`` set, every mutation is appended to the
    write-ahead journal; ``restore=True`` replays it first so a rebuilt
    service resumes with the SAME monotonic ``version`` and
    ``failure_seq`` its predecessor published (survivors' watchers
    baseline those counters — see elastic/journal.py for why a reset
    would silently disable the peer-liveness rescue)."""

    def __init__(self, secret_key: bytes, bind_host: str = "0.0.0.0",
                 journal_path: Optional[str] = None, restore: bool = False,
                 clock: Callable[[], float] = time.monotonic):
        self._key = secret_key
        self._clock = clock
        self._lock = threading.Lock()
        # Long-poll park/wake shares the service lock: mutators already
        # hold it, so notify_all from inside their critical sections is
        # legal, and parked handlers re-check state without a second lock.
        self._cond = threading.Condition(self._lock)
        self._closing = False
        self._version = 0
        self._hosts: Dict[str, int] = {}
        self._np = 0
        self._started: Dict[int, float] = {}   # process_id -> monotonic ts
        # Peer-liveness push (docs/failure_model.md): worker exits the
        # driver observed this generation. ``_failure_seq`` is monotonic
        # across generations so a worker's watcher can detect NEW failures
        # by comparing sequence numbers; the failure list itself is scoped
        # to one generation (cleared by update_world) so a relaunched
        # survivor does not re-arm on its predecessor's death.
        self._failures: list = []
        self._failure_seq = 0
        # Announced graceful departures (core/lifecycle.py → POST
        # /preempt). Generation-scoped like ``_failures`` (cleared by
        # update_world) but carried on the VERSION counter: survivors take
        # the graceful HostsUpdatedInterrupt path and the peer-failure
        # grace deadline never arms for an announced exit.
        self._preempts: list = []
        # Delta window: (eid, record) pairs in journal-record format; eid
        # is version+failure_seq AFTER the record applied (consecutive —
        # each mutation bumps exactly one counter by 1). Registrations do
        # not enter the window: they are not part of the world payload, so
        # a registration storm cannot evict membership history.
        self._events: collections.deque = collections.deque(
            maxlen=max(1, _env_int(C.EVENT_BUFFER_ENV,
                                   C.DEFAULT_EVENT_BUFFER)))
        self._target_rps = max(0.0, _env_float(C.TARGET_RPS_ENV,
                                               C.DEFAULT_TARGET_RPS))
        self._compact_every = max(0, _env_int(C.COMPACT_EVERY_ENV,
                                              C.DEFAULT_COMPACT_EVERY))
        # Aggregated worker telemetry: rank (str) -> {"c": {...}, "g":
        # {...}} compact snapshots (core/telemetry.py wire shape). NOT
        # part of the /world payload (WORLD_KEYS is frozen) and never
        # enters the delta window — served separately at GET /metrics.
        self._metrics: Dict[str, dict] = {}
        # Serving-plane publish pointer (serving/publisher.py): the newest
        # known-good published weights record, plus its own monotonic
        # cursor. Like metrics it never bumps version/failure_seq and
        # never enters the delta window (WORLD_KEYS stays frozen) — it
        # rides on /world replies as extra keys only for clients that ask
        # (``since_p``), and has its own long-poll wake so a serving
        # process learns of a publish immediately without new RPCs.
        self._publish: Optional[dict] = None
        self._publish_seq = 0
        # Serving-replica registry (serving/fleet.py): replica_id ->
        # {"addr", "rank", "draining", "last_seen"}. Registration/drain/
        # deregistration are journaled (op:"replica"); heartbeats —
        # ``last_seen`` bumps from ``/world?replica=<id>`` arrivals and
        # replies — are ephemeral. A replica silent past
        # ``HOROVOD_REPLICA_GRACE_SECONDS`` is health-gated out of
        # ``/replicas`` (journaled, so replay agrees). Replica churn never
        # bumps version/failure_seq: the TRAINING world's delta cursors
        # must not move for serving-plane membership.
        self._replicas: Dict[str, dict] = {}
        # Fleet-arbiter decision state (elastic/arbiter.py): its own
        # monotonic sequence plus the last decided fleet shape, both
        # journal-replayed so a coordinator crash-restart resumes the
        # SAME rebalance instead of forgetting it mid-move.
        self._arbiter_seq = 0
        self._fleet: Optional[dict] = None
        self._journal = CoordinatorJournal(journal_path) if journal_path \
            else None
        if restore and journal_path:
            state = _journal_replay(journal_path)
            if state is not None:
                self._version = state["version"]
                self._hosts = state["hosts"]
                self._np = state["np"]
                self._failures = state["failures"]
                self._failure_seq = state["failure_seq"]
                self._preempts = [dict(p) for p
                                  in state.get("preempts", [])]
                self._started = {int(k): v for k, v
                                 in state["registrations"].items()}
                self._metrics = state.get("metrics", {})
                self._publish = state.get("publish")
                self._publish_seq = int(state.get("publish_seq", 0))
                # Restored replicas get ONE fresh grace window from the
                # restart (last_seen is liveness, not membership — the
                # journal cannot know who survived the coordinator).
                now = self._clock()
                self._replicas = {
                    str(k): {"addr": v["addr"],
                             "rank": int(v.get("rank", 0)),
                             "draining": bool(v.get("draining", False)),
                             "last_seen": now}
                    for k, v in state.get("replicas", {}).items()}
                self._arbiter_seq = int(state.get("arbiter_seq", 0))
                fleet = state.get("fleet")
                self._fleet = dict(fleet) if fleet is not None else None
                get_logger().info(
                    "coordinator state restored from journal %s "
                    "(version=%d failure_seq=%d hosts=%s)", journal_path,
                    self._version, self._failure_seq, self._hosts)

        svc = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _peer(self) -> str:
                try:
                    return f"{self.client_address[0]}:{self.client_address[1]}"
                except (TypeError, IndexError):
                    return "?"

            def _reply(self, obj, code=200):
                body = json.dumps(obj).encode()
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header(SIG_HEADER,
                                     _secret.sign(svc._key, body))
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (OSError, ValueError):
                    # The peer gave up (typically: timed out and hung up
                    # while this handler was parked in a long-poll).
                    # Nothing left to tell it.
                    pass

            def _reply_text(self, text: str, code=200):
                body = text.encode()
                try:
                    self.send_response(code)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header(SIG_HEADER,
                                     _secret.sign(svc._key, body))
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (OSError, ValueError):
                    pass

            def do_GET(self):
                parsed = urlsplit(self.path)
                if parsed.path == "/metrics":
                    # Prometheus text exposition: per-rank samples (rank
                    # label injected) + fleet rollup. Plain text so a
                    # stock scraper / curl needs no HMAC support (the
                    # signature header is still set for our own client).
                    self._reply_text(svc.metrics_text())
                    return
                if parsed.path == "/replicas":
                    self._reply(svc.replicas_view())
                    return
                if parsed.path != "/world":
                    get_logger().debug(
                        "coordinator: unknown GET path %s from %s",
                        self.path, self._peer())
                    self._reply({"error": "not found"}, 404)
                    return
                q = parse_qs(parsed.query)

                def _qnum(name, cast):
                    try:
                        return cast(q[name][0])
                    except (KeyError, IndexError, ValueError, TypeError):
                        return None

                since_v = _qnum("since_v", int)
                since_s = _qnum("since_s", int)
                since_p = _qnum("since_p", int)
                replica_id = None
                try:
                    replica_id = q["replica"][0] or None
                except (KeyError, IndexError):
                    pass
                wait_s = min(max(_qnum("wait", float) or 0.0, 0.0),
                             C.LONG_POLL_CAP_S)
                cursor = (since_v + since_s) \
                    if since_v is not None and since_s is not None else None
                with svc._cond:
                    # Replica heartbeat rides the existing poll: touch at
                    # arrival AND at reply, so a request parked in the
                    # long-poll below still proves liveness on both ends
                    # of the park.
                    svc._touch_replica_locked(replica_id)
                    if (cursor is not None or since_p is not None) \
                            and wait_s > 0:
                        svc._cond.wait_for(
                            lambda: svc._closing or
                            (cursor is not None and
                             svc._version + svc._failure_seq != cursor) or
                            (since_p is not None and
                             svc._publish_seq != since_p),
                            timeout=wait_s)
                    svc._touch_replica_locked(replica_id)
                    reply = svc._world_reply_locked(since_v, since_s)
                    if since_p is not None:
                        # Publish extras ride as reply-level keys the
                        # canonical-world extraction strips (same channel
                        # poll_s uses) — only for clients that asked.
                        reply["publish_seq"] = svc._publish_seq
                        reply["publish"] = svc._publish
                self._reply(reply)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(n)
                sig = self.headers.get(SIG_HEADER, "")
                if not _secret.check(svc._key, body, sig):
                    get_logger().debug(
                        "coordinator: bad request signature on %s from %s",
                        self.path, self._peer())
                    self._reply({"error": "bad signature"}, 403)
                    return
                msg = json.loads(body or b"{}")
                if self.path == "/register":
                    if "process_ids" in msg:
                        svc._record_register_batch(
                            [int(p) for p in msg["process_ids"]],
                            time.monotonic())
                    else:
                        svc._record_register(int(msg["process_id"]),
                                             time.monotonic())
                    self._reply({"ok": True})
                elif self.path == "/metrics":
                    # Worker metrics push, piggybacked on the existing
                    # poll cadence (watchdog watcher / commit-time check).
                    svc._record_metrics(msg)
                    self._reply({"ok": True})
                elif self.path == "/publish":
                    # Training-side publish announcement
                    # (serving/publisher.py): journaled, wakes publish
                    # long-pollers, never bumps version/failure_seq.
                    ok = svc._record_publish(msg)
                    self._reply({"ok": ok})
                elif self.path == "/replica":
                    # Serving-replica lifecycle (serving/fleet.py):
                    # register / drain / deregister, journaled.
                    ok = svc._record_replica(msg)
                    self._reply({"ok": ok})
                elif self.path == "/preempt":
                    # Graceful-departure notice (core/lifecycle.py via
                    # run_fn): journaled world shrink on the VERSION
                    # counter — survivors reset gracefully, no
                    # peer-failure grace window burns.
                    try:
                        host = str(msg["host"])
                    except (KeyError, TypeError):
                        self._reply({"error": "bad preempt"}, 400)
                        return
                    svc.mark_preempt(host)
                    self._reply({"ok": True})
                else:
                    get_logger().debug(
                        "coordinator: unknown POST path %s from %s",
                        self.path, self._peer())
                    self._reply({"error": "not found"}, 404)

        self._server = ThreadingHTTPServer((bind_host, 0), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def addr(self, advertise_host: str) -> str:
        return f"{advertise_host}:{self.port}"

    def alive(self) -> bool:
        """The serve thread is still running. Any death of that thread
        (unhandled exception in serve_forever, torn socket) ends it — the
        driver polls this and rebuilds from the journal."""
        return self._thread.is_alive()

    # -- /world reply triage (caller holds the lock) -------------------------

    def _poll_s_locked(self) -> float:
        """Advertised poll cadence: stretch with world size toward the
        target aggregate request rate, never below the reference interval
        (small worlds keep the snappy cadence the chaos tests rely on)."""
        if self._target_rps <= 0:
            return C.DEFAULT_POLL_INTERVAL_S
        return max(C.DEFAULT_POLL_INTERVAL_S, self._np / self._target_rps)

    def _snapshot_locked(self) -> dict:
        return {"version": self._version, "hosts": dict(self._hosts),
                "np": self._np, "failures": [dict(f) for f in self._failures],
                "failure_seq": self._failure_seq}

    def _world_reply_locked(self, since_v: Optional[int],
                            since_s: Optional[int]) -> dict:
        poll_s = self._poll_s_locked()
        if since_v is None or since_s is None:
            # Legacy/first-contact bare GET: the full payload (plus the
            # pacing hint, which canonicalizing clients strip).
            full = self._snapshot_locked()
            full["poll_s"] = poll_s
            return full
        if since_v == self._version and since_s == self._failure_seq:
            return {"nm": True, "version": self._version,
                    "failure_seq": self._failure_seq, "poll_s": poll_s}
        cursor = since_v + since_s
        eid = self._version + self._failure_seq
        if cursor < eid and self._events \
                and self._events[0][0] <= cursor + 1:
            delta = [rec for (e, rec) in self._events if e > cursor]
            return {"delta": delta, "version": self._version,
                    "failure_seq": self._failure_seq, "poll_s": poll_s}
        # Cursor fell out of the retained window, runs AHEAD of this
        # service (its predecessor crashed before journaling?), or the
        # counters are incoherent: full-snapshot fallback.
        return {"snapshot": self._snapshot_locked(), "poll_s": poll_s}

    # -- mutators ------------------------------------------------------------

    def _maybe_compact_locked(self) -> None:
        if (self._journal and self._compact_every > 0
                and self._journal.records_since_snapshot
                >= self._compact_every):
            state = self._snapshot_locked()
            state["registrations"] = {str(k): v
                                      for k, v in self._started.items()}
            state["metrics"] = {k: {"c": dict(v.get("c", {})),
                                    "g": dict(v.get("g", {}))}
                                for k, v in self._metrics.items()}
            state["publish"] = dict(self._publish) \
                if self._publish is not None else None
            state["publish_seq"] = self._publish_seq
            state["replicas"] = {
                k: {"addr": v["addr"], "rank": v["rank"],
                    "draining": v["draining"]}
                for k, v in self._replicas.items()}
            state["arbiter_seq"] = self._arbiter_seq
            state["fleet"] = dict(self._fleet) \
                if self._fleet is not None else None
            state["preempts"] = [dict(p) for p in self._preempts]
            self._journal.compact(state)

    def _record_register(self, process_id: int, ts: float) -> None:
        with self._lock:
            self._started[process_id] = ts
            if self._journal:
                self._journal.append({"op": "register",
                                      "process_id": process_id, "ts": ts})
                self._maybe_compact_locked()

    def _record_register_batch(self, process_ids: Iterable[int],
                               ts: float) -> None:
        """Coalesced per-host registration: one request and ONE journal
        fsync for a whole host's worth of workers."""
        pids = [int(p) for p in process_ids]
        with self._lock:
            for pid in pids:
                self._started[pid] = ts
            if self._journal:
                self._journal.append({"op": "register_batch",
                                      "process_ids": pids, "ts": ts})
                self._maybe_compact_locked()

    def _record_metrics(self, msg: dict) -> None:
        """Merge one worker's cumulative metrics delta and journal it so
        the aggregate survives a coordinator crash-restart. Does NOT bump
        ``version``/``failure_seq`` or enter the delta window — metrics
        churn must not wake long-polls or evict membership history."""
        try:
            rank = str(int(msg["rank"]))
            c = {str(k): float(v) for k, v in msg.get("c", {}).items()}
            g = {str(k): float(v) for k, v in msg.get("g", {}).items()}
        except (KeyError, TypeError, ValueError):
            get_logger().debug("coordinator: malformed metrics push "
                               "ignored: %r", msg)
            return
        with self._lock:
            per_rank = self._metrics.setdefault(rank, {"c": {}, "g": {}})
            per_rank["c"].update(c)
            per_rank["g"].update(g)
            if self._journal:
                self._journal.append({"op": "metrics", "rank": rank,
                                      "c": c, "g": g})
                self._maybe_compact_locked()

    def _record_publish(self, msg: dict) -> bool:
        """Adopt one publish record (serving/publisher.py wire shape:
        ``{"record": {...}}`` with at least ``manifest_seq`` and
        ``commit_dir``), journal it so serving discovery survives a
        coordinator crash-restart, and wake publish long-pollers. Like
        metrics, does NOT bump ``version``/``failure_seq`` or enter the
        delta window — a publish is not a membership event."""
        try:
            record = dict(msg["record"])
            int(record["manifest_seq"])
            str(record["commit_dir"])
        except (KeyError, TypeError, ValueError):
            get_logger().debug("coordinator: malformed publish "
                               "announcement ignored: %r", msg)
            return False
        with self._lock:
            self._publish = record
            self._publish_seq += 1
            if self._journal:
                self._journal.append({"op": "publish", "record": record})
                self._maybe_compact_locked()
            self._cond.notify_all()
        get_logger().info(
            "coordinator: publish #%d adopted (manifest_seq=%s step=%s)",
            self._publish_seq, record.get("manifest_seq"),
            record.get("step"))
        return True

    def publish_snapshot(self) -> tuple:
        """``(publish_seq, record-or-None)`` — driver/test observability."""
        with self._lock:
            rec = dict(self._publish) if self._publish is not None else None
            return self._publish_seq, rec

    # -- serving-replica registry (serving/fleet.py; docs/fleet.md) ----------

    def _replica_grace_s(self) -> float:
        return max(0.0, _env_float(C.REPLICA_GRACE_ENV,
                                   C.DEFAULT_REPLICA_GRACE_S))

    def _touch_replica_locked(self, replica_id: Optional[str]) -> None:
        """Heartbeat: bump ``last_seen`` for a replica riding its poll.
        Unknown ids are ignored (a pruned replica must re-register, not
        resurrect itself through a stale poll loop)."""
        if replica_id is None:
            return
        rep = self._replicas.get(str(replica_id))
        if rep is not None:
            rep["last_seen"] = self._clock()

    def _prune_replicas_locked(self, now: float) -> None:
        """Health gate: drop replicas silent past the grace window.
        Journaled as deregisters so a crash-restart replays to the same
        membership the live service was serving."""
        grace = self._replica_grace_s()
        if grace <= 0:
            return
        for rid in [r for r, v in self._replicas.items()
                    if now - v["last_seen"] > grace]:
            self._replicas.pop(rid)
            _telemetry.inc("hvd_fleet_replica_expired_total")
            get_logger().warning(
                "coordinator: replica %s health-gated out (no heartbeat "
                "for > %.1fs)", rid, grace)
            if self._journal:
                self._journal.append({"op": "replica",
                                      "action": "deregister",
                                      "replica_id": rid,
                                      "reason": "grace"})
                self._maybe_compact_locked()

    def _record_replica(self, msg: dict) -> bool:
        """Apply one replica lifecycle mutation (POST /replica):
        ``{"action": "register"|"drain"|"deregister", "replica_id": ...,
        "addr": ..., "rank": ...}``. Journaled; never bumps
        version/failure_seq."""
        try:
            action = str(msg.get("action", "register"))
            rid = str(msg["replica_id"])
            if action not in ("register", "drain", "deregister"):
                raise ValueError(action)
            if action == "register":
                addr = str(msg["addr"])
                rank = int(msg.get("rank", 0))
        except (KeyError, TypeError, ValueError):
            get_logger().debug("coordinator: malformed replica message "
                               "ignored: %r", msg)
            return False
        with self._lock:
            if action == "register":
                self._replicas[rid] = {"addr": addr, "rank": rank,
                                       "draining": False,
                                       "last_seen": self._clock()}
                rec = {"op": "replica", "action": "register",
                       "replica_id": rid, "addr": addr, "rank": rank}
            elif action == "drain":
                rep = self._replicas.get(rid)
                if rep is None:
                    return False
                rep["draining"] = True
                rec = {"op": "replica", "action": "drain",
                       "replica_id": rid}
            else:
                if self._replicas.pop(rid, None) is None:
                    return True     # idempotent: already gone
                rec = {"op": "replica", "action": "deregister",
                       "replica_id": rid,
                       "reason": str(msg.get("reason", ""))}
            if self._journal:
                self._journal.append(rec)
                self._maybe_compact_locked()
        get_logger().info("coordinator: replica %s %s", rid, action)
        return True

    def replicas_view(self) -> dict:
        """The ``GET /replicas`` payload: currently-healthy replicas
        (expired ones pruned right here — the list a client fails over
        against must never name a dead replica for longer than the grace
        window), plus the arbiter's fleet shape for observability."""
        with self._lock:
            self._prune_replicas_locked(self._clock())
            reps = [{"id": rid, "addr": v["addr"], "rank": v["rank"],
                     "draining": v["draining"]}
                    for rid, v in sorted(self._replicas.items())]
            fleet = dict(self._fleet) if self._fleet is not None else None
            return {"replicas": reps, "fleet": fleet,
                    "arbiter_seq": self._arbiter_seq}

    def replicas_snapshot(self) -> Dict[str, dict]:
        """Raw registry copy (tests / driver observability) — no pruning."""
        with self._lock:
            return {k: dict(v) for k, v in self._replicas.items()}

    # -- fleet arbiter decisions (elastic/arbiter.py) ------------------------

    def record_arbiter_decision(self, serving_target: int, training_np: int,
                                reason: str = "") -> int:
        """Journal one arbiter decision under the arbiter's own monotonic
        sequence and adopt it as the current fleet shape. Returns the new
        sequence number. Never bumps version/failure_seq — enacting the
        shape (graceful training reset, replica start/stop) is the
        harness's move and lands as its own world/replica records."""
        with self._lock:
            self._arbiter_seq += 1
            self._fleet = {"serving_target": int(serving_target),
                           "training_np": int(training_np),
                           "reason": str(reason)}
            if self._journal:
                self._journal.append({"op": "arbiter",
                                      "seq": self._arbiter_seq,
                                      "serving_target": int(serving_target),
                                      "training_np": int(training_np),
                                      "reason": str(reason)})
                self._maybe_compact_locked()
            seq = self._arbiter_seq
        _telemetry.inc("hvd_fleet_arbiter_decisions_total")
        get_logger().info(
            "coordinator: arbiter decision #%d -> serving=%d training=%d "
            "(%s)", seq, serving_target, training_np, reason)
        return seq

    def fleet_view(self) -> dict:
        """``{"arbiter_seq", "fleet"}`` — the last decided shape (None
        before any decision). The arbiter seeds itself from this after a
        coordinator crash-restart."""
        with self._lock:
            return {"arbiter_seq": self._arbiter_seq,
                    "fleet": dict(self._fleet)
                    if self._fleet is not None else None}

    def serving_signals(self) -> dict:
        """The arbiter's inputs, read from the coordinator-merged metrics
        (core/telemetry.py wire shape): worst per-rank serving queue
        depth and staleness across ranks >= the serving rank band, and
        the median training step wall time across the rest."""
        with self._lock:
            ranks = {int(r): v for r, v in self._metrics.items()}
        from ..serving import constants as SC
        band = SC.serving_rank()

        def _vals(g: dict, name: str) -> list:
            # Series ids are ``name`` or ``name{labels}`` (telemetry.py
            # _series_id) — match both.
            return [float(v) for k, v in g.items()
                    if k == name or k.startswith(name + "{")]

        queue_depth = 0.0
        staleness = 0.0
        steps = []
        for rank, m in ranks.items():
            g = m.get("g", {})
            if rank >= band:
                queue_depth = max([queue_depth] + _vals(
                    g, "hvd_serving_queue_depth"))
                staleness = max([staleness] + _vals(
                    g, "hvd_serving_staleness_seconds"))
            else:
                steps.extend(_vals(g, "hvd_step_wall_seconds"))
        steps.sort()
        return {"queue_depth": queue_depth, "staleness_s": staleness,
                "step_wall_s": steps[len(steps) // 2] if steps else None}

    def metrics_snapshot(self) -> Dict[str, dict]:
        """Per-rank compact snapshots (deep-copied) — the incident
        report embeds this to carry the victim's last-known state."""
        with self._lock:
            return {k: {"c": dict(v.get("c", {})), "g": dict(v.get("g", {}))}
                    for k, v in self._metrics.items()}

    def metrics_text(self) -> str:
        """The ``GET /metrics`` body: Prometheus text exposition of
        per-rank samples plus the fleet rollup."""
        return _telemetry.render_prometheus(self.metrics_snapshot())

    def update_world(self, hosts: Dict[str, int], np_: int) -> int:
        """Publish a new membership view; returns the new version."""
        with self._lock:
            self._version += 1
            self._hosts = dict(hosts)
            self._np = np_
            self._failures = []   # failures are per-generation; seq stays
            self._preempts = []   # ditto — the new generation starts clean
            self._events.append(
                (self._version + self._failure_seq,
                 {"op": "world", "version": self._version,
                  "hosts": dict(self._hosts), "np": np_}))
            if self._journal:
                self._journal.append({"op": "world",
                                      "version": self._version,
                                      "hosts": self._hosts, "np": np_})
                self._maybe_compact_locked()
            self._cond.notify_all()
            return self._version

    def mark_preempt(self, host: str) -> int:
        """Record an ANNOUNCED graceful departure (the preempted worker's
        run_fn posts this after its out-of-cadence commit): drop the host
        from the membership view and publish the shrink on the VERSION
        counter — the same wake path as :meth:`update_world`, so
        survivors take the graceful ``HostsUpdatedInterrupt`` reset.
        ``failure_seq`` is deliberately untouched: the peer-failure grace
        deadline (core/watchdog.py) must never arm for a preemption.
        Returns the new version. Idempotent per (host, generation)."""
        with self._lock:
            if any(p["host"] == host for p in self._preempts):
                return self._version     # duplicate notice (e.g. retry)
            self._version += 1
            self._hosts.pop(host, None)
            self._np = sum(self._hosts.values())
            self._failures = []          # same world-op clear semantics
            self._preempts.append({"host": host})
            rec = {"op": "preempt", "version": self._version,
                   "hosts": dict(self._hosts), "np": self._np,
                   "host": host}
            self._events.append(
                (self._version + self._failure_seq, dict(rec)))
            if self._journal:
                self._journal.append(rec)
                self._maybe_compact_locked()
            self._cond.notify_all()
            version, np_ = self._version, self._np
        _telemetry.inc("hvd_elastic_preempts_total")
        get_logger().warning(
            "coordinator: host %s preempted (graceful) — world v%d np=%d",
            host, version, np_)
        return version

    def preempts_view(self) -> list:
        """This generation's announced departures (driver/tests)."""
        with self._lock:
            return [dict(p) for p in self._preempts]

    def mark_failure(self, host: str, code: int) -> int:
        """Record a worker-process death for the peer-liveness push
        (driver's ``run_one`` calls this the moment a worker exits
        non-zero). Survivors' step monitors poll it off ``/world`` and arm
        the ``HOROVOD_PEER_FAILURE_GRACE_SECONDS`` deadline on the step
        they are blocked in. Returns the new failure sequence number."""
        with self._lock:
            self._failure_seq += 1
            self._failures.append({"host": host, "code": int(code)})
            self._events.append(
                (self._version + self._failure_seq,
                 {"op": "failure", "host": host, "code": int(code),
                  "seq": self._failure_seq}))
            if self._journal:
                self._journal.append({"op": "failure", "host": host,
                                      "code": int(code),
                                      "seq": self._failure_seq})
                self._maybe_compact_locked()
            self._cond.notify_all()
            return self._failure_seq

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    @property
    def failure_seq(self) -> int:
        with self._lock:
            return self._failure_seq

    def registered_workers(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._started)

    def journal_size_bytes(self) -> int:
        """On-disk journal size (scale-harness observability; 0 when the
        service runs journal-less)."""
        return self._journal.size_bytes() if self._journal else 0

    def _release_parked(self) -> None:
        with self._cond:
            self._closing = True
            self._cond.notify_all()

    def close(self) -> None:
        self._release_parked()
        self._server.shutdown()
        self._server.server_close()
        if self._journal:
            self._journal.close()

    def simulate_crash(self) -> None:
        """Chaos-test hook: die the way a real service death looks from
        the driver's side — the socket is torn down and the serve thread
        exits WITHOUT journal finalization or any orderly handoff.
        Parked long-polls are released first (a dead process drops them
        immediately; daemon threads parked for the long-poll cap would
        leak the sockets into the next test instead)."""
        self._release_parked()
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


class CoordinatorClient:
    """Worker-side client (used by the commit-time membership watcher and
    the step monitor's failure-feed poll).

    Each logical call (:meth:`get_world`, :meth:`register`) retries under
    :class:`RetryPolicy`; on connect failure the coordinator address is
    re-resolved from the address file (a driver that crash-restarted its
    service republishes the new port there). ``sleep``/``clock`` are
    injectable so retry/escalation tests run on a fake clock — no real
    sleeps in tier-1.

    The client keeps the last world it assembled and sends its cursor on
    every subsequent ``/world``, so unchanged worlds cost a not-modified
    reply and changed worlds cost only the delta (replayed through
    journal.apply_record — the same semantics journal rebuild uses).
    Whatever the wire shape, :meth:`get_world` returns the SAME canonical
    payload dict (``WORLD_KEYS`` exactly) the full response always had."""

    def __init__(self, addr: str, secret_key: bytes,
                 timeout_s: Optional[float] = None,
                 policy: Optional[RetryPolicy] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None,
                 delta: bool = True, watch_publish: bool = False,
                 replica_id: Optional[str] = None):
        self._base = f"http://{addr}"
        self._key = secret_key
        #: Serving-replica identity (serving/fleet.py): when set, every
        #: ``/world`` poll carries ``replica=<id>`` so the poll doubles as
        #: the replica's heartbeat — no extra RPC surface for liveness.
        self.replica_id = replica_id
        #: False = never send a cursor: every /world is a full fetch (the
        #: pre-delta wire protocol — the A/B baseline arm of
        #: benchmarks/control_plane.py; no production caller sets this).
        self._delta = delta
        #: True = subscribe to serving-plane publish announcements: every
        #: /world carries ``since_p`` so the server attaches the newest
        #: publish record and wakes this client's long-poll when it moves
        #: (serving/registry.py). Training workers leave this off — their
        #: replies and wake conditions are unchanged.
        self._watch_publish = watch_publish
        #: Newest publish record seen (None until one arrives) + its
        #: server-side cursor. Only maintained when ``watch_publish``.
        self.last_publish: Optional[dict] = None
        self.publish_seq = 0
        self._policy = policy or RetryPolicy.from_env()
        if timeout_s is not None:
            self._policy.timeout_s = timeout_s
        self._sleep = sleep
        self._clock = clock
        self._rng = rng
        #: HMAC verification failures observed (tampered/corrupt replies),
        #: counted separately from transport errors.
        self.sig_failures = 0
        #: HTTP attempts made (the rpc fault schedule's call-count axis).
        self.calls = 0
        #: Response-body bytes received across all calls (the scale
        #: harness's bytes-per-membership-change metric reads this).
        self.bytes_received = 0
        #: Times a delta request came back as a full snapshot (cursor fell
        #: out of the server's event window / crash-restarted server).
        self.snapshot_fallbacks = 0
        #: Times the delta replay was abandoned and re-fetched from
        #: scratch (incoherent delta — should stay 0 outside fault tests).
        self.resyncs = 0
        #: Server-advertised poll cadence from the last ``/world`` reply
        #: (None until one arrives). Pollers stretch to it (state.py).
        self.advertised_poll_s: Optional[float] = None
        self._failing_since: Optional[float] = None
        self._world: Optional[dict] = None
        self._lock = threading.Lock()

    # -- persistent-loss bookkeeping ----------------------------------------

    def _lost_timeout_s(self) -> float:
        return _env_float(C.COORD_LOST_TIMEOUT_ENV,
                          C.DEFAULT_COORD_LOST_TIMEOUT_S)

    def _note_success(self) -> None:
        with self._lock:
            self._failing_since = None

    def _note_failure(self) -> None:
        """Track continuous failure; raise CoordinatorLostError once it
        exceeds the lost-timeout window (0 disables)."""
        timeout = self._lost_timeout_s()
        now = self._clock()
        with self._lock:
            if self._failing_since is None:
                self._failing_since = now
            elapsed = now - self._failing_since
        if timeout > 0 and elapsed >= timeout:
            raise CoordinatorLostError(
                f"coordinator {self._base} unreachable for {elapsed:.0f}s "
                f"(>= {C.COORD_LOST_TIMEOUT_ENV}={timeout:.0f}s of "
                "continuous failure) — control plane lost")

    # -- address re-resolution ----------------------------------------------

    def _refresh_addr(self) -> bool:
        """Re-read the driver's address file (if visible): a crash-
        restarted coordinator serves on a fresh port. True if the base
        URL changed."""
        path = os.environ.get(C.COORD_ADDR_FILE_ENV)
        if not path:
            return False
        try:
            with open(path, "r", encoding="utf-8") as fh:
                addr = fh.read().strip()
        except OSError:
            return False
        if not addr or f"http://{addr}" == self._base:
            return False
        get_logger().info(
            "coordinator address changed %s -> http://%s (re-resolved "
            "from %s)", self._base, addr, path)
        self._base = f"http://{addr}"
        return True

    # -- fault seam (testing/faults.py rpc_* kinds) -------------------------

    def _next_call_fault(self):
        with self._lock:
            call = self.calls
            self.calls += 1
        if not os.environ.get("HOROVOD_FAULT_SPEC"):
            return None
        from ..testing import faults
        return faults.on_rpc_call(call)

    def _apply_pre_fault(self, fault) -> None:
        if fault is None:
            return
        if fault.kind == "rpc_drop":
            raise TimeoutError("fault rpc_drop: request dropped")
        if fault.kind == "rpc_refuse":
            raise ConnectionRefusedError("fault rpc_refuse: "
                                         "connection refused")
        if fault.kind == "rpc_delay":
            self._sleep(float(fault.params.get("seconds", "0.5")))

    # -- one attempt ---------------------------------------------------------

    def _request(self, path: str, data: Optional[bytes], fault,
                 timeout_s: Optional[float] = None) -> dict:
        """One HTTP attempt. Raises OSError on transport failure and
        _SignatureError on HMAC mismatch (counted + logged distinctly)."""
        from urllib import request as _urlreq
        self._apply_pre_fault(fault)
        url = f"{self._base}{path}"
        if data is None:
            req = _urlreq.Request(url)
        else:
            req = _urlreq.Request(
                url, data=data,
                headers={"Content-Type": "application/json",
                         SIG_HEADER: _secret.sign(self._key, data)})
        with _urlreq.urlopen(
                req, timeout=timeout_s if timeout_s is not None
                else self._policy.timeout_s) as r:
            body = r.read()
            sig = r.headers.get(SIG_HEADER, "")
        if fault is not None and fault.kind == "rpc_garble":
            body = b"\x00GARBLED\x00" + body
        if fault is not None and fault.kind == "rpc_badsig":
            sig = "0" * 64
        if not _secret.check(self._key, body, sig):
            with self._lock:
                self.sig_failures += 1
                count = self.sig_failures
            get_logger().warning(
                "coordinator response failed HMAC verification "
                "(signature failure #%d on %s — tampered or corrupt "
                "control-plane reply, NOT a network error)", count, url)
            raise _SignatureError(url)
        with self._lock:
            self.bytes_received += len(body)
        return json.loads(body)

    # -- the retrying logical call ------------------------------------------

    def _call(self, path: str, data: Optional[bytes] = None,
              timeout_s: Optional[float] = None) -> Optional[dict]:
        """Retry ``_request`` under the policy. Returns the decoded reply,
        or None when every attempt failed (transient failure — callers
        treat it as 'no change'). Raises CoordinatorLostError once the
        continuous-failure window exceeds the lost timeout."""
        delays = self._policy.delays(self._rng)
        last: Optional[BaseException] = None
        for attempt in range(self._policy.attempts):
            fault = self._next_call_fault()
            try:
                reply = self._request(path, data, fault, timeout_s)
                self._note_success()
                return reply
            except _SignatureError:
                last = None  # already counted + logged distinctly
            except OSError as e:
                last = e
                _telemetry.inc("hvd_rpc_attempt_failures_total")
                _telemetry.record_event("rpc_retry", path=path,
                                        attempt=attempt, error=str(e))
                # A refused connect is what a crash-restarted coordinator
                # looks like until the new port is published: re-resolve
                # from the address file before backing off.
                if self._refresh_addr():
                    continue
            delay = next(delays, None)
            if delay is not None:
                self._sleep(delay)
        if last is not None:
            get_logger().debug(
                "coordinator call %s failed after %d attempts: %s",
                path, self._policy.attempts, last)
        self._note_failure()
        return None

    # -- world-cache maintenance ---------------------------------------------

    def _world_copy(self) -> Optional[dict]:
        """The canonical payload (exactly ``WORLD_KEYS``), copied so
        callers mutating it cannot poison the delta cache."""
        with self._lock:
            w = self._world
            if w is None:
                return None
            return {"version": w["version"], "hosts": dict(w["hosts"]),
                    "np": w["np"],
                    "failures": [dict(f) for f in w["failures"]],
                    "failure_seq": w["failure_seq"]}

    @staticmethod
    def _canonical(payload: dict) -> dict:
        return {"version": int(payload["version"]),
                "hosts": dict(payload["hosts"]),
                "np": int(payload["np"]),
                "failures": [dict(f) for f in payload["failures"]],
                "failure_seq": int(payload["failure_seq"])}

    def _resync(self, reason: str) -> Optional[dict]:
        """Abandon the cursor and fetch one fresh full world (used when a
        delta/nm reply does not cohere with the cache)."""
        with self._lock:
            self._world = None
            self.resyncs += 1
        get_logger().warning(
            "coordinator delta state incoherent (%s) — resyncing with a "
            "full /world fetch", reason)
        reply = self._call("/world")
        if reply is None:
            return None
        return self._ingest_world(reply, allow_resync=False)

    def _ingest_world(self, reply: dict,
                      allow_resync: bool = True) -> Optional[dict]:
        """Fold one ``/world`` reply (any wire shape) into the cached
        world and return the canonical payload."""
        poll = reply.get("poll_s")
        if poll is not None:
            try:
                self.advertised_poll_s = float(poll)
            except (TypeError, ValueError):
                pass
        if "publish_seq" in reply:
            try:
                self.publish_seq = int(reply["publish_seq"])
                pub = reply.get("publish")
                self.last_publish = dict(pub) if pub is not None else None
            except (TypeError, ValueError):
                pass
        try:
            if reply.get("nm"):
                with self._lock:
                    w = self._world
                    ok = (w is not None
                          and w["version"] == reply.get("version")
                          and w["failure_seq"] == reply.get("failure_seq"))
                if ok:
                    return self._world_copy()
                if not allow_resync:
                    return None
                return self._resync("not-modified for a cursor we no "
                                    "longer hold")
            if "delta" in reply:
                with self._lock:
                    w = self._world
                    state = None if w is None else \
                        {"version": w["version"], "hosts": dict(w["hosts"]),
                         "np": w["np"],
                         "failures": [dict(f) for f in w["failures"]],
                         "failure_seq": w["failure_seq"]}
                if state is None:
                    if not allow_resync:
                        return None
                    return self._resync("delta without a cached base")
                for rec in reply["delta"]:
                    _apply_record(state, rec)
                if state["version"] != int(reply["version"]) or \
                        state["failure_seq"] != int(reply["failure_seq"]):
                    if not allow_resync:
                        return None
                    return self._resync(
                        "delta replay landed on "
                        f"v{state['version']}/s{state['failure_seq']}, "
                        f"server says v{reply['version']}/"
                        f"s{reply['failure_seq']}")
                with self._lock:
                    self._world = state
                return self._world_copy()
            if "snapshot" in reply:
                state = self._canonical(reply["snapshot"])
                with self._lock:
                    had_cursor = self._world is not None
                    self._world = state
                    if had_cursor:
                        self.snapshot_fallbacks += 1
                return self._world_copy()
            # Full payload (legacy server / first contact).
            state = self._canonical(reply)
        except (KeyError, TypeError, ValueError) as e:
            if not allow_resync:
                return None
            return self._resync(f"malformed reply ({e!r})")
        with self._lock:
            self._world = state
        return self._world_copy()

    # -- the public surface ---------------------------------------------------

    def get_world(self, wait: Optional[float] = None) -> Optional[dict]:
        """Current membership view, or None while the driver is merely
        *transiently* unreachable (callers treat that as 'no change').
        Persistent loss raises CoordinatorLostError instead — a dead
        driver must not look like a quiet network forever.

        ``wait`` (seconds) long-polls: the server parks the request until
        the membership/failure counters move or the bound expires, then
        answers as usual (``nm`` if nothing moved). Only takes effect once
        a first world has been fetched (the cursor is what the server
        parks on); the per-attempt HTTP timeout is extended by the bound
        so a full park does not read as a transport failure."""
        timeout_s: Optional[float] = None
        with self._lock:
            w = self._world
        params = []
        if w is not None and self._delta:
            params += [f"since_v={w['version']}",
                       f"since_s={w['failure_seq']}"]
        if self._watch_publish:
            params.append(f"since_p={self.publish_seq}")
        if self.replica_id:
            params.append(f"replica={self.replica_id}")
        if params and wait is not None and wait > 0:
            bound = min(float(wait), C.LONG_POLL_CAP_S)
            params.append(f"wait={bound:g}")
            timeout_s = self._policy.timeout_s + bound
        path = "/world" + ("?" + "&".join(params) if params else "")
        reply = self._call(path, timeout_s=timeout_s)
        if reply is None:
            return None
        return self._ingest_world(reply)

    def notify_preempt(self, host: str) -> bool:
        """Post this host's graceful-departure notice (the run_fn
        wrapper's last coordinator call before exiting with
        ``PREEMPT_EXIT_CODE``). Best-effort: a dropped notice degrades to
        the ordinary exit-code path — the driver still skips the
        blacklist because of the exit code."""
        body = json.dumps({"host": str(host)}).encode()
        reply = self._call("/preempt", data=body)
        return bool(reply and reply.get("ok"))

    def register(self, process_id: int) -> bool:
        """Announce this worker; retried under the same policy. Returns
        False on (transient) failure — the driver logs never-registered
        workers when its start-timeout trips, so a dropped registration
        is visible on the driver side too."""
        body = json.dumps({"process_id": process_id}).encode()
        reply = self._call("/register", data=body)
        return bool(reply and reply.get("ok"))

    def push_metrics(self, rank: int, delta: dict) -> bool:
        """Push one compact cumulative metrics delta
        (``core/telemetry.py::export_delta`` shape). Piggybacked on the
        poll cadence by its callers; a dropped push is healed by the next
        one (values are cumulative, not increments)."""
        body = json.dumps({"rank": int(rank),
                           "c": delta.get("c", {}),
                           "g": delta.get("g", {})}).encode()
        reply = self._call("/metrics", data=body)
        return bool(reply and reply.get("ok"))

    def announce_publish(self, record: dict) -> bool:
        """Announce one published-weights record (training side,
        serving/publisher.py). Best-effort under the usual retry policy:
        a dropped announcement is healed by the pin file in the CAS dir
        (store-watch discovery) and by the next publish."""
        body = json.dumps({"record": dict(record)}).encode()
        reply = self._call("/publish", data=body)
        return bool(reply and reply.get("ok"))

    def register_replica(self, replica_id: str, addr: str,
                         rank: int = 0) -> bool:
        """Register one serving replica (serving/fleet.py ReplicaAgent).
        Journaled server-side; the replica then stays in ``/replicas``
        for as long as its polls keep heartbeating inside
        ``HOROVOD_REPLICA_GRACE_SECONDS``."""
        body = json.dumps({"action": "register",
                           "replica_id": str(replica_id),
                           "addr": str(addr), "rank": int(rank)}).encode()
        reply = self._call("/replica", data=body)
        return bool(reply and reply.get("ok"))

    def drain_replica(self, replica_id: str) -> bool:
        """Mark a replica draining: it stays registered (in-flight work
        finishes) but failover clients stop routing NEW traffic to it."""
        body = json.dumps({"action": "drain",
                           "replica_id": str(replica_id)}).encode()
        reply = self._call("/replica", data=body)
        return bool(reply and reply.get("ok"))

    def deregister_replica(self, replica_id: str, reason: str = "") -> bool:
        """Remove a replica from the registry (graceful drain complete,
        or the hosting agent shutting down). Idempotent server-side."""
        body = json.dumps({"action": "deregister",
                           "replica_id": str(replica_id),
                           "reason": str(reason)}).encode()
        reply = self._call("/replica", data=body)
        return bool(reply and reply.get("ok"))

    def get_replicas(self) -> Optional[dict]:
        """The coordinator's current healthy-replica list + fleet shape
        (``GET /replicas``), or None on transient failure."""
        return self._call("/replicas")

    def register_batch(self, process_ids: Iterable[int]) -> bool:
        """Announce a whole host's workers in ONE request (and one journal
        fsync server-side) — the pod-scale path the launcher's per-host
        process uses instead of np parallel :meth:`register` calls."""
        body = json.dumps(
            {"process_ids": [int(p) for p in process_ids]}).encode()
        reply = self._call("/register", data=body)
        return bool(reply and reply.get("ok"))
