"""Subprocess execution with kill-tree cleanup.

Reference parity: ``horovod/runner/common/util/safe_shell_exec.py``
(SURVEY.md §2.5): run worker commands in their own process group, stream
stdout/stderr, and guarantee no orphaned grandchildren on termination —
the property the reference needs so a dying launcher never leaks workers.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import IO, Dict, List, Optional

GRACEFUL_TERMINATION_TIME_S = 5.0


def _tee(src: IO[bytes], sinks: List[IO]) -> None:
    for line in iter(src.readline, b""):
        for sink in sinks:
            try:
                if hasattr(sink, "buffer"):
                    sink.buffer.write(line)
                else:
                    sink.write(line)
                sink.flush()
            except (ValueError, OSError):
                pass
    src.close()


def terminate_process_group(proc: subprocess.Popen,
                            grace_s: float = GRACEFUL_TERMINATION_TIME_S
                            ) -> None:
    """SIGTERM the child's process group, escalate to SIGKILL after grace."""
    if proc.poll() is not None:
        return
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except ProcessLookupError:
        return
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return
        time.sleep(0.05)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except ProcessLookupError:
        pass


def execute(command: "List[str] | str",
            env: Optional[Dict[str, str]] = None,
            stdout: Optional[IO] = None,
            stderr: Optional[IO] = None,
            prefix: Optional[str] = None,
            events: Optional[List[threading.Event]] = None,
            timeout_s: Optional[float] = None,
            stdin_data: Optional[bytes] = None,
            sweep_note: Optional[dict] = None) -> int:
    """Run ``command`` in a new process group; return its exit code.

    ``events``: if any event is set, the process tree is torn down (the
    reference uses this to propagate launcher shutdown to every worker).
    ``prefix``: per-line tag, the reference's ``[1]<stdout>`` style.
    ``timeout_s``: wall-clock cap on THIS process (used for bounded probes,
    not worker lifetimes). ``stdin_data``: written to the child's stdin then
    closed (secret delivery; keeps it off the command line).
    ``sweep_note``: if given, ``sweep_note["swept"] = True`` is set when the
    process was terminated BY the events sweep rather than dying on its own
    — the elastic driver needs the distinction to record organic deaths as
    failures without also recording its own teardown's collateral exits.
    """
    shell = isinstance(command, str)
    out_sink = stdout if stdout is not None else sys.stdout
    err_sink = stderr if stderr is not None else sys.stderr
    proc = subprocess.Popen(
        command, shell=shell, env=env, start_new_session=True,
        stdin=subprocess.PIPE if stdin_data is not None else subprocess.DEVNULL,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    if stdin_data is not None:
        try:
            proc.stdin.write(stdin_data)
            proc.stdin.flush()
        except BrokenPipeError:
            pass
        finally:
            proc.stdin.close()

    sinks_out: List[IO] = [out_sink]
    sinks_err: List[IO] = [err_sink]
    if prefix is not None:
        class _Prefixer:
            def __init__(self, sink, tag):
                self.sink, self.tag = sink, tag
            def write(self, line: bytes):
                text = line.decode("utf-8", "replace")
                w = getattr(self.sink, "write")
                w(f"{self.tag}{text}")
            def flush(self):
                self.sink.flush()
        sinks_out = [_Prefixer(out_sink, f"[{prefix}]<stdout> ")]
        sinks_err = [_Prefixer(err_sink, f"[{prefix}]<stderr> ")]

    t_out = threading.Thread(target=_tee, args=(proc.stdout, sinks_out),
                             daemon=True)
    t_err = threading.Thread(target=_tee, args=(proc.stderr, sinks_err),
                             daemon=True)
    t_out.start(); t_err.start()

    deadline = (time.monotonic() + timeout_s) if timeout_s else None
    try:
        while True:
            if proc.poll() is not None:
                break
            if events and any(e.is_set() for e in events):
                if sweep_note is not None:
                    sweep_note["swept"] = True
                terminate_process_group(proc)
                break
            if deadline and time.monotonic() > deadline:
                terminate_process_group(proc)
                break
            time.sleep(0.05)
    finally:
        if proc.poll() is None:
            terminate_process_group(proc)
    t_out.join(timeout=2)
    t_err.join(timeout=2)
    return proc.wait()
