"""Store-backed training data: out-of-core materialisation + streaming.

Reference parity: the data half of ``horovod/spark/common/store.py`` plus
the Petastorm streaming role (SURVEY.md §2.5): the reference's estimators
materialise the DataFrame into store-resident parquet and each worker
streams its shard during training, so dataset size is bounded by the store,
not driver RAM.

TPU-native rendering: partitions are spilled into fixed-size-record binary
part files under ``store.train_data_path(run_id)`` (one record = the raw
bytes of one feature row + one label), and training streams them through
``native.RecordPipeline`` — the C++ multithreaded prefetching reader (GIL-
free, numpy fallback with identical ordering). Peak producer memory is one
part (``rows_per_part`` records); the consumer holds one prefetch window.

    ds = materialize_to_store(df_or_arrays_or_chunks, store, "run1")
    model = JaxEstimator(..., store=store).fit(ds)     # streams, no RAM copy
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterable, Iterator, Optional, Tuple

import numpy as np

from ..checkpoint.store import Store
from ..core.logging import get_logger

_META = "meta.json"


def _row_chunks(data, feature_col: str, label_col: str,
                rows_per_part: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield (X_chunk, y_chunk) arrays of <= rows_per_part rows from any
    supported source WITHOUT materialising the whole dataset:

    - pyspark DataFrame → ``toLocalIterator()`` (row-streamed off executors)
    - pandas DataFrame / (X, y) tuple → sliced views
    - an iterator/generator of (X_chunk, y_chunk) pairs → passed through
      (the fake-ctx seam: tests and custom sources feed partitions here)
    """
    if isinstance(data, tuple) and len(data) == 2:
        X, y = np.asarray(data[0]), np.asarray(data[1])
        for s in range(0, len(X), rows_per_part):
            yield X[s:s + rows_per_part], y[s:s + rows_per_part]
        return
    try:
        import pyspark  # noqa: F401
        from pyspark.sql import DataFrame as SparkDF
        if isinstance(data, SparkDF):
            buf_x, buf_y = [], []
            for row in data.select(feature_col, label_col).toLocalIterator():
                buf_x.append(np.asarray(row[0]))
                buf_y.append(row[1])
                if len(buf_x) >= rows_per_part:
                    yield np.stack(buf_x), np.asarray(buf_y)
                    buf_x, buf_y = [], []
            if buf_x:
                yield np.stack(buf_x), np.asarray(buf_y)
            return
    except ImportError:
        pass
    if hasattr(data, "columns") and hasattr(data, "__getitem__"):
        # Stack per WINDOW, not the whole column — peak memory stays one
        # part, the bound this module promises.
        fcol, lcol = data[feature_col], data[label_col]
        for s in range(0, len(fcol), rows_per_part):
            window = fcol[s:s + rows_per_part]
            yield (np.stack([np.asarray(v) for v in window]),
                   np.asarray(lcol[s:s + rows_per_part]))
        return
    if isinstance(data, (Iterator,)) or (isinstance(data, Iterable)
                                         and not hasattr(data, "shape")):
        for X, y in data:
            X, y = np.asarray(X), np.asarray(y)
            for s in range(0, len(X), rows_per_part):
                yield X[s:s + rows_per_part], y[s:s + rows_per_part]
        return
    raise TypeError(
        f"cannot materialise {type(data).__name__}; pass a Spark/pandas "
        "DataFrame, an (X, y) tuple, or an iterator of (X, y) chunks")


def _to_records(X: np.ndarray, y: np.ndarray) -> np.ndarray:
    """[n, ...] features + [n, ...] labels → [n, record_bytes] uint8."""
    n = X.shape[0]
    Xb = np.ascontiguousarray(X).reshape(n, -1).view(np.uint8)
    yb = np.ascontiguousarray(y).reshape(n, -1).view(np.uint8)
    return np.concatenate([Xb, yb], axis=1)


def materialize_to_store(data, store: Store, run_id: str, *,
                         feature_col: str = "features",
                         label_col: str = "label",
                         rows_per_part: int = 65536) -> "StoreDataset":
    """Spill ``data`` into fixed-record part files under the store and
    return the :class:`StoreDataset` handle. Bounded memory: one part."""
    # Remote stores work through the same ``store.write()`` calls: each
    # part is built in memory (bounded: rows_per_part records) and
    # uploaded — the reference's local-spill→store-upload staging
    # (spark/common/store.py) collapsed to one step because parts are
    # already assembled chunk-wise. The download side stages per-shard in
    # StoreDataset._shard_paths.
    base = store.train_data_path(run_id)
    store.makedirs(base)
    meta: Optional[dict] = None
    parts = []
    for i, (X, y) in enumerate(_row_chunks(data, feature_col, label_col,
                                           rows_per_part)):
        if len(X) != len(y):
            raise ValueError(f"chunk {i}: {len(X)} features vs "
                             f"{len(y)} labels")
        sig = {
            "feature_shape": list(X.shape[1:]),
            "feature_dtype": str(X.dtype),
            "label_shape": list(y.shape[1:]),
            "label_dtype": str(y.dtype),
        }
        if meta is None:
            meta = sig
        elif sig != meta:
            # Fixed-size records: ANY drift (features OR labels, shape or
            # dtype) would corrupt the file layout or silently cast.
            raise ValueError(
                f"chunk {i}: inconsistent row signature across chunks: "
                f"{sig} vs {meta}")
        recs = _to_records(X, y)
        name = f"part-{i:05d}.bin"
        blob = recs.tobytes()
        store.write(os.path.join(base, name), blob)
        import hashlib
        parts.append({"name": name, "rows": int(len(X)),
                      "digest": hashlib.blake2b(blob,
                                                digest_size=16).hexdigest()})
    if meta is None:
        raise ValueError("empty dataset: no chunks produced")
    meta["parts"] = parts
    meta["n_rows"] = int(sum(p["rows"] for p in parts))
    store.write(os.path.join(base, _META),
                json.dumps(meta).encode())
    get_logger().info(
        "materialized %d rows into %d part(s) at %s", meta["n_rows"],
        len(parts), base)
    return StoreDataset(store, run_id)


class StoreDataset:
    """Handle to a materialised training set inside a Store.

    ``batches(...)`` streams (features, labels) host batches through
    ``native.RecordPipeline``; per-process sharding assigns part files
    round-robin (reference: per-executor Petastorm shards)."""

    def __init__(self, store: Store, run_id: str):
        self.store = store
        self.run_id = run_id
        self.base = store.train_data_path(run_id)
        self.meta = json.loads(store.read(
            os.path.join(self.base, _META)).decode())
        self.feature_shape = tuple(self.meta["feature_shape"])
        self.feature_dtype = np.dtype(self.meta["feature_dtype"])
        self.label_shape = tuple(self.meta["label_shape"])
        self.label_dtype = np.dtype(self.meta["label_dtype"])
        self.n_rows = self.meta["n_rows"]
        self._fbytes = (int(np.prod(self.feature_shape, dtype=np.int64))
                        * self.feature_dtype.itemsize)
        self._lbytes = (int(np.prod(self.label_shape, dtype=np.int64))
                        * self.label_dtype.itemsize)

    @property
    def record_bytes(self) -> int:
        return self._fbytes + self._lbytes

    def sample_features(self, n: int = 1) -> np.ndarray:
        """Zeros of the feature shape — for model init without data."""
        return np.zeros((n,) + self.feature_shape, self.feature_dtype)

    def _shard_paths(self, rank: int, num_replicas: int):
        """LOCAL file paths for this process's shard. On a remote store,
        the shard's parts are staged down to a local cache first
        (reference behavior: each executor stages its Petastorm shard
        from HDFS/S3/DBFS to local disk before streaming) — only THIS
        rank's parts move, cached across epochs by name+size."""
        rows_by_name = {p["name"]: p["rows"] for p in self.meta["parts"]}
        names = [p["name"] for p in self.meta["parts"]]
        mine = names[rank::num_replicas]
        if not mine:
            raise ValueError(
                f"{len(names)} part file(s) cannot shard over "
                f"{num_replicas} processes; lower rows_per_part when "
                "materializing")
        if not self.store.is_remote():
            return [os.path.join(self.base, n) for n in mine]
        digest_by_name = {p["name"]: p.get("digest")
                          for p in self.meta["parts"]}
        stage = self._staging_dir()
        out = []
        for n in mine:
            local = os.path.join(stage, n)
            marker = f"{local}.digest"
            want_bytes = rows_by_name[n] * self.record_bytes
            want_digest = digest_by_name[n]
            # Size alone cannot distinguish a RE-materialized run_id with
            # the same row signature from the cached one — the content
            # digest recorded at materialize time is the cache key.
            fresh = (os.path.exists(local)
                     and os.path.getsize(local) == want_bytes
                     and (want_digest is None
                          or (os.path.exists(marker)
                              and open(marker).read() == want_digest)))
            if not fresh:
                data = self.store.read(os.path.join(self.base, n))
                tmp = f"{local}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(data)
                # Data first, marker second: a crash between the two leaves
                # a MISSING/stale marker (cache miss, re-fetch) — the other
                # order would leave a fresh marker vouching for stale bytes.
                os.replace(tmp, local)  # atomic: concurrent ranks race ok
                if want_digest is not None:
                    with open(f"{marker}.tmp.{os.getpid()}", "w") as f:
                        f.write(want_digest)
                    os.replace(f"{marker}.tmp.{os.getpid()}", marker)
            out.append(local)
        return out

    def _staging_dir(self) -> str:
        import hashlib
        import tempfile
        key = hashlib.blake2b(
            f"{self.store.prefix_path}:{self.run_id}".encode(),
            digest_size=6).hexdigest()
        uid = os.getuid() if hasattr(os, "getuid") else "u"
        # Per-user root (mode 0700): a shared /tmp/horovod_tpu_staging
        # owned by another user would make makedirs fail for everyone
        # else on a multi-user host.
        root = os.path.join(tempfile.gettempdir(),
                            f"horovod_tpu_staging_{uid}")
        os.makedirs(root, mode=0o700, exist_ok=True)
        d = os.path.join(root, key)
        os.makedirs(d, exist_ok=True)
        return d

    def batches(self, batch_size: int, *, shuffle: bool = True,
                seed: int = 0, rank: int = 0, num_replicas: int = 1,
                drop_remainder: bool = True):
        """Yield (features, labels) batches for this process's shard.
        One pass; call again (new seed) for the next epoch."""
        from .. import native

        pipe = native.RecordPipeline(
            self._shard_paths(rank, num_replicas),
            record_shape=(self.record_bytes,), dtype=np.uint8,
            batch_size=batch_size, shuffle=shuffle, seed=seed,
            drop_remainder=drop_remainder)
        try:
            for raw in pipe:
                n = raw.shape[0]
                feats = np.ascontiguousarray(raw[:, :self._fbytes]) \
                    .view(self.feature_dtype) \
                    .reshape((n,) + self.feature_shape)
                labels = np.ascontiguousarray(raw[:, self._fbytes:]) \
                    .view(self.label_dtype) \
                    .reshape((n,) + self.label_shape)
                yield feats, labels
        finally:
            pipe.close()

    def steps_per_epoch(self, batch_size: int, num_replicas: int = 1) -> int:
        return self.n_rows // num_replicas // batch_size

    def shard_rows(self, rank: int, num_replicas: int) -> int:
        rows = [p["rows"] for p in self.meta["parts"]]
        return sum(rows[rank::num_replicas])

    def min_steps(self, local_batch: int, num_replicas: int) -> int:
        """Steps every rank can take — collective-paired training loops
        must run the SAME count on each rank even when part files are
        unbalanced across shards."""
        return min(self.shard_rows(r, num_replicas) // local_batch
                   for r in range(num_replicas))
